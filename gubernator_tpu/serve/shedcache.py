"""Host-side over-limit shed cache: answer sticky verdicts before the device.

Under the Zipf workloads the ROADMAP targets, the keys that dominate
traffic are exactly the ones sitting over limit — and the token-bucket
kernel makes their verdict *sticky*: an existing token entry whose
remaining is 0 answers every hit-carrying request with exactly
(OVER_LIMIT, stored_limit, remaining=0, stored_reset_time) and mutates
nothing until the window expires (kernels.py decide_presorted: rem_vis
== 0 forces the OVER branch; the writeback re-stores identical values;
oracle.token_bucket's `remaining == 0` path is the same fixed point).
Today every one of those hits still pays the full enqueue -> prep ->
merge -> dispatch -> device round trip. This module is the standard
scalable-rate-limiter move (Raghavan et al., arXiv:2602.11741): a tiny
bounded host cache of those frozen verdicts, consulted BEFORE a request
enters the batcher, absorbing the hot head of the skew.

Shedding is gated to the cases where the cached verdict is provably
byte-identical to what the device would return:

- token bucket only — a leaky bucket refills continuously, so its
  OVER_LIMIT verdict (and reset_time = now + rate) changes every
  millisecond and must never be shed;
- `hits > 0` only — peeks are read-only probes and always reach the
  device (they are also how the GLOBAL broadcast loop peeks status);
- request limit/duration must equal the cached window's params — the
  stores never rewrite an existing window's params (kernels.py
  new_limit/new_duration; oracle keeps the cached resp), so an entry
  created under other params is answered by the device from the STORED
  params and the mismatched request must go see it;
- `now < reset_time` — the first post-reset hit must reach the device
  (it recreates the window there).

Population is device-authoritative: only a device/oracle response with
status == OVER_LIMIT and remaining == 0 whose params echo the request's
inserts an entry; any other response for a cached fingerprint DROPS it
(an under-limit or param-drifted response proves the cached window is
gone — recreated, evicted, or algorithm-switched). Invalidation:

- entries lazily expire at `reset_time`, compared against the same
  unix-ms clock the engines feed their EpochClock (decide converts
  engine-ms responses back with the identical epoch arithmetic, so the
  unix-domain comparison is exactly the device's `g_exp >= now` check);
- a `generation` check against the engine's reset counter
  (core/engine.py reset_generation) clears the whole cache when the
  engine wipes its store (clock jump past the rebase envelope);
- `purge()` is called for every key an UpdatePeerGlobals install or
  update_globals broadcast touches (serve/instance.py), so GLOBAL mode
  cannot serve a stale verdict after an owner-side reset;
- a LEAKY request for a cached fingerprint drops the entry when its
  response is observed (algorithm switch recreates the window).

Accepted staleness (documented, bounded by the original window): an
entry EVICTED from the device store by way pressure, or recreated by
another NODE's algorithm-switch traffic, keeps shedding OVER_LIMIT
until its reset_time — the fail-closed direction for a rate limiter,
and the same over-admission-adjacent envelope the store's eviction
counters already flag.

Thread model: event-loop confined like the rest of the serving tier
(the bridge and instance both consult from the loop); the only
cross-thread reader is the /metrics scrape, which reads plain ints.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    RateLimitResp,
    Status,
    millisecond_now,
    over_limit_resp,
)
from gubernator_tpu.core.algorithms import ALGO_TOKEN, SHEDDABLE_ALGOS

# r15 interplay audit: every consult and populate path below is gated
# on Algorithm.TOKEN_BUCKET because the frozen-verdict fixed point this
# cache serves exists ONLY there — a leaky reset_time refills
# continuously, a sliding blend's weight decays every millisecond, and
# a GCRA TAT drains every millisecond, so none of their OVER verdicts
# is provably current after the response that produced it. This pin
# keeps the registry (core/algorithms.py SHEDDABLE_ALGOS) and this
# module from drifting apart: marking a new algorithm sheddable there
# without teaching lookup/screen_fields/_observe_one its fixed point
# fails at import, not silently in production.
assert SHEDDABLE_ALGOS == {ALGO_TOKEN}, (
    "shed cache only understands the token bucket's frozen verdicts; "
    "extend serve/shedcache.py before marking another algorithm "
    "sheddable in core/algorithms.py"
)

#: default LRU bound (GUBER_SHED_CACHE_KEYS): sized to the hot head a
#: Zipf workload can keep over limit at once, not the whole key space
DEFAULT_KEYS = 1 << 16

#: rough per-entry host footprint (OrderedDict node + uint64 key + the
#: 3-int tuple) used by the boot-time lint, measured on CPython 3.10
ENTRY_BYTES = 200

#: per-call bound on observe_fields' population walk (uncached frozen
#: verdicts); correctness rows (cached fingerprints) are never capped
OBSERVE_INSERT_CAP = 512


def footprint_mib(keys: int) -> float:
    return keys * ENTRY_BYTES / (1 << 20)


def lint_footprint(keys: int, store_capacity: int = 0) -> str:
    """Boot-time sizing lint, the shed-cache sibling of the store
    sizing pass (core/store.check_store_budget): returns a warning
    string ('' = fine). The cache holds only the over-limit head, so a
    bound beyond the store's own entry capacity can never be used."""
    if store_capacity and keys > store_capacity:
        return (
            f"GUBER_SHED_CACHE_KEYS={keys} exceeds the store's entry "
            f"capacity ({store_capacity}); the shed cache mirrors "
            f"store-resident over-limit windows, so the excess "
            f"({footprint_mib(keys - store_capacity):.0f} MiB) can "
            f"never hold a live verdict — lower it"
        )
    if footprint_mib(keys) > 512:
        return (
            f"GUBER_SHED_CACHE_KEYS={keys} ~ {footprint_mib(keys):.0f} "
            f"MiB of host memory for shed verdicts; the cache only "
            f"needs to cover the over-limit HEAD of the key "
            f"distribution, not the key space"
        )
    return ""


class ShedCache:
    """Bounded LRU of frozen token-bucket over-limit verdicts.

    Keys are the uint64 slot-hash fingerprints the device store is
    addressed by (core/hashing.slot_hash_batch) — shared between the
    instance tier (which hashes key strings once per batch anyway) and
    the bridge tier (whose fast frames arrive pre-hashed)."""

    def __init__(
        self,
        capacity: int = DEFAULT_KEYS,
        now_fn=millisecond_now,
        generation_fn=None,
    ):
        self.capacity = max(1, int(capacity))
        self.now_fn = now_fn
        # engine reset counter (backend.shed_generation); None = the
        # backend never wholesale-resets (exact backend)
        self.generation_fn = generation_fn
        self._gen = generation_fn() if generation_fn is not None else 0
        # fingerprint -> (limit, duration, reset_time_unix_ms)
        self._entries: "OrderedDict[int, Tuple[int, int, int]]" = (
            OrderedDict()
        )
        # vectorized-screen snapshot (sorted key/limit/duration/reset
        # arrays), rebuilt lazily after any mutation: the bridge
        # screens thousand-item frames, and per-item dict probes from
        # a Python loop measured ~1.4 ms/frame on a throttled 2-core
        # box — a searchsorted against a sorted snapshot is ~30 us.
        # Under steady over-limit load the entry set barely changes,
        # so rebuilds (O(entries)) are rare.
        self._snap = None
        # monotonic counters (ints: GIL-atomic, scrape reads them raw)
        self.hits = 0
        self.lookups = 0

    # -- lifecycle -----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    def __bool__(self) -> bool:
        # an EMPTY cache must not read as "no cache": len() above would
        # otherwise make `if shed:` silently skip population
        return True

    def refresh_generation(self) -> None:
        """Clear everything when the engine wiped its store (EpochClock
        reset_required -> engine.reset()): every cached verdict pointed
        at state that no longer exists. One int compare per screen."""
        if self.generation_fn is None:
            return
        g = self.generation_fn()
        if g != self._gen:
            self._gen = g
            self._entries.clear()
            self._snap = None

    def purge(self, fingerprints) -> None:
        """Drop entries for these uint64 fingerprints (GLOBAL installs:
        the owner's broadcast replaced the replica, so the cached
        verdict is no longer provably current)."""
        for h in fingerprints:
            if self._entries.pop(int(h), None) is not None:
                self._snap = None

    def purge_all(self) -> None:
        self._entries.clear()
        self._snap = None

    def reset_counters(self) -> None:
        """Zero the hit/lookup counters (entries stay live) — the
        profiler scopes measurement windows with
        /v1/debug/stages?reset=1, and per-window hit rates need the
        same scoping."""
        self.hits = 0
        self.lookups = 0

    def stats(self) -> dict:
        lk = self.lookups
        return dict(
            entries=len(self._entries),
            capacity=self.capacity,
            hits=self.hits,
            lookups=lk,
            hit_rate=round(self.hits / lk, 4) if lk else 0.0,
            generation=self._gen,
        )

    # -- consult -------------------------------------------------------------

    def lookup(
        self, h: int, limit: int, duration: int, now: Optional[int] = None
    ) -> Optional[int]:
        """reset_time for a sheddable verdict, or None. The caller has
        already gated algorithm == TOKEN_BUCKET and hits > 0; this
        checks entry existence, param match, and expiry. A param
        mismatch is a MISS, not a drop — the mismatched request goes to
        the device, and its response drops the entry only if the stored
        window really drifted (observe())."""
        self.lookups += 1
        e = self._entries.get(h)
        if e is None:
            return None
        if now is None:
            now = self.now_fn()
        if now >= e[2]:
            # expired: the first post-reset hit must reach the device
            del self._entries[h]
            self._snap = None
            return None
        if e[0] != limit or e[1] != duration:
            return None
        self._entries.move_to_end(h)
        self.hits += 1
        return e[2]

    def lookup_resp(
        self, h: int, req: RateLimitReq, now: Optional[int] = None
    ) -> Optional[RateLimitResp]:
        """Instance-tier consult: the full shed gate over a request
        object. Returns the verdict response (a fresh object — callers
        stamp metadata) or None."""
        if req.hits <= 0 or req.algorithm != Algorithm.TOKEN_BUCKET:
            return None
        reset = self.lookup(h, req.limit, req.duration, now)
        if reset is None:
            return None
        return over_limit_resp(req.limit, reset)

    def _snapshot(self):
        """(keys_sorted u64, limit i64, duration i64, reset i64) of
        the live entries, rebuilt lazily after mutations — the
        vectorized screen's lookup table."""
        import numpy as np

        snap = self._snap
        if snap is None:
            m = len(self._entries)
            keys = np.fromiter(self._entries.keys(), np.uint64, m)
            vals = np.fromiter(
                (v for e in self._entries.values() for v in e),
                np.int64, 3 * m,
            ).reshape(m, 3)
            order = np.argsort(keys)
            snap = self._snap = (
                keys[order],
                vals[order, 0],
                vals[order, 1],
                vals[order, 2],
            )
        return snap

    def screen_fields(self, fields: Dict, now: Optional[int] = None):
        """Bridge-tier consult over one frame's dense arrays
        (key_hash/hits/limit/duration/algo[/gnp]). Returns None when
        nothing sheds, else (shed_mask bool[n], (status, limit,
        remaining, reset) int64[n] with the shed rows filled; residue
        rows are zero and overwritten by the device results).

        Fully vectorized — one searchsorted against the sorted entry
        snapshot plus elementwise gates — so a thousand-item frame
        screens in tens of microseconds of event-loop time (the
        per-item dict-probe loop this replaced measured ~1.4 ms/frame
        on a throttled 2-core host, which ate the shed's own win).
        Two deliberate approximations vs lookup(): screen hits do not
        refresh LRU recency (entries refresh on insert; with the
        bound sized to the over-limit head that's ample), and expired
        entries are skipped, not deleted (lookup()/observe/insert
        pressure prunes them)."""
        import numpy as np

        if not self._entries:
            return None
        if now is None:
            now = self.now_fn()
        kh = np.asarray(fields["key_hash"], np.uint64)
        keys_s, lim_s, dur_s, reset_s = self._snapshot()
        idx = np.searchsorted(keys_s, kh)
        idx[idx == keys_s.shape[0]] = 0
        found = keys_s[idx] == kh
        eligible = (
            (np.asarray(fields["algo"]) == int(Algorithm.TOKEN_BUCKET))
            & (np.asarray(fields["hits"]) > 0)
        )
        gnp = fields.get("gnp")
        if gnp is not None:
            # replica reads answer from the live replica entry;
            # screening them here would skip the replica-miss
            # local-processing path — leave them to the device
            eligible &= ~np.asarray(gnp, bool)
        limit = np.asarray(fields["limit"], np.int64)
        mask = (
            found
            & eligible
            & (lim_s[idx] == limit)
            & (dur_s[idx] == np.asarray(fields["duration"], np.int64))
            & (now < reset_s[idx])
        )
        shed = int(mask.sum())
        self.lookups += int(eligible.sum())
        self.hits += shed
        if not shed:
            return None
        status = np.where(
            mask, int(Status.OVER_LIMIT), 0
        ).astype(np.int64)
        limit_out = np.where(mask, limit, 0)
        remaining = np.zeros(kh.shape[0], np.int64)
        reset_out = np.where(mask, reset_s[idx], 0)
        return mask, (status, limit_out, remaining, reset_out)

    # -- populate / invalidate ----------------------------------------------

    def seed(
        self,
        h: int,
        limit: int,
        duration: int,
        reset_time: int,
        now: Optional[int] = None,
    ) -> None:
        """Promoter feed (r13, serve/promoter.py): install a frozen
        verdict for a hot key whose PROMOTION just wrote an over-limit
        token window (remaining=0, sticky over, this reset_time) into
        the device store — the cached verdict matches store state by
        construction, the same authority as observing the device's own
        response. Expired seeds are ignored."""
        if now is None:
            now = self.now_fn()
        if now >= reset_time:
            return
        entries = self._entries
        if entries.get(h) != (limit, duration, reset_time):
            self._snap = None
        entries[int(h)] = (int(limit), int(duration), int(reset_time))
        entries.move_to_end(int(h))
        if len(entries) > self.capacity:
            entries.popitem(last=False)

    def _observe_one(
        self,
        h: int,
        req_hits: int,
        req_limit: int,
        req_duration: int,
        req_algo: int,
        r_status: int,
        r_limit: int,
        r_remaining: int,
        r_reset: int,
        now: int,
    ) -> None:
        if req_algo != int(Algorithm.TOKEN_BUCKET):
            # a leaky request recreates a stored token window
            # (algorithm switch, kernels.py mismatch path): whatever we
            # cached for this fingerprint no longer exists
            if self._entries.pop(h, None) is not None:
                self._snap = None
            return
        frozen = (
            r_status == int(Status.OVER_LIMIT) and r_remaining == 0
        )
        if frozen and r_limit == req_limit and now < r_reset:
            # the frozen fixed point: stored remaining is 0 and sticky,
            # and every same-param hit until r_reset echoes this exact
            # response (module docstring)
            entries = self._entries
            if entries.get(h) != (req_limit, req_duration, r_reset):
                self._snap = None
            entries[h] = (req_limit, req_duration, r_reset)
            entries.move_to_end(h)
            if len(entries) > self.capacity:
                entries.popitem(last=False)
            return
        e = self._entries.get(h)
        if e is None:
            return
        if frozen and r_limit == e[0] and r_reset == e[2]:
            # the response ECHOES the cached window (the device answers
            # an existing window's hits with the STORED limit, so a
            # param-mismatched request confirms the entry rather than
            # disproving it — dropping here would let mixed-param
            # traffic thrash the cache on exactly the hottest keys)
            return
        # a response that contradicts the cached window (under limit,
        # different stored params, different reset) proves it is gone —
        # reset, evicted, or rewritten
        del self._entries[h]
        self._snap = None

    def observe_resps(
        self,
        fingerprints: Sequence[int],
        reqs: Sequence[RateLimitReq],
        resps: Sequence[RateLimitResp],
        now: Optional[int] = None,
    ) -> None:
        """Object-path population (instance tier): one device/owner
        response per request. Error and degraded responses are skipped
        entirely — they carry no authoritative window state."""
        if now is None:
            now = self.now_fn()
        for h, r, resp in zip(fingerprints, reqs, resps):
            if resp.error or resp.metadata.get("degraded"):
                continue
            self._observe_one(
                int(h), r.hits, r.limit, r.duration, int(r.algorithm),
                int(resp.status), resp.limit, resp.remaining,
                resp.reset_time, now,
            )

    def observe_fields(
        self, fields: Dict, results, now: Optional[int] = None
    ) -> None:
        """Array-path population (bridge tier): `results` is the
        (status, limit, remaining, reset) tuple the batcher resolved
        for exactly these `fields` rows. The walk is bounded: every
        row touching a CACHED fingerprint is visited (confirm / drop /
        leaky pop — the correctness rows, pre-filtered with one
        vectorized snapshot membership test), while frozen-verdict
        rows for UNCACHED fingerprints — pure population — are capped
        at OBSERVE_INSERT_CAP per call, so an over-limit-heavy frame
        whose key cardinality exceeds the cache bound cannot drag a
        ~1 ms/frame Python walk into steady state (the cost the
        vectorized screen exists to avoid)."""
        import numpy as np

        status, limit_r, remaining, reset = results
        sa = np.asarray(status)
        ra = np.asarray(remaining)
        frozen = (sa == int(Status.OVER_LIMIT)) & (ra == 0)
        kh = np.asarray(fields["key_hash"], np.uint64)
        if self._entries:
            keys_s = self._snapshot()[0]
            pos = np.searchsorted(keys_s, kh)
            pos[pos == keys_s.shape[0]] = 0
            cached = keys_s[pos] == kh
        else:
            cached = np.zeros(kh.shape[0], bool)
        must = np.flatnonzero(cached)
        ins = np.flatnonzero(frozen & ~cached)
        if ins.shape[0] > OBSERVE_INSERT_CAP:
            ins = ins[:OBSERVE_INSERT_CAP]
        if not must.shape[0] and not ins.shape[0]:
            return
        # a key's rows all land on one side of the cached split, and
        # flatnonzero keeps row order within each side, so last-wins
        # semantics per key survive the concat
        if now is None:
            now = self.now_fn()
        hits = fields["hits"]
        limit = fields["limit"]
        duration = fields["duration"]
        algo = fields.get("algo")
        limit_a = np.asarray(limit_r)
        reset_a = np.asarray(reset)
        token = int(Algorithm.TOKEN_BUCKET)
        for i in np.concatenate([must, ins]).tolist():
            self._observe_one(
                int(kh[i]), int(hits[i]), int(limit[i]),
                int(duration[i]),
                int(algo[i]) if algo is not None else token,
                int(sa[i]), int(limit_a[i]), int(ra[i]),
                int(reset_a[i]), now,
            )
