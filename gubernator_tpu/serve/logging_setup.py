"""Logging setup: leveled, optionally JSON-formatted, category-tagged.

The reference logs through logrus with a `category` field per subsystem
and a JSON-(un)marshallable level knob (reference logging/logging.go:25-54,
gubernator.go:54). Here: stdlib logging with logger names as the category,
a JSON formatter for machine-shipped logs, and level parsing that accepts
the same spellings logrus does ("panic" through "trace").
"""

from __future__ import annotations

import json
import logging
import sys
import time

# logrus level names (logging/logging.go) -> stdlib levels
_LEVELS = {
    "panic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
    "error": logging.ERROR,
    "warning": logging.WARNING,
    "warn": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
    "trace": logging.DEBUG,
}


def parse_level(name: str) -> int:
    """Parse a log level name; raises ValueError on unknown (the unmarshal
    contract of reference logging/logging.go:37-53)."""
    try:
        return _LEVELS[name.strip().lower()]
    except KeyError:
        raise ValueError(f"unknown log level {name!r}") from None


class JsonFormatter(logging.Formatter):
    """One JSON object per line: time, level, category, message."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S%z", time.localtime(record.created)
            ),
            "level": record.levelname.lower(),
            "category": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry)


def setup_logging(level: str = "info", json_format: bool = False) -> None:
    """Configure the root logger for the daemon."""
    handler = logging.StreamHandler(sys.stderr)
    if json_format:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(name)s %(levelname)s %(message)s"
            )
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(parse_level(level))
