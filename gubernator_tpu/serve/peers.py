"""Peer routing: consistent-hash ownership + batching peer RPC client.

The host-level ring is wire- and placement-compatible with the reference
(crc32 point per peer, sorted ring, binary-search successor with
wraparound — reference hash.go:62-96), so a mixed cluster of reference
nodes and gubernator-tpu nodes would agree on key ownership. Within one
host, keys further shard across TPU chips (parallel/sharded.py); this ring
only decides which *host* coordinates a key.

PeerClient mirrors the reference's forwarding semantics (peers.go):
BATCHING/GLOBAL requests coalesce into micro-batches flushed every
`batch_wait` or at `batch_limit`; NO_BATCHING goes out as a direct unary
call. Implemented on asyncio instead of goroutines+channels: one flusher
task per peer, futures instead of response channels.
"""

from __future__ import annotations

import asyncio
import bisect
import logging
import random
from typing import Dict, List, Optional, Sequence, Tuple

import grpc

from gubernator_tpu.api import convert
from gubernator_tpu.api.grpc_glue import PeersV1Stub
from gubernator_tpu.api.proto.gen import peers_pb2
from gubernator_tpu.api.types import Behavior, RateLimitReq, RateLimitResp
from gubernator_tpu.core.hashing import ring_hash
from gubernator_tpu.serve import metrics, tracing
from gubernator_tpu.serve.aio import collect_batch
from gubernator_tpu.serve.breaker import (
    OPEN as BREAKER_OPEN,
    BreakerOpenError,
    CircuitBreaker,
)
from gubernator_tpu.serve.config import BehaviorConfig
from gubernator_tpu.serve.faults import FAULTS, FaultError

log = logging.getLogger("gubernator_tpu.peers")


def is_retryable(exc: BaseException, all_peek: bool = False) -> bool:
    """Safe-to-resend classification for the peer retry policy.

    `all_peek=True` (every request in the batch carries hits=0) makes
    ANY failure retryable — re-running a peek is free. Otherwise only
    failures where the request never reached the peer's application
    layer qualify: gRPC UNAVAILABLE (connection refused / reset before
    dispatch), plain connection errors, and injected faults flagged
    retryable. DEADLINE_EXCEEDED and application errors are NOT safe —
    the peer may have already applied the hits, and a rate limiter that
    double-counts under partial failure is worse than one that errors.
    """
    if all_peek:
        return True
    if isinstance(exc, FaultError):
        return exc.retryable
    if isinstance(exc, ConnectionError):
        return True
    if isinstance(exc, grpc.RpcError):
        code = getattr(exc, "code", None)
        try:
            return callable(code) and code() == grpc.StatusCode.UNAVAILABLE
        except Exception:
            return False
    return False


class PeerClient:
    """Connection to one peer (possibly this server itself)."""

    def __init__(
        self,
        conf: BehaviorConfig,
        host: str,
        is_owner: bool = False,
        mesh_local: bool = False,
    ):
        self.conf = conf
        self.host = host
        self.is_owner = is_owner  # true when this peer is this server
        # true when this peer's replica state rides THIS node's mesh
        # (PeerInfo.mesh_local): broadcast installs for it short-circuit
        # to one local mesh install (r21, global_mgr._update_peers)
        self.mesh_local = mesh_local
        self.channel: Optional[grpc.aio.Channel] = None
        self.stub: Optional[PeersV1Stub] = None
        # queue items are GROUPS: (reqs list, future resolving to the
        # matching resps list). One future per group (r7 owner
        # batching): a request batch forwarding hundreds of items to
        # one owner costs one enqueue + one future, not one per item.
        self._queue: "asyncio.Queue[Tuple[List[RateLimitReq], asyncio.Future]]" = (  # noqa: E501
            asyncio.Queue()
        )
        # one-slot park for a group that would overflow the previous
        # batch (aio.collect_batch carry contract)
        self._carry: List = []
        self._flusher: Optional[asyncio.Task] = None
        self._closed = False
        # per-peer circuit breaker (r8): failures on THIS peer's RPCs
        # trip it; while open every call fails fast (BreakerOpenError)
        # instead of paying a deadline. State survives set_peers churn
        # because existing clients are reused there.
        self.breaker = self._make_breaker()

    def _make_breaker(self) -> Optional[CircuitBreaker]:
        c = self.conf
        if getattr(c, "breaker_failures", 0) <= 0:
            return None  # GUBER_BREAKER_FAILURES=0 disables

        def on_transition(frm: str, to: str) -> None:
            from gubernator_tpu.serve.breaker import STATE_CODES

            log.warning(
                "peer '%s' circuit breaker: %s -> %s", self.host, frm, to
            )
            try:
                metrics.PEER_BREAKER_TRANSITIONS.labels(
                    peer=self.host, to=to
                ).inc()
                metrics.PEER_BREAKER_STATE.labels(peer=self.host).set(
                    STATE_CODES[to]
                )
            except Exception:  # pragma: no cover - defensive
                pass

        return CircuitBreaker(
            failures=c.breaker_failures,
            ratio=c.breaker_ratio,
            window=c.breaker_window,
            cooldown=c.breaker_cooldown,
            probes=c.breaker_probes,
            on_transition=on_transition,
        )

    def connect(self) -> None:
        self._closed = False  # (re)opening
        if self.channel is None:
            # grpc.aio dials lazily and accepts any string, so validate
            # the target's SYNTAX eagerly. This mirrors the reference,
            # whose non-blocking grpc.Dial also only fails fast on
            # unparsable targets (gubernator.go:260-291): health reports
            # unhealthy for malformed peers, while well-formed but
            # unreachable ones surface at request time, as there.
            host, _, port = self.host.rpartition(":")
            if not host or not port.isdigit() or not (
                0 < int(port) < 65536
            ):
                raise ValueError(f"invalid peer address {self.host!r}")
            self.channel = grpc.aio.insecure_channel(
                self.host,
                options=[
                    # bound gRPC's reconnect backoff to the breaker
                    # cooldown: during an outage the channel's redial
                    # backoff grows (default cap 120s!), so without
                    # this the half-open probe after a peer RETURNS
                    # fails against a still-backed-off channel and
                    # recovery stretches far past the breaker's
                    # contract (measured 4s vs the 2-cooldown bound in
                    # the chaos soak)
                    ("grpc.initial_reconnect_backoff_ms", 100),
                    (
                        "grpc.max_reconnect_backoff_ms",
                        max(
                            200,
                            int(
                                getattr(
                                    self.conf, "breaker_cooldown", 1.0
                                )
                                * 1000
                            ),
                        ),
                    ),
                ],
            )
            self.stub = PeersV1Stub(self.channel)
        if self._flusher is None:
            self._flusher = asyncio.ensure_future(self._run())

    async def close(self) -> None:
        # before cancelling the flusher: an enqueue AFTER its cancel-time
        # queue drain would land in a queue nothing reads — the flag makes
        # late forwards (a caller holding this peer across set_peers)
        # fail fast instead
        self._closed = True
        if self._flusher is not None:
            self._flusher.cancel()
            try:
                await self._flusher
            except asyncio.CancelledError:
                pass
            self._flusher = None
        if self.channel is not None:
            await self.channel.close()
            self.channel = None

    # -- forwarding ---------------------------------------------------------

    async def get_peer_rate_limit(self, r: RateLimitReq) -> RateLimitResp:
        """Forward one request; batches unless NO_BATCHING
        (reference peers.go:73-90)."""
        if r.behavior in (Behavior.BATCHING, Behavior.GLOBAL):
            resps = await self.get_peer_rate_limits_grouped([r])
            return resps[0]
        if self._closed:
            raise RuntimeError(
                f"peer client for '{self.host}' is closed"
            )
        resp = await self.get_peer_rate_limits([r])
        return resp[0]

    async def get_peer_rate_limits_grouped(
        self, reqs: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        """Forward a whole group through the micro-batch flusher with
        ONE queue entry and ONE future (r7 owner batching). The group
        still coalesces with other callers' groups up to batch_limit
        — same wire behavior as per-item enqueueing, a fraction of the
        event-loop cost."""
        if self._closed:
            raise RuntimeError(
                f"peer client for '{self.host}' is closed"
            )
        if not reqs:
            return []
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        # the caller's trace context rides the queue entry (r16): the
        # flusher task that sends the batched RPC runs outside the
        # caller's context, so the traceparent must be captured HERE —
        # one branch, None for unsampled/untraced callers
        self._queue.put_nowait(
            (list(reqs), fut, tracing.propagation_header())
        )
        return await fut

    async def get_peer_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        traceparent: Optional[str] = None,
    ) -> List[RateLimitResp]:
        pb_req = peers_pb2.GetPeerRateLimitsReq(
            requests=[convert.req_to_pb(r) for r in reqs]
        )
        timeout = self.conf.effective_peer_timeout()
        if traceparent is None:
            # direct callers (NO_BATCHING forwards, GLOBAL gossip) run
            # in their own context; batched callers pass the captured
            # header through _send_batch
            traceparent = tracing.propagation_header()
        # kwargs-style so the metadata key is ABSENT on untraced calls:
        # test fakes (and any stub-shaped embedder hook) predating r16
        # keep working untraced
        kw = (
            {"metadata": ((tracing.TRACEPARENT, traceparent),)}
            if traceparent
            else {}
        )

        async def call() -> List[RateLimitResp]:
            pb_resp = await self.stub.GetPeerRateLimits(
                pb_req, timeout=timeout or None, **kw
            )
            if len(pb_resp.rate_limits) != len(reqs):
                raise RuntimeError(
                    "peer responded with mismatched rate limit list size"
                )
            return [convert.resp_from_pb(p) for p in pb_resp.rate_limits]

        # a batch of pure peeks (hits all 0) is idempotent end to end;
        # anything carrying hits only retries transport-level failures
        # (is_retryable) so a slow peer is never double-counted
        return await self._call_resilient(
            call, idempotent=all(r.hits == 0 for r in reqs),
            timeout=timeout,
        )

    async def update_peer_globals(self, updates) -> None:
        """updates: sequence of (key, RateLimitResp). Installing a
        status replica is last-write-wins idempotent, so retries are
        always safe here."""
        pb_req = peers_pb2.UpdatePeerGlobalsReq(
            globals=[
                peers_pb2.UpdatePeerGlobal(
                    key=k, status=convert.resp_to_pb(s)
                )
                for k, s in updates
            ]
        )
        timeout = self.conf.global_timeout
        # originating context rides along when the install happens
        # inside a traced request (r16); the background gossip loops
        # have no context and send bare metadata
        tp = tracing.propagation_header()
        kw = {"metadata": ((tracing.TRACEPARENT, tp),)} if tp else {}

        async def call() -> None:
            await self.stub.UpdatePeerGlobals(
                pb_req, timeout=timeout or None, **kw
            )

        await self._call_resilient(call, idempotent=True, timeout=timeout)

    async def replicate_buckets(self, snaps, owner: str) -> None:
        """Ship owned-bucket snapshots to this peer (the key's ring
        successor, or — for a reconcile handback — its returned owner).
        `snaps`: sequence of serve/replication.Snapshot. Installs are
        last-write-wins by (reset_time, snapshot_ms), so retries and
        duplicate deliveries are always safe."""
        pb_req = peers_pb2.ReplicateBucketsReq(
            owner=owner,
            buckets=[
                peers_pb2.BucketSnapshot(
                    key=s.key,
                    algorithm=s.algorithm,
                    limit=s.limit,
                    duration=s.duration,
                    remaining=s.remaining,
                    reset_time=s.reset_time,
                    status=s.status,
                    snapshot_ms=s.snapshot_ms,
                )
                for s in snaps
            ],
        )
        timeout = self.conf.global_timeout
        tp = tracing.propagation_header()
        kw = {"metadata": ((tracing.TRACEPARENT, tp),)} if tp else {}

        async def call() -> None:
            await self.stub.ReplicateBuckets(
                pb_req, timeout=timeout or None, **kw
            )

        await self._call_resilient(call, idempotent=True, timeout=timeout)

    # -- resilience envelope (r8) -------------------------------------------

    async def _call_resilient(
        self, do_call, idempotent: bool, timeout: float
    ):
        """Deadline + circuit breaker + bounded retry around one peer
        RPC. The deadline wraps fault injection AND the RPC, so an
        injected hang (GUBER_FAULT_SPEC peer_rpc:hang) is bounded
        exactly like a wedged peer. Retries use exponential backoff
        with FULL jitter; only is_retryable failures re-send."""
        c = self.conf
        attempt = 0
        while True:
            b = self.breaker
            token = b.acquire() if b is not None else None
            if b is not None and not token:
                raise BreakerOpenError(
                    f"peer '{self.host}' circuit open (failing fast)"
                )
            try:
                result = await asyncio.wait_for(
                    self._guarded(do_call), timeout or None
                )
            except asyncio.CancelledError:
                # teardown, not peer health: release a half-open probe
                # slot without counting an outcome
                if b is not None:
                    b.record_cancel(token)
                raise
            except Exception as e:
                if b is not None:
                    b.record_failure(token)
                retries = getattr(c, "peer_retries", 0)
                if (
                    attempt < retries
                    and is_retryable(e, idempotent)
                    # when THIS failure tripped the breaker, don't
                    # sleep a backoff only to raise BreakerOpenError
                    # on re-acquire: fail fast with the root-cause
                    # error instead
                    and (b is None or b.state != BREAKER_OPEN)
                ):
                    attempt += 1
                    try:
                        metrics.PEER_RPC_RETRIES.labels(
                            peer=self.host
                        ).inc()
                    except Exception:  # pragma: no cover - defensive
                        pass
                    await asyncio.sleep(
                        random.uniform(
                            0.0,
                            min(
                                c.peer_backoff_max,
                                c.peer_backoff * (2 ** (attempt - 1)),
                            ),
                        )
                    )
                    continue
                raise
            if b is not None:
                b.record_success(token)
            return result

    async def _guarded(self, do_call):
        if FAULTS.enabled:
            await FAULTS.inject("peer_rpc", peer=self.host)
        return await do_call()

    # -- micro-batch flusher ------------------------------------------------

    async def _run(self) -> None:
        """Coalesce queued requests; flush at batch_limit or after
        batch_wait from the first enqueue (reference peers.go:143-172).
        Everything already enqueued is drained without waiting, so batches
        grow with in-flight RPC load while a lone request only waits the
        configured window (batch_wait=0 disables even that)."""
        while True:
            batch: List[Tuple[List[RateLimitReq], asyncio.Future]] = []
            try:
                await collect_batch(
                    self._queue,
                    self.conf.batch_limit,
                    self.conf.batch_wait,
                    batch,
                    weight=lambda g: max(1, len(g[0])),
                    carry=self._carry,
                )
                await self._send_batch(batch)
            except asyncio.CancelledError:
                # close() (e.g. set_peers replacing this peer) mid-collect
                # or mid-send: every caller parked on a queued future gets
                # an error, never a hang
                exc = RuntimeError(
                    f"peer client for '{self.host}' closed mid-batch"
                )
                for _, fut, _tp in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                for _, fut, _tp in self._carry:
                    if not fut.done():
                        fut.set_exception(exc)
                self._carry.clear()
                while True:
                    try:
                        _, fut, _tp = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if not fut.done():
                        fut.set_exception(exc)
                raise

    async def _send_batch(self, batch) -> None:
        # groups flatten into one peer RPC; responses slice back per
        # group (reference peers.go:143-172, group-granular here)
        reqs = [r for g, _, _tp in batch for r in g]
        # one traceparent per RPC: micro-batching can coalesce groups
        # from different traced callers, so the FIRST traced group's
        # context represents the wire hop (documented scope limit —
        # head sampling makes same-flush collisions rare)
        tp = next((g[2] for g in batch if g[2]), None)
        try:
            resps = await self.get_peer_rate_limits(reqs, traceparent=tp)
        except Exception as e:  # entire batch failed (peers.go:186-192)
            for _, fut, _tp in batch:
                if not fut.done():
                    fut.set_exception(
                        RuntimeError(f"while fetching from peer - '{e}'")
                    )
            return
        k = 0
        for g, fut, _tp in batch:
            span = resps[k : k + len(g)]
            k += len(g)
            if not fut.done():
                fut.set_result(span)


class ConsistentHashPicker:
    """Ring-placement-compatible peer picker (reference hash.go)."""

    def __init__(self, hash_fn=ring_hash):
        self._hash = hash_fn
        self._keys: List[int] = []
        self._by_point: Dict[int, PeerClient] = {}
        self._by_host: Dict[str, PeerClient] = {}

    def new(self) -> "ConsistentHashPicker":
        return ConsistentHashPicker(self._hash)

    def add(self, peer: PeerClient) -> None:
        point = self._hash(peer.host)
        existing = self._by_point.get(point)
        if existing is not None and existing.host != peer.host:
            # Two addresses colliding on one crc32 point (~2^-32 per
            # pair) would silently split ownership: this picker's
            # dict-overwrite (last add wins) disagrees with the edge's
            # sort-order tie-break, and the membership fingerprint
            # cannot catch it (same host set). Refuse loudly; set_peers
            # surfaces it through health (ADVICE r5 #3).
            raise ValueError(
                f"ring point collision: '{peer.host}' and "
                f"'{existing.host}' both hash to {point:#x}; rename one "
                f"peer address (placement would silently diverge "
                f"between pickers)"
            )
        if existing is None:
            bisect.insort(self._keys, point)
        self._by_point[point] = peer
        self._by_host[peer.host] = peer

    def size(self) -> int:
        return len(self._keys)

    def peers(self) -> List[PeerClient]:
        return list(self._by_host.values())

    def get_peer_by_host(self, host: str) -> Optional[PeerClient]:
        return self._by_host.get(host)

    def get(self, key: str) -> PeerClient:
        """Successor peer on the ring for this key's point, wrapping
        (reference hash.go:80-96)."""
        if not self._keys:
            raise RuntimeError("unable to pick a peer; pool is empty")
        point = self._hash(key)
        i = bisect.bisect_left(self._keys, point)
        if i == len(self._keys):
            i = 0
        return self._by_point[self._keys[i]]

    def get_successor(self, key: str) -> Optional[PeerClient]:
        """The peer that would own `key` if its current owner left the
        ring: the next ring point after the key's, skipping points
        belonging to the owner itself (wraparound like get()). This is
        where the consistent hash routes the key on owner removal, so
        it is both the replication target (serve/replication.py) and
        the takeover route when the owner's breaker opens. None when
        the ring has fewer than two distinct hosts."""
        if not self._keys:
            return None
        point = self._hash(key)
        i = bisect.bisect_left(self._keys, point)
        if i == len(self._keys):
            i = 0
        owner = self._by_point[self._keys[i]]
        n = len(self._keys)
        for step in range(1, n):
            peer = self._by_point[self._keys[(i + step) % n]]
            if peer.host != owner.host:
                return peer
        return None

    def ownership_diff(
        self, new: "ConsistentHashPicker", keys: Sequence[str]
    ) -> Dict[str, Tuple["PeerClient", List[str]]]:
        """Keys THIS ring routes to this node (is_owner) that `new`
        routes to a DIFFERENT host, grouped by their new owner:
        {new_owner_host: (new_owner_client, [keys])}. This is the
        planned-handoff work list on a ring change (serve/rescale.py):
        call on the OLD picker with the new picker and the keys this
        node holds live windows for. Keys the old ring did not route
        here, and keys still owned here under `new`, contribute
        nothing; an empty old ring (never populated) diffs to nothing
        rather than raising."""
        out: Dict[str, Tuple[PeerClient, List[str]]] = {}
        if not self._keys or not new._keys:
            return out
        for key in keys:
            if not self.get(key).is_owner:
                continue
            owner = new.get(key)
            if owner.is_owner:
                continue
            entry = out.get(owner.host)
            if entry is None:
                out[owner.host] = (owner, [key])
            else:
                entry[1].append(key)
        return out

    def self_owned_mask(self, keys: Sequence[str]):
        """bool[len(keys)]: the key's ring successor is this server
        itself (is_owner). Vectorized ownership screen for the edge
        bridge's string->array fold (r7): one hash call per key plus a
        single searchsorted against the ring, instead of a get() with
        its dict lookups per key. Placement parity with get():
        bisect_left == searchsorted side='left', wraparound to 0."""
        import numpy as np

        if not self._keys:
            raise RuntimeError("unable to pick a peer; pool is empty")
        pts = np.fromiter(
            (self._hash(k) for k in keys), dtype=np.uint64, count=len(keys)
        )
        ring = np.asarray(self._keys, dtype=np.uint64)
        idx = np.searchsorted(ring, pts, side="left")
        idx[idx == len(ring)] = 0
        own = np.fromiter(
            (self._by_point[p].is_owner for p in self._keys),
            dtype=bool,
            count=len(self._keys),
        )
        return own[idx]
