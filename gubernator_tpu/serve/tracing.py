"""End-to-end distributed tracing + in-process flight recorder (r16).

The r7 stage clock says where the AVERAGE frame's wall time goes;
Prometheus says how slow the average RPC was. Neither can answer "why
was THIS request slow, and on which hop" — the per-request question
PAPERS.md's scalable-rate-limiting survey names as the operational
prerequisite for running distributed limiters at fleet scale. This
module is that layer:

- **TraceContext**: W3C-trace-context-shaped identity (128-bit trace
  id, 64-bit span id, sampled flag), carried as a `traceparent` header
  string over the HTTP doors, as gRPC metadata on V1/PeersV1 (peer
  forwards, `UpdatePeerGlobals`, `ReplicateBuckets`), and as a binary
  extension on windowed GEB frames (GEBT framing behind the
  HELLO_TRACE capability bit, serve/edge_bridge.py). Fast 33-byte
  records stay trace-free by design — those frames are head-sampled
  bridge-side instead.

- **Trace**: one request's span list, filled from three sources with
  ONE branch per site and no second clock: (a) the existing stage
  clock — `StageStats.add` forwards its span into the active trace
  when one is set (serve/stages.py), so bridge_decode / shed /
  instance_route / encode timings are the same numbers the stage
  profile reports; (b) the device batcher, whose queue marks carry the
  caller's trace so batch_queue and device spans land with batch
  size / ladder rung / algorithm-mix annotations even though the
  flusher runs outside the caller's context; (c) explicit hop spans
  (peer_forward) at the instance tier.

- **Tracer + FlightRecorder**: per-instance (so a LocalCluster's nodes
  keep separate recorders). Head sampling admits a request with
  probability `GUBER_TRACE_SAMPLE` (default 0 = off). Tail capture
  (`GUBER_TRACE_SLOW_MS` > 0) arms span collection for EVERY request
  but only RETAINS completed traces slower than
  max(GUBER_TRACE_SLOW_MS, rolling p99 of recent requests) — the
  "always keep the outliers" half head sampling cannot give. Retained
  traces land in a bounded ring served as JSON at /v1/debug/traces
  (serve/server.py), with counters exported lazily at /metrics scrape.

Cost contract: with sampling AND tail capture off, every hot-path site
pays exactly one `ContextVar.get` / attribute check and no trace ids
are ever generated; ids are generated lazily even for armed traces
(first propagation or retention), so a tail-armed request that
finishes fast allocates a Trace and its span tuples, nothing else.
Pinned by the perf-gate `trace_r16` pair and the tracing differential
fuzz (decisions byte-identical ON vs OFF). Stdlib-only on purpose:
the JAX-free client tier (client_geb.py) imports this module too.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from typing import Dict, List, Optional

#: the active request's Trace (or None); set at the door, read by the
#: stage clock, the batcher's enqueue, and the peer client
_CURRENT: "contextvars.ContextVar[Optional[Trace]]" = contextvars.ContextVar(
    "guber_trace", default=None
)

TRACEPARENT = "traceparent"

#: rolling window of recent request durations backing the tail-capture
#: threshold; 512 keeps the p99 meaningful while staying O(KiB)
_WINDOW = 512
#: recompute the rolling p99 every this many finished requests — the
#: hot path never sorts
_P99_EVERY = 64


def _gen_trace_id() -> int:
    return random.getrandbits(128) or 1


def _gen_span_id() -> int:
    return random.getrandbits(64) or 1


class TraceContext:
    """One hop's identity triple, in W3C traceparent shape."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: int, span_id: int, sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def header(self) -> str:
        return "00-%032x-%016x-%02x" % (
            self.trace_id & ((1 << 128) - 1),
            self.span_id & ((1 << 64) - 1),
            1 if self.sampled else 0,
        )

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TraceContext({self.header()})"


def parse_traceparent(value) -> Optional[TraceContext]:
    """Parse a traceparent header; None on anything malformed (a bad
    header from an untrusted client must degrade to 'untraced', never
    to an error)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    ver, tid, sid, flags = parts
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 or len(flags) != 2:
        return None
    try:
        trace_id = int(tid, 16)
        span_id = int(sid, 16)
        fl = int(flags, 16)
        int(ver, 16)
    except ValueError:
        return None
    if trace_id == 0 or span_id == 0:
        return None
    return TraceContext(trace_id, span_id, bool(fl & 1))


class Trace:
    """One request's span collection. Span adds are lock-guarded: the
    device batcher resolves futures from fetch-pool threads while the
    serving loop records door-side stages."""

    __slots__ = (
        "door",
        "sampled",
        "t0",
        "start_unix_ms",
        "_trace_id",
        "_span_id",
        "parent_span_id",
        "_spans",
        "_ann",
        "_lock",
    )

    def __init__(
        self,
        door: str,
        sampled: bool,
        remote: Optional[TraceContext] = None,
    ):
        self.door = door
        self.sampled = sampled
        self.t0 = time.monotonic()
        self.start_unix_ms = int(time.time() * 1000)
        # ids are LAZY: generated on first propagation or retention, so
        # an armed-but-fast-and-unsampled request never pays them
        self._trace_id = remote.trace_id if remote is not None else None
        self._span_id: Optional[int] = None
        self.parent_span_id = (
            remote.span_id if remote is not None else None
        )
        self._spans: List[tuple] = []
        self._ann: Dict[str, object] = {}
        self._lock = threading.Lock()

    @property
    def trace_id(self) -> int:
        if self._trace_id is None:
            self._trace_id = _gen_trace_id()
        return self._trace_id

    @property
    def span_id(self) -> int:
        if self._span_id is None:
            self._span_id = _gen_span_id()
        return self._span_id

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, self.sampled)

    def header(self) -> Optional[str]:
        """Propagation header — only SAMPLED traces cross process
        boundaries (a tail-armed trace cannot know it will be slow, so
        it stays local; the remote hop has its own tail capture)."""
        if not self.sampled:
            return None
        return self.context().header()

    def add_span(
        self,
        name: str,
        start: Optional[float] = None,
        end: Optional[float] = None,
        duration_s: Optional[float] = None,
        **annotations,
    ) -> None:
        """Record one span. Times are time.monotonic seconds; pass
        either (start[, end]) or duration_s (stage-clock style: the
        span just ended and lasted duration_s)."""
        now = time.monotonic()
        if duration_s is not None:
            end = now if end is None else end
            start = end - max(0.0, duration_s)
        elif start is None:
            start = now
        if end is None:
            end = now
        with self._lock:
            self._spans.append(
                (name, start, end, annotations or None)
            )

    def annotate(self, **kv) -> None:
        with self._lock:
            self._ann.update(kv)

    def freeze(self, duration_s: float, tail: bool) -> dict:
        """Serialize for the recorder (called once, at retention).
        Span times become millisecond offsets from the trace start."""
        with self._lock:
            spans = [
                {
                    "name": name,
                    "start_ms": round((s - self.t0) * 1e3, 3),
                    "duration_ms": round((e - s) * 1e3, 3),
                    **({"annotations": ann} if ann else {}),
                }
                for name, s, e, ann in self._spans
            ]
            ann = dict(self._ann)
        doc = {
            "trace_id": "%032x" % self.trace_id,
            "span_id": "%016x" % self.span_id,
            "door": self.door,
            "sampled": self.sampled,
            "tail": tail,
            "start_unix_ms": self.start_unix_ms,
            "duration_ms": round(duration_s * 1e3, 3),
            "spans": spans,
        }
        if self.parent_span_id is not None:
            doc["parent_span_id"] = "%016x" % self.parent_span_id
        if ann:
            doc["annotations"] = ann
        return doc


class FlightRecorder:
    """Bounded in-process ring of completed traces + plain-int counters
    (exported lazily at /metrics scrape, the shed_entries pattern)."""

    def __init__(self, capacity: int = 256, slow_ms: float = 0.0):
        self.capacity = max(1, int(capacity))
        self.slow_ms = float(slow_ms)
        self._lock = threading.Lock()
        self._traces: List[dict] = []
        # counters: plain ints under the lock — the hot path (record)
        # already holds it, the scrape reads without caring about a
        # torn read of a monotonic int
        self.started = 0  # traces begun (sampled or tail-armed)
        self.sampled = 0  # head-sampled at a door
        self.recorded = 0  # retained in the ring
        self.tail_captured = 0  # retained by the slow-threshold rule
        self.dropped = 0  # evicted from the ring (capacity)
        # rolling duration window for the p99 threshold
        self._durs: List[float] = []
        self._dur_i = 0
        self._since_p99 = 0
        self._p99_ms = 0.0

    def threshold_ms(self) -> float:
        """Tail-capture retention threshold: the knob is the FLOOR, the
        rolling p99 lifts it under load so the recorder keeps outliers
        relative to current behavior, not a stale absolute."""
        return max(self.slow_ms, self._p99_ms)

    def observe(self, trace: Trace, duration_s: float) -> None:
        """One finished trace: decide retention, update the rolling
        window."""
        dur_ms = duration_s * 1e3
        tail = False
        with self._lock:
            if self.slow_ms > 0:
                if len(self._durs) < _WINDOW:
                    self._durs.append(dur_ms)
                else:
                    self._durs[self._dur_i] = dur_ms
                    self._dur_i = (self._dur_i + 1) % _WINDOW
                self._since_p99 += 1
                if self._since_p99 >= _P99_EVERY:
                    self._since_p99 = 0
                    s = sorted(self._durs)
                    self._p99_ms = s[max(0, int(len(s) * 0.99) - 1)]
                tail = not trace.sampled and dur_ms >= self.threshold_ms()
            if not (trace.sampled or tail):
                return
        # freeze outside the recorder lock (it takes the trace's own)
        doc = trace.freeze(duration_s, tail)
        with self._lock:
            self.recorded += 1
            if tail:
                self.tail_captured += 1
            self._traces.append(doc)
            if len(self._traces) > self.capacity:
                del self._traces[0]
                self.dropped += 1

    def get(self, trace_id_hex: str) -> Optional[dict]:
        tid = trace_id_hex.lower().lstrip("0") or "0"
        with self._lock:
            for doc in reversed(self._traces):
                if doc["trace_id"].lstrip("0") == tid:
                    return doc
        return None

    def snapshot(self, limit: int = 64) -> dict:
        with self._lock:
            # limit<=0 means "counters only": [-0:] would slice the
            # WHOLE ring, so branch explicitly
            traces = list(self._traces[-limit:]) if limit > 0 else []
            return {
                "traces": traces,
                "count": len(self._traces),
                "capacity": self.capacity,
                "slow_threshold_ms": round(self.threshold_ms(), 3),
                "counters": self.counters(),
            }

    def counters(self) -> dict:
        return {
            "started": self.started,
            "sampled": self.sampled,
            "recorded": self.recorded,
            "tail_captured": self.tail_captured,
            "dropped": self.dropped,
        }

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self.started = self.sampled = self.recorded = 0
            self.tail_captured = self.dropped = 0
            self._durs = []
            self._dur_i = self._since_p99 = 0
            self._p99_ms = 0.0


class Tracer:
    """Per-instance sampling policy + recorder. `sample` and `slow_ms`
    are plain attributes so the perf gate (and an operator with a
    debugger) can flip them on a live process."""

    def __init__(
        self,
        sample: float = 0.0,
        slow_ms: float = 0.0,
        capacity: int = 256,
    ):
        self.sample = float(sample)
        self.slow_ms = float(slow_ms)
        self.recorder = FlightRecorder(capacity, slow_ms=slow_ms)

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0 or self.slow_ms > 0.0

    def begin(self, door: str) -> Optional[Trace]:
        """Door entry with no incoming context: head-sample, else arm
        for tail capture, else None (the disabled fast path — one
        float compare, no allocation)."""
        if self.sample > 0.0 and random.random() < self.sample:
            tr = Trace(door, sampled=True)
            rec = self.recorder
            rec.started += 1
            rec.sampled += 1
            return tr
        if self.slow_ms > 0.0:
            # slow_ms may have been flipped at runtime; keep the
            # recorder's threshold floor in step
            rec = self.recorder
            rec.slow_ms = self.slow_ms
            rec.started += 1
            return Trace(door, sampled=False)
        return None

    def join(
        self, door: str, ctx: Optional[TraceContext]
    ) -> Optional[Trace]:
        """Door entry with a (possibly absent) incoming context. A
        remote SAMPLED context is honored whenever this node has
        tracing enabled AT ALL (any sample rate or tail capture) — the
        origin made the sampling decision for the whole request, and
        re-rolling the dice here would sever the cross-node trace. A
        node with tracing fully OFF ignores carried contexts too:
        traceparent arrives on UNTRUSTED doors (client HTTP/gRPC, the
        GEB port), and a client-supplied header must not be able to
        force span collection + recorder churn past the operator's
        GUBER_TRACE_*=0 policy. Anything else falls back to this
        node's own head/tail policy."""
        if ctx is not None and ctx.sampled and self.enabled:
            tr = Trace(door, sampled=True, remote=ctx)
            self.recorder.started += 1
            return tr
        return self.begin(door)

    def finish(self, trace: Optional[Trace]) -> None:
        if trace is None:
            return
        self.recorder.observe(trace, time.monotonic() - trace.t0)


# -- context plumbing --------------------------------------------------------


def active() -> Optional[Trace]:
    """The caller's active trace, or None — THE one-branch probe every
    instrumented site uses."""
    return _CURRENT.get()


def activate(trace: Trace):
    return _CURRENT.set(trace)


def deactivate(token) -> None:
    _CURRENT.reset(token)


def propagation_header() -> Optional[str]:
    """traceparent for an outbound hop from the current context, or
    None (unsampled / untraced — nothing crosses the wire)."""
    tr = _CURRENT.get()
    if tr is None:
        return None
    return tr.header()


class scope:
    """`with tracing.scope(tracer, trace):` — activate for the body,
    then finish into the recorder. A None trace is a no-op, so door
    code stays branch-free."""

    __slots__ = ("tracer", "trace", "_token")

    def __init__(self, tracer: Optional[Tracer], trace: Optional[Trace]):
        self.tracer = tracer
        self.trace = trace
        self._token = None

    def __enter__(self) -> Optional[Trace]:
        if self.trace is not None:
            self._token = _CURRENT.set(self.trace)
        return self.trace

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
        if self.trace is not None and self.tracer is not None:
            self.tracer.finish(self.trace)
        return False
