"""Elastic ring rescale: planned state handoff on membership change.

r11 replication covers DEATH of an owner (snapshot to the ring
successor, takeover, reconcile handback). But at fleet scale the ring
changes far more often than nodes die: every rolling deploy
deregisters and re-registers a node, and every autoscale event adds or
removes one — and a ring change silently REASSIGNS ownership with no
state handoff. The new owner of a moved key opens a fresh window, so a
planned deploy un-rate-limits every idle over-limit key it moves (the
consistency window during membership change that PAPERS.md's
scalable-rate-limiting survey names as the core distributed-correctness
problem; the reference Gubernator simply accepts the amnesia).

This module closes it with machinery that already exists — the r11
snapshot surfaces (`snapshot_read`, non-mutating), the ReplicateBuckets
peer RPC and its LWW install rules, and the ring itself
(`ConsistentHashPicker.ownership_diff`):

- Owners track the token-bucket keys they decide (bounded,
  GUBER_RESCALE_TRACK_KEYS; freshest-kept like the r11 standby table).
  Installed handoff/standby seeds are tracked too, so a window a node
  RECEIVED in one rescale survives the next one even if only peeked.
- On every ring change (Instance.set_peers diff), the flush loop
  computes `old_picker.ownership_diff(new_picker, tracked_keys)`,
  snapshot-reads the moved keys' windows (non-mutating; device
  backends on the batcher's serialized submit thread, the r11
  contract) and ships them to each NEW owner over ReplicateBuckets.
  Installs are last-write-wins by (reset_time, snapshot_ms), so
  duplicates and retries no-op — the exact r11 standby discipline.
- Receivers: with replication on, the r11 install path handles both
  halves (owned -> store, not-yet-owned -> standby, seeded on the
  first owned touch). With replication OFF, RescaleManager.install
  provides the same two-way split against its own bounded pending
  table, so GUBER_RESCALE stands alone.
- Double-serve window (GUBER_RESCALE_DOUBLE_SERVE_MS): for a bounded
  window after a ring change, FORWARDERS keep routing moved keys to
  their OLD owner (route_override — one extra ring lookup per
  forwarded request, only while a window is open), whose store is
  still warm, while the new owner installs the handoff; the old owner
  counts these serves (rescale_double_serve_answers_total) and re-dirties
  the keys so the end-of-window flush ships any hits it absorbed.
  When the window closes, forwarding flips to the new owner and LWW
  reconciliation closes any race. A node that LEFT the ring is never
  double-served (its doors are draining); the drain handoff below
  covers that direction instead.
- Drain handoff (Server.drain, BEFORE deregistration): a SIGTERMed
  node ships every tracked window to the owner the ring elects once
  it is gone (ownership_diff against the ring minus self). Receivers
  park the snapshots until their own ring flips, then seed on first
  touch — so the windows are in place before any peer re-routes.
- GUBER_SHARDS changes on the mesh backend re-partition the store
  itself: PartitionedEngine.export_windows reads every live token
  window host-side (the full key hash is reconstructable from each
  entry's L_TAG|L_KEYLOW lanes since r14) and install_windows lays
  them out under the new ShardingPolicy
  (parallel/sharded.py repartition; MeshBackend.repartition).

Deliberate scope (documented in docs/operations.md):

- Token bucket only, the same structural exclusion as r11 (leaky
  refills continuously and self-heals within one leak tick).
- With a static ring, ON is byte-identical to OFF: tracking is two
  dict ops on the owned hot path, the flush loop only acts on ring
  changes, and snapshot reads are non-mutating
  (tests/test_rescale.py pins it differentially, flat and mesh).
- Direct traffic AT the new owner in the handoff-lag window (before
  the old owner's snapshots land) may open a fresh window; the LWW
  install then overwrites it — the fail-closed direction, bounded by
  the handoff lag (metric: rescale_handoff_lag_seconds, target under
  two flush windows). Double-serve closes this window entirely for
  forwarded traffic.
- Chain levels and pre-hashed GEB6/GEB7 windows are outside the
  tracked set (no key strings), the r11 scope limits verbatim.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    millisecond_now,
)
from gubernator_tpu.serve import metrics
from gubernator_tpu.serve.replication import Snapshot, snapshot_resp

log = logging.getLogger("gubernator_tpu.rescale")


class RescaleManager:
    """Supervised ring-change handoff loop + owned-key tracking.

    Event-loop confined like GlobalManager/ReplicationManager; the only
    cross-thread work is the device snapshot read, which runs on the
    batcher's single submit thread (DeviceBatcher.run_serialized)."""

    def __init__(self, conf, instance):
        self.conf = conf
        self.instance = instance
        # flush tick shared with r11 (one knob, one staleness story):
        # also the double-serve re-flush cadence
        self.sync_wait = getattr(conf, "replication_sync_wait", 0.1)
        self.track_cap = getattr(conf, "rescale_track_keys", 1 << 16)
        self.double_serve_s = (
            getattr(conf, "rescale_double_serve", 0.5)
        )
        # owner-side: key -> (algo, limit, duration) of the last decide
        # (duration backfill for backends whose rows don't persist it,
        # the r11 Snapshot convention). Freshest-kept at capacity:
        # pop-then-insert so dict order tracks touch recency.
        self._tracked: Dict[str, Tuple[int, int, int]] = {}
        # receiver-side pending table (replication OFF only): snapshots
        # for keys this node does not own YET, LWW by
        # (reset_time, snapshot_ms), popped on the first owned touch —
        # the r11 standby discipline without the takeover machinery
        self._pending: Dict[str, Snapshot] = {}
        # ring transition state: (old_picker, new_picker, deadline_mono)
        # of the latest change; route_override and the double-serve
        # accounting read it, the flush loop retires it
        self._transition = None
        # moved keys of the latest transition awaiting their
        # end-of-window reconcile flush: key -> (algo, limit, duration)
        self._moved: Dict[str, Tuple[int, int, int]] = {}
        self._pending_changes: List[tuple] = []
        self._event = asyncio.Event()
        self._tasks: list = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if not self._tasks:
            from gubernator_tpu.serve.global_mgr import supervise

            self._tasks = [
                asyncio.ensure_future(
                    supervise("rescale", self._run_flush)
                )
            ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []

    async def drain(self) -> None:
        """Planned-departure handoff (Server.drain, BEFORE the
        discovery deregistration): ship every tracked window to the
        owner the ring elects once this node is gone, so the snapshots
        are parked on their new owners before any peer's ring flips.
        No distinct other host (single-node ring) leaves nothing to
        do."""
        picker = self.instance.picker
        minus_self = picker.new()
        for p in picker.peers():
            if not p.is_owner:
                minus_self.add(p)
        if minus_self.size() == 0:
            return
        moved = picker.ownership_diff(minus_self, list(self._tracked))
        if moved:
            await self._handoff(moved, dict(self._tracked),
                                what="drain_handoff")
        if self._pending:
            # parked snapshots for keys whose first owned touch never
            # came (idle windows handed to us in an earlier rescale):
            # they are live state too — forward them to whoever the
            # ring-minus-self elects, or they die with this process
            now = millisecond_now()
            by_host: Dict[str, Tuple] = {}
            for key, s in self._pending.items():
                if s.reset_time <= now:
                    continue
                owner = minus_self.get(key)
                entry = by_host.get(owner.host)
                if entry is None:
                    by_host[owner.host] = (owner, [s])
                else:
                    entry[1].append(s)
            for host, (peer, snaps) in by_host.items():
                await self._send(peer, snaps, what="drain_pending")

    # -- owner-side tracking (hot path: dict ops, only when enabled) --------

    def note_owned(self, r: RateLimitReq) -> None:
        """Track an owned, hit-carrying token-bucket key as holding a
        live window this node must hand off on a ring change. Peeks
        change nothing (a peek cannot create a window; keys kept alive
        by peeks alone were tracked when they were created or
        installed)."""
        if r.hits <= 0 or r.algorithm != Algorithm.TOKEN_BUCKET:
            return
        self._note_key(r.hash_key(), (int(r.algorithm), r.limit, r.duration))

    def note_owned_fields(self, keys, fields, elig=None) -> None:
        """Bridge-tier tracking (edge string->array fold), same gates
        as note_owned; `elig` carries pre-computed
        eligible_field_indices like queue_dirty_fields."""
        from gubernator_tpu.serve.replication import (
            eligible_field_indices,
        )

        if elig is None:
            elig = eligible_field_indices(fields)
        if not elig.size:
            return
        limit = fields["limit"]
        duration = fields["duration"]
        token = int(Algorithm.TOKEN_BUCKET)
        for i in elig.tolist():
            self._note_key(
                keys[i], (token, int(limit[i]), int(duration[i]))
            )

    def note_seeded(self, seeds: List[Tuple[str, Snapshot]]) -> None:
        """Account an installed seed batch (standby takeover or pending
        handoff): track each window for the next ring change and stamp
        the handoff-lag gauge from the snapshots' owner-clock age."""
        for k, s in seeds:
            self.note_installed(k, s.limit, s.duration)
        self._lag_from_snaps(millisecond_now(), [s for _, s in seeds])

    def note_installed(self, key: str, limit: int, duration: int) -> None:
        """Track a window this node INSTALLED (handoff/standby seed or
        reconcile handback): it is live local state this node is now
        responsible for handing off, even if only ever peeked here —
        without this, a window that rode one rescale would amnesia on
        the next."""
        self._note_key(
            key, (int(Algorithm.TOKEN_BUCKET), int(limit), int(duration))
        )

    def _note_key(self, key: str, meta: Tuple[int, int, int]) -> None:
        tracked = self._tracked
        prev = tracked.pop(key, None)
        if prev is None and len(tracked) >= self.track_cap:
            # evict the stalest-touched key (dict order = touch
            # recency under pop-then-insert), counting the loss
            tracked.pop(next(iter(tracked)))
            self._drop("track_evict")
        tracked[key] = meta

    @property
    def tracked_len(self) -> int:
        return len(self._tracked)

    @property
    def pending_len(self) -> int:
        return len(self._pending)

    def _drop(self, what: str) -> None:
        try:
            metrics.RESCALE_DROPPED.labels(what=what).inc()
        except Exception:  # pragma: no cover - defensive
            pass

    # -- ring-change intake (called from Instance.set_peers) ----------------

    def note_ring_change(self, old_picker, new_picker) -> None:
        """Record a membership change; the flush loop performs the
        handoff. Non-blocking — set_peers must not wait on RPCs."""
        if old_picker.size() == 0:
            return  # initial membership: nothing to move
        now = time.monotonic()
        self._transition = (
            old_picker, new_picker, now + self.double_serve_s
        )
        self._pending_changes.append((old_picker, new_picker, now))
        self._event.set()

    def route_override(self, key: str, r: RateLimitReq):
        """Double-serve routing: while the latest ring change's window
        is open, a key whose owner moved keeps routing to its OLD
        owner (still warm) when that host is still in the new ring —
        including THIS node (the returned client then has is_owner set
        and the caller serves locally, counted as a double-serve).
        None = route normally. One extra ring lookup per call, only
        while a transition window is open."""
        tr = self._transition
        if tr is None:
            return None
        old_picker, new_picker, deadline = tr
        if time.monotonic() >= deadline:
            self._transition = None
            return None
        try:
            old = old_picker.get(key)
            new = new_picker.get(key)
        except Exception:  # pragma: no cover - ring flap
            return None
        if old.host == new.host or new.is_owner:
            # unmoved, or this node IS the new owner: serve locally —
            # the handoff seed/install path covers its window
            return None
        if old.is_owner:
            # this node is the OLD owner: keep answering from the warm
            # local store until the window closes (set_peers reuses
            # client objects, so `old` is the live self client)
            self._count_double_serve(r)
            return old
        live = new_picker.get_peer_by_host(old.host)
        return live  # None when the old owner left the ring

    def note_double_serve(self, r: RateLimitReq) -> bool:
        """Old-owner accounting for a peer-forwarded request on a key
        this node no longer owns but is double-serving: count it and
        re-dirty the key so the end-of-window flush ships the window
        (with any hits absorbed here) to the new owner. Returns True
        when the key is inside an open double-serve window."""
        tr = self._transition
        if tr is None:
            return False
        old_picker, _new_picker, deadline = tr
        if time.monotonic() >= deadline:
            return False
        try:
            if not old_picker.get(r.hash_key()).is_owner:
                return False
        except Exception:  # pragma: no cover - ring flap
            return False
        self._count_double_serve(r)
        return True

    def _count_double_serve(self, r: RateLimitReq) -> None:
        if r.algorithm == Algorithm.TOKEN_BUCKET:
            self._moved.setdefault(
                r.hash_key(), (int(r.algorithm), r.limit, r.duration)
            )
        try:
            metrics.RESCALE_DOUBLE_SERVE.inc()
        except Exception:  # pragma: no cover - defensive
            pass

    # -- receiver side (replication OFF; with it on, r11 installs) ----------

    async def install(self, owner: str, snaps: List[Snapshot]) -> None:
        """ReplicateBuckets receive path when ReplicationManager is not
        constructed: snapshots for keys this node OWNS install straight
        into the local store (through Instance.update_peer_globals, so
        the shed cache purges exactly as for a GLOBAL broadcast);
        others park in the bounded pending table, LWW by
        (reset_time, snapshot_ms), until the ring flips and the first
        owned touch seeds them."""
        now = millisecond_now()
        installs: List[Snapshot] = []
        for s in snaps:
            if (
                s.reset_time <= now
                or s.algorithm != int(Algorithm.TOKEN_BUCKET)
            ):
                continue
            try:
                we_own = self.instance.get_peer(s.key).is_owner
            except Exception:
                we_own = False
            if we_own:
                installs.append(s)
                continue
            cur = self._pending.get(s.key)
            if cur is not None and (
                (cur.reset_time, cur.snapshot_ms)
                >= (s.reset_time, s.snapshot_ms)
            ):
                continue
            self._pending.pop(s.key, None)
            self._pending[s.key] = s
            while len(self._pending) > self.track_cap:
                self._pending.pop(next(iter(self._pending)))
                self._drop("pending_evict")
        if installs:
            await self.instance.update_peer_globals(
                [(s.key, snapshot_resp(s)) for s in installs]
            )
            for s in installs:
                self.note_installed(s.key, s.limit, s.duration)
            self._lag_from_snaps(now, installs)
            log.info(
                "rescale: installed %d moved window(s) from '%s'",
                len(installs), owner,
            )

    def pending_purge(self, keys) -> None:
        """Drop pending rows for these keys: an UpdatePeerGlobals
        install means their owner is alive and broadcasting — its
        authoritative status supersedes any handed-off snapshot (the
        r11 standby rule applied to the pending table)."""
        if not self._pending:
            return
        for k in keys:
            self._pending.pop(k, None)

    def pending_pop(self, key: str) -> Optional[Snapshot]:
        """Take the pending handoff snapshot for a key about to be
        decided as owner — the first owned touch after this node's ring
        flipped. Expired rows answer None (the first post-reset touch
        must open a fresh window, the r11 standby rule)."""
        if not self._pending:
            return None
        s = self._pending.pop(key, None)
        if s is None or s.reset_time <= millisecond_now():
            return None
        return s

    def _lag_from_snaps(self, now: int, snaps: List[Snapshot]) -> None:
        try:
            lag_ms = max(now - s.snapshot_ms for s in snaps)
            metrics.RESCALE_HANDOFF_LAG.set(max(0.0, lag_ms / 1000.0))
        except Exception:  # pragma: no cover - defensive
            pass

    # -- flush loop ----------------------------------------------------------

    async def _run_flush(self) -> None:
        while True:
            if self._pending_changes or self._moved:
                # a change is queued (handoff NOW — lag is the
                # contract) or a double-serve window is open (tick at
                # the flush cadence until it closes)
                await asyncio.sleep(
                    0 if self._pending_changes else self.sync_wait
                )
            else:
                await self._event.wait()
                self._event.clear()
                continue
            await self.flush_once()

    async def flush_once(self) -> None:
        """One handoff round: perform every queued ring change's moved-
        key handoff, then re-flush the open double-serve window's moved
        keys (LWW reconcile), retiring the window past its deadline."""
        changes, self._pending_changes = self._pending_changes, []
        for old_picker, new_picker, t_change in changes:
            # diff against the tracked set as of NOW (keys installed
            # since the change was queued are included — freshness only
            # helps). Delivery clients come from the change's own new
            # picker; set_peers reuses client objects, so they are the
            # live connections unless a LATER flip removed the host —
            # those sends fail loudly and the next change's diff
            # re-moves the keys from the current state.
            tracked = dict(self._tracked)
            moved = old_picker.ownership_diff(
                new_picker, list(tracked)
            )
            if not moved:
                continue
            n = await self._handoff(moved, tracked, what="ring_change")
            lag = time.monotonic() - t_change
            try:
                metrics.RESCALE_HANDOFF_LAG.set(lag)
            except Exception:  # pragma: no cover - defensive
                pass
            log.info(
                "rescale: ring change moved %d tracked key(s) to %d "
                "new owner(s) in %.0f ms", n, len(moved), lag * 1e3,
            )
            # arm the double-serve reconcile set: every moved key
            # re-flushes each tick until the window closes
            for _host, (_peer, keys) in moved.items():
                for k in keys:
                    self._moved.setdefault(k, tracked[k])
        if self._moved and not changes:
            # reconcile on the ticks AFTER a change's own handoff —
            # re-snapshotting the identical windows in the same pass
            # would double the device gathers and RPCs at exactly the
            # moment the lag metric measures, with no double-serve
            # hits accrued yet to reconcile
            await self._reconcile_moved()

    async def _reconcile_moved(self) -> None:
        """Re-ship the open window's moved keys to their CURRENT owners
        (LWW: receivers keep the freshest). A key retires from the
        moved set — and from the tracked table — only once its window
        DELIVERED to the new owner or expired out of the store; a
        failed send (new owner's door not ready yet, breaker cooldown
        outlasting the window) keeps it retrying every flush tick even
        past the double-serve deadline, because dropping it would
        strand the window here forever (a later ring change's diff
        only covers keys the OLD ring routed to this node) — the exact
        amnesia this subsystem exists to prevent. Keys the ring moved
        BACK to us (flap) leave the moved set but STAY tracked: they
        are live owned windows again."""
        moved = dict(self._moved)
        by_host: Dict[str, Tuple] = {}
        for key in moved:
            try:
                owner = self.instance.get_peer(key)
            except Exception:
                continue
            if owner.is_owner:
                self._moved.pop(key, None)
                continue
            entry = by_host.get(owner.host)
            if entry is None:
                by_host[owner.host] = (owner, [key])
            else:
                entry[1].append(key)
        done: List[str] = []
        sent = 0
        for host, (peer, keys) in by_host.items():
            snaps = await self._snapshot(
                [(k, moved[k]) for k in keys]
            )
            snap_keys = {s.key for s in snaps}
            # rows missing from the gather expired or evicted: nothing
            # left to move for them
            done.extend(k for k in keys if k not in snap_keys)
            if snaps and await self._send(peer, snaps,
                                          what="reconcile"):
                done.extend(snap_keys)
                sent += len(snaps)
        if sent:
            try:
                metrics.RESCALE_KEYS_MOVED.inc(sent)
            except Exception:  # pragma: no cover - defensive
                pass
        tr = self._transition
        window_open = tr is not None and time.monotonic() < tr[2]
        if not window_open:
            self._transition = None
            for key in done:
                self._moved.pop(key, None)
                self._tracked.pop(key, None)

    async def _handoff(
        self,
        moved: Dict[str, Tuple],
        metas: Dict[str, Tuple[int, int, int]],
        what: str,
    ) -> int:
        """Snapshot-read and ship one work list ({host: (peer, keys)}).
        Returns the number of snapshots delivered (expired/evicted rows
        snapshot to None and drop out — nothing to move)."""
        sent = 0
        for host, (peer, keys) in moved.items():
            snaps = await self._snapshot(
                [(k, metas[k]) for k in keys if k in metas]
            )
            if not snaps:
                continue
            if await self._send(peer, snaps, what=what):
                sent += len(snaps)
        if sent:
            try:
                metrics.RESCALE_KEYS_MOVED.inc(sent)
            except Exception:  # pragma: no cover - defensive
                pass
        return sent

    async def _snapshot(
        self, metas: List[Tuple[str, Tuple[int, int, int]]]
    ) -> List[Snapshot]:
        from gubernator_tpu.serve.replication import snapshot_windows

        return await snapshot_windows(self.instance, metas)

    async def _send(self, peer, snaps: List[Snapshot], what: str) -> bool:
        """One new owner's snapshots over ReplicateBuckets, chunked
        under the peer batch cap; LWW installs make retries and
        duplicate deliveries free. Returns True when every chunk
        landed."""
        advertise = self.conf.resolved_advertise()
        lim = self.conf.behaviors.global_batch_limit
        ok = True
        for i in range(0, len(snaps), lim):
            chunk = snaps[i : i + lim]
            try:
                await peer.replicate_buckets(chunk, owner=advertise)
            except Exception as e:
                ok = False
                log.warning(
                    "rescale: error sending %s snapshots to '%s': %s",
                    what, peer.host, e,
                )
        return ok
