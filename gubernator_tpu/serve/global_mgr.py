"""GLOBAL behavior gossip: async hit forwarding + owner status broadcasts.

The host-level twin of the reference's globalManager (reference
global.go:29-232), on asyncio instead of goroutines:

- Non-owners answer GLOBAL requests from their local replica and queue the
  hits here; hits aggregate per key and flush to owning peers every
  `global_sync_wait` or at `global_batch_limit` (global.go:72-111).
- Owners queue every GLOBAL key they decide; the broadcast loop dedups,
  peeks current status (a zero-hit decide), and pushes UpdatePeerGlobals to
  every other peer (global.go:158-232).

When the peers are TPU shards of one mesh rather than remote hosts, the
same aggregate->apply->broadcast cycle runs as collectives instead
(parallel/sharded.py sync_globals); this module is the DCN/gRPC edge of
the gossip.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import replace
from typing import Dict, Optional

from gubernator_tpu.api.types import Behavior, RateLimitReq
from gubernator_tpu.serve.config import BehaviorConfig
from gubernator_tpu.serve.metrics import (
    GLOBAL_ASYNC_DURATIONS,
    GLOBAL_BACKLOG_DROPPED,
    GLOBAL_BROADCAST_DURATIONS,
    GLOBAL_FLUSH_BYTES,
    GLOBAL_TASK_RESTARTS,
)

log = logging.getLogger("gubernator_tpu.global")

#: supervision backoff bounds for a crashing gossip loop: restart fast
#: after a one-off (a dead loop silently stops ALL GLOBAL gossip), back
#: off exponentially while the crash repeats, reset once a run survives
#: SUPERVISE_RESET_S
SUPERVISE_BACKOFF_S = 0.05
SUPERVISE_BACKOFF_MAX_S = 5.0
SUPERVISE_RESET_S = 60.0

#: concurrent per-peer sends per gossip flush (r9): sequential awaits
#: made flush latency O(#peers x RTT) — at 20 peers x 5ms that's 100ms
#: of serialized wall time per broadcast, directly in BASELINE config
#: 3's p99 path. Bounded so a large fleet can't open hundreds of
#: simultaneous RPCs from one flush.
SEND_FANOUT = 16


async def supervise(name: str, loop_factory) -> None:
    """Keep a gossip-style background loop alive: an unexpected death
    restarts it with bounded exponential backoff instead of only
    logging (the pre-r8 behavior left GLOBAL gossip silently dead for
    the rest of the process). A loop that ran healthily for longer than
    SUPERVISE_RESET_S before dying restarts at the BASE backoff, not
    the escalated one. Restarts are counted in
    global_task_restarts_total{task}. Shared by GlobalManager and
    ReplicationManager (serve/replication.py)."""
    backoff = SUPERVISE_BACKOFF_S
    while True:
        started = time.monotonic()
        try:
            await loop_factory()
            return  # loops are infinite; a clean return means done
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if time.monotonic() - started > SUPERVISE_RESET_S:
                backoff = SUPERVISE_BACKOFF_S
            log.error(
                "%s loop died: %r; restarting in %.2fs",
                name, e, backoff, exc_info=e,
            )
            try:
                GLOBAL_TASK_RESTARTS.labels(task=name).inc()
            except Exception:  # pragma: no cover - defensive
                pass
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, SUPERVISE_BACKOFF_MAX_S)


class GlobalManager:
    def __init__(self, conf: BehaviorConfig, instance):
        self.conf = conf
        self.instance = instance
        self._hits: Dict[str, RateLimitReq] = {}
        self._updates: Dict[str, RateLimitReq] = {}
        self._hits_event = asyncio.Event()
        self._updates_event = asyncio.Event()
        self._tasks = []
        self._dropped = {"hits": 0, "updates": 0}

    def start(self) -> None:
        if not self._tasks:
            self._tasks = [
                asyncio.ensure_future(
                    self._supervise("async_hits", self._run_async_hits)
                ),
                asyncio.ensure_future(
                    self._supervise("broadcasts", self._run_broadcasts)
                ),
            ]

    async def _supervise(self, name: str, loop_factory) -> None:
        # the plain task name keeps the metric label stable
        # (global_task_restarts_total{task="async_hits"|"broadcasts"})
        await supervise(name, loop_factory)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []

    async def drain(self) -> None:
        """Graceful-drain flush: push whatever is aggregated NOW instead
        of waiting out the sync window — pending non-owner hits reach
        their owners and owned-key statuses broadcast before shutdown.
        Send errors are already logged per peer by the senders."""
        hits, self._hits = self._hits, {}
        self._hits_event.clear()
        if hits:
            await self._send_hits(hits)
        updates, self._updates = self._updates, {}
        self._updates_event.clear()
        if updates:
            await self._update_peers(updates)

    def backlog_sizes(self) -> Dict[str, int]:
        """Standing aggregation occupancy for the scrape-time
        global_backlog_entries gauge (r16): distinct keys waiting in
        each queue, against the GUBER_GLOBAL_BACKLOG bound."""
        return {"hits": len(self._hits), "updates": len(self._updates)}

    # -- queue entry points (non-blocking, called on the serving loop) ------

    def queue_hit(self, r: RateLimitReq) -> None:
        """Aggregate a non-owner hit for async forwarding
        (global.go:62-64,78-86). Bounded: an unreachable owner must not
        grow the backlog for the whole outage — past
        GUBER_GLOBAL_BACKLOG distinct keys, NEW keys are dropped (and
        counted); keys already aggregating keep accumulating for free."""
        key = r.hash_key()
        cur = self._hits.get(key)
        if cur is not None:
            cur.hits += r.hits
        elif len(self._hits) >= self.conf.global_backlog:
            self._drop("hits")
            return
        else:
            self._hits[key] = replace(r)
        self._hits_event.set()

    def queue_update(self, r: RateLimitReq) -> None:
        """Mark an owned GLOBAL key for status broadcast
        (global.go:66-68,164-165). Bounded like queue_hit."""
        key = r.hash_key()
        if key not in self._updates and (
            len(self._updates) >= self.conf.global_backlog
        ):
            self._drop("updates")
            return
        self._updates[key] = replace(r)
        self._updates_event.set()

    def _drop(self, queue: str) -> None:
        self._dropped[queue] += 1
        n = self._dropped[queue]
        if n & (n - 1) == 0:  # log at powers of two, not per drop
            log.warning(
                "GLOBAL %s backlog full (GUBER_GLOBAL_BACKLOG=%d): "
                "%d new key(s) dropped so far this process",
                queue, self.conf.global_backlog, n,
            )
        try:
            GLOBAL_BACKLOG_DROPPED.labels(queue=queue).inc()
        except Exception:  # pragma: no cover - defensive
            pass

    # -- loops --------------------------------------------------------------

    async def _run_async_hits(self) -> None:
        while True:
            await self._hits_event.wait()
            # batch-limit flush happens immediately; otherwise wait out the
            # sync window to coalesce (global.go:88-104)
            if len(self._hits) < self.conf.global_batch_limit:
                await asyncio.sleep(self.conf.global_sync_wait)
            hits, self._hits = self._hits, {}
            self._hits_event.clear()
            if hits:
                await self._send_hits(hits)

    @staticmethod
    def _payload_bytes(reqs) -> int:
        """Approximate wire payload of a hit chunk (name + unique-key
        UTF-8 bytes plus ~40B of fixed int fields per request) — cheap
        accounting for global_flush_bytes_total. The metric's point is
        the rpc/mesh SPLIT, not exact protobuf framing."""
        return sum(len(r.name) + len(r.unique_key) + 40 for r in reqs)

    async def _apply_local(self, reqs) -> None:
        """Self-destined flush chunk (r20): this node IS the ring owner
        of these keys, so the 'send' is an in-mesh apply — one psum
        collective charging each key's owner SHARD
        (instance.apply_global_hits_local) — instead of a loopback
        gossip RPC. Backends without the collective surface fall back
        to the plain local decide path inside the instance hook. Errors
        are logged, not raised: a failed local apply must not kill the
        flush loop any more than a failed peer RPC does."""
        try:
            apply = getattr(self.instance, "apply_global_hits_local", None)
            if apply is not None:
                await apply(reqs)
            else:
                await self.instance.decide_local(
                    reqs, [False] * len(reqs)
                )
        except Exception as e:
            log.error("error applying mesh-local global hits: %s", e)

    async def _send_hits(self, hits: Dict[str, RateLimitReq]) -> None:
        """Per-destination flush of aggregated hits (global.go:115-155 +
        r20 mesh-native GLOBAL): keys owned by an off-mesh ring peer
        forward over gossip RPC; keys owned by THIS node (the ring
        handed them back, or the flush raced a ring change) short-
        circuit through the local apply path — one in-mesh collective
        instead of a loopback RPC. GUBER_GLOBAL_MESH=0 restores the
        all-RPC fan-out. The r16 trace span carries the per-path hop
        counts so the collective win is visible per flush, not just as
        aggregate throughput."""
        start = time.monotonic()
        tracer = getattr(self.instance, "tracer", None)
        trace = tracer.begin("global_flush") if tracer is not None else None
        by_peer: Dict[str, list] = {}
        clients = {}
        local: list = []
        use_mesh = getattr(self.conf, "global_mesh", True)
        for key, r in hits.items():
            try:
                peer = self.instance.get_peer(key)
            except Exception as e:
                log.error("while getting peer for hash key '%s': %s", key, e)
                continue
            if use_mesh and getattr(peer, "is_owner", False):
                local.append(r)
                continue
            by_peer.setdefault(peer.host, []).append(r)
            clients[peer.host] = peer
        lim = self.conf.global_batch_limit
        hops_mesh = 0
        if local:
            # one collective per chunk; a steady-state flush fits one
            for i in range(0, len(local), lim):
                hops_mesh += 1
                await self._apply_local(local[i : i + lim])
            try:
                GLOBAL_FLUSH_BYTES.labels(path="mesh").inc(
                    self._payload_bytes(local)
                )
            except Exception:  # pragma: no cover - defensive
                pass
        # fan the per-peer sends out concurrently (bounded): each key
        # appears in exactly one aggregated chunk, so cross-chunk order
        # is immaterial and flush latency becomes ~one RTT instead of
        # O(#peers x RTT). Errors stay logged per peer, per chunk.
        sem = asyncio.Semaphore(SEND_FANOUT)

        async def send(host, chunk):
            async with sem:
                try:
                    await asyncio.wait_for(
                        clients[host].get_peer_rate_limits(chunk),
                        timeout=self.conf.global_timeout,
                    )
                except Exception as e:
                    log.error(
                        "error sending global hits to '%s': %s", host, e
                    )

        sends = [
            send(host, reqs[i : i + lim])
            for host, reqs in by_peer.items()
            # a flush can have aggregated more keys than one peer RPC
            # may carry (the owner hard-rejects >MAX_BATCH_SIZE); chunk
            for i in range(0, len(reqs), lim)
        ]
        if sends:
            await asyncio.gather(*sends)
            try:
                GLOBAL_FLUSH_BYTES.labels(path="rpc").inc(
                    sum(self._payload_bytes(c) for c in by_peer.values())
                )
            except Exception:  # pragma: no cover - defensive
                pass
        if trace is not None:
            # hop-count evidence for the r20 collective path: a mesh-
            # local flush is hops_mesh=1 regardless of #peers, where
            # the RPC path pays one hop per (peer, chunk)
            trace.add_span(
                "global_flush_hits",
                start=start,
                hops_rpc=len(sends),
                hops_mesh=hops_mesh,
                keys_mesh=len(local),
                keys_rpc=sum(len(v) for v in by_peer.values()),
                peers_rpc=len(by_peer),
            )
            tracer.finish(trace)
        GLOBAL_ASYNC_DURATIONS.observe(time.monotonic() - start)

    async def _run_broadcasts(self) -> None:
        while True:
            await self._updates_event.wait()
            if len(self._updates) < self.conf.global_batch_limit:
                await asyncio.sleep(self.conf.global_sync_wait)
            updates, self._updates = self._updates, {}
            self._updates_event.clear()
            if updates:
                await self._update_peers(updates)

    @staticmethod
    def _update_bytes(updates) -> int:
        """Approximate wire payload of an update chunk (key UTF-8 bytes
        plus ~48B of status fields per entry) — same cheap accounting
        stance as _payload_bytes: the metric's point is the rpc/mesh
        split, not protobuf framing."""
        return sum(len(k) + 48 for k, _ in updates)

    async def _install_local(self, updates) -> None:
        """Mesh-local broadcast chunk (r21): these replicas live in THIS
        node's mesh (lockstep followers / a co-scheduled server sharing
        the device store), so ONE local install covers every mesh-local
        peer — the same replica-install path the gossip door runs on
        receive (instance.update_peer_globals), without the loop of
        per-peer RPCs. Errors are logged, not raised, mirroring
        _apply_local: a failed install must not kill the broadcast
        loop."""
        try:
            install = getattr(
                self.instance, "update_peer_globals_local", None
            ) or self.instance.update_peer_globals
            await install(updates)
        except Exception as e:
            log.error("error installing mesh-local global updates: %s", e)

    async def _update_peers(self, updates: Dict[str, RateLimitReq]) -> None:
        """Peek authoritative status for each updated key and broadcast to
        all other peers (global.go:193-232), split per destination like
        _send_hits (r20 -> r21): peers marked mesh_local receive the
        whole batch through ONE local mesh install regardless of their
        count, off-mesh peers keep the bounded-concurrency RPC fan-out.
        GUBER_GLOBAL_MESH=0 restores the all-RPC broadcast."""
        start = time.monotonic()
        tracer = getattr(self.instance, "tracer", None)
        trace = (
            tracer.begin("global_broadcast") if tracer is not None else None
        )
        globals_batch = []
        peek_reqs = []
        keys = []
        for key, r in updates.items():
            peek = replace(r, hits=0, behavior=Behavior.BATCHING)
            peek_reqs.append(peek)
            keys.append(key)
        try:
            statuses = await self.instance.decide_local(
                peek_reqs, gnp=[False] * len(peek_reqs)
            )
            globals_batch = list(zip(keys, statuses))
        except Exception as e:
            log.error("while peeking global statuses: %s", e)

        hops_mesh = 0
        sends = []
        rpc_peers = []
        mesh_peers = 0
        if globals_batch:
            use_mesh = getattr(self.conf, "global_mesh", True)
            for peer in self.instance.peer_list():
                if peer.is_owner:  # never broadcast to ourselves
                    continue
                if use_mesh and getattr(peer, "mesh_local", False):
                    mesh_peers += 1
                else:
                    rpc_peers.append(peer)
            lim = self.conf.global_batch_limit
            if mesh_peers:
                # one install per chunk covers EVERY mesh-local peer:
                # the replicas share this node's device store
                for i in range(0, len(globals_batch), lim):
                    hops_mesh += 1
                    await self._install_local(globals_batch[i : i + lim])
                try:
                    GLOBAL_FLUSH_BYTES.labels(path="mesh").inc(
                        self._update_bytes(globals_batch)
                    )
                except Exception:  # pragma: no cover - defensive
                    pass
            # bounded concurrent fan-out (r9): the broadcast used to
            # await each peer in turn, making gossip propagation — and
            # with it the replicas' staleness window — scale linearly
            # with fleet size. Installs are idempotent last-writer-wins
            # upserts, so concurrent delivery is safe; per-peer error
            # logging is preserved inside each send.
            sem = asyncio.Semaphore(SEND_FANOUT)

            async def send(peer, chunk):
                async with sem:
                    try:
                        await asyncio.wait_for(
                            peer.update_peer_globals(chunk),
                            timeout=self.conf.global_timeout,
                        )
                    except Exception as e:
                        log.error(
                            "error sending global updates to '%s': %s",
                            peer.host,
                            e,
                        )

            sends = [
                send(peer, globals_batch[i : i + lim])
                for peer in rpc_peers
                for i in range(0, len(globals_batch), lim)
            ]
            if sends:
                await asyncio.gather(*sends)
                try:
                    GLOBAL_FLUSH_BYTES.labels(path="rpc").inc(
                        self._update_bytes(globals_batch) * len(rpc_peers)
                    )
                except Exception:  # pragma: no cover - defensive
                    pass
        if trace is not None:
            # hop-count evidence mirroring global_flush_hits: the whole
            # mesh-local replica SET costs hops_mesh=1 per chunk, while
            # the RPC path pays one hop per (peer, chunk)
            trace.add_span(
                "global_flush_updates",
                start=start,
                hops_rpc=len(sends),
                hops_mesh=hops_mesh,
                keys_mesh=len(globals_batch) if hops_mesh else 0,
                keys_rpc=len(globals_batch) * len(rpc_peers),
                peers_mesh=mesh_peers,
                peers_rpc=len(rpc_peers),
            )
            tracer.finish(trace)
        GLOBAL_BROADCAST_DURATIONS.observe(time.monotonic() - start)
