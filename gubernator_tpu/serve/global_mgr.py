"""GLOBAL behavior gossip: async hit forwarding + owner status broadcasts.

The host-level twin of the reference's globalManager (reference
global.go:29-232), on asyncio instead of goroutines:

- Non-owners answer GLOBAL requests from their local replica and queue the
  hits here; hits aggregate per key and flush to owning peers every
  `global_sync_wait` or at `global_batch_limit` (global.go:72-111).
- Owners queue every GLOBAL key they decide; the broadcast loop dedups,
  peeks current status (a zero-hit decide), and pushes UpdatePeerGlobals to
  every other peer (global.go:158-232).

When the peers are TPU shards of one mesh rather than remote hosts, the
same aggregate->apply->broadcast cycle runs as collectives instead
(parallel/sharded.py sync_globals); this module is the DCN/gRPC edge of
the gossip.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import replace
from typing import Dict, Optional

from gubernator_tpu.api.types import Behavior, RateLimitReq
from gubernator_tpu.serve.config import BehaviorConfig
from gubernator_tpu.serve.metrics import (
    GLOBAL_ASYNC_DURATIONS,
    GLOBAL_BROADCAST_DURATIONS,
)

log = logging.getLogger("gubernator_tpu.global")


def _log_task_death(task: asyncio.Task) -> None:
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        log.error("global manager loop died: %r", exc, exc_info=exc)


class GlobalManager:
    def __init__(self, conf: BehaviorConfig, instance):
        self.conf = conf
        self.instance = instance
        self._hits: Dict[str, RateLimitReq] = {}
        self._updates: Dict[str, RateLimitReq] = {}
        self._hits_event = asyncio.Event()
        self._updates_event = asyncio.Event()
        self._tasks = []

    def start(self) -> None:
        if not self._tasks:
            self._tasks = [
                asyncio.ensure_future(self._run_async_hits()),
                asyncio.ensure_future(self._run_broadcasts()),
            ]
            for t in self._tasks:
                t.add_done_callback(_log_task_death)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []

    # -- queue entry points (non-blocking, called on the serving loop) ------

    def queue_hit(self, r: RateLimitReq) -> None:
        """Aggregate a non-owner hit for async forwarding
        (global.go:62-64,78-86)."""
        key = r.hash_key()
        cur = self._hits.get(key)
        if cur is not None:
            cur.hits += r.hits
        else:
            self._hits[key] = replace(r)
        self._hits_event.set()

    def queue_update(self, r: RateLimitReq) -> None:
        """Mark an owned GLOBAL key for status broadcast
        (global.go:66-68,164-165)."""
        self._updates[r.hash_key()] = replace(r)
        self._updates_event.set()

    # -- loops --------------------------------------------------------------

    async def _run_async_hits(self) -> None:
        while True:
            await self._hits_event.wait()
            # batch-limit flush happens immediately; otherwise wait out the
            # sync window to coalesce (global.go:88-104)
            if len(self._hits) < self.conf.global_batch_limit:
                await asyncio.sleep(self.conf.global_sync_wait)
            hits, self._hits = self._hits, {}
            self._hits_event.clear()
            if hits:
                await self._send_hits(hits)

    async def _send_hits(self, hits: Dict[str, RateLimitReq]) -> None:
        """Group aggregated hits by owning peer and forward
        (global.go:115-155)."""
        start = time.monotonic()
        by_peer: Dict[str, list] = {}
        clients = {}
        for key, r in hits.items():
            try:
                peer = self.instance.get_peer(key)
            except Exception as e:
                log.error("while getting peer for hash key '%s': %s", key, e)
                continue
            by_peer.setdefault(peer.host, []).append(r)
            clients[peer.host] = peer
        for host, reqs in by_peer.items():
            # a flush can have aggregated more keys than one peer RPC may
            # carry (the owner hard-rejects >MAX_BATCH_SIZE); chunk it
            for i in range(0, len(reqs), self.conf.global_batch_limit):
                chunk = reqs[i : i + self.conf.global_batch_limit]
                try:
                    await asyncio.wait_for(
                        clients[host].get_peer_rate_limits(chunk),
                        timeout=self.conf.global_timeout,
                    )
                except Exception as e:
                    log.error(
                        "error sending global hits to '%s': %s", host, e
                    )
        GLOBAL_ASYNC_DURATIONS.observe(time.monotonic() - start)

    async def _run_broadcasts(self) -> None:
        while True:
            await self._updates_event.wait()
            if len(self._updates) < self.conf.global_batch_limit:
                await asyncio.sleep(self.conf.global_sync_wait)
            updates, self._updates = self._updates, {}
            self._updates_event.clear()
            if updates:
                await self._update_peers(updates)

    async def _update_peers(self, updates: Dict[str, RateLimitReq]) -> None:
        """Peek authoritative status for each updated key and broadcast to
        all other peers (global.go:193-232)."""
        start = time.monotonic()
        globals_batch = []
        peek_reqs = []
        keys = []
        for key, r in updates.items():
            peek = replace(r, hits=0, behavior=Behavior.BATCHING)
            peek_reqs.append(peek)
            keys.append(key)
        try:
            statuses = await self.instance.decide_local(
                peek_reqs, gnp=[False] * len(peek_reqs)
            )
            globals_batch = list(zip(keys, statuses))
        except Exception as e:
            log.error("while peeking global statuses: %s", e)

        if globals_batch:
            for peer in self.instance.peer_list():
                if peer.is_owner:
                    continue  # never broadcast to ourselves
                for i in range(0, len(globals_batch), self.conf.global_batch_limit):
                    chunk = globals_batch[i : i + self.conf.global_batch_limit]
                    try:
                        await asyncio.wait_for(
                            peer.update_peer_globals(chunk),
                            timeout=self.conf.global_timeout,
                        )
                    except Exception as e:
                        log.error(
                            "error sending global updates to '%s': %s",
                            peer.host,
                            e,
                        )
        GLOBAL_BROADCAST_DURATIONS.observe(time.monotonic() - start)
