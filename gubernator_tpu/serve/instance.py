"""The serving instance: validation, owner routing, and peer fan-out.

The engine-room of one server process, mirroring the reference Instance's
contract (reference gubernator.go:41-322) with an asyncio + batched-device
execution model:

- GetRateLimits validates each entry, decides key ownership on the ring,
  screens the over-limit shed cache (serve/shedcache.py: frozen
  token-bucket refusals answer host-side, before the batcher or any
  forward RPC), and splits the residue three ways: locally-owned
  requests coalesce into
  device batches; GLOBAL non-owned requests answer from local replicas
  (with hits queued to the gossip manager); other non-owned requests
  forward to their owner peer (micro-batched per peer unless NO_BATCHING).
  Responses reassemble in request order (gubernator.go:75-169).
- GetPeerRateLimits serves owner-side batches for other peers
  (gubernator.go:210-227).
- UpdatePeerGlobals installs owner-broadcast GLOBAL replicas
  (gubernator.go:199-207).
- set_peers rebuilds the picker on membership change, reusing existing
  connections, and recomputes health (gubernator.go:254-292).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Sequence, Tuple

from gubernator_tpu.api.types import (
    Behavior,
    HealthCheckResp,
    PeerInfo,
    RateLimitReq,
    RateLimitResp,
)
from gubernator_tpu.core.hashing import slot_hash_batch
from gubernator_tpu.core.sketches import TrafficStats
from gubernator_tpu.serve import metrics, tracing
from gubernator_tpu.serve.batcher import DeviceBatcher
from gubernator_tpu.serve.breaker import OPEN as BREAKER_OPEN
from gubernator_tpu.serve.config import MAX_BATCH_SIZE, ServerConfig
from gubernator_tpu.serve.faults import FAULTS
from gubernator_tpu.serve.global_mgr import GlobalManager
from gubernator_tpu.serve.peers import ConsistentHashPicker, PeerClient
from gubernator_tpu.serve.stages import STAGES

log = logging.getLogger("gubernator_tpu.instance")

HEALTHY = "healthy"
UNHEALTHY = "unhealthy"


class BatchTooLargeError(ValueError):
    pass


def chain_error(r: RateLimitReq, conf: ServerConfig) -> str:
    """Validation for hierarchical quota chains (r15). Returns '' when
    the chain is acceptable, else the per-item error string."""
    if not getattr(conf, "chains", True):
        return "quota chains are disabled (GUBER_CHAINS=0)"
    max_depth = getattr(conf, "chain_max_depth", 3)
    if len(r.chain) > max_depth:
        return (
            f"chain has {len(r.chain)} ancestor levels; "
            f"GUBER_CHAIN_MAX_DEPTH allows {max_depth}"
        )
    if r.behavior == Behavior.GLOBAL:
        # GLOBAL's replica/broadcast machinery is per-key; a chain must
        # debit all levels atomically on one owner — incompatible
        return "behavior GLOBAL is incompatible with a quota chain"
    for lv in r.chain:
        if not lv.unique_key:
            return "chain level 'unique_key' cannot be empty"
    return ""


class Instance:
    def __init__(self, conf: ServerConfig, backend):
        self.conf = conf
        self.backend = backend
        self.batcher = DeviceBatcher(
            backend,
            batch_wait=conf.device_batch_wait,
            batch_limit=conf.device_batch_limit,
            fetch_depth=getattr(conf, "device_fetch_depth", None),
            deep_batch=getattr(conf, "device_deep_batch", False),
            prep_at_arrival=getattr(conf, "prep_at_arrival", None),
            prep_threads=getattr(conf, "prep_threads", None) or None,
        )
        self.global_mgr = GlobalManager(conf.behaviors, self)
        # distributed tracing (r16, serve/tracing.py): per-instance so
        # an in-process LocalCluster keeps one flight recorder per
        # node. Disabled by default (GUBER_TRACE_SAMPLE=0,
        # GUBER_TRACE_SLOW_MS=0) — every instrumented site then pays
        # one branch and nothing allocates.
        self.tracer = tracing.Tracer(
            sample=getattr(conf, "trace_sample", 0.0),
            slow_ms=getattr(conf, "trace_slow_ms", 0.0),
            capacity=getattr(conf, "trace_buffer", 256),
        )
        self.picker = ConsistentHashPicker()
        self.health = HealthCheckResp(status=HEALTHY, peer_count=0)
        self.traffic = TrafficStats()
        # over-limit shed cache (r10, serve/shedcache.py): host-side
        # answers for frozen token-bucket refusals, consulted before
        # anything enqueues toward the device. Shared with the edge
        # bridge, which screens its array frames against the same
        # cache. None = disabled (GUBER_SHED_CACHE=0 or a zero bound).
        shed_keys = getattr(conf, "shed_cache_keys", 0)
        if getattr(conf, "shed_cache", False) and shed_keys > 0:
            from gubernator_tpu.serve.shedcache import ShedCache

            self.shed = ShedCache(
                shed_keys,
                generation_fn=getattr(backend, "shed_generation", None),
            )
        else:
            self.shed = None
        # bucket replication (r11, serve/replication.py): owned windows
        # snapshot to each key's ring successor so a killed owner's
        # quota state survives takeover. OFF by default
        # (GUBER_REPLICATION=0); requires the backend's non-mutating
        # snapshot surface — refused loudly at boot otherwise.
        if getattr(conf, "replication", False):
            if getattr(backend, "snapshot_read", None) is None:
                raise ValueError(
                    "GUBER_REPLICATION=1 needs a backend with a "
                    "non-mutating snapshot_read surface (exact/tpu); "
                    f"backend '{conf.backend}' does not expose one"
                )
            from gubernator_tpu.serve.replication import (
                ReplicationManager,
            )

            self.repl = ReplicationManager(conf, self)
        else:
            self.repl = None
        # elastic ring rescale (r17, serve/rescale.py): planned state
        # handoff on every membership change — moved keys' windows ship
        # to their new ring owners, with a bounded double-serve window
        # and LWW reconcile, so deploys and autoscaling never cause
        # quota amnesia. OFF by default (GUBER_RESCALE=0); needs the
        # same non-mutating snapshot surface as replication.
        if getattr(conf, "rescale", False):
            if getattr(backend, "snapshot_read", None) is None:
                raise ValueError(
                    "GUBER_RESCALE=1 needs a backend with a "
                    "non-mutating snapshot_read surface (exact/tpu/"
                    f"mesh); backend '{conf.backend}' does not expose "
                    "one"
                )
            from gubernator_tpu.serve.rescale import RescaleManager

            self.rescale = RescaleManager(conf, self)
        else:
            self.rescale = None
        # cluster-wide checkpoint/restore (r19, serve/checkpoint.py):
        # periodic quota-state checkpoints to local disk + boot-time
        # warm restore, so a FULL-fleet restart (power event, blue-
        # green cutover) never causes quota amnesia. Enabled by a
        # non-empty GUBER_CHECKPOINT_DIR (disk) and/or
        # GUBER_CHECKPOINT_EXPORT_PEERS (blue-green import stream);
        # needs the same non-mutating snapshot surface as replication.
        if getattr(conf, "checkpoint_dir", "") or getattr(
            conf, "checkpoint_export_peers", ()
        ):
            if getattr(backend, "snapshot_read", None) is None:
                raise ValueError(
                    "GUBER_CHECKPOINT_DIR / "
                    "GUBER_CHECKPOINT_EXPORT_PEERS need a backend "
                    "with a non-mutating snapshot_read surface "
                    f"(exact/tpu/mesh); backend '{conf.backend}' does "
                    "not expose one"
                )
            from gubernator_tpu.serve.checkpoint import (
                CheckpointManager,
            )

            self.checkpoint = CheckpointManager(conf, self)
        else:
            self.checkpoint = None
        # sketch-tier promoter (r13, serve/promoter.py): streaming
        # SpaceSaving top-K over dispatched key hashes; hot sketch-tier
        # keys migrate into exact buckets on a flush-tick cadence, and
        # over-limit candidates seed the shed cache. Only constructed
        # when the backend actually carries the count-min tier.
        if getattr(conf, "sketch", False) and getattr(
            backend, "sketch_enabled", False
        ):
            from gubernator_tpu.serve.promoter import SketchPromoter

            self.promoter = SketchPromoter(conf, self)
        else:
            self.promoter = None

    def start(self) -> None:
        self.batcher.start()
        self.global_mgr.start()
        if self.repl is not None:
            self.repl.start()
        if self.rescale is not None:
            self.rescale.start()
        if self.checkpoint is not None:
            self.checkpoint.start()
        if self.promoter is not None:
            self.promoter.start()

    async def stop(self) -> None:
        if self.promoter is not None:
            await self.promoter.stop()
        if self.checkpoint is not None:
            await self.checkpoint.stop()
        if self.rescale is not None:
            await self.rescale.stop()
        if self.repl is not None:
            await self.repl.stop()
        await self.global_mgr.stop()
        await self.batcher.stop()
        for peer in self.picker.peers():
            await peer.close()

    # -- public API (gubernator.go:75-169) ----------------------------------

    async def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        stage_frame: bool = False,
    ) -> List[RateLimitResp]:
        """`stage_frame=True` (edge bridge string path only) marks the
        local device group as one edge frame's work for the per-frame
        stage clock; direct gRPC/HTTP/peer callers stay unattributed so
        frame coverage keeps its denominator (serve/stages.py)."""
        if len(reqs) > MAX_BATCH_SIZE:
            raise BatchTooLargeError(
                f"Requests.RateLimits list too large; max size is "
                f"'{MAX_BATCH_SIZE}'"
            )

        out: List[Optional[RateLimitResp]] = [None] * len(reqs)
        local: List[Tuple[int, RateLimitReq, bool]] = []  # idx, req, gnp
        forwards: List[Tuple[int, RateLimitReq, PeerClient]] = []
        t_route0 = time.monotonic()

        # validation pass first so the whole batch's fingerprints hash
        # in ONE native call — the routing pass below consults the
        # over-limit shed cache with them, and the response hooks use
        # them to populate it (fps: out-index -> fingerprint)
        valid: List[Tuple[int, RateLimitReq, str]] = []
        for i, r in enumerate(reqs):
            if not r.unique_key:
                out[i] = RateLimitResp(
                    error="field 'unique_key' cannot be empty"
                )
                continue
            if not r.name:
                out[i] = RateLimitResp(
                    error="field 'namespace' cannot be empty"
                )
                continue
            if r.chain:
                err = chain_error(r, self.conf)
                if err:
                    out[i] = RateLimitResp(error=err)
                    continue
            valid.append((i, r, r.hash_key()))

        hashes = (
            slot_hash_batch([k for _, _, k in valid]) if valid else None
        )
        shed = self.shed
        if shed is not None:
            shed.refresh_generation()
        repl = self.repl
        resc = self.rescale
        ckpt = self.checkpoint
        # takeover/handoff seeds (r11/r17/r19): owned first touches
        # whose key has a replicated standby snapshot, a pending
        # rescale handoff, or a parked checkpoint import install it
        # BEFORE deciding
        seeds: List[Tuple[int, str, object]] = []
        fps = {}

        chain_local: List[Tuple[int, RateLimitReq]] = []
        for j, (i, r, key) in enumerate(valid):
            h = int(hashes[j])
            fps[i] = h
            try:
                # chained requests route by the chain HEAD's key so one
                # owner debits the whole chain atomically (r15)
                peer = self.get_peer(
                    r.routing_key() if r.chain else key
                )
            except Exception as e:
                out[i] = RateLimitResp(
                    error=(
                        f"while finding peer that owns rate limit "
                        f"'{key}' - '{e}'"
                    )
                )
                continue
            if r.chain:
                # shed cache bypassed for chains (r15 audit): a cached
                # LEAF verdict cannot speak for parent levels, and a
                # collapsed chain response must never populate a
                # leaf-fingerprint entry (observe calls below are
                # likewise chain-gated)
                if peer.is_owner:
                    chain_local.append((i, r))
                else:
                    forwards.append((i, r, peer))
                continue
            # over-limit shed screen (serve/shedcache.py): a cached
            # frozen refusal answers here — no batcher, no forward RPC.
            # GLOBAL side effects are preserved exactly as the
            # non-shed path would produce them: non-owners still
            # aggregate the hit toward the owner, owners still queue
            # the status broadcast (the broadcast loop's peeks carry
            # hits=0 and therefore always bypass the shed).
            verdict = (
                shed.lookup_resp(h, r) if shed is not None else None
            )
            if not peer.is_owner and resc is not None and (
                resc._transition is not None
                and r.behavior != Behavior.GLOBAL
            ):
                # double-serve routing (r17): while a ring change's
                # window is open, MOVED keys keep forwarding to their
                # old (warm) owner — or serve locally when that is this
                # node — until the new owner has installed the handoff.
                # GLOBAL items keep their replica-answer semantics;
                # chained requests never reach here (the chain branch
                # above routed and continued)
                ov = resc.route_override(key, r)
                if ov is not None:
                    peer = ov
            if peer.is_owner:
                if repl is not None:
                    repl.queue_dirty(r)
                if resc is not None:
                    resc.note_owned(r)
                if ckpt is not None:
                    ckpt.note_owned(r)
                if verdict is not None:
                    if r.behavior == Behavior.GLOBAL:
                        self.global_mgr.queue_update(r)
                    out[i] = verdict
                    continue
                s = repl.standby_pop(key) if repl is not None else None
                if s is None and resc is not None:
                    s = resc.pending_pop(key)
                if s is None and ckpt is not None:
                    s = ckpt.pending_pop(key)
                if s is not None:
                    seeds.append((i, key, s))
                local.append((i, r, False))
            elif r.behavior == Behavior.GLOBAL:
                # replica answer + async hit forward (gubernator.go:133-140)
                self.global_mgr.queue_hit(r)
                if verdict is not None:
                    out[i] = verdict
                    continue
                local.append((i, r, True))
            else:
                if verdict is not None:
                    # parity with forward(): forwarded answers carry
                    # the owner tag, shed or not
                    verdict.metadata["owner"] = peer.host
                    out[i] = verdict
                    continue
                forwards.append((i, r, peer))

        if valid:
            self.traffic.observe([k for _, _, k in valid], hashes)
        # instance-side routing overhead (validation + ring lookups +
        # shed screen + sketches), attributed apart from the batcher's
        # queue/device stages — the string path's own cost in the
        # stage profile
        STAGES.add("instance_route", time.monotonic() - t_route0)

        async def forward(i, r, peer):
            key = r.hash_key()
            tr = tracing.active()
            t_fwd = time.monotonic() if tr is not None else 0.0
            try:
                resp = await peer.get_peer_rate_limit(r)
                if tr is not None:
                    tr.add_span(
                        "peer_forward", start=t_fwd,
                        peer=peer.host, items=1,
                    )
                resp.metadata["owner"] = peer.host
                if shed is not None and not r.chain:
                    shed.observe_resps([fps[i]], [r], [resp])
            except Exception as e:
                taken = await self._takeover_fallback([(i, r)], peer, e)
                if taken is not None:
                    out[i] = taken[0]
                    return
                degraded = await self._degraded_fallback([(i, r)], peer, e)
                if degraded is not None:
                    out[i] = degraded[0]
                    return
                resp = RateLimitResp(
                    error=(
                        f"while fetching rate limit '{key}' from peer - '{e}'"
                    )
                )
            out[i] = resp

        async def forward_group(peer, items):
            # owner batching (r7): the whole per-owner group rides ONE
            # queue entry + ONE future through the peer's micro-batch
            # flusher — a 1000-item RPC forwarding two thirds of its
            # items no longer pays per-item future/enqueue overhead
            # (the slow-path funnel the edge cluster bench exposed).
            # Failures keep per-item error parity with forward().
            tr = tracing.active()
            t_fwd = time.monotonic() if tr is not None else 0.0
            try:
                resps = await peer.get_peer_rate_limits_grouped(
                    [r for _, r in items]
                )
                if tr is not None:
                    # the hop span a sampled request's timeline needs:
                    # schedule -> peer response, annotated with the
                    # owner host (r16)
                    tr.add_span(
                        "peer_forward", start=t_fwd,
                        peer=peer.host, items=len(items),
                    )
                for (i, r), resp in zip(items, resps):
                    resp.metadata["owner"] = peer.host
                    out[i] = resp
                if shed is not None:
                    plain = [
                        (i, r, resp)
                        for (i, r), resp in zip(items, resps)
                        if not r.chain  # collapsed chain responses
                        # must never seed leaf-fingerprint entries
                    ]
                    if plain:
                        shed.observe_resps(
                            [fps[i] for i, _, _ in plain],
                            [r for _, r, _ in plain],
                            [resp for _, _, resp in plain],
                        )
            except Exception as e:
                taken = await self._takeover_fallback(items, peer, e)
                if taken is not None:
                    for (i, _), resp in zip(items, taken):
                        out[i] = resp
                    return
                degraded = await self._degraded_fallback(items, peer, e)
                if degraded is not None:
                    for (i, _), resp in zip(items, degraded):
                        out[i] = resp
                    return
                for i, r in items:
                    out[i] = RateLimitResp(
                        error=(
                            f"while fetching rate limit "
                            f"'{r.hash_key()}' from peer - '{e}'"
                        )
                    )

        # group BATCHING forwards per owner; NO_BATCHING keeps its
        # direct-unary contract (reference peers.go:73-90)
        grouped: dict = {}
        singles = []
        for i, r, peer in forwards:
            if r.behavior == Behavior.NO_BATCHING:
                singles.append((i, r, peer))
            else:
                grouped.setdefault(peer, []).append((i, r))

        # schedule forwards immediately so their RPCs overlap the local
        # device batch instead of queueing behind it
        tasks = [
            asyncio.ensure_future(forward(i, r, p)) for i, r, p in singles
        ]
        tasks += [
            asyncio.ensure_future(forward_group(p, items))
            for p, items in grouped.items()
        ]

        if chain_local:
            # owned chains ride the batcher's dedicated chain lane,
            # overlapped with the plain local batch below
            # frame attribution (r16 audit): a chain-only frame's stage
            # span rides the chain lane; a frame with BOTH plain and
            # chained local work flags only the plain lane (the two
            # lanes overlap in wall time, and one frame must contribute
            # one batch_queue/device span — the r7 chunk convention)
            chain_frame = stage_frame and not local

            async def chain_decide(items):
                try:
                    resps = await self.batcher.decide_chain(
                        [r for _, r in items], frame=chain_frame
                    )
                    for (i, _), resp in zip(items, resps):
                        out[i] = resp
                except Exception as e:
                    for i, r in items:
                        out[i] = RateLimitResp(
                            error=(
                                f"while applying chained rate limit "
                                f"for '{r.hash_key()}' - '{e}'"
                            )
                        )

            tasks.append(
                asyncio.ensure_future(chain_decide(chain_local))
            )

        seeded_idx: List[int] = []
        if seeds:
            # install the standby snapshots BEFORE the batch decides;
            # the awaited install funnels through the same flusher
            # queue as the decide, so ordering is guaranteed and the
            # first owned touch continues the dead owner's window
            seeded_idx = await self._seed_standby(seeds)
        if local:
            local_reqs = [r for _, r, _ in local]
            gnp = [g for _, _, g in local]
            try:
                resps = await self.decide_local(
                    local_reqs, gnp, frame=stage_frame
                )
                for (i, _, _), resp in zip(local, resps):
                    out[i] = resp
                if shed is not None:
                    shed.observe_resps(
                        [fps[i] for i, _, _ in local], local_reqs, resps
                    )
            except Exception as e:
                for i, r, _ in local:
                    out[i] = RateLimitResp(
                        error=(
                            f"while applying rate limit for "
                            f"'{r.hash_key()}' - '{e}'"
                        )
                    )
        if tasks:
            await asyncio.gather(*tasks)
        for i in seeded_idx:
            resp = out[i]
            if resp is not None and not resp.error:
                resp.metadata["replicated"] = "true"
        return [r if r is not None else RateLimitResp() for r in out]

    async def _install_seeds(self, seeds) -> bool:
        """Install popped standby snapshots ((key, Snapshot) pairs)
        into the local store through the UpdatePeerGlobals machinery —
        which also purges shed-cache entries for those keys, keeping
        the r10 invalidation rules intact. Returns False on install
        failure: the caller's decide then proceeds un-seeded (a fresh
        window — amnesia for those keys, not an outage)."""
        from gubernator_tpu.serve.replication import snapshot_resp

        try:
            await self.update_peer_globals(
                [(k, snapshot_resp(s)) for k, s in seeds]
            )
        except Exception as e:
            log.warning("standby seed install failed: %s", e)
            return False
        if self.repl is not None:
            self.repl.note_seeded(seeds)
        if self.rescale is not None:
            # a seeded window is live local state this node must hand
            # off on the NEXT ring change, even if only peeked here
            self.rescale.note_seeded(seeds)
        if self.checkpoint is not None:
            # likewise live state the next checkpoint must capture
            self.checkpoint.note_seeded(seeds)
        return True

    async def _seed_standby(self, seeds) -> List[int]:
        """(out_index, key, Snapshot) triples -> installed; returns the
        out-indices seeded (their responses get
        metadata["replicated"]="true")."""
        if not await self._install_seeds([(k, s) for _, k, s in seeds]):
            return []
        return [i for i, _, _ in seeds]

    async def _takeover_local(self, reqs: Sequence[RateLimitReq]):
        """Decide items locally in a dead owner's stead (this node is
        their ring successor): seed first touches from the standby
        table, and track every key for the reconcile handback once the
        owner returns."""
        repl = self.repl
        seeds = []
        for r in reqs:
            repl.mark_taken(r)
            s = repl.standby_pop(r.hash_key())
            if s is not None:
                seeds.append((r.hash_key(), s))
        if seeds:
            await self._install_seeds(seeds)
        return await self.decide_local(reqs, [False] * len(reqs))

    async def _takeover_fallback(self, items, peer, exc):
        """Successor takeover (GUBER_REPLICATION=1): a forward that
        failed because its owner is unreachable (breaker open — which
        fails fast, so this is usually cheap — retries exhausted, or
        deadline) is routed to each key's ring SUCCESSOR: the node the
        consistent hash elects on owner removal, and the one holding
        the replicated standby snapshots. Served locally when the
        successor is this node, via one forwarded group otherwise (the
        remote successor seeds from its own standby table in
        get_peer_rate_limits). Responses carry metadata owner=successor
        and replicated="true". Returns the responses or None
        (replication off / no distinct successor / successor also
        unreachable — the caller then falls through to degraded mode
        and per-item errors, the r8 ladder)."""
        repl = self.repl
        if repl is None:
            return None
        out: List[Optional[RateLimitResp]] = [None] * len(items)
        by_succ: dict = {}
        for j, (_, r) in enumerate(items):
            if r.chain:
                # chains are outside the replication scope (documented
                # r15 limit): no standby snapshot holds level state,
                # and deciding only the leaf here would silently skip
                # every ancestor quota — refuse honestly instead
                out[j] = RateLimitResp(
                    error=(
                        f"owner '{peer.host}' unreachable and chained "
                        f"requests are outside the takeover scope "
                        f"(chain levels are not replicated) - '{exc}'"
                    )
                )
                continue
            try:
                succ = self.picker.get_successor(r.hash_key())
            except Exception:
                succ = None
            if succ is None or succ.host == peer.host:
                return None
            by_succ.setdefault(succ, []).append(j)
        try:
            for succ, idxs in by_succ.items():
                reqs = [items[j][1] for j in idxs]
                if succ.is_owner:
                    resps = await self._takeover_local(reqs)
                else:
                    resps = await succ.get_peer_rate_limits_grouped(reqs)
                for j, resp in zip(idxs, resps):
                    if not resp.error:
                        resp.metadata["owner"] = succ.host
                        resp.metadata["replicated"] = "true"
                    out[j] = resp
        except Exception as e2:
            log.warning(
                "takeover route for %d item(s) failed (owner '%s': %s; "
                "successor: %s)", len(items), peer.host, exc, e2,
            )
            return None
        return out

    async def _degraded_fallback(self, items, peer, exc):
        """Degraded mode (GUBER_DEGRADED_LOCAL=1): a forward that failed
        with its owner unreachable is answered from the LOCAL store,
        stamped metadata["degraded"]="true" — availability over global
        accuracy, the reference's documented eventual-consistency
        stance, opt-in. `items`: [(out_index, req)]. Returns the
        responses or None (mode off / local decide itself failed →
        caller surfaces the original per-item error)."""
        if not getattr(self.conf, "degraded_local", False):
            return None
        try:
            # chained items keep FULL chain semantics against the
            # local store (every level consulted, no-partial-debit)
            # via the chain lane — degrading a chain to a leaf-only
            # decide would silently skip its ancestor quotas (r15)
            chained = [j for j, (_, r) in enumerate(items) if r.chain]
            if chained:
                resps = [None] * len(items)
                cresps = await self.batcher.decide_chain(
                    [items[j][1] for j in chained]
                )
                for j, resp in zip(chained, cresps):
                    resps[j] = resp
                plain = [
                    j for j, (_, r) in enumerate(items) if not r.chain
                ]
                if plain:
                    presps = await self.decide_local(
                        [items[j][1] for j in plain],
                        [False] * len(plain),
                    )
                    for j, resp in zip(plain, presps):
                        resps[j] = resp
            else:
                resps = await self.decide_local(
                    [r for _, r in items], [False] * len(items)
                )
        except Exception:
            return None
        for resp in resps:
            resp.metadata["degraded"] = "true"
            resp.metadata["owner"] = peer.host
        log.warning(
            "degraded mode: answered %d item(s) locally, owner '%s' "
            "unreachable (%s)", len(items), peer.host, exc,
        )
        try:
            metrics.DEGRADED_RESPONSES.inc(len(items))
        except Exception:  # pragma: no cover - defensive
            pass
        return resps

    async def decide_local(
        self,
        reqs: Sequence[RateLimitReq],
        gnp: Sequence[bool],
        frame: bool = False,
    ) -> List[RateLimitResp]:
        """Run requests through the device batcher; owned GLOBAL keys are
        queued for status broadcast (gubernator.go:240-242)."""
        for r, is_gnp in zip(reqs, gnp):
            if r.behavior == Behavior.GLOBAL and not is_gnp:
                self.global_mgr.queue_update(r)
        return await self.batcher.decide(reqs, gnp, frame=frame)

    async def apply_global_hits_local(
        self, reqs: Sequence[RateLimitReq]
    ) -> None:
        """Mesh-native GLOBAL flush target (r20): apply aggregated gossip
        hits for keys THIS node owns in one in-mesh collective
        (backend.apply_global_hits_reqs on the serialized submit thread),
        then queue each key for the owner status broadcast — the same
        post-charge gossip a remote owner's decide_local would have
        queued, so off-mesh ring peers still learn the new remaining.
        Backends without the collective surface fall back to the plain
        local decide path."""
        fn = getattr(self.backend, "apply_global_hits_reqs", None)
        if fn is None:
            await self.decide_local(reqs, [False] * len(reqs))
            return
        await self.batcher.run_serialized(fn, list(reqs))
        for r in reqs:
            self.global_mgr.queue_update(r)

    # -- peer-facing API ----------------------------------------------------

    async def get_peer_rate_limits(
        self, reqs: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        if len(reqs) > MAX_BATCH_SIZE:
            raise BatchTooLargeError(
                f"'PeerRequest.rate_limits' list too large; max size is "
                f"'{MAX_BATCH_SIZE}'"
            )
        try:
            if FAULTS.enabled:
                # owner-side injection point: a chaos spec can make THIS
                # node a slow/failing owner for its peers' forwards
                await FAULTS.inject("peer_serve")
            if self.repl is not None or self.rescale is not None:
                await self._peer_serve_replication(reqs)
            chained_idx = [i for i, r in enumerate(reqs) if r.chain]
            if chained_idx:
                # forwarded chains decide on THIS node's chain lane
                # (the forwarder routed them here by the chain head);
                # shed screen and population are chain-bypassed.
                # Validation runs with the RECEIVING node's config —
                # the forwarder validated too, but the kill switch,
                # the depth bound (the device-row expansion cap a
                # hostile peer could otherwise demand: the proto
                # repeated field has no wire-level limit), and the
                # GLOBAL check must hold at every door
                out_c: List[Optional[RateLimitResp]] = [None] * len(reqs)
                ok_idx = []
                for i in chained_idx:
                    err = chain_error(reqs[i], self.conf)
                    if err:
                        out_c[i] = RateLimitResp(error=err)
                    else:
                        ok_idx.append(i)
                if ok_idx:
                    cresps = await self.batcher.decide_chain(
                        [reqs[i] for i in ok_idx]
                    )
                    for i, resp in zip(ok_idx, cresps):
                        out_c[i] = resp
                plain = [
                    (i, r) for i, r in enumerate(reqs) if not r.chain
                ]
                if plain:
                    presps = await self._peer_serve_plain(
                        [r for _, r in plain]
                    )
                    for (i, _), resp in zip(plain, presps):
                        out_c[i] = resp
                return [
                    o if o is not None else RateLimitResp()
                    for o in out_c
                ]
            return await self._peer_serve_plain(reqs)
        except Exception as e:
            return [RateLimitResp(error=str(e)) for _ in reqs]

    async def _peer_serve_plain(
        self, reqs: Sequence[RateLimitReq]
    ) -> List[RateLimitResp]:
        """The owner-side decide for PLAIN (non-chained) forwarded
        batches: shed screen + device decide (the pre-r15
        get_peer_rate_limits interior)."""
        try:
            shed = self.shed
            if shed is None:
                return await self.decide_local(reqs, [False] * len(reqs))
            # owner-side shed screen: forwarded items for a frozen
            # over-limit key are answered without a device trip; the
            # residue decides normally and its responses populate the
            # cache. Forwarded GLOBAL hits keep their broadcast side
            # effect (decide_local would have queued the update).
            shed.refresh_generation()
            hashes = slot_hash_batch([r.hash_key() for r in reqs])
            out: List[Optional[RateLimitResp]] = [None] * len(reqs)
            residue: List[Tuple[int, RateLimitReq]] = []
            res_fps: List[int] = []
            for i, r in enumerate(reqs):
                verdict = shed.lookup_resp(int(hashes[i]), r)
                if verdict is not None:
                    if r.behavior == Behavior.GLOBAL:
                        self.global_mgr.queue_update(r)
                    out[i] = verdict
                else:
                    residue.append((i, r))
                    res_fps.append(int(hashes[i]))
            if residue:
                resps = await self.decide_local(
                    [r for _, r in residue], [False] * len(residue)
                )
                shed.observe_resps(
                    res_fps, [r for _, r in residue], resps
                )
                for (i, _), resp in zip(residue, resps):
                    out[i] = resp
            return [
                o if o is not None else RateLimitResp() for o in out
            ]
        except Exception as e:
            return [RateLimitResp(error=str(e)) for _ in reqs]

    async def _peer_serve_replication(
        self, reqs: Sequence[RateLimitReq]
    ) -> None:
        """Owner-side replication/rescale hooks for a forwarded batch:
        owned keys dirty the snapshot queue and the rescale tracked
        set; keys the ring says ANOTHER node owns were routed here by a
        peer's takeover fallback or by double-serve routing after a
        ring change — track them for the reconcile handback and count
        the double-serve answer; and any first touch with a standby
        snapshot or pending handoff seeds the store before the batch
        decides."""
        repl = self.repl
        resc = self.rescale
        ckpt = self.checkpoint
        seeds = []
        for r in reqs:
            if r.chain:
                # chain levels are outside the replication scope (r15
                # documented limit, like leaky): level keys are owned
                # by the chain head's ring position, not their own
                continue
            key = r.hash_key()
            try:
                own = self.get_peer(key).is_owner
            except Exception:
                own = True
            if own:
                if repl is not None:
                    repl.queue_dirty(r)
                if resc is not None:
                    resc.note_owned(r)
                if ckpt is not None:
                    ckpt.note_owned(r)
            else:
                if repl is not None:
                    repl.mark_taken(r)
                if resc is not None:
                    # the old owner answering a moved key inside its
                    # double-serve window (forwarders still route it
                    # here): counted, and re-dirtied for the
                    # end-of-window reconcile flush
                    resc.note_double_serve(r)
            s = repl.standby_pop(key) if repl is not None else None
            if s is None and resc is not None and own:
                s = resc.pending_pop(key)
            if s is None and ckpt is not None and own:
                s = ckpt.pending_pop(key)
            if s is not None:
                seeds.append((key, s))
        if seeds:
            await self._install_seeds(seeds)

    async def replicate_buckets(self, owner: str, snaps) -> None:
        """ReplicateBuckets receive path (peers.proto): file or install
        another owner's bucket snapshots. With replication on, the r11
        install handles both halves (owned -> store, others ->
        standby); with only rescale on, its install provides the same
        split against the pending handoff table. A node with both off
        accepts and ignores — knob/version skew across the fleet must
        not fail the sender."""
        if self.checkpoint is not None and (
            owner.startswith("import:") or owner.startswith("importfwd:")
        ):
            # blue-green import batch (r19): the owner marker routes it
            # to the checkpoint manager REGARDLESS of repl/rescale
            # knobs — the green fleet's import handling must not depend
            # on matching the blue fleet's replication config
            await self.checkpoint.install_import(owner, snaps)
        elif self.repl is not None:
            await self.repl.install(owner, snaps)
        elif self.rescale is not None:
            await self.rescale.install(owner, snaps)
        elif self.checkpoint is not None:
            await self.checkpoint.install(owner, snaps)

    async def update_peer_globals(
        self, updates: Sequence[Tuple[str, RateLimitResp]]
    ) -> None:
        if self.repl is not None and updates:
            # an owner broadcasting status for these keys is alive and
            # authoritative: any replicated standby snapshot for them
            # is superseded (the reconcile contract, r11)
            self.repl.standby_purge([k for k, _ in updates])
        if self.rescale is not None and updates:
            # the same supersession rule for pending handoff snapshots
            self.rescale.pending_purge([k for k, _ in updates])
        if self.checkpoint is not None and updates:
            # and for parked checkpoint-import rows
            self.checkpoint.pending_purge([k for k, _ in updates])
        if self.shed is None or not updates:
            await self.batcher.update_globals(list(updates))
            return
        # device-authoritative invalidation: an owner broadcast
        # replaced these keys' replicas, so any cached verdict for
        # them is no longer provably current (the next hit reads the
        # fresh replica and repopulates). Purge BEFORE the install
        # (stop shedding from the doomed entries immediately) and
        # AGAIN after it: an in-flight decide that resolved during the
        # install await could otherwise re-insert the PRE-install
        # verdict just after the first purge and shadow the fresh
        # replica until its old reset_time.
        hashes = slot_hash_batch([k for k, _ in updates])
        self.shed.purge(hashes)
        try:
            await self.batcher.update_globals(list(updates))
        finally:
            self.shed.purge(hashes)

    def health_check(self) -> HealthCheckResp:
        """Membership health (set_peers) merged with live breaker state:
        a peer whose circuit is open is a dialable-but-dead peer, the
        exact condition the reference's health contract (peer
        dialability) cannot see. Reported unhealthy so orchestration
        rotates traffic away while the breaker does the same per-RPC."""
        h = self.health
        # effective_state, not raw state: an idle breaker past its
        # cooldown is "half-open pending first probe", and reporting it
        # open would leave this node unhealthy forever once traffic is
        # routed away (no forwards -> no acquire -> no transition)
        open_peers = sorted(
            p.host
            for p in self.picker.peers()
            if p.breaker is not None
            and p.breaker.effective_state() == BREAKER_OPEN
        )
        if not open_peers:
            return h
        msg = "circuit open: " + ",".join(open_peers)
        if h.message:
            msg = h.message + "|" + msg
        return HealthCheckResp(
            status=UNHEALTHY, message=msg, peer_count=h.peer_count
        )

    # -- membership (gubernator.go:254-310) ---------------------------------

    async def set_peers(self, peers: Sequence[PeerInfo]) -> None:
        picker = self.picker.new()
        errs = []
        for info in peers:
            existing = self.picker.get_peer_by_host(info.address)
            if existing is not None:
                peer = existing
            else:
                peer = PeerClient(self.conf.behaviors, info.address)
            peer.is_owner = info.is_owner
            peer.mesh_local = getattr(info, "mesh_local", False)
            try:
                peer.connect()
            except Exception:
                errs.append(
                    f"failed to connect to peer '{info.address}'; "
                    f"consistent hash is incomplete"
                )
                continue
            try:
                picker.add(peer)
            except ValueError as e:
                # crc32 ring-point collision (picker.add): surface it
                # through health instead of silently splitting
                # ownership between tie-break rules (ADVICE r5 #3)
                log.error("%s", e)
                errs.append(str(e))
                # a freshly built client was already connect()ed; close
                # it or every set_peers round leaks a channel + flusher
                # task while the collision persists
                if existing is None:
                    await peer.close()
                continue

        old_hosts = {p.host for p in self.picker.peers()}
        new_hosts = {p.host for p in picker.peers()}
        removed = [
            self.picker.get_peer_by_host(h) for h in old_hosts - new_hosts
        ]

        old_picker = self.picker
        self.picker = picker
        if old_hosts != new_hosts:
            if self.rescale is not None:
                # planned handoff (r17): the flush loop diffs the old
                # ring against the new one and ships moved keys'
                # windows to their new owners; non-blocking here
                self.rescale.note_ring_change(old_picker, picker)
            if self.repl is not None:
                # r11 standby hygiene: rows whose keys this node no
                # longer succeeds (or owns) after the reshuffle could
                # seed a WRONG takeover window later — purge them now
                await self.repl.purge_unsucceeded_standby()
        self.health = HealthCheckResp(
            status=UNHEALTHY if errs else HEALTHY,
            message="|".join(errs),
            peer_count=picker.size(),
        )
        # Unlike the reference (which leaks old clients, gubernator.go:276),
        # departed peers' channels are closed once replaced.
        for peer in removed:
            if peer is not None:
                await peer.close()
        log.info("peers updated: %s", [p.address for p in peers])

    def get_peer(self, key: str) -> PeerClient:
        return self.picker.get(key)

    def peer_list(self) -> List[PeerClient]:
        return self.picker.peers()
