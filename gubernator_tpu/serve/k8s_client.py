"""Vendored minimal Kubernetes Endpoints client (no kubernetes package).

The reference watches the Endpoints API through client-go informers
(reference kubernetes.go:56-157). This image has no `kubernetes` python
package, so the repo vendors the one-resource slice K8sPool needs — LIST
and WATCH of v1 Endpoints with a label selector — over the plain
Kubernetes REST API via stdlib http.client (bearer token; the watch is a
line-delimited JSON event stream, chunked decoding handled by
http.client transparently).

Surface mirrors the kubernetes library's exactly where K8sPool touches
it: `api.list_namespaced_endpoints(ns, label_selector=)`,
`watch.stream(fn, ns, label_selector=)` yielding
`{"type": ..., "object": <endpoints>}` with `.subsets[].addresses[].ip`,
and `watch.stop()` — so the pool runs identically on either
implementation.
"""

from __future__ import annotations

import http.client
import json
import logging
import ssl
import threading
import urllib.parse
from typing import Optional

log = logging.getLogger("gubernator_tpu.k8s")

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# bounds the stranded-thread window when stop() races a connect: the
# watch's socket itself runs with NO timeout once established
_CONNECT_TIMEOUT_S = 10.0


class _Address:
    def __init__(self, d: dict):
        self.ip = d.get("ip", "")


class _Subset:
    def __init__(self, d: dict):
        self.addresses = [_Address(a) for a in d.get("addresses") or []]


class _Endpoints:
    """Shape-compatible stand-in for V1Endpoints (the pool reads only
    .subsets[].addresses[].ip)."""

    def __init__(self, d: dict):
        self.subsets = [_Subset(s) for s in d.get("subsets") or []]
        self.metadata = d.get("metadata", {})


class _EndpointsList:
    def __init__(self, items):
        self.items = items


class VendoredK8sApi:
    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        timeout: float = 10.0,
    ):
        """Defaults load the in-cluster config the way client libraries
        do: KUBERNETES_SERVICE_HOST/PORT env + the mounted
        serviceaccount token/CA. Tests inject base_url (plain http)."""
        import os

        self._token_path: Optional[str] = None
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in a kubernetes cluster "
                    "(KUBERNETES_SERVICE_HOST unset) and no base_url given"
                )
            base_url = f"https://{host}:{port}"
            if token is None:
                # remember the PATH, not the value: bound service-account
                # tokens rotate (~1h on modern clusters) and a stale
                # bearer would 401 every reconnect forever
                self._token_path = f"{_SA_DIR}/token"
            if ca_cert is None:
                ca_cert = f"{_SA_DIR}/ca.crt"
        self.token = token
        self.timeout = timeout
        u = urllib.parse.urlparse(base_url.rstrip("/"))
        self._host = u.hostname
        self._port = u.port or (443 if u.scheme == "https" else 80)
        self._ssl: Optional[ssl.SSLContext] = None
        if u.scheme == "https":
            self._ssl = ssl.create_default_context(cafile=ca_cert)

    # -- low-level ----------------------------------------------------------

    def _open(self, path: str, timeout: Optional[float]):
        """One GET; returns (conn, resp). `timeout=None` means a true
        no-timeout socket (watches) — the connect itself is still
        bounded so teardown never waits on an unreachable apiserver."""
        if self._ssl is not None:
            conn = http.client.HTTPSConnection(
                self._host, self._port, context=self._ssl,
                timeout=_CONNECT_TIMEOUT_S,
            )
        else:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=_CONNECT_TIMEOUT_S
            )
        headers = {"Accept": "application/json"}
        token = self.token
        if self._token_path is not None:
            with open(self._token_path) as f:
                token = f.read().strip()  # fresh per request (rotation)
        if token:
            headers["Authorization"] = f"Bearer {token}"
        try:
            conn.request("GET", path, headers=headers)
            resp = conn.getresponse()
            # lift the connect deadline off the established socket;
            # sock.settimeout(None) is the ONLY way to get an unbounded
            # watch (a falsy-None passed through `or` defaults would
            # silently reimpose a deadline — the bug this replaces)
            conn.sock.settimeout(timeout)
        except Exception:
            conn.close()
            raise
        return conn, resp

    @staticmethod
    def _endpoints_path(namespace: str, label_selector: str,
                        watch: bool) -> str:
        q = {}
        if label_selector:
            q["labelSelector"] = label_selector
        if watch:
            q["watch"] = "true"
        qs = ("?" + urllib.parse.urlencode(q)) if q else ""
        return f"/api/v1/namespaces/{namespace}/endpoints{qs}"

    # -- kubernetes-library-compatible surface ------------------------------

    def list_namespaced_endpoints(
        self, namespace: str, label_selector: str = ""
    ) -> _EndpointsList:
        conn, resp = self._open(
            self._endpoints_path(namespace, label_selector, False),
            self.timeout,
        )
        try:
            body = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"endpoints list failed: HTTP {resp.status}: "
                    f"{body[:200]!r}"
                )
            doc = json.loads(body)
            return _EndpointsList(
                [_Endpoints(i) for i in doc.get("items", [])]
            )
        finally:
            conn.close()

    def open_endpoints_watch(
        self, namespace: str, label_selector: str = ""
    ):
        """EAGERLY open the watch request; returns (resp, close_fn).
        `resp` is the live http.client response (chunked decoding
        transparent; readline() yields one JSON event per line).
        Kubernetes synthesizes ADDED events for current state on an
        rv-less watch — the informer-style initial LIST the pool needs.
        close_fn is thread-safe and unblocks a parked readline()."""
        conn, resp = self._open(
            self._endpoints_path(namespace, label_selector, True), None
        )
        if resp.status != 200:
            body = resp.read(200)
            conn.close()
            raise RuntimeError(
                f"watch failed: HTTP {resp.status}: {body!r}"
            )

        def close():
            # shutdown-then-close from another thread makes a blocked
            # readline() return/raise instead of waiting forever
            try:
                import socket as _socket

                if conn.sock is not None:
                    conn.sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()

        return resp, close


class VendoredK8sWatch:
    """kubernetes.watch.Watch-shaped wrapper over the vendored API."""

    def __init__(self):
        self._lock = threading.Lock()
        self._close = None
        self._stopped = False

    def stream(self, list_fn, namespace: str, label_selector: str = ""):
        """NOT a generator function: the watch connection opens eagerly
        HERE, before any iteration, so a stop() racing startup always
        has a socket to close (a lazy generator would park the worker
        thread in a long-poll nothing can reach)."""
        # list_fn is the bound api.list_namespaced_endpoints — recover
        # the api object the way the kubernetes library dispatches on
        # the function identity
        api: VendoredK8sApi = list_fn.__self__
        with self._lock:
            if self._stopped:
                return iter(())
        resp, close = api.open_endpoints_watch(
            namespace, label_selector=label_selector
        )
        with self._lock:
            self._close = close
            if self._stopped:  # stop() landed during the connect
                close()
                return iter(())

        def events():
            try:
                while True:
                    try:
                        raw = resp.readline()
                    except (
                        OSError,
                        ValueError,
                        AttributeError,  # resp.fp=None after conn.close()
                        http.client.HTTPException,
                    ):
                        return  # closed underneath us (stop())
                    if not raw:
                        return
                    if not raw.strip():
                        continue
                    try:
                        ev = json.loads(raw)
                    except ValueError:
                        log.warning(
                            "k8s watch: undecodable event line; skipping"
                        )
                        continue
                    with self._lock:
                        if self._stopped:
                            return
                    if ev.get("type") == "ERROR":
                        # a Status object, not Endpoints (expired watch,
                        # internal error). The kubernetes library raises
                        # here too: yielding it would push an EMPTY peer
                        # list and un-own every key until the next real
                        # event. Raising routes into the pool's
                        # retry/relist path instead.
                        raise RuntimeError(
                            "k8s watch ERROR event: "
                            f"{ev.get('object', {})!r}"
                        )
                    yield {
                        "type": ev.get("type", ""),
                        "object": _Endpoints(ev.get("object", {})),
                    }
            finally:
                close()

        return events()

    def stop(self):
        with self._lock:
            self._stopped = True
            close = self._close
        if close is not None:
            close()
