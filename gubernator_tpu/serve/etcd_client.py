"""Vendored minimal etcd v3 client over grpcio.

The reference registers membership through etcd clientv3
(reference etcd.go:36-316: lease grant + keepalive, prefix put/watch).
This image has no `etcd3` python package, so the repo vendors the thin
slice it needs — KV Put/Range/DeleteRange, Lease Grant/Revoke/KeepAlive,
Watch — over the already-present grpcio stack and the vendored etcd
protos (api/proto/etcd_rpc.proto, field-number-exact with real etcd).

The public surface is deliberately etcd3-library-compatible (the subset
serve/discovery.py's EtcdPool consumes: `lease()`, `put()`,
`get_prefix()`, `watch_prefix()`, `delete()`), so the pool runs
identically on either implementation and the discovery contract tests
(tests/_discovery_contract.py) pin both from each side.

Sync client (discovery drives it from worker threads via to_thread,
matching the etcd3 library's model).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Iterator, List, Optional, Tuple

import grpc

from gubernator_tpu.api.proto.gen import etcd_rpc_pb2 as rpc

log = logging.getLogger("gubernator_tpu.etcd")

_KV = "etcdserverpb.KV"
_LEASE = "etcdserverpb.Lease"
_WATCH = "etcdserverpb.Watch"


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd prefix query convention: range_end = prefix with its last
    byte incremented (0xff bytes roll into the next position; an
    all-0xff prefix scans to the end of keyspace, '\\0')."""
    end = bytearray(prefix)
    while end:
        if end[-1] < 0xFF:
            end[-1] += 1
            return bytes(end)
        end.pop()
    return b"\0"


class VendoredLease:
    """Mirror of etcd3.Lease: holds the ID, refreshes via one-shot
    keepalive calls."""

    def __init__(self, client: "VendoredEtcdClient", lease_id: int,
                 ttl: int):
        self._client = client
        self.id = lease_id
        self.ttl = ttl

    def refresh(self) -> None:
        resp = self._client._keepalive_once(self.id)
        if resp.TTL <= 0:
            raise RuntimeError(f"lease {self.id} expired (TTL<=0)")

    def revoke(self) -> None:
        self._client._lease_revoke(self.id)


class _KVMeta:
    """Shape-compatible stand-in for etcd3's KVMetadata (the pool only
    reads .key)."""

    def __init__(self, kv):
        self.key = kv.key
        self.create_revision = kv.create_revision
        self.mod_revision = kv.mod_revision
        self.version = kv.version
        self.lease_id = kv.lease


class VendoredEtcdClient:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 2379,
        ca_cert: Optional[str] = None,
        cert_cert: Optional[str] = None,
        cert_key: Optional[str] = None,
        timeout: float = 10.0,
    ):
        target = f"{host}:{port}"
        if ca_cert or cert_cert:
            def read(path):
                with open(path, "rb") as f:
                    return f.read()

            creds = grpc.ssl_channel_credentials(
                root_certificates=read(ca_cert) if ca_cert else None,
                private_key=read(cert_key) if cert_key else None,
                certificate_chain=read(cert_cert) if cert_cert else None,
            )
            self._chan = grpc.secure_channel(target, creds)
        else:
            self._chan = grpc.insecure_channel(target)
        self._timeout = timeout
        u = self._chan.unary_unary
        self._put = u(
            f"/{_KV}/Put",
            request_serializer=rpc.PutRequest.SerializeToString,
            response_deserializer=rpc.PutResponse.FromString,
        )
        self._range = u(
            f"/{_KV}/Range",
            request_serializer=rpc.RangeRequest.SerializeToString,
            response_deserializer=rpc.RangeResponse.FromString,
        )
        self._delete_range = u(
            f"/{_KV}/DeleteRange",
            request_serializer=rpc.DeleteRangeRequest.SerializeToString,
            response_deserializer=rpc.DeleteRangeResponse.FromString,
        )
        self._lease_grant = u(
            f"/{_LEASE}/LeaseGrant",
            request_serializer=rpc.LeaseGrantRequest.SerializeToString,
            response_deserializer=rpc.LeaseGrantResponse.FromString,
        )
        self._lease_revoke_rpc = u(
            f"/{_LEASE}/LeaseRevoke",
            request_serializer=rpc.LeaseRevokeRequest.SerializeToString,
            response_deserializer=rpc.LeaseRevokeResponse.FromString,
        )
        self._keepalive_stream = self._chan.stream_stream(
            f"/{_LEASE}/LeaseKeepAlive",
            request_serializer=rpc.LeaseKeepAliveRequest.SerializeToString,
            response_deserializer=rpc.LeaseKeepAliveResponse.FromString,
        )
        self._watch_stream = self._chan.stream_stream(
            f"/{_WATCH}/Watch",
            request_serializer=rpc.WatchRequest.SerializeToString,
            response_deserializer=rpc.WatchResponse.FromString,
        )

    # -- etcd3-compatible surface ------------------------------------------

    def lease(self, ttl: int) -> VendoredLease:
        resp = self._lease_grant(
            rpc.LeaseGrantRequest(TTL=ttl), timeout=self._timeout
        )
        if resp.error:
            raise RuntimeError(f"lease grant failed: {resp.error}")
        return VendoredLease(self, resp.ID, resp.TTL)

    def put(self, key, value, lease: Optional[VendoredLease] = None):
        self._put(
            rpc.PutRequest(
                key=_b(key),
                value=_b(value),
                lease=lease.id if lease is not None else 0,
            ),
            timeout=self._timeout,
        )

    def get_prefix(self, prefix) -> List[Tuple[bytes, _KVMeta]]:
        p = _b(prefix)
        resp = self._range(
            rpc.RangeRequest(key=p, range_end=prefix_range_end(p)),
            timeout=self._timeout,
        )
        return [(kv.value, _KVMeta(kv)) for kv in resp.kvs]

    def delete(self, key) -> bool:
        resp = self._delete_range(
            rpc.DeleteRangeRequest(key=_b(key)), timeout=self._timeout
        )
        return resp.deleted > 0

    def watch_prefix(self, prefix):
        """(events_iterator, cancel) — the iterator yields one object per
        etcd event and blocks between events; cancel() unblocks and ends
        it (the etcd3 library contract the pool consumes)."""
        p = _b(prefix)
        req_q: "queue.Queue" = queue.Queue()
        req_q.put(
            rpc.WatchRequest(
                create_request=rpc.WatchCreateRequest(
                    key=p, range_end=prefix_range_end(p)
                )
            )
        )
        done = threading.Event()

        def requests():
            while not done.is_set():
                try:
                    item = req_q.get(timeout=0.5)
                except queue.Empty:
                    continue
                if item is None:
                    return
                yield item

        call = self._watch_stream(requests())

        def events() -> Iterator[object]:
            try:
                for resp in call:
                    if resp.canceled:
                        return
                    for ev in resp.events:
                        yield ev
            except grpc.RpcError as e:
                if e.code() in (
                    grpc.StatusCode.CANCELLED,
                    grpc.StatusCode.UNAVAILABLE,
                ) and done.is_set():
                    return  # cancel() path: not an error
                raise

        def cancel():
            done.set()
            req_q.put(None)
            call.cancel()

        return events(), cancel

    # -- internals ----------------------------------------------------------

    def _keepalive_once(self, lease_id: int):
        """One-shot keepalive: open the stream, send one request, read
        one response. The pool refreshes at TTL/3, so a persistent
        stream buys nothing and one-shot keeps failure handling local."""
        call = self._keepalive_stream(
            iter([rpc.LeaseKeepAliveRequest(ID=lease_id)]),
            timeout=self._timeout,
        )
        for resp in call:
            return resp
        raise RuntimeError("keepalive stream closed without a response")

    def _lease_revoke(self, lease_id: int) -> None:
        self._lease_revoke_rpc(
            rpc.LeaseRevokeRequest(ID=lease_id), timeout=self._timeout
        )

    def close(self) -> None:
        self._chan.close()


def _b(v) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)
