"""Owner->successor bucket replication: node death without quota amnesia.

r8 made node failure *graceful* (breakers trip, victims' keys get
degraded-local answers) but a SIGKILLed owner still lost every bucket it
owned: after takeover or restart each key started from a full window, so
a fleet-wide deploy briefly un-rate-limited every hot key — the core
consistency/availability trade-off the scalable-rate-limiting survey
(PAPERS.md) flags for distributed limiters. This module closes it by
piggybacking on machinery that already exists: the ring defines each
key's successor (peers.ConsistentHashPicker), the gossip tier already
moves per-key status between peers (UpdatePeerGlobals install path), and
the store exposes a non-mutating snapshot read of owned rows.

Shape mirrors GlobalManager (supervise/flush/drain):

- Owners mark each decided token-bucket key dirty (queue_dirty, bounded
  by GUBER_REPLICATION_BACKLOG). Every GUBER_REPLICATION_SYNC_WAIT_MS
  the flush loop snapshot-reads the dirty keys' windows — NON-MUTATING
  (backends.snapshot_read / engine.snapshot_read), so replication ON is
  byte-identical to OFF in the no-failure case — and ships
  BucketSnapshots to each key's ring successor over the new
  ReplicateBuckets peer RPC. Installs are last-write-wins by
  (reset_time, snapshot_ms), so retries and duplicates are idempotent.
- Receivers file snapshots for keys they do NOT own in a bounded
  standby table (GUBER_REPLICATION_STANDBY_KEYS) that is consulted ONLY
  on takeover; snapshots for keys they DO own (the reconcile handback
  below) install straight into the local store through the existing
  UpdatePeerGlobals machinery — which also purges the shed cache, so
  the r10 device-authoritative invalidation rules apply unchanged.
- Takeover: when a key's owner dies, its traffic reaches the successor
  either because discovery removed the owner (the ring now routes
  there) or because the forwarding node's breaker opened and it
  re-routed to the successor (instance._takeover_fallback). The
  successor's FIRST touch of such a key pops the standby snapshot and
  installs it before deciding, so the decision continues the dead
  owner's window instead of opening a fresh one; those responses carry
  metadata["replicated"]="true" and count in
  replicated_takeovers_total, with replication_lag_seconds set from
  the snapshot's owner-clock stamp.
- Reconcile: keys served in another owner's stead are tracked (_taken);
  each flush tick they are snapshot-read and handed BACK to their
  current ring owner via the same ReplicateBuckets RPC (the attempt
  doubles as a breaker probe, so the handback typically lands within
  one cooldown of the owner returning). The returning owner installs
  them store-directly; its own next GLOBAL broadcast then supersedes
  any interim successor state, and UpdatePeerGlobals installs purge
  matching standby rows on every receiver.

Deliberate scope (documented in docs/operations.md):

- Token bucket only — a leaky bucket refills continuously (its state
  changes every millisecond and self-heals within one leak tick), the
  same structural exclusion as the r10 shed cache. The wire message
  carries `algorithm` for forward compatibility.
- A standby seed OVERWRITES whatever window the successor's traffic
  may have created mid-takeover (e.g. through the pre-hashed edge fast
  path, which does not consult the standby table) — the fail-closed
  direction for a rate limiter, bounded by the original window.
- Pre-hashed edge frames (GEB6/GEB7) carry no key strings, so windows
  driven EXCLUSIVELY through them are never dirtied for replication;
  the bridge's string->array fold (queue_dirty_fields) and every
  instance path are covered.
- Keys decided but never flushed before the owner died (at most one
  sync window's worth) are lost, as are keys whose successor also died:
  the staleness/loss bound is one flush window + one RTT.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    RateLimitResp,
    Status,
    millisecond_now,
)
from gubernator_tpu.serve import metrics

log = logging.getLogger("gubernator_tpu.replication")

#: rough per-entry host footprint of a standby row (dict node + key
#: string + Snapshot tuple), for the boot-time sizing log
ENTRY_BYTES = 400


class Snapshot(NamedTuple):
    """One owned bucket window on the wire (peers.proto BucketSnapshot)."""

    key: str
    algorithm: int
    limit: int
    duration: int
    remaining: int
    reset_time: int  # unix-ms; the last-write-wins version
    status: int  # Status enum value (carries "sticky over")
    snapshot_ms: int  # owner's clock at snapshot time (lag metric)


def snapshot_resp(s: Snapshot) -> RateLimitResp:
    """The store-install form of a snapshot: exactly what the owner's
    window would answer, installed through the UpdatePeerGlobals
    machinery (exact backend: a cached RateLimitResp IS a token window;
    device backends: upsert_globals_jit)."""
    return RateLimitResp(
        status=Status(s.status),
        limit=s.limit,
        remaining=s.remaining,
        reset_time=s.reset_time,
    )


def footprint_mib(keys: int) -> float:
    return keys * ENTRY_BYTES / (1 << 20)


def eligible_field_indices(fields):
    """Indices of hit-carrying token-bucket rows in one folded frame's
    dense field arrays — the single eligibility rule the r11 dirty
    marking and the r17 tracked-set marking share (the bridge computes
    it once per frame and hands it to both managers)."""
    import numpy as np

    return np.flatnonzero(
        (np.asarray(fields["hits"]) > 0)
        & (np.asarray(fields["algo"]) == int(Algorithm.TOKEN_BUCKET))
    )


async def snapshot_windows(
    instance, metas: List[Tuple[str, Tuple[int, int, int]]]
) -> List["Snapshot"]:
    """Non-mutating window read for (key, (algo, limit, duration))
    pairs through the backend's snapshot surface — the ONE gather both
    the r11 replication flush and the r17 rescale handoff use, so the
    thread contract (device backends on the batcher's serialized
    submit thread), the expiry filtering, the duration backfill, and
    the LWW stamping can never drift between them. Expired/missing
    rows drop out."""
    from gubernator_tpu.api.types import Status

    be = instance.backend
    fn = getattr(be, "snapshot_read", None)
    if fn is None:  # pragma: no cover - gated at Instance init
        return []
    keys = [k for k, _ in metas]
    if not keys:
        return []
    now = millisecond_now()
    if getattr(be, "inline_decide", False):
        rows = fn(keys, now)
    else:
        rows = await instance.batcher.run_serialized(fn, keys, now)
    snaps = []
    for (key, meta), row in zip(metas, rows):
        if row is None:
            continue
        limit, duration, remaining, reset_time, over = row
        if reset_time <= now:
            continue
        snaps.append(Snapshot(
            key=key,
            algorithm=int(Algorithm.TOKEN_BUCKET),
            limit=int(limit),
            # exact-backend token windows don't persist duration;
            # fall back to the dirtying request's
            duration=int(duration) if duration > 0 else int(meta[2]),
            remaining=int(remaining),
            reset_time=int(reset_time),
            status=int(
                Status.OVER_LIMIT if over else Status.UNDER_LIMIT
            ),
            snapshot_ms=now,
        ))
    return snaps


class ReplicationManager:
    """Supervised owner->successor snapshot loop + receiver tables.

    Event-loop confined like GlobalManager; the only cross-thread work
    is the device snapshot read, which runs on the batcher's single
    submit thread (DeviceBatcher.run_serialized) so it can never race a
    store-donating dispatch."""

    def __init__(self, conf, instance):
        self.conf = conf
        self.instance = instance
        self.sync_wait = getattr(conf, "replication_sync_wait", 0.1)
        self.backlog_cap = getattr(conf, "replication_backlog", 1 << 16)
        self.standby_cap = getattr(conf, "replication_standby_keys", 1 << 16)
        # owner-side: key -> (algo, limit, duration) of the last decided
        # request (the request params back stored-duration gaps on
        # backends whose rows don't persist duration)
        self._dirty: Dict[str, Tuple[int, int, int]] = {}
        # takeover-side: keys this node served in another owner's stead
        # (handback candidates), same value shape as _dirty
        self._taken: Dict[str, Tuple[int, int, int]] = {}
        # receiver-side standby table: key -> Snapshot, LRU-bounded,
        # consulted ONLY on takeover (standby_pop)
        self._standby: "Dict[str, Snapshot]" = {}
        self._event = asyncio.Event()
        self._tasks: list = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if not self._tasks:
            from gubernator_tpu.serve.global_mgr import supervise

            self._tasks = [
                asyncio.ensure_future(
                    supervise("replication", self._run_flush)
                )
            ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []

    async def drain(self) -> None:
        """Graceful-drain flush (Server.drain step): ship whatever is
        dirty NOW to successors, and attempt one handback round, so a
        SIGTERMed owner's freshest windows survive it."""
        await self.flush_once()

    @property
    def standby_len(self) -> int:
        return len(self._standby)

    @property
    def backlog_len(self) -> int:
        """Dirty owned keys + takeover-tracked keys awaiting the next
        flush — the scrape-time replication_backlog_entries gauge
        (r16), against the GUBER_REPLICATION_BACKLOG bound."""
        return len(self._dirty) + len(self._taken)

    # -- owner-side queueing (hot path: two dict ops) -----------------------

    def queue_dirty(self, r: RateLimitReq) -> None:
        """Mark an owned, hit-carrying token-bucket key dirty for the
        next snapshot flush. Peeks change nothing (nothing to
        replicate); leaky buckets are out of scope (module docstring)."""
        if r.hits <= 0 or r.algorithm != Algorithm.TOKEN_BUCKET:
            return
        key = r.hash_key()
        if key not in self._dirty and len(self._dirty) >= self.backlog_cap:
            self._drop("dirty_backlog")
            return
        self._dirty[key] = (int(r.algorithm), r.limit, r.duration)
        self._event.set()

    def queue_dirty_fields(self, keys, fields, elig=None) -> None:
        """Bridge-tier dirty marking (edge_bridge string->array fold):
        one all-owned folded frame's keys and dense field arrays, same
        gates as queue_dirty. `elig` carries pre-computed
        eligible_field_indices so the bridge screens once per frame
        for every manager. Pre-hashed GEB6/GEB7 frames carry no key
        strings and cannot be marked — a documented scope limit."""
        if elig is None:
            elig = eligible_field_indices(fields)
        if not elig.size:
            return
        limit = fields["limit"]
        duration = fields["duration"]
        dirty = self._dirty
        token = int(Algorithm.TOKEN_BUCKET)
        for i in elig.tolist():
            key = keys[i]
            if key not in dirty and len(dirty) >= self.backlog_cap:
                self._drop("dirty_backlog")
                continue
            dirty[key] = (token, int(limit[i]), int(duration[i]))
        self._event.set()

    def mark_taken(self, r: RateLimitReq) -> None:
        """Record a key this node decided in another owner's stead
        (takeover serve): each flush tick tries to hand its window back
        to the current ring owner."""
        if r.algorithm != Algorithm.TOKEN_BUCKET:
            return
        key = r.hash_key()
        if key not in self._taken and len(self._taken) >= self.backlog_cap:
            self._drop("taken_backlog")
            return
        self._taken[key] = (int(r.algorithm), r.limit, r.duration)
        self._event.set()

    def _drop(self, what: str) -> None:
        try:
            metrics.REPLICATION_DROPPED.labels(what=what).inc()
        except Exception:  # pragma: no cover - defensive
            pass

    # -- receiver-side tables ------------------------------------------------

    def standby_pop(self, key: str) -> Optional[Snapshot]:
        """Take the standby snapshot for a key about to be decided as
        owner/authority — the ONLY reader of the table. Expired rows
        answer None (the first post-reset touch must open a fresh
        window, same rule as the shed cache)."""
        if not self._standby:
            return None
        s = self._standby.pop(key, None)
        if s is None or s.reset_time <= millisecond_now():
            return None
        return s

    async def purge_unsucceeded_standby(self) -> None:
        """Ring-change hygiene (r17 satellite): drop standby rows for
        keys this node neither owns nor succeeds on the CURRENT ring.
        A stale row surviving a reshuffle could seed a WRONG takeover
        window later — e.g. after two membership changes move the
        succession elsewhere and back with a different key split, the
        first touch would install a window frozen at the pre-reshuffle
        state instead of the interim owner's. Called from
        Instance.set_peers after every membership change; the scan is
        two ring lookups per row over a table bounded at 65536, so it
        yields the event loop every chunk rather than stalling every
        in-flight request for the full pass. Rows installed while the
        scan yields are judged against the same (current) ring when
        their chunk comes up, or survive to the next change's pass."""
        if not self._standby:
            return
        picker = self.instance.picker
        for i, key in enumerate(list(self._standby)):
            if i and i % 2048 == 0:
                await asyncio.sleep(0)
            if key not in self._standby:
                continue  # popped/seeded while we yielded
            try:
                if picker.get(key).is_owner:
                    continue  # we own it now: seeded on first touch
                succ = picker.get_successor(key)
                if succ is not None and succ.is_owner:
                    continue  # still the takeover target
            except Exception:  # pragma: no cover - ring flap
                continue
            self._standby.pop(key, None)
            self._drop("standby_reshuffle")

    def standby_purge(self, keys) -> None:
        """Drop standby rows for these keys: an UpdatePeerGlobals
        install means their owner is alive and broadcasting — its
        authoritative status supersedes any replicated snapshot (the
        r10 invalidation stance, applied to the standby table)."""
        if not self._standby:
            return
        for k in keys:
            self._standby.pop(k, None)

    async def install(self, owner: str, snaps: List[Snapshot]) -> None:
        """ReplicateBuckets receive path. Snapshots for keys this node
        OWNS (reconcile handback) install straight into the local store
        — through Instance.update_peer_globals, so the shed cache is
        purged exactly as for a GLOBAL broadcast; snapshots for other
        keys become standby rows, last-write-wins by
        (reset_time, snapshot_ms) so duplicates and retries no-op."""
        now = millisecond_now()
        store_installs: List[Snapshot] = []
        for s in snaps:
            if (
                s.reset_time <= now
                or s.algorithm != int(Algorithm.TOKEN_BUCKET)
            ):
                continue
            try:
                we_own = self.instance.get_peer(s.key).is_owner
            except Exception:
                we_own = False
            if we_own:
                store_installs.append(s)
                continue
            cur = self._standby.get(s.key)
            if cur is not None and (
                (cur.reset_time, cur.snapshot_ms)
                >= (s.reset_time, s.snapshot_ms)
            ):
                continue
            # pop-then-insert so dict order tracks install FRESHNESS:
            # at capacity the evictee must be the stalest snapshot, not
            # the first-ever-inserted key (which under steady
            # re-replication is exactly the hottest one)
            self._standby.pop(s.key, None)
            self._standby[s.key] = s
            while len(self._standby) > self.standby_cap:
                self._standby.pop(next(iter(self._standby)))
                self._drop("standby_evict")
        if store_installs:
            await self.instance.update_peer_globals(
                [(s.key, snapshot_resp(s)) for s in store_installs]
            )
            resc = getattr(self.instance, "rescale", None)
            if resc is not None:
                # installed windows are live local state the rescale
                # manager must hand off on the NEXT ring change (r17)
                for s in store_installs:
                    resc.note_installed(s.key, s.limit, s.duration)
            # the handback restored owner state: count + stamp lag
            try:
                metrics.REPLICATION_RECONCILES.inc(len(store_installs))
            except Exception:  # pragma: no cover - defensive
                pass
            self._set_lag(now, store_installs)
            log.info(
                "reconciled %d bucket(s) handed back by '%s'",
                len(store_installs), owner,
            )

    def note_seeded(self, seeds: List[Tuple[str, Snapshot]]) -> None:
        """Account a takeover seed batch (Instance popped the rows and
        installed them before deciding)."""
        try:
            metrics.REPLICATED_TAKEOVERS.inc(len(seeds))
        except Exception:  # pragma: no cover - defensive
            pass
        self._set_lag(millisecond_now(), [s for _, s in seeds])

    def _set_lag(self, now: int, snaps: List[Snapshot]) -> None:
        try:
            lag_ms = max(now - s.snapshot_ms for s in snaps)
            metrics.REPLICATION_LAG.set(max(0.0, lag_ms / 1000.0))
        except Exception:  # pragma: no cover - defensive
            pass

    # -- flush loop ----------------------------------------------------------

    async def _run_flush(self) -> None:
        while True:
            if not self._dirty and not self._taken:
                await self._event.wait()
            # coalesce one window's worth of decides per snapshot
            # (GlobalManager's sync-wait shape); this is also the
            # handback retry tick while an owner is unreachable
            await asyncio.sleep(self.sync_wait)
            self._event.clear()
            await self.flush_once()

    async def flush_once(self) -> None:
        dirty, self._dirty = self._dirty, {}
        owned: Dict[str, Tuple[int, int, int]] = {}
        for key, meta in dirty.items():
            try:
                if self.instance.get_peer(key).is_owner:
                    owned[key] = meta
                else:
                    # ownership moved between decide and flush: treat
                    # like a takeover serve and hand the window to the
                    # new owner below
                    self._taken.setdefault(key, meta)
            except Exception:
                # ring flap (empty/rebuilding picker): re-queue for the
                # next tick instead of losing the window silently; past
                # the cap the loss is at least accounted
                if (
                    key not in self._dirty
                    and len(self._dirty) >= self.backlog_cap
                ):
                    self._drop("dirty_backlog")
                else:
                    self._dirty.setdefault(key, meta)
                    self._event.set()
                continue
        if owned:
            await self._replicate_owned(owned)
        if self._taken:
            await self._handback()

    async def _replicate_owned(
        self, owned: Dict[str, Tuple[int, int, int]]
    ) -> None:
        """Snapshot-read owned dirty keys and ship each to its ring
        successor (skipping keys without a distinct successor).

        Successors resolve AFTER the snapshot await, against the ring
        as it stands at send time (r17 satellite): a membership change
        landing while the device gather is in flight used to leave this
        flush shipping a whole window's worth of dirty keys to the
        PRE-change successor — which the reshuffle may have demoted to
        a bystander whose stale standby row could seed a wrong takeover
        later (see purge_unsucceeded_standby). Pinned by the
        ring-flip-mid-flush test in tests/test_rescale.py."""
        if self.instance.picker.size() <= 1:
            # single-host ring: no key has a distinct successor, so
            # don't pay the serialized device gather every tick only
            # to discard every row at the successor screen below
            return
        snaps = await self._snapshot(list(owned.items()))
        by_peer: Dict[str, List[Snapshot]] = {}
        clients = {}
        for s in snaps:
            try:
                if not self.instance.get_peer(s.key).is_owner:
                    # ownership moved while the gather was in flight:
                    # the window belongs to the new owner now — route
                    # it through the handback path next tick instead
                    # of seeding the wrong successor's standby table
                    self._taken.setdefault(s.key, owned[s.key])
                    self._event.set()
                    continue
                succ = self.instance.picker.get_successor(s.key)
            except Exception as e:  # pragma: no cover - defensive
                log.error(
                    "while finding successor for '%s': %s", s.key, e
                )
                continue
            if succ is None or succ.is_owner:
                continue
            by_peer.setdefault(succ.host, []).append(s)
            clients[succ.host] = succ
        for host, chunk in by_peer.items():
            await self._send(clients[host], chunk)

    async def _handback(self) -> None:
        """Try to return interim windows to their current ring owner.
        Failures (owner still down, breaker open) keep the keys for the
        next tick; the attempt itself doubles as a breaker probe.

        Owners resolve AFTER the snapshot await (the same
        ring-flip-mid-flush rule as _replicate_owned): a rescale
        landing mid-gather must not hand a window to the PRE-change
        owner."""
        taken = dict(self._taken)
        snaps = await self._snapshot(list(taken.items()))
        alive = {s.key: s for s in snaps}
        for key in taken:
            if key not in alive:  # nothing left to hand back (expired)
                self._taken.pop(key, None)
        by_peer: Dict[str, List[Snapshot]] = {}
        clients = {}
        for key, s in alive.items():
            try:
                owner = self.instance.get_peer(key)
            except Exception:
                continue
            if owner.is_owner:
                # the ring moved the key to US: it is a normally owned
                # key now, covered by queue_dirty on its next decide
                self._taken.pop(key, None)
                continue
            by_peer.setdefault(owner.host, []).append(s)
            clients[owner.host] = owner
        for host, chunk in by_peer.items():
            if await self._send(clients[host], chunk, what="handback"):
                for s in chunk:
                    self._taken.pop(s.key, None)

    async def _snapshot(
        self, metas: List[Tuple[str, Tuple[int, int, int]]]
    ) -> List[Snapshot]:
        return await snapshot_windows(self.instance, metas)

    async def _send(self, peer, snaps: List[Snapshot], what="replicate"):
        """One peer's snapshots, chunked under the peer batch cap.
        Returns True when every chunk was delivered."""
        start = time.monotonic()
        advertise = self.conf.resolved_advertise()
        lim = self.conf.behaviors.global_batch_limit
        ok = True
        for i in range(0, len(snaps), lim):
            chunk = snaps[i : i + lim]
            try:
                await peer.replicate_buckets(chunk, owner=advertise)
                try:
                    metrics.REPLICATION_SNAPSHOTS_SENT.inc(len(chunk))
                except Exception:  # pragma: no cover - defensive
                    pass
            except Exception as e:
                ok = False
                log.log(
                    # a failing handback is EXPECTED for the whole
                    # outage (it retries every tick until the owner
                    # returns); don't spam warnings for it
                    logging.DEBUG if what == "handback" else logging.WARNING,
                    "error sending %s snapshots to '%s': %s",
                    what, peer.host, e,
                )
        log.debug(
            "%s: %d snapshot(s) -> %s in %.1f ms%s",
            what, len(snaps), peer.host,
            (time.monotonic() - start) * 1e3,
            "" if ok else " (failed)",
        )
        return ok
