"""Env-driven fault injection for the serving tier.

The availability features this repo grew in r8 (peer deadlines, retries,
circuit breaking, degraded mode, graceful drain) are only as real as the
failures they were tested against. This module is the single injection
surface threaded through the serving hot paths so tests and the chaos
soak (scripts/chaos_soak.py) can create latency, errors, partitions, and
hangs in a REAL process — no monkeypatching, no test-only forks of the
code under test.

Spec grammar (GUBER_FAULT_SPEC): comma-separated rules

    <point>:<action>[=<value>][:<param>=<value>...]

    points : peer_rpc      — PeerClient outbound RPCs (forwards + gossip)
             peer_serve    — owner-side Instance.get_peer_rate_limits
             device_submit — the device batcher's flush path
             edge_frame    — one edge bridge frame's service
             checkpoint_write — one checkpoint flush's file write (r19)
             checkpoint_read  — the boot-time checkpoint restore read (r19)
    actions: delay=<dur>   — add latency (e.g. 200ms, 1.5s, bare ms)
             error[=<msg>] — raise FaultError (retryable by default)
             hang          — block forever (deadlines must save the caller)
    params : p=<0..1>      — injection probability (default 1.0)
             host=<substr> — only when the call's peer tag contains this
             n=<count>     — stop after injecting <count> times

Examples:

    GUBER_FAULT_SPEC='peer_rpc:delay=200ms:p=0.1,peer_rpc:error:p=0.05'
    GUBER_FAULT_SPEC='peer_rpc:error:host=10.0.0.3'     # partition one peer
    GUBER_FAULT_SPEC='device_submit:hang'

GUBER_FAULT_SEED pins the RNG so probabilistic specs are reproducible in
tests. With no spec configured the hot-path cost is one attribute check
(`FAULTS.enabled`, a plain bool). Injections are counted in
faults_injected_total{point,action} so a soak can prove its faults fired.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
from dataclasses import dataclass
from typing import Dict, List, Optional

log = logging.getLogger("gubernator_tpu.faults")

POINTS = (
    "peer_rpc",
    "peer_serve",
    "device_submit",
    "edge_frame",
    "checkpoint_write",
    "checkpoint_read",
)
ACTIONS = ("delay", "error", "hang")


class FaultError(RuntimeError):
    """An injected failure. `retryable` mirrors the transport-level
    "never reached the peer" class (serve/peers.py retry policy), so a
    spec can exercise both the retry path and the give-up path."""

    def __init__(self, msg: str, retryable: bool = True):
        super().__init__(msg)
        self.retryable = retryable


def parse_duration_s(text: str) -> float:
    """'200ms' / '1.5s' / bare number (milliseconds) -> seconds."""
    t = text.strip().lower()
    try:
        if t.endswith("ms"):
            return float(t[:-2]) / 1000.0
        if t.endswith("s"):
            return float(t[:-1])
        return float(t) / 1000.0
    except ValueError:
        raise ValueError(f"unparsable fault duration {text!r}") from None


@dataclass
class FaultRule:
    point: str
    action: str
    delay_s: float = 0.0
    message: str = "injected fault"
    p: float = 1.0
    host: str = ""  # substring match against the call's peer tag
    budget: Optional[int] = None  # remaining injections; None = unbounded
    injected: int = 0

    def matches(self, peer: str, rng: random.Random) -> bool:
        if self.budget is not None and self.budget <= 0:
            return False
        if self.host and self.host not in peer:
            return False
        if self.p < 1.0 and rng.random() >= self.p:
            return False
        if self.budget is not None:
            self.budget -= 1
        self.injected += 1
        return True


def parse_fault_spec(spec: str) -> List[FaultRule]:
    """Parse GUBER_FAULT_SPEC; raises ValueError with the offending rule
    on any typo — a chaos run with a silently-ignored rule would pass
    for the wrong reason."""
    rules: List[FaultRule] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault rule {raw!r} must be '<point>:<action>[...]'"
            )
        point = parts[0].strip()
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r} in {raw!r} "
                f"(known: {', '.join(POINTS)})"
            )
        action_part = parts[1].strip()
        action, _, value = action_part.partition("=")
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r} in {raw!r} "
                f"(known: {', '.join(ACTIONS)})"
            )
        rule = FaultRule(point=point, action=action)
        if action == "delay":
            if not value:
                raise ValueError(f"delay needs a duration in {raw!r}")
            rule.delay_s = parse_duration_s(value)
        elif action == "error" and value:
            rule.message = value
        elif action == "hang" and value:
            raise ValueError(f"hang takes no value in {raw!r}")
        for param in parts[2:]:
            k, sep, v = param.partition("=")
            k = k.strip()
            if not sep:
                raise ValueError(f"malformed fault param {param!r} in {raw!r}")
            if k == "p":
                rule.p = float(v)
                if not (0.0 <= rule.p <= 1.0):
                    raise ValueError(f"p={v} out of [0,1] in {raw!r}")
            elif k == "host":
                rule.host = v.strip()
            elif k == "n":
                rule.budget = int(v)
            else:
                raise ValueError(
                    f"unknown fault param {k!r} in {raw!r} "
                    f"(known: p, host, n)"
                )
        rules.append(rule)
    return rules


class FaultInjector:
    """Process-wide injector. `enabled` is the hot-path guard: call
    sites check it (a plain attribute) before awaiting inject(), so a
    production process with no spec pays one bool load per site."""

    def __init__(self):
        self.enabled = False
        self._by_point: Dict[str, List[FaultRule]] = {}
        self._rng = random.Random()

    def configure(self, spec: str, seed: Optional[int] = None) -> None:
        rules = parse_fault_spec(spec or "")
        self._by_point = {}
        for r in rules:
            self._by_point.setdefault(r.point, []).append(r)
        if seed is not None:
            self._rng = random.Random(seed)
        self.enabled = bool(rules)
        if rules:
            log.warning(
                "fault injection ACTIVE: %s",
                "; ".join(
                    f"{r.point}:{r.action} p={r.p}"
                    + (f" host~{r.host}" if r.host else "")
                    for r in rules
                ),
            )

    def clear(self) -> None:
        self._by_point = {}
        self.enabled = False

    def rules(self) -> List[FaultRule]:
        return [r for rs in self._by_point.values() for r in rs]

    async def inject(self, point: str, peer: str = "") -> None:
        """Fire every matching rule at `point`. delay sleeps, error
        raises FaultError, hang parks forever (the caller's deadline is
        what's under test). Call sites guard with `FAULTS.enabled`."""
        for rule in self._by_point.get(point, ()):
            if not rule.matches(peer, self._rng):
                continue
            self._count(point, rule.action)
            if rule.action == "delay":
                await asyncio.sleep(rule.delay_s)
            elif rule.action == "error":
                raise FaultError(
                    f"{rule.message} (injected at {point}"
                    + (f", peer {peer}" if peer else "")
                    + ")"
                )
            elif rule.action == "hang":
                log.warning("injected hang at %s (peer %r)", point, peer)
                await asyncio.Event().wait()

    @staticmethod
    def _count(point: str, action: str) -> None:
        # lazy import: faults.py must stay importable before metrics
        # (and metrics must never be able to break an injection)
        try:
            from gubernator_tpu.serve import metrics

            metrics.FAULTS_INJECTED.labels(point=point, action=action).inc()
        except Exception:  # pragma: no cover - defensive
            pass


#: process-wide injector, configured from the environment at import so
#: daemons (and their subprocess tests) opt in with plain env vars
FAULTS = FaultInjector()
_spec = os.environ.get("GUBER_FAULT_SPEC", "")
if _spec:
    _seed = os.environ.get("GUBER_FAULT_SEED")
    FAULTS.configure(_spec, seed=int(_seed) if _seed else None)
