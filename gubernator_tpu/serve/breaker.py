"""Per-peer circuit breaker for the forwarding path.

The reference has no breaker: a dead owner costs every forwarded request
a full RPC failure, forever ("Designing Scalable Rate Limiting Systems",
PAPERS.md, names this the classic availability gap). This breaker gives
each PeerClient a three-state machine:

    closed    — calls flow; failures are counted (consecutive + a
                sliding window ratio).
    open      — calls fail fast with BreakerOpenError (no RPC, no
                deadline wait) until `cooldown` elapses.
    half-open — up to `probes` concurrent calls are let through; all
                succeeding closes the breaker, any failing re-opens it
                (restarting the cooldown).

Trip conditions (either): `failures` consecutive failures, or a failure
ratio >= `ratio` over the last `window` outcomes once the window is
full. Consecutive-failure tripping catches a dead peer in ~failures
RPCs; the ratio catches a brown-out that never fails twice in a row.

The breaker is intentionally not thread-safe: like everything else in
the serving tier it lives on the asyncio loop. acquire/record pairs DO
straddle awaits (the RPC runs between them), so acquire() hands out an
epoch token and record_*() ignores outcomes from an earlier epoch — a
slow pre-trip call resolving after the breaker opened (or while a
half-open probe is deciding) must not close, re-open, or restart the
cooldown of a state it was never part of.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: /metrics encoding of the state (peer_breaker_state gauge)
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpenError(RuntimeError):
    """Fail-fast refusal: the peer's circuit is open."""


class CircuitBreaker:
    def __init__(
        self,
        failures: int = 5,
        ratio: float = 0.5,
        window: int = 20,
        cooldown: float = 1.0,
        probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        self.failures = max(1, int(failures))
        self.ratio = float(ratio)
        self.window = max(1, int(window))
        self.cooldown = float(cooldown)
        self.probes = max(1, int(probes))
        self._clock = clock
        self._on_transition = on_transition
        self.state = CLOSED
        self._consecutive = 0
        self._outcomes: deque = deque(maxlen=self.window)
        self._opened_at = 0.0
        self._probes_inflight = 0
        self._probe_successes = 0
        # epoch: bumped on every state transition. acquire() stamps each
        # admission with it; a record_* carrying an older stamp is a
        # STALE outcome (admitted under a previous state) and is ignored
        # — a slow pre-trip call resolving during a later half-open must
        # not close the breaker (its success says nothing about the
        # probes) or restart the cooldown.
        self._epoch = 1

    # -- gate ---------------------------------------------------------------

    def acquire(self) -> int:
        """Admission check, called before each RPC. Returns the epoch
        token (truthy) to hand back to record_*, or 0 (falsy) when the
        call must fail fast. Every token MUST be paired with exactly
        one record_success/failure/cancel — in half-open the acquire
        reserves a probe slot."""
        if self.state == CLOSED:
            return self._epoch
        if self.state == OPEN:
            if self._clock() - self._opened_at < self.cooldown:
                return 0
            self._transition(HALF_OPEN)
            self._probes_inflight = 0
            self._probe_successes = 0
        # HALF_OPEN: bound concurrent probes
        if self._probes_inflight >= self.probes:
            return 0
        self._probes_inflight += 1
        return self._epoch

    def _stale(self, token) -> bool:
        # token None = caller predates epochs (ad-hoc/test use): treat
        # as current
        return token is not None and token != self._epoch

    # -- outcomes -----------------------------------------------------------

    def record_success(self, token: int = None) -> None:
        if self._stale(token):
            return
        if self.state == HALF_OPEN:
            self._probes_inflight = max(0, self._probes_inflight - 1)
            self._probe_successes += 1
            if self._probe_successes >= self.probes:
                self._reset()
                self._transition(CLOSED)
            return
        self._consecutive = 0
        self._outcomes.append(True)

    def record_cancel(self, token: int = None) -> None:
        """The admitted call was cancelled (teardown): release a
        half-open probe slot without counting an outcome."""
        if self.state == HALF_OPEN and not self._stale(token):
            self._probes_inflight = max(0, self._probes_inflight - 1)

    def record_failure(self, token: int = None) -> None:
        if self._stale(token):
            return
        if self.state == HALF_OPEN:
            # the probe failed: the peer is still down — re-open and
            # restart the cooldown clock
            self._probes_inflight = max(0, self._probes_inflight - 1)
            self._open()
            return
        if self.state == OPEN:
            # late failure from a call admitted before the trip
            return
        self._consecutive += 1
        self._outcomes.append(False)
        if self._consecutive >= self.failures:
            self._open()
            return
        if len(self._outcomes) == self.window:
            bad = sum(1 for ok in self._outcomes if not ok)
            if bad / self.window >= self.ratio:
                self._open()

    # -- internals ----------------------------------------------------------

    def _open(self) -> None:
        self._opened_at = self._clock()
        self._reset()
        self._transition(OPEN)

    def _reset(self) -> None:
        self._consecutive = 0
        self._outcomes.clear()
        self._probes_inflight = 0
        self._probe_successes = 0

    def _transition(self, to: str) -> None:
        if self.state == to:
            return
        frm, self.state = self.state, to
        self._epoch += 1  # outcomes admitted before this point are stale
        if self._on_transition is not None:
            try:
                self._on_transition(frm, to)
            except Exception:  # pragma: no cover - defensive
                pass

    # -- observability ------------------------------------------------------

    def effective_state(self) -> str:
        """The state an outside observer (health, /metrics) should
        read. The OPEN->HALF_OPEN transition happens lazily at the
        next acquire(), so with no traffic the stored state stays OPEN
        forever — and a health check reading it raw would report a
        long-recovered peer as down indefinitely (exactly the rotation
        deadlock the breaker exists to avoid: unhealthy -> traffic
        routed away -> no acquire -> never probes). OPEN past its
        cooldown is therefore reported as half-open pending its first
        probe."""
        if (
            self.state == OPEN
            and self._clock() - self._opened_at >= self.cooldown
        ):
            return HALF_OPEN
        return self.state

    @property
    def state_code(self) -> int:
        return STATE_CODES[self.effective_state()]
