"""Cluster-wide checkpoint/restore: quota state that survives a fleet.

Everything this repo grew for availability so far assumes SOMEONE
stays alive: r11 replication snapshots owned windows to ring
successors, r17 rescale hands state to new owners on membership
change. A full-fleet restart — power event, kernel patch rebooting
every node, a blue-green cutover that replaces the whole deployment —
has no survivor to hand to, so every over-limit window in the cluster
resets and the abusive traffic the limits were holding back gets a
free window (the "quota amnesia" failure mode; the reference
Gubernator accepts it by design). This module closes that last gap
with the machinery the repo already trusts:

- A supervised CheckpointManager (the GlobalManager/Replication/
  Rescale shape: event-loop confined, `supervise()` restart-on-crash)
  periodically captures quota state OFF the hot path and streams it
  to local disk (GUBER_CHECKPOINT_DIR):

  * tracked-key rows: the r11/r17 owner-side tracked set (bounded by
    GUBER_CHECKPOINT_TRACK_KEYS, freshest-kept) snapshot-read through
    the ONE non-mutating gather replication and rescale use
    (replication.snapshot_windows — device backends on the batcher's
    serialized submit thread). String-keyed, so the rows are
    wire-exportable (blue-green below) and exact-backend friendly.
  * full store lanes: on device backends the engine's export_windows
    dump rides along — EVERY live entry (token, leaky, sliding, GCRA,
    chain-level rows) with raw duration/ts/flags lanes, so restore is
    byte-exact for every algorithm and needs no key strings.

- The on-disk format is torn-write safe: chunk files written
  tmp+fsync+rename with a CRC32 each, then a manifest (format
  version, snapshot stamp, chunk list) written the same way LAST, and
  the directory fsynced. A reader either sees a complete checkpoint
  or the previous one; a half-written chunk fails its CRC and the
  boot falls back COLD, loudly (checkpoint_failures_total{what}),
  never wedged. A manifest from a FUTURE format version is refused
  the same way (version skew during a rolling upgrade must not guess).

- Boot-time warm restore (Server._start_inner, right after
  instance.start()): gated by a staleness bound
  (GUBER_CHECKPOINT_MAX_AGE_MS) — a checkpoint older than the bound
  is worthless (every window it holds would have expired or deserves
  a fresh start) and restoring it would only delay boot; it boots
  cold and counts checkpoint_failures_total{what="stale"}. Fresh
  checkpoints install through the SAME paths live traffic uses:
  string rows through Instance.update_peer_globals (which purges the
  shed cache and standby/pending tables for those keys — a restored
  OVER window can never be shadowed by a pre-restart cached verdict),
  lanes through engine.install_windows on the batcher's submit
  thread, followed by the same shed purge for their hashes. Restore
  re-hashes keys under the CURRENT ring and store geometry, so a
  GUBER_SHARDS change across the restart is just a re-partition
  (parallel/sharded.py install_windows routes by hash).

- Blue-green import (GUBER_CHECKPOINT_EXPORT_PEERS): a fleet being
  replaced streams its tracked windows to the REPLACEMENT fleet's
  doors over the existing ReplicateBuckets RPC — no new RPC, no new
  wire format. Batches carry owner="import:<advertise>" so receivers
  route them here regardless of their repl/rescale knobs; rows the
  receiver does not own under ITS ring forward ONCE to their owner as
  owner="importfwd:<advertise>" (forwarded batches are never
  re-forwarded — loop-free by construction), and still-unowned rows
  park in a bounded LWW pending table the flush loop re-ships and the
  first owned decide seeds (rescale's pending discipline). Installs
  are last-write-wins by (reset_time, snapshot_ms), so duplicate
  delivery — export every interval PLUS a final drain export — is a
  no-op, and the old fleet can keep serving while the new one warms.

- Drain (Server.drain) flushes a final checkpoint + export, so a
  SIGTERM'd fleet leaves state at most ONE in-flight request stale
  rather than one interval stale.

Deliberate scope:

- With a healthy fleet, checkpointing ON is byte-identical to OFF:
  the capture surfaces are non-mutating and the writes happen in a
  worker thread (tests/test_checkpoint.py pins it differentially —
  exact, single-device, mesh).
- The staleness/loss bound is one checkpoint interval
  (GUBER_CHECKPOINT_INTERVAL_MS) + write time; the restored state is
  at-least-as-restrictive as the pre-kill windows within that bound
  (remaining can only be over-counted by hits lost in the last
  interval — the fail-closed direction for an over-limit key).
- Wire export is token-bucket windows (the r11 Snapshot scope); the
  on-disk lanes section covers every algorithm locally.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import zlib
from typing import Dict, List, Optional, Tuple

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    millisecond_now,
)
from gubernator_tpu.serve import metrics
from gubernator_tpu.serve.faults import FAULTS
from gubernator_tpu.serve.replication import Snapshot, snapshot_resp

log = logging.getLogger("gubernator_tpu.checkpoint")

#: on-disk format version. Readers refuse anything NEWER than this
#: (cold boot + checkpoint_failures_total{what="version"}) — a rolled-
#: back binary must never misparse a new fleet's checkpoint silently.
FORMAT_VERSION = 1

MANIFEST = "manifest.json"

#: rough per-window on-disk footprint (JSON row + chunk overhead), for
#: the boot-time sizing log and the docs sizing math
ENTRY_DISK_BYTES = 120

#: snapshot rows per chunk file: bounds the blast radius of one torn
#: write and keeps each file's parse cheap
CHUNK_ROWS = 4096

#: full-lane columns, serialization order (matches export_windows)
LANE_COLS = (
    "key_hash", "limit", "remaining", "reset_time",
    "duration", "ts", "flags",
)


class CheckpointError(Exception):
    """A checkpoint that exists but cannot be used. `kind` is the
    checkpoint_failures_total label: 'read' (I/O), 'corrupt' (CRC/
    parse/count mismatch — torn write), 'version' (future format)."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


def disk_footprint_mib(windows: int) -> float:
    return windows * ENTRY_DISK_BYTES / (1 << 20)


# -- blocking file I/O (asyncio.to_thread from the manager) ------------------


def _fsync_write(path: str, data: bytes) -> None:
    """Torn-write-safe single file: tmp + fsync + atomic rename. A
    crash mid-write leaves the previous content (or a stray .tmp the
    next write replaces), never a half-file under the real name."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _fsync_dir(dirpath: str) -> None:
    try:
        dfd = os.open(dirpath, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover - platform-dependent
        pass


def write_checkpoint(
    dirpath: str,
    snaps: List[Snapshot],
    lanes: Optional[dict],
    advertise: str,
    snapshot_ms: int,
) -> None:
    """One complete checkpoint under `dirpath` (blocking; call in a
    worker thread). Chunks first, manifest LAST — the manifest names
    every chunk with its CRC, so a reader sees either this checkpoint
    whole or the previous one. Raises on any I/O failure (the manager
    counts it; the previous checkpoint stays valid)."""
    os.makedirs(dirpath, exist_ok=True)
    chunk_meta: List[dict] = []
    for idx in range((len(snaps) + CHUNK_ROWS - 1) // CHUNK_ROWS):
        rows = [list(s) for s in snaps[idx * CHUNK_ROWS:(idx + 1) * CHUNK_ROWS]]
        data = json.dumps({"rows": rows}, separators=(",", ":")).encode()
        name = f"chunk-{idx:04d}.json"
        _fsync_write(os.path.join(dirpath, name), data)
        chunk_meta.append({
            "file": name,
            "crc": zlib.crc32(data) & 0xFFFFFFFF,
            "count": len(rows),
        })
    lane_meta: List[dict] = []
    lane_count = 0
    if lanes is not None and len(lanes.get("key_hash", ())):
        n = len(lanes["key_hash"])
        cols = {c: [int(v) for v in lanes[c]] for c in LANE_COLS}
        for idx, start in enumerate(range(0, n, CHUNK_ROWS)):
            part = {c: cols[c][start:start + CHUNK_ROWS] for c in LANE_COLS}
            data = json.dumps(
                {"cols": part}, separators=(",", ":")
            ).encode()
            name = f"lanes-{idx:04d}.json"
            _fsync_write(os.path.join(dirpath, name), data)
            lane_meta.append({
                "file": name,
                "crc": zlib.crc32(data) & 0xFFFFFFFF,
                "count": len(part["key_hash"]),
            })
            lane_count += len(part["key_hash"])
    manifest = {
        "format_version": FORMAT_VERSION,
        "advertise": advertise,
        "snapshot_ms": int(snapshot_ms),
        "windows": len(snaps),
        "lane_windows": lane_count,
        "chunks": chunk_meta,
        "lane_chunks": lane_meta,
    }
    _fsync_write(
        os.path.join(dirpath, MANIFEST),
        json.dumps(manifest, indent=1).encode(),
    )
    # chunks beyond this checkpoint's set belonged to an earlier,
    # larger one: the new manifest no longer references them
    keep = {m["file"] for m in chunk_meta} | {m["file"] for m in lane_meta}
    for fn in os.listdir(dirpath):
        if (
            (fn.startswith("chunk-") or fn.startswith("lanes-"))
            and fn.endswith(".json")
            and fn not in keep
        ):
            try:
                os.remove(os.path.join(dirpath, fn))
            except OSError:  # pragma: no cover - races a concurrent rm
                pass
    _fsync_dir(dirpath)


def read_checkpoint(
    dirpath: str,
) -> Optional[Tuple[dict, List[Snapshot], Optional[dict]]]:
    """Read and verify one checkpoint (blocking; worker thread).
    Returns None when no manifest exists (a fresh node — cold boot, no
    failure), (manifest, snaps, lanes|None) on success, and raises
    CheckpointError (kind: read/corrupt/version) for a checkpoint that
    exists but cannot be trusted."""
    mpath = os.path.join(dirpath, MANIFEST)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath, "rb") as f:
            manifest = json.loads(f.read())
    except OSError as e:
        raise CheckpointError("read", f"manifest unreadable: {e}")
    except ValueError as e:
        raise CheckpointError("corrupt", f"manifest unparsable: {e}")
    ver = manifest.get("format_version")
    if not isinstance(ver, int) or ver < 1:
        raise CheckpointError(
            "corrupt", f"manifest format_version {ver!r} is not valid"
        )
    if ver > FORMAT_VERSION:
        raise CheckpointError(
            "version",
            f"checkpoint format v{ver} is newer than this binary's "
            f"v{FORMAT_VERSION} (rolling upgrade skew?) — refusing to "
            "guess at its layout",
        )

    def chunk_bytes(m: dict) -> bytes:
        p = os.path.join(dirpath, m["file"])
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError as e:
            raise CheckpointError("read", f"chunk {m['file']}: {e}")
        if (zlib.crc32(data) & 0xFFFFFFFF) != m["crc"]:
            raise CheckpointError(
                "corrupt",
                f"chunk {m['file']}: CRC mismatch (torn write?)",
            )
        return data

    snaps: List[Snapshot] = []
    for m in manifest.get("chunks", []):
        data = chunk_bytes(m)
        try:
            rows = json.loads(data)["rows"]
        except (ValueError, KeyError, TypeError) as e:
            raise CheckpointError("corrupt", f"chunk {m['file']}: {e}")
        if len(rows) != m.get("count"):
            raise CheckpointError(
                "corrupt", f"chunk {m['file']}: row count mismatch"
            )
        for r in rows:
            snaps.append(Snapshot(
                str(r[0]), int(r[1]), int(r[2]), int(r[3]),
                int(r[4]), int(r[5]), int(r[6]), int(r[7]),
            ))
    lanes: Optional[dict] = None
    lane_meta = manifest.get("lane_chunks", [])
    if lane_meta:
        cols: Dict[str, list] = {c: [] for c in LANE_COLS}
        for m in lane_meta:
            data = chunk_bytes(m)
            try:
                part = json.loads(data)["cols"]
            except (ValueError, KeyError, TypeError) as e:
                raise CheckpointError(
                    "corrupt", f"lane chunk {m['file']}: {e}"
                )
            if len(part.get("key_hash", ())) != m.get("count"):
                raise CheckpointError(
                    "corrupt",
                    f"lane chunk {m['file']}: row count mismatch",
                )
            for c in LANE_COLS:
                if c not in part:
                    raise CheckpointError(
                        "corrupt",
                        f"lane chunk {m['file']}: missing column {c!r}",
                    )
                cols[c].extend(part[c])
        lanes = cols
    return manifest, snaps, lanes


# -- the manager -------------------------------------------------------------


class CheckpointManager:
    """Supervised periodic checkpoint loop + restore/import receiver.

    Event-loop confined like the other serve-tier managers; the only
    cross-thread work is the device gathers/installs (the batcher's
    single submit thread, the r11 contract) and the file I/O
    (asyncio.to_thread — a slow or hung disk never blocks serving)."""

    def __init__(self, conf, instance):
        self.conf = conf
        self.instance = instance
        self.dir = getattr(conf, "checkpoint_dir", "") or ""
        self.sync_wait = getattr(conf, "checkpoint_interval", 5.0)
        self.max_age = getattr(conf, "checkpoint_max_age", 300.0)
        self.track_cap = getattr(conf, "checkpoint_track_keys", 1 << 16)
        self.export_peers: List[str] = list(
            getattr(conf, "checkpoint_export_peers", ()) or ()
        )
        # owner-side: key -> (algo, limit, duration) of the last decide
        # (duration backfill, the r11 Snapshot convention);
        # freshest-kept at capacity via pop-then-insert
        self._tracked: Dict[str, Tuple[int, int, int]] = {}
        # receiver-side: imported/restored rows this node does not own
        # YET, LWW by (reset_time, snapshot_ms); re-shipped to ring
        # owners by the flush loop, popped on the first owned decide
        self._pending: Dict[str, Snapshot] = {}
        # lazy PeerClients for the blue-green export targets (these
        # doors are NOT ring members — they are the replacement fleet)
        self._export_clients: Dict[str, object] = {}
        # unix-ms stamp of the last successful write (or the restored
        # manifest's stamp at boot); the checkpoint_age_seconds basis
        self.last_ok_ms = 0
        self._event = asyncio.Event()
        self._tasks: list = []

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if not self._tasks:
            from gubernator_tpu.serve.global_mgr import supervise

            self._tasks = [
                asyncio.ensure_future(
                    supervise("checkpoint", self._run_flush)
                )
            ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []
        for c in self._export_clients.values():
            try:
                await c.close()
            except Exception:  # pragma: no cover - defensive
                pass
        self._export_clients = {}

    async def drain(self) -> None:
        """Final flush on planned shutdown (Server.drain): the state
        on disk — and on the replacement fleet, in a blue-green — is
        then at most one in-flight request stale, not one interval."""
        try:
            await self.flush_once()
        except Exception as e:  # pragma: no cover - drain must not fail
            log.warning("checkpoint: drain flush failed: %s", e)

    @property
    def age_seconds(self) -> Optional[float]:
        if not self.last_ok_ms:
            return None
        return max(0.0, (millisecond_now() - self.last_ok_ms) / 1000.0)

    @property
    def tracked_len(self) -> int:
        return len(self._tracked)

    @property
    def pending_len(self) -> int:
        return len(self._pending)

    # -- owner-side tracking (hot path: dict ops only) ----------------------

    def note_owned(self, r: RateLimitReq) -> None:
        """Track an owned, hit-carrying token-bucket key as holding a
        live window worth checkpointing (the r11/r17 eligibility rule;
        peeks cannot create windows). Non-token windows are covered by
        the full-lane store dump, which needs no tracking."""
        if r.hits <= 0 or r.algorithm != Algorithm.TOKEN_BUCKET:
            return
        self._note_key(r.hash_key(), (int(r.algorithm), r.limit, r.duration))

    def note_owned_fields(self, keys, fields, elig=None) -> None:
        """Bridge-tier tracking (edge string->array fold), same gates
        as note_owned; `elig` carries pre-computed
        eligible_field_indices like queue_dirty_fields."""
        from gubernator_tpu.serve.replication import (
            eligible_field_indices,
        )

        if elig is None:
            elig = eligible_field_indices(fields)
        if not elig.size:
            return
        limit = fields["limit"]
        duration = fields["duration"]
        token = int(Algorithm.TOKEN_BUCKET)
        for i in elig.tolist():
            self._note_key(
                keys[i], (token, int(limit[i]), int(duration[i]))
            )

    def note_seeded(self, seeds: List[Tuple[str, Snapshot]]) -> None:
        for k, s in seeds:
            self.note_installed(k, s.limit, s.duration)

    def note_installed(self, key: str, limit: int, duration: int) -> None:
        self._note_key(
            key, (int(Algorithm.TOKEN_BUCKET), int(limit), int(duration))
        )

    def _note_key(self, key: str, meta: Tuple[int, int, int]) -> None:
        tracked = self._tracked
        prev = tracked.pop(key, None)
        if prev is None and len(tracked) >= self.track_cap:
            tracked.pop(next(iter(tracked)))
            self._fail("track_evict")
        tracked[key] = meta

    # -- receiver side ------------------------------------------------------

    async def install(self, owner: str, snaps: List[Snapshot]) -> None:
        """ReplicateBuckets receive fallback (repl and rescale both
        off): the same two-way owned/pending split rescale provides,
        against this manager's pending table."""
        await self._split_install(owner, snaps, forward=False)

    async def install_import(self, owner: str, snaps: List[Snapshot]) -> None:
        """A blue-green import batch (owner carries the import:/
        importfwd: marker). Owned rows install; non-owned rows of a
        FIRST-delivery batch (import:) forward once to their owner
        under this ring; rows of an already-forwarded batch
        (importfwd:) — or rows whose forward fails — park in the
        pending table for the flush loop to re-ship. One forwarding
        hop maximum: loop-free however the two rings disagree."""
        forward = owner.startswith("import:")
        await self._split_install(owner, snaps, forward=forward)

    async def _split_install(
        self, owner: str, snaps: List[Snapshot], forward: bool
    ) -> None:
        now = millisecond_now()
        installs: List[Snapshot] = []
        by_host: Dict[str, Tuple] = {}
        for s in snaps:
            if (
                s.reset_time <= now
                or s.algorithm != int(Algorithm.TOKEN_BUCKET)
            ):
                continue
            peer = None
            try:
                peer = self.instance.get_peer(s.key)
                we_own = peer.is_owner
            except Exception:
                # no ring yet (boot-time restore, single node): this
                # node IS the whole ring
                we_own = True
            if we_own:
                installs.append(s)
            elif forward and peer is not None:
                entry = by_host.get(peer.host)
                if entry is None:
                    by_host[peer.host] = (peer, [s])
                else:
                    entry[1].append(s)
            else:
                self._park(s)
        if installs:
            await self._install_snaps(installs, what=owner)
        if by_host:
            fwd_owner = f"importfwd:{self.conf.resolved_advertise()}"
            lim = self.conf.behaviors.global_batch_limit
            for host, (peer, group) in by_host.items():
                for i in range(0, len(group), lim):
                    chunk = group[i:i + lim]
                    try:
                        await peer.replicate_buckets(
                            chunk, owner=fwd_owner
                        )
                    except Exception as e:
                        # park instead of drop: the flush loop retries
                        for s in chunk:
                            self._park(s)
                        log.warning(
                            "checkpoint: import forward to '%s' "
                            "failed (%s); parked %d row(s)",
                            host, e, len(chunk),
                        )

    async def _install_snaps(
        self, snaps: List[Snapshot], what: str
    ) -> None:
        """Install owned snapshots through Instance.update_peer_globals
        — the ONE replica-install path, so the shed-cache purge and
        standby/pending supersession fire exactly as for an owner
        broadcast (a restored OVER window is never shadowed by a
        pre-restart cached verdict). Tracks every row here and in the
        sibling managers (live state to replicate/hand off/checkpoint
        next round)."""
        inst = self.instance
        lim = self.conf.behaviors.global_batch_limit
        now = millisecond_now()
        for i in range(0, len(snaps), lim):
            chunk = snaps[i:i + lim]
            await inst.update_peer_globals(
                [(s.key, snapshot_resp(s)) for s in chunk]
            )
            seeds = [(s.key, s) for s in chunk]
            self.note_seeded(seeds)
            if inst.repl is not None:
                inst.repl.note_seeded(seeds)
            if inst.rescale is not None:
                inst.rescale.note_seeded(seeds)
        try:
            metrics.RESTORED_WINDOWS.inc(len(snaps))
            lag_ms = max(now - s.snapshot_ms for s in snaps)
            metrics.RESTORE_LAG.set(max(0.0, lag_ms / 1000.0))
        except Exception:  # pragma: no cover - defensive
            pass
        log.info(
            "checkpoint: installed %d window(s) (%s)", len(snaps), what
        )

    def _park(self, s: Snapshot) -> None:
        cur = self._pending.get(s.key)
        if cur is not None and (
            (cur.reset_time, cur.snapshot_ms)
            >= (s.reset_time, s.snapshot_ms)
        ):
            return
        self._pending.pop(s.key, None)
        self._pending[s.key] = s
        while len(self._pending) > self.track_cap:
            self._pending.pop(next(iter(self._pending)))
            self._fail("pending_evict")

    def pending_pop(self, key: str) -> Optional[Snapshot]:
        """Take the parked snapshot for a key about to be decided as
        owner — the first owned touch after an import/restore landed
        here before the ring agreed. Expired rows answer None (the
        first post-reset touch must open a fresh window)."""
        if not self._pending:
            return None
        s = self._pending.pop(key, None)
        if s is None or s.reset_time <= millisecond_now():
            return None
        return s

    def pending_purge(self, keys) -> None:
        """An UpdatePeerGlobals install supersedes parked rows for
        these keys (the r11 standby rule applied here)."""
        if not self._pending:
            return
        for k in keys:
            self._pending.pop(k, None)

    # -- boot-time restore --------------------------------------------------

    async def restore(self) -> int:
        """Warm restore from GUBER_CHECKPOINT_DIR (Server boot, after
        instance.start()). Every failure path boots COLD and loudly —
        a checkpoint problem must never wedge a boot: missing manifest
        is a fresh node (no failure counted); stale/corrupt/future-
        version checkpoints count checkpoint_failures_total{what} and
        return 0. Returns the number of windows restored."""
        if not self.dir:
            return 0
        try:
            if FAULTS.enabled:
                await FAULTS.inject("checkpoint_read")
            doc = await asyncio.to_thread(read_checkpoint, self.dir)
        except CheckpointError as e:
            self._fail(e.kind)
            log.error(
                "checkpoint: restore from %r failed (%s): %s — "
                "booting cold", self.dir, e.kind, e,
            )
            return 0
        except Exception as e:
            self._fail("read")
            log.error(
                "checkpoint: restore from %r failed: %s — booting "
                "cold", self.dir, e,
            )
            return 0
        if doc is None:
            log.info(
                "checkpoint: no checkpoint in %r — cold boot", self.dir
            )
            return 0
        manifest, snaps, lanes = doc
        now = millisecond_now()
        age_ms = now - int(manifest.get("snapshot_ms", 0))
        if self.max_age > 0 and age_ms > self.max_age * 1000.0:
            self._fail("stale")
            log.error(
                "checkpoint: %r is %.1fs old, past "
                "GUBER_CHECKPOINT_MAX_AGE_MS (%.0fs) — booting cold",
                self.dir, age_ms / 1000.0, self.max_age,
            )
            return 0
        restored = 0
        lanes_installed = await self._restore_lanes(lanes, now)
        restored += lanes_installed
        live = [s for s in snaps if s.reset_time > now]
        if lanes_installed:
            # the lanes dump carried every live entry byte-exact
            # (including these token rows); re-installing the string
            # rows through update_globals would zero their duration
            # lane. Use them for TRACKING only — plus the shed purge
            # the lanes install already did by hash.
            seeds = [(s.key, s) for s in live]
            self.note_seeded(seeds)
            inst = self.instance
            if inst.repl is not None:
                inst.repl.note_seeded(seeds)
            if inst.rescale is not None:
                inst.rescale.note_seeded(seeds)
        elif live:
            await self._split_install(
                f"restore:{self.dir}", live, forward=False
            )
            restored += len(live)
        self.last_ok_ms = int(manifest.get("snapshot_ms", now))
        try:
            metrics.RESTORE_LAG.set(max(0.0, age_ms / 1000.0))
            if lanes_installed:
                metrics.RESTORED_WINDOWS.inc(lanes_installed)
        except Exception:  # pragma: no cover - defensive
            pass
        log.warning(
            "checkpoint: restored %d window(s) from %r "
            "(age %.1fs, %d tracked row(s), %d lane row(s))",
            restored, self.dir, age_ms / 1000.0, len(live),
            lanes_installed,
        )
        return restored

    async def _restore_lanes(
        self, lanes: Optional[dict], now: int
    ) -> int:
        """Byte-exact full-store reinstall on engine backends: the
        lanes columns land through install_windows on the batcher's
        submit thread (routes by hash under the CURRENT ShardingPolicy
        — restore across a GUBER_SHARDS change is a re-partition),
        then the shed cache purges those hashes, the same
        invalidation update_peer_globals performs for string keys."""
        if not lanes or not lanes.get("key_hash"):
            return 0
        eng = getattr(self.instance.backend, "engine", None)
        if eng is None or not hasattr(eng, "install_windows"):
            return 0
        import numpy as np

        live = [
            i for i, rt in enumerate(lanes["reset_time"]) if rt > now
        ]
        if not live:
            return 0
        cols = {
            c: np.asarray(
                [lanes[c][i] for i in live],
                np.uint64 if c == "key_hash" else np.int64,
            )
            for c in LANE_COLS
        }

        def _do_install():
            eng.install_windows(
                cols["key_hash"], cols["limit"], cols["remaining"],
                cols["reset_time"], None, now=now,
                duration=cols["duration"], ts=cols["ts"],
                flags=cols["flags"],
            )

        await self.instance.batcher.run_serialized(_do_install)
        if self.instance.shed is not None:
            self.instance.shed.purge(cols["key_hash"])
        return len(live)

    # -- flush loop ---------------------------------------------------------

    async def _run_flush(self) -> None:
        while True:
            try:
                await asyncio.wait_for(
                    self._event.wait(), timeout=self.sync_wait
                )
                self._event.clear()
            except asyncio.TimeoutError:
                pass
            await self.flush_once()

    def kick(self) -> None:
        """Wake the flush loop now (tests, drain helpers)."""
        self._event.set()

    async def flush_once(self) -> int:
        """One checkpoint round: gather tracked rows (+ the engine
        lanes dump), write to disk in a worker thread, export to the
        blue-green targets, re-ship parked rows. Any failure counts
        and leaves the previous checkpoint intact. Returns the number
        of tracked rows captured."""
        metas = dict(self._tracked)
        from gubernator_tpu.serve.replication import snapshot_windows

        snaps = await snapshot_windows(self.instance, list(metas.items()))
        now = millisecond_now()
        if self.dir:
            try:
                if FAULTS.enabled:
                    await FAULTS.inject("checkpoint_write")
                lanes = await self._gather_lanes(now)
                await asyncio.to_thread(
                    write_checkpoint, self.dir, snaps, lanes,
                    self.conf.resolved_advertise(), now,
                )
                self.last_ok_ms = now
                try:
                    metrics.CHECKPOINT_AGE.set(0.0)
                except Exception:  # pragma: no cover - defensive
                    pass
            except Exception as e:
                self._fail("write")
                log.warning(
                    "checkpoint: write to %r failed: %s", self.dir, e
                )
        if self.export_peers and snaps:
            await self._export(snaps)
        if self._pending:
            await self._reship_pending()
        return len(snaps)

    async def _gather_lanes(self, now: int) -> Optional[dict]:
        """The engine's full-store dump (non-mutating; submit-thread
        contract), None on backends without one (exact)."""
        eng = getattr(self.instance.backend, "engine", None)
        fn = getattr(eng, "export_windows", None)
        if fn is None:
            return None
        w = await self.instance.batcher.run_serialized(fn, now)
        if not w["key_hash"].shape[0]:
            return None
        return {c: w[c] for c in LANE_COLS}

    # -- blue-green export --------------------------------------------------

    def _export_client(self, host: str):
        c = self._export_clients.get(host)
        if c is None:
            from gubernator_tpu.serve.peers import PeerClient

            c = PeerClient(self.conf.behaviors, host)
            c.connect()
            self._export_clients[host] = c
        return c

    async def _export(self, snaps: List[Snapshot]) -> None:
        """Stream tracked windows to the replacement fleet's doors,
        chunks round-robin across the listed targets (each receiver
        re-routes rows under ITS ring, so any door works for any
        row). LWW installs make every interval's re-send a no-op."""
        lim = self.conf.behaviors.global_batch_limit
        owner = f"import:{self.conf.resolved_advertise()}"
        chunks = [
            snaps[i:i + lim] for i in range(0, len(snaps), lim)
        ]
        for i, chunk in enumerate(chunks):
            host = self.export_peers[i % len(self.export_peers)]
            try:
                peer = self._export_client(host)
                await peer.replicate_buckets(chunk, owner=owner)
            except Exception as e:
                self._fail("export")
                log.warning(
                    "checkpoint: export to '%s' failed: %s", host, e
                )

    async def _reship_pending(self) -> None:
        """Re-route parked rows: rows this node owns NOW install; the
        rest re-ship to their current ring owners (importfwd — the
        receiver parks rather than re-forwards, so a flapping ring
        cannot make a row orbit). Failures keep the row parked for
        the next tick."""
        now = millisecond_now()
        installs: List[Snapshot] = []
        by_host: Dict[str, Tuple] = {}
        for key, s in list(self._pending.items()):
            if s.reset_time <= now:
                self._pending.pop(key, None)
                continue
            try:
                peer = self.instance.get_peer(key)
            except Exception:
                continue
            if peer.is_owner:
                self._pending.pop(key, None)
                installs.append(s)
            else:
                entry = by_host.get(peer.host)
                if entry is None:
                    by_host[peer.host] = (peer, [s])
                else:
                    entry[1].append(s)
        if installs:
            await self._install_snaps(installs, what="pending")
        if by_host:
            fwd_owner = f"importfwd:{self.conf.resolved_advertise()}"
            lim = self.conf.behaviors.global_batch_limit
            for host, (peer, group) in by_host.items():
                for i in range(0, len(group), lim):
                    chunk = group[i:i + lim]
                    try:
                        await peer.replicate_buckets(
                            chunk, owner=fwd_owner
                        )
                    except Exception as e:
                        log.warning(
                            "checkpoint: pending re-ship to '%s' "
                            "failed: %s", host, e,
                        )
                        continue
                    for s in chunk:
                        self._pending.pop(s.key, None)

    @staticmethod
    def _fail(what: str) -> None:
        try:
            metrics.CHECKPOINT_FAILURES.labels(what=what).inc()
        except Exception:  # pragma: no cover - defensive
            pass
