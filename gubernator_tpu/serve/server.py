"""Server daemon: gRPC V1 + PeersV1 services, HTTP JSON gateway, /metrics.

Wires config -> backend -> Instance -> servers, mirroring the reference
daemon's shape (reference cmd/gubernator/main.go:40-147): gRPC on one
listener, an HTTP gateway exposing POST /v1/GetRateLimits and
GET /v1/HealthCheck as JSON plus GET /metrics for Prometheus, discovery
(static peers, etcd, or kubernetes) pushing peer updates into
Instance.set_peers, and graceful shutdown.
"""

from __future__ import annotations

import asyncio
import os
import json
import logging
import time
from typing import Optional

import grpc
from aiohttp import web

from gubernator_tpu.api import convert
from gubernator_tpu.api.grpc_glue import add_peers_servicer, add_v1_servicer
from gubernator_tpu.api.proto.gen import gubernator_pb2, peers_pb2
from gubernator_tpu.serve import metrics, tracing
from gubernator_tpu.serve.backends import (
    ExactBackend,
    MeshBackend,
    TpuBackend,
)
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import BatchTooLargeError, Instance

log = logging.getLogger("gubernator_tpu.server")


def make_backend(conf: ServerConfig):
    if conf.jax_platform:
        import jax

        jax.config.update("jax_platforms", conf.jax_platform)

    if conf.backend == "exact":
        return ExactBackend(conf.cache_size)
    # sizing knobs (GUBER_STORE_MIB / GUBER_STORE_TARGET_KEYS) resolve
    # here; an oversized/undersized footprint for the declared key
    # budget warns (or fails under GUBER_STORE_SIZE_STRICT) at boot,
    # per the measured footprint≍throughput law
    store = conf.store_config(logger=log)
    from gubernator_tpu.core.store import (
        check_host_budget,
        store_capacity,
        store_footprint_bytes,
    )

    # whole-host budget accounting (r13): the boot log reports the
    # per-tier split, and the lint checks that GUBER_STORE_MIB covers
    # exact + sketch + shed + replication standby — not just the exact
    # tier (warning, or a hard failure under GUBER_STORE_SIZE_STRICT)
    sketch = conf.sketch_config()
    sketch_bytes = 0
    if sketch is not None:
        from gubernator_tpu.core.sketches import sketch_footprint_bytes

        sketch_bytes = sketch_footprint_bytes(sketch)
    from gubernator_tpu.serve.shedcache import ENTRY_BYTES as SHED_BYTES

    shed_bytes = (
        conf.shed_cache_keys * SHED_BYTES if conf.shed_cache else 0
    )
    # a standby snapshot is a small dataclass + dict node; ~160 B
    # measured on CPython 3.10 (serve/replication.py)
    standby_bytes = (
        conf.replication_standby_keys * 160 if conf.replication else 0
    )
    log.info(
        "store tiers: exact %d slots x %d ways = %d entries (%.0f MiB)"
        "%s + shed %.1f MiB + standby %.1f MiB",
        store.slots, store.rows, store_capacity(store),
        store_footprint_bytes(store) / (1 << 20),
        (
            f" + sketch {sketch.rows}x{sketch.width} "
            f"int{sketch.counter_bytes * 8} "
            f"({sketch_bytes / (1 << 20):.0f} MiB)"
            if sketch is not None
            else " (sketch tier off)"
        ),
        shed_bytes / (1 << 20),
        standby_bytes / (1 << 20),
    )
    host_lint = check_host_budget(
        conf.store_mib,
        {
            "exact store": store_footprint_bytes(store),
            "sketch": sketch_bytes,
            "shed cache": shed_bytes,
            "replication standby": standby_bytes,
        },
    )
    if host_lint:
        # STRICT hard-fails only when a HOST-side part was explicitly
        # sized (the operator oversubscribed on purpose): the device
        # tiers always fit by the carve-out, but the DEFAULT shed
        # cache (~12.5 MiB) overflows any tiny budget on its own, and
        # failing a pre-r13 strict config whose knobs never changed
        # would be a regression — those boots warn instead
        fields = type(conf).__dataclass_fields__
        host_explicit = (
            conf.shed_cache_keys != fields["shed_cache_keys"].default
        ) or (
            conf.replication
            and conf.replication_standby_keys
            != fields["replication_standby_keys"].default
        )
        if conf.store_size_strict and host_explicit:
            raise ValueError(f"GUBER_STORE_SIZE_STRICT: {host_lint}")
        log.warning("%s", host_lint)
    from gubernator_tpu.core.engine import buckets_for_limit

    buckets = buckets_for_limit(conf.device_batch_limit)
    if conf.device_deep_batch:
        log.info(
            "throughput mode: deep-batch accumulation toward %d "
            "(ladder %s)",
            conf.device_batch_limit, buckets,
        )
    if conf.backend == "tpu":
        return TpuBackend(store, buckets=buckets, sketch=sketch)
    if conf.backend == "mesh":
        devices = None
        if conf.shards:
            import jax

            avail = jax.devices()
            if conf.shards > len(avail):
                raise ValueError(
                    f"GUBER_SHARDS={conf.shards} exceeds the "
                    f"{len(avail)} visible devices; on CPU, raise "
                    "XLA_FLAGS --xla_force_host_platform_device_count"
                )
            devices = avail[: conf.shards]
        backend = MeshBackend(
            store, devices=devices, buckets=buckets, sketch=sketch
        )
        # the operator's confirmation that GUBER_SHARDS took effect
        log.info(
            "partitioned engine: %s", backend.engine.policy.describe()
        )
        return backend
    if conf.backend == "multihost":
        from gubernator_tpu.serve.backends import MultiHostBackend

        return MultiHostBackend(
            store, followers=conf.dist_followers, buckets=buckets,
            sketch=sketch,
        )
    raise ValueError(f"unknown backend '{conf.backend}'")


class _Timed:
    """Method timing -> grpc_request_counts / duration histograms
    (the stats-handler role, reference prometheus.go:104-127)."""

    def __init__(self, method: str):
        self.method = method

    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, exc_type, *_):
        ms = (time.monotonic() - self.start) * 1000.0
        metrics.GRPC_REQUEST_DURATION.labels(self.method).observe(ms)
        metrics.GRPC_REQUEST_COUNTS.labels(
            "failed" if exc_type else "success", self.method
        ).inc()
        return False


class StatsInterceptor(grpc.aio.ServerInterceptor):
    """Times EVERY unary RPC generically by method name — the
    stats-handler contract of the reference (prometheus.go:104-127): a
    method added tomorrow is metered automatically instead of silently
    unmetered (r1 hand-wrapped exactly four methods)."""

    async def intercept_service(self, continuation, handler_call_details):
        handler = await continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler  # only unary-unary RPCs exist in this API
        method = handler_call_details.method
        inner = handler.unary_unary

        async def timed(request, context):
            with _Timed(method):
                return await inner(request, context)

        return grpc.unary_unary_rpc_method_handler(
            timed,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


def _md_traceparent(context) -> "Optional[str]":
    """The traceparent entry of an RPC's invocation metadata, or None.
    One pass over a handful of per-RPC metadata pairs — never per-item
    work, so the untraced path stays flat."""
    try:
        for k, v in context.invocation_metadata() or ():
            if k == tracing.TRACEPARENT:
                return v
    except Exception:  # pragma: no cover - defensive
        pass
    return None


class V1Servicer:
    def __init__(self, instance: Instance):
        self.instance = instance

    async def GetRateLimits(self, request, context):
        reqs = [convert.req_from_pb(p) for p in request.requests]
        tracer = self.instance.tracer
        trace = tracer.join(
            "grpc", tracing.parse_traceparent(_md_traceparent(context))
        )
        try:
            with tracing.scope(tracer, trace) as tr:
                if tr is not None:
                    tr.annotate(items=len(reqs))
                resps = await self.instance.get_rate_limits(reqs)
        except BatchTooLargeError as e:
            await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        return gubernator_pb2.GetRateLimitsResp(
            responses=[convert.resp_to_pb(r) for r in resps]
        )

    async def HealthCheck(self, request, context):
        h = self.instance.health_check()
        return gubernator_pb2.HealthCheckResp(
            status=h.status, message=h.message, peer_count=h.peer_count
        )


class PeersV1Servicer:
    def __init__(self, instance: Instance):
        self.instance = instance

    async def GetPeerRateLimits(self, request, context):
        reqs = [convert.req_from_pb(p) for p in request.requests]
        # owner-serve hop of a distributed trace (r16): a forwarding
        # peer's sampled context arrives as gRPC metadata; the owner
        # records its own queue/device spans under the SAME trace id
        # in its own flight recorder
        tracer = self.instance.tracer
        trace = tracer.join(
            "peers", tracing.parse_traceparent(_md_traceparent(context))
        )
        try:
            with tracing.scope(tracer, trace) as tr:
                if tr is not None:
                    tr.annotate(items=len(reqs))
                resps = await self.instance.get_peer_rate_limits(reqs)
        except BatchTooLargeError as e:
            await context.abort(grpc.StatusCode.OUT_OF_RANGE, str(e))
        return peers_pb2.GetPeerRateLimitsResp(
            rate_limits=[convert.resp_to_pb(r) for r in resps]
        )

    async def UpdatePeerGlobals(self, request, context):
        updates = [
            (g.key, convert.resp_from_pb(g.status))
            for g in request.globals
        ]
        # background gossip sends bare metadata; only an install that
        # originated inside a traced request carries context here
        tracer = self.instance.tracer
        tp = _md_traceparent(context)
        trace = (
            tracer.join("peers_update", tracing.parse_traceparent(tp))
            if tp
            else None
        )
        with tracing.scope(tracer, trace):
            await self.instance.update_peer_globals(updates)
        return peers_pb2.UpdatePeerGlobalsResp()

    async def ReplicateBuckets(self, request, context):
        from gubernator_tpu.serve.replication import Snapshot

        snaps = [
            Snapshot(
                key=b.key,
                algorithm=b.algorithm,
                limit=b.limit,
                duration=b.duration,
                remaining=b.remaining,
                reset_time=b.reset_time,
                status=b.status,
                snapshot_ms=b.snapshot_ms,
            )
            for b in request.buckets
        ]
        tracer = self.instance.tracer
        tp = _md_traceparent(context)
        trace = (
            tracer.join(
                "peers_replicate", tracing.parse_traceparent(tp)
            )
            if tp
            else None
        )
        with tracing.scope(tracer, trace):
            await self.instance.replicate_buckets(request.owner, snaps)
        return peers_pb2.ReplicateBucketsResp()


def register_servicers(grpc_server, instance: Instance):
    """Embed gubernator in a caller-owned `grpc.aio` server.

    The reference explicitly supports this shape: the application
    provides the gRPC server and drives peer membership itself
    (reference config.go:29-30, architecture.md:79-91). Here the same
    contract: register the V1 + PeersV1 services on `grpc_server` and
    return the instance (for chaining). The caller owns the server
    lifecycle and discovery:

        backend = make_backend(conf)          # or any backend object
        instance = Instance(conf, backend)
        instance.start()                      # batcher + gossip tasks
        register_servicers(my_grpc_server, instance)
        await my_grpc_server.start()
        await instance.set_peers([PeerInfo(address=..., is_owner=...)])
        ...
        await instance.stop()                 # before the loop closes

    Notes: call inside the event loop that will run the server —
    Instance.start() binds its batcher to the running loop; set_peers
    replaces the full membership each call (pass every live peer, with
    is_owner=True on this node's own advertise address); warmup of a
    device backend (backend.warmup()) is the caller's pre-serve step,
    as in Server._start_inner."""
    add_v1_servicer(grpc_server, V1Servicer(instance))
    add_peers_servicer(grpc_server, PeersV1Servicer(instance))
    return instance


#: content type gating the HTTP gateway's binary GEB door (r12) — a
#: deliberate mirror of client_geb.GEB_CONTENT_TYPE (the client module
#: must not be a serving-tier dependency; test-pinned equal)
GEB_CONTENT_TYPE = "application/x-guber-geb"


class Server:
    """One daemon: gRPC + HTTP, an Instance, and discovery."""

    _profiling = False
    _edge = None
    _geb = None
    _geb_core = None

    def __init__(self, conf: ServerConfig, backend=None):
        self.conf = conf
        self.backend = backend if backend is not None else make_backend(conf)
        self.instance = Instance(conf, self.backend)
        self.grpc_server: Optional[grpc.aio.Server] = None
        self._http_runner: Optional[web.AppRunner] = None
        self._pool = None

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        try:
            await self._start_inner()
        except Exception:
            # a partial start (bind failure, bad static peer, edge socket
            # in use, ...) must not leak the instance's already-running
            # tasks: the caller's loop may close next, and a still-pending
            # flusher dies with "Task was destroyed but it is pending"
            await self.stop()
            raise

    async def _start_inner(self) -> None:
        warmup = getattr(self.backend, "warmup", None)
        if warmup is not None:
            # compile every device-batch bucket before accepting traffic;
            # first jit on a TPU can take tens of seconds and must never be
            # paid inside a request deadline
            await asyncio.to_thread(warmup)
        self.instance.start()
        if self.instance.checkpoint is not None:
            # boot-time warm restore (r19) BEFORE any door opens: the
            # batcher is running (installs need it) but no traffic can
            # race the install. Every failure path inside boots cold
            # and loudly — a bad checkpoint must never wedge a boot.
            await self.instance.checkpoint.restore()

        self.grpc_server = grpc.aio.server(
            interceptors=[StatsInterceptor()],
            options=[("grpc.max_receive_message_length", 1 << 20)],
        )
        register_servicers(self.grpc_server, self.instance)
        bound = self.grpc_server.add_insecure_port(self.conf.grpc_address)
        if bound == 0:
            raise RuntimeError(
                f"failed to bind gRPC address {self.conf.grpc_address}"
            )
        await self.grpc_server.start()
        log.info("gRPC listening on %s", self.conf.grpc_address)
        try:
            from gubernator_tpu.native import hashlib_native as _hn

            has_prep = getattr(_hn, "_HAS_PREP", False)
        except (ImportError, AttributeError, OSError):
            # same fallback envelope as the engine's import (a
            # present-but-unloadable .so must not abort startup — the
            # numpy paths serve fine)
            has_prep = False
        if has_prep:
            log.info(
                "native prep: %d thread(s) (GUBER_PREP_THREADS), "
                "writeback=%s (GUBER_WRITEBACK), arrival prep %s "
                "(GUBER_PREP_AT_ARRIVAL)",
                _hn.prep_threads(),
                os.environ.get("GUBER_WRITEBACK", "auto"),
                "on" if self.instance.batcher.prep_at_arrival
                and self.instance.batcher._prep_ok else "off",
            )
        else:
            log.info(
                "native prep library not built/loadable; numpy "
                "fallbacks active"
            )

        shed = self.instance.shed
        if shed is not None:
            # boot-time sizing lint, like the store footprint pass in
            # make_backend: an over-provisioned shed bound is host
            # memory that can never hold a live verdict
            from gubernator_tpu.serve.shedcache import (
                footprint_mib,
                lint_footprint,
            )

            cap = 0
            stats = self.backend.stats()
            if "size" not in stats:  # device backends: rows * slots
                try:
                    sc = self.conf.store_config(logger=log)
                    cap = sc.rows * sc.slots
                except Exception:
                    cap = 0
            lint = lint_footprint(shed.capacity, cap)
            if lint:
                log.warning("%s", lint)
            log.info(
                "over-limit shed cache: %d keys (~%.1f MiB) "
                "(GUBER_SHED_CACHE / GUBER_SHED_CACHE_KEYS)",
                shed.capacity, footprint_mib(shed.capacity),
            )
        else:
            log.info("over-limit shed cache: off (GUBER_SHED_CACHE=0)")

        repl = self.instance.repl
        if repl is not None:
            from gubernator_tpu.serve.replication import footprint_mib

            log.info(
                "bucket replication: on — window %.0f ms, standby "
                "bound %d keys (~%.1f MiB), backlog %d "
                "(GUBER_REPLICATION / GUBER_REPLICATION_SYNC_WAIT_MS / "
                "GUBER_REPLICATION_STANDBY_KEYS / "
                "GUBER_REPLICATION_BACKLOG)",
                repl.sync_wait * 1e3, repl.standby_cap,
                footprint_mib(repl.standby_cap), repl.backlog_cap,
            )
        else:
            log.info("bucket replication: off (GUBER_REPLICATION=0)")

        resc = self.instance.rescale
        if resc is not None:
            log.info(
                "elastic rescale: on — double-serve window %.0f ms, "
                "tracked-key bound %d, flush tick %.0f ms "
                "(GUBER_RESCALE / GUBER_RESCALE_DOUBLE_SERVE_MS / "
                "GUBER_RESCALE_TRACK_KEYS / "
                "GUBER_REPLICATION_SYNC_WAIT_MS)",
                resc.double_serve_s * 1e3, resc.track_cap,
                resc.sync_wait * 1e3,
            )
        else:
            log.info("elastic rescale: off (GUBER_RESCALE=0)")

        ckpt = self.instance.checkpoint
        if ckpt is not None:
            from gubernator_tpu.serve.checkpoint import (
                disk_footprint_mib,
            )

            log.info(
                "checkpoint/restore: on — dir %r, interval %.0f ms, "
                "max restore age %.0f s, tracked-key bound %d "
                "(~%.1f MiB on disk), export targets %s "
                "(GUBER_CHECKPOINT_DIR / GUBER_CHECKPOINT_INTERVAL_MS "
                "/ GUBER_CHECKPOINT_MAX_AGE_MS / "
                "GUBER_CHECKPOINT_TRACK_KEYS / "
                "GUBER_CHECKPOINT_EXPORT_PEERS)",
                ckpt.dir, ckpt.sync_wait * 1e3, ckpt.max_age,
                ckpt.track_cap, disk_footprint_mib(ckpt.track_cap),
                ckpt.export_peers or "none",
            )
        else:
            log.info(
                "checkpoint/restore: off (GUBER_CHECKPOINT_DIR unset)"
            )

        if self.conf.geb_port:
            from gubernator_tpu.serve.edge_bridge import GebListener

            geb_peer_doors = {}
            for pair in self.conf.geb_peer_doors.split(","):
                if not pair.strip():
                    continue
                grpc_addr, sep, door = pair.strip().partition("=")
                if not sep or not grpc_addr or not door:
                    raise ValueError(
                        "GUBER_GEB_PEER_DOORS entries must be "
                        f"'grpc_addr=door_addr', got {pair!r}"
                    )
                geb_peer_doors[grpc_addr] = door
            self._geb = GebListener(
                self.instance,
                f"0.0.0.0:{self.conf.geb_port}",
                fast_enabled=self.conf.edge_fast,
                window=self.conf.geb_window or self.conf.edge_window,
                string_fold=self.conf.edge_string_fold,
                peer_bridges=geb_peer_doors or None,
            )
            await self._geb.start()
            log.info(
                "GEB client protocol door on :%d (GUBER_GEB_PORT; "
                "window %d, GUBER_GEB_WINDOW)",
                self.conf.geb_port, self._geb.window,
            )
        if self.conf.http_address:
            await self._start_http()
        if self.conf.edge_socket or self.conf.edge_tcp:
            from gubernator_tpu.serve.edge_bridge import EdgeBridge

            peer_bridges = {}
            for pair in self.conf.edge_peer_bridges.split(","):
                if not pair.strip():
                    continue
                grpc_addr, sep, bridge = pair.strip().partition("=")
                if not sep or not grpc_addr or not bridge:
                    raise ValueError(
                        "GUBER_EDGE_PEER_BRIDGES entries must be "
                        f"'grpc_addr=bridge_addr', got {pair!r}"
                    )
                peer_bridges[grpc_addr] = bridge
            self._edge = EdgeBridge(
                self.instance,
                self.conf.edge_socket,
                tcp_address=self.conf.edge_tcp,
                peer_bridges=peer_bridges,
                fast_enabled=self.conf.edge_fast,
                window=self.conf.edge_window,
                string_fold=self.conf.edge_string_fold,
                max_payload=self.conf.edge_max_frame_mib << 20,
                shm_enabled=self.conf.shm,
                shm_ring_kib=self.conf.shm_ring_kib,
                shm_poll_us=self.conf.shm_poll_us,
            )
            await self._edge.start()

        await self._start_discovery()

    async def drain(self) -> dict:
        """Graceful drain (SIGTERM path), bounded end to end by
        GUBER_DRAIN_TIMEOUT_MS: (1) deregister from discovery so peers
        and edges stop routing new work here; (2) the edge bridge
        refuses NEW frames (GEBR drain code) after answering the ones
        in flight; (3) the gRPC server and (4) the HTTP gateway stop
        accepting and let in-flight requests finish — every request
        door is closed BEFORE the queue flushes, or the batcher's
        run-dry wait could chase a moving target; (5) aggregated
        GLOBAL hits/updates flush to their owners; (6) the device
        batcher runs dry. Each step gets the budget remaining; a step
        that times out keeps its handle so the caller's stop() still
        hard-closes it. Returns step timings (the chaos soak records
        them)."""
        t0 = time.monotonic()
        budget = getattr(self.conf, "drain_timeout", 5.0)
        deadline = t0 + budget

        def remaining() -> float:
            return max(0.05, deadline - time.monotonic())

        timings = {}

        async def step(name, coro) -> bool:
            t = time.monotonic()
            ok = True
            try:
                await asyncio.wait_for(coro, remaining())
            except asyncio.TimeoutError:
                log.warning("drain step '%s' exceeded the budget", name)
                ok = False
            except Exception as e:
                log.warning("drain step '%s' failed: %s", name, e)
            timings[name] = time.monotonic() - t
            return ok

        if self.instance.rescale is not None:
            # planned-departure handoff (r17) BEFORE deregistration:
            # every tracked window ships to the owner the ring elects
            # once this node is gone, so the snapshots are parked on
            # their new owners before any peer's ring flips — the
            # receiving side seeds them on its first owned touch
            await step(
                "rescale_handoff", self.instance.rescale.drain()
            )
        if self._pool is not None:
            if await step("deregister", self._pool.close()):
                self._pool = None
        if self._edge is not None:
            # self-bounding (its poll loop carries the deadline): no
            # wait_for, so it is never cancelled mid-refusal
            t = time.monotonic()
            await self._edge.drain(remaining())
            timings["edge"] = time.monotonic() - t
        if self._geb is not None:
            # the client-protocol door drains like the bridge: answer
            # accepted frames, GEBR-refuse new ones, close the listener
            t = time.monotonic()
            await self._geb.drain(remaining())
            timings["geb"] = time.monotonic() - t
        if self.conf.http_address:
            # HTTP-door frame core: flag it so a frame POSTed
            # mid-drain gets the GEBR drain body (the HTTP runner
            # cleanup below bounds the in-flight ones). Built through
            # _frame_core(), not checked-if-built: a node that saw no
            # GEB traffic yet must still refuse the first frame that
            # races the drain, instead of lazily building an
            # un-flagged core for it
            self._frame_core()._draining = True
        if self.grpc_server is not None:
            # grace makes stop() self-bounding (handlers are
            # force-cancelled when it expires) — and it must NOT run
            # under wait_for: cancelling grpc.aio's stop() mid-flight
            # leaves the server in a state where a LATER stop() can
            # await forever (observed as SIGTERMed daemons outliving
            # their supervisor's kill timeout by minutes)
            t = time.monotonic()
            await self.grpc_server.stop(grace=remaining())
            timings["grpc"] = time.monotonic() - t
            self.grpc_server = None
        if self._http_runner is not None:
            # stops the sites (no new connections) and shuts the app
            # down, finishing in-flight handlers — without this, HTTP
            # requests accepted mid-drain would be reset by stop().
            # Bounded by the site's shutdown_timeout (2s, _start_http)
            # on top of the wait_for; a timed-out cleanup keeps the
            # handle so stop() finishes it
            if await step("http", self._http_runner.cleanup()):
                self._http_runner = None
        await step("global_flush", self.instance.global_mgr.drain())
        if self.instance.repl is not None:
            # ship still-dirty owned windows to their successors (and
            # attempt one handback round) before the batcher runs dry —
            # a SIGTERMed owner must not take its freshest quota state
            # down with it
            await step(
                "replication_flush", self.instance.repl.drain()
            )
        if self.instance.checkpoint is not None:
            # final checkpoint + blue-green export (r19): state on
            # disk (and on the replacement fleet) leaves at most one
            # in-flight request stale instead of one interval
            await step(
                "checkpoint_flush", self.instance.checkpoint.drain()
            )
        await step("batcher", self.instance.batcher.drain())
        timings["total"] = time.monotonic() - t0
        try:
            metrics.DRAIN_DURATION.set(timings["total"])
        except Exception:  # pragma: no cover - defensive
            pass
        log.info(
            "drained in %.0f ms (budget %.0f ms): %s",
            timings["total"] * 1e3, budget * 1e3,
            {k: round(v * 1e3, 1) for k, v in timings.items()},
        )
        return timings

    async def stop(self) -> None:
        if self._edge is not None:
            await self._edge.stop()
            self._edge = None
        if self._geb is not None:
            await self._geb.stop()
            self._geb = None
        self._geb_core = None
        if self._pool is not None:
            await self._pool.close()
            self._pool = None
        if self._http_runner is not None:
            await self._http_runner.cleanup()
            self._http_runner = None
        if self.grpc_server is not None:
            await self.grpc_server.stop(grace=1.0)
            self.grpc_server = None
        await self.instance.stop()
        close = getattr(self.backend, "close", None)
        if close is not None:
            close()  # e.g. MultiHostBackend: clean step-pipe shutdown

    # -- HTTP gateway -------------------------------------------------------

    async def _start_http(self) -> None:
        app = web.Application()
        app.router.add_post("/v1/GetRateLimits", self._http_get_rate_limits)
        # protobuf-free binary door (r12): one GEB frame per POST body,
        # content-type gated; GET serves the hello (ring + flags) so a
        # fast client can negotiate exactly like the socket doors
        app.router.add_post("/v1/geb", self._http_geb)
        app.router.add_get("/v1/geb", self._http_geb_hello)
        app.router.add_get("/v1/HealthCheck", self._http_health)
        app.router.add_get("/metrics", self._http_metrics)
        app.router.add_get("/v1/debug/stats", self._http_debug_stats)
        app.router.add_get("/v1/debug/stages", self._http_debug_stages)
        app.router.add_get("/v1/debug/traces", self._http_debug_traces)
        app.router.add_get("/v1/debug/profile", self._http_debug_profile)
        self._http_runner = web.AppRunner(app)
        await self._http_runner.setup()
        host, _, port = self.conf.http_address.rpartition(":")
        # shutdown_timeout bounds how long cleanup() waits for open
        # connections (aiohttp default: 60s!). Rate-limit requests are
        # milliseconds of work, so 2s covers any in-flight handler
        # while keeping SIGTERM (drain, then stop) promptly bounded —
        # a lingering idle keep-alive must not stall shutdown.
        site = web.TCPSite(
            self._http_runner, host or "0.0.0.0", int(port),
            shutdown_timeout=2.0,
        )
        await site.start()
        log.info("HTTP listening on %s", self.conf.http_address)

    async def _http_get_rate_limits(self, request: web.Request):
        try:
            body = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            # JSONDecodeError for bad JSON; UnicodeDecodeError for a
            # non-UTF-8 body (raised by aiohttp's .text() underneath)
            return web.json_response({"error": "invalid json"}, status=400)
        # shape-validate before field access: a JSON array or scalar body
        # (or a non-list "requests") must be a 400, not an unhandled
        # AttributeError turned 500
        if not isinstance(body, dict) or not isinstance(
            body.get("requests", []), list
        ):
            return web.json_response(
                {"error": "body must be an object with a 'requests' list"},
                status=400,
            )
        reqs = []
        try:
            for item in body.get("requests", []):
                pb = gubernator_pb2.RateLimitReq(
                    name=item.get("name", ""),
                    unique_key=item.get(
                        "uniqueKey", item.get("unique_key", "")
                    ),
                    hits=int(item.get("hits", 0)),
                    limit=int(item.get("limit", 0)),
                    duration=int(item.get("duration", 0)),
                    algorithm=_enum_val(
                        gubernator_pb2.Algorithm, item.get("algorithm", 0)
                    ),
                    behavior=_enum_val(
                        gubernator_pb2.Behavior, item.get("behavior", 0)
                    ),
                )
                # hierarchical quota chain (r15): ancestor levels,
                # shallow to deep; depth/behavior validation happens
                # serving-side (instance.chain_error)
                for lv in item.get("chain", []) or []:
                    pb.chain.add(
                        unique_key=str(lv.get("uniqueKey",
                                              lv.get("unique_key", ""))),
                        limit=int(lv.get("limit", 0)),
                        duration=int(lv.get("duration", 0)),
                    )
                reqs.append(convert.req_from_pb(pb))
        except (AttributeError, TypeError, ValueError) as e:
            # non-object items, non-numeric int64 fields, bad enum names
            return web.json_response(
                {"error": f"invalid request item: {e}"}, status=400
            )
        # traceparent on the JSON door (r16): an incoming sampled
        # context joins the distributed trace; otherwise head/tail
        # sampling applies exactly as on the socket doors
        tracer = self.instance.tracer
        trace = tracer.join(
            "http",
            tracing.parse_traceparent(
                request.headers.get(tracing.TRACEPARENT)
            ),
        )
        try:
            with tracing.scope(tracer, trace) as tr:
                if tr is not None:
                    tr.annotate(items=len(reqs))
                resps = await self.instance.get_rate_limits(reqs)
        except BatchTooLargeError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(
            {
                "responses": [
                    {
                        "status": r.status.name,
                        "limit": str(r.limit),
                        "remaining": str(r.remaining),
                        "resetTime": str(r.reset_time),
                        "error": r.error,
                        "metadata": r.metadata,
                    }
                    for r in resps
                ]
            }
        )

    def _frame_core(self):
        """Frame-service core backing the HTTP binary door: the GEB
        listener when enabled (so drain state is shared), else a
        lazily-built listenerless FrameService over the same instance
        — either way the exact decode/shed/batch/encode pipeline the
        socket doors run (serve/edge_bridge.py)."""
        if self._geb is not None:
            return self._geb
        if self._geb_core is None:
            from gubernator_tpu.serve.edge_bridge import FrameService

            self._geb_core = FrameService(
                self.instance,
                fast_enabled=self.conf.edge_fast,
                window=self.conf.geb_window or self.conf.edge_window,
                string_fold=self.conf.edge_string_fold,
            )
        return self._geb_core

    async def _http_geb_hello(self, request: web.Request):
        return web.Response(
            body=self._frame_core().hello_bytes(),
            content_type=GEB_CONTENT_TYPE,
        )

    async def _http_geb(self, request: web.Request):
        """Binary GEB frame door (r12): the edge wire protocol with an
        HTTP request body as the transport — for clients whose
        infrastructure only passes HTTP. Content-type gated so a JSON
        client posting to the wrong path gets a clear 415, never a
        frame-decode of its JSON bytes."""
        if request.content_type != GEB_CONTENT_TYPE:
            return web.json_response(
                {
                    "error": (
                        f"content-type must be {GEB_CONTENT_TYPE} "
                        f"(one binary GEB frame per request body)"
                    )
                },
                status=415,
            )
        import struct

        from gubernator_tpu.serve.edge_bridge import MAX_FRAME_PAYLOAD

        # this door's legal frames exceed aiohttp's 1 MiB default
        # client_max_size (a full 65536-item fast frame is ~2.1 MiB),
        # so it reads the raw stream under its OWN cap — the socket
        # doors' payload bound plus frame-header slack — rather than
        # raising the app-wide bound for the JSON routes too. Not
        # request.read(): that enforces (only) the app-wide limit.
        max_body = MAX_FRAME_PAYLOAD + 64
        if (request.content_length or 0) > max_body:
            return web.json_response(
                {"error": "GEB frame exceeds the payload bound"},
                status=413,
            )
        chunks, got = [], 0
        while True:
            # StreamReader.read(n) short-reads, so loop to EOF,
            # bailing the moment the cap is crossed
            chunk = await request.content.read(1 << 16)
            if not chunk:
                break
            got += len(chunk)
            if got > max_body:
                return web.json_response(
                    {"error": "GEB frame exceeds the payload bound"},
                    status=413,
                )
            chunks.append(chunk)
        body = b"".join(chunks)
        try:
            # a traceparent header on the binary door joins the frame
            # to an existing trace (the GEBT in-frame extension works
            # here too; the header covers clients that can set HTTP
            # headers more easily than re-framing)
            resp = await self._frame_core().serve_frame_bytes(
                body,
                remote_ctx=tracing.parse_traceparent(
                    request.headers.get(tracing.TRACEPARENT)
                ),
            )
        except (ValueError, struct.error) as e:
            # struct.error covers truncated varlen payloads that pass
            # the outer length checks — client garbage, still a 400
            return web.json_response(
                {"error": f"bad GEB frame: {e}"}, status=400
            )
        except BatchTooLargeError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.Response(body=resp, content_type=GEB_CONTENT_TYPE)

    async def _http_health(self, request: web.Request):
        h = self.instance.health_check()
        return web.json_response(
            {
                "status": h.status,
                "message": h.message,
                "peerCount": h.peer_count,
            }
        )

    async def _http_metrics(self, request: web.Request):
        self._refresh_store_metrics()
        return web.Response(
            body=metrics.render(), content_type="text/plain", charset="utf-8"
        )

    def _refresh_store_metrics(self) -> None:
        stats = self.backend.stats()
        if "size" in stats:
            metrics.CACHE_SIZE.set(stats["size"])
        metrics.DISTINCT_KEYS.set(self.instance.traffic.hll.estimate())
        # per-peer breaker state gauges refresh at scrape time (state
        # also changes lazily at acquire, so transitions alone would
        # leave the gauge stale between calls)
        for peer in self.instance.peer_list():
            if peer.breaker is not None:
                metrics.PEER_BREAKER_STATE.labels(peer=peer.host).set(
                    peer.breaker.state_code
                )
        # shed-cache totals export lazily at scrape time too: the hot
        # path only bumps plain ints (serve/shedcache.py)
        shed = self.instance.shed
        if shed is not None:
            metrics.SHED_HITS.set(shed.hits)
            metrics.SHED_LOOKUPS.set(shed.lookups)
            metrics.SHED_ENTRIES.set(len(shed))
        if self.instance.repl is not None:
            metrics.REPLICATION_STANDBY_ENTRIES.set(
                self.instance.repl.standby_len
            )
        # stage totals export lazily at scrape time: the hot path only
        # touches the plain-float accumulator (serve/stages.py)
        from gubernator_tpu.serve.stages import STAGES

        snap = STAGES.snapshot()
        for name, s in snap["stages"].items():
            metrics.STAGE_SECONDS.labels(stage=name).set(s["total_s"])
            metrics.STAGE_SAMPLES.labels(stage=name).set(s["count"])
        # queue-visibility gauges (r16): standing occupancy the stage
        # clock can't express, set lazily at scrape like shed_entries
        qs = self.instance.batcher.queue_stats()
        metrics.BATCHER_QUEUE_DEPTH.set(qs["depth"])
        metrics.BATCHER_QUEUE_AGE.set(qs["oldest_age_s"])
        metrics.PREP_BACKLOG.set(qs["prep_backlog"])
        for door, svc in (("edge", self._edge), ("geb", self._geb)):
            if svc is not None:
                metrics.FRAME_INFLIGHT.labels(door=door).set(
                    svc._active_frames
                )
                metrics.FRAME_CONNECTIONS.labels(door=door).set(
                    len(svc._conns)
                )
        if self.instance.repl is not None:
            metrics.REPLICATION_BACKLOG_ENTRIES.set(
                self.instance.repl.backlog_len
            )
        ckpt = self.instance.checkpoint
        if ckpt is not None:
            metrics.CHECKPOINT_TRACKED_ENTRIES.set(ckpt.tracked_len)
            # age refreshes at scrape time (the flush loop only stamps
            # last_ok_ms) so operators see it GROW when writes fail
            age = ckpt.age_seconds
            if age is not None:
                metrics.CHECKPOINT_AGE.set(age)
        if self.instance.rescale is not None:
            metrics.RESCALE_TRACKED_ENTRIES.set(
                self.instance.rescale.tracked_len
                + self.instance.rescale.pending_len
            )
        for queue, size in (
            self.instance.global_mgr.backlog_sizes().items()
        ):
            metrics.GLOBAL_BACKLOG_ENTRIES.labels(queue=queue).set(size)
        # flight-recorder counters (r16): plain ints on the recorder,
        # exported here
        rec = self.instance.tracer.recorder
        metrics.TRACES_STARTED.set(rec.started)
        metrics.TRACES_RECORDED.set(rec.recorded)
        metrics.TRACES_TAIL_CAPTURED.set(rec.tail_captured)
        metrics.TRACES_DROPPED.set(rec.dropped)
        metrics.TRACE_SLOW_THRESHOLD.set(rec.threshold_ms())

    async def _http_debug_stats(self, request: web.Request):
        """Traffic observability: HLL cardinality + top hot keys + backend
        counters (no reference analogue; see core/sketches.py)."""
        try:
            top_n = int(request.query.get("top", "20"))
        except ValueError:
            return web.json_response(
                {"error": "'top' must be an integer"}, status=400
            )
        body = self.instance.traffic.snapshot(max(top_n, 0))
        body["backend"] = self.backend.stats()
        return web.json_response(body)

    async def _http_debug_stages(self, request: web.Request):
        """Serving-pipeline stage attribution (serve/stages.py): where
        one served decision's wall time goes — edge transit, frame
        decode, batcher queue, device span (with the submit/fetch
        split), response encode — plus the coverage of those stages
        against frame end-to-end time. `?reset=1` zeroes the
        accumulators (the profiler scopes a measurement window with
        it). The reference has per-RPC Prometheus totals only; this is
        the decomposition that says which stage to attack next."""
        from gubernator_tpu.serve.stages import STAGES

        shed = self.instance.shed
        if request.query.get("reset") in ("1", "true"):
            STAGES.reset()
            if shed is not None:
                shed.reset_counters()
        body = STAGES.snapshot()
        # over-limit shed cache counters ride along (entries, hits,
        # lookups, hit_rate): the shed stage's spans above say where
        # the time went, this says how much work never became a stage
        if shed is not None:
            body["shed_cache"] = shed.stats()
        return web.json_response(body)

    async def _http_debug_traces(self, request: web.Request):
        """The flight recorder (r16, serve/tracing.py): completed
        sampled + tail-captured traces, newest last. `?id=<32-hex>`
        fetches one trace by id (404 when it aged out of the ring);
        `?limit=N` bounds the listing (default 64); `?reset=1` clears
        the ring and counters (a profiler scopes a window with it,
        like /v1/debug/stages)."""
        rec = self.instance.tracer.recorder
        if request.query.get("reset") in ("1", "true"):
            rec.reset()
        tid = request.query.get("id", "")
        if tid:
            doc = rec.get(tid)
            if doc is None:
                return web.json_response(
                    {"error": f"no retained trace with id '{tid}'"},
                    status=404,
                )
            return web.json_response(doc)
        try:
            limit = int(request.query.get("limit", "64"))
        except ValueError:
            return web.json_response(
                {"error": "'limit' must be an integer"}, status=400
            )
        body = rec.snapshot(limit=max(0, limit))
        body["sample"] = self.instance.tracer.sample
        body["slow_ms"] = self.instance.tracer.slow_ms
        return web.json_response(body)

    async def _http_debug_profile(self, request: web.Request):
        """Capture a JAX/XLA device profile for ?ms= milliseconds (default
        1000) and write it under /tmp/guber-profile/<?name=> (?name= is a
        single path component, default "trace"). View with TensorBoard or
        Perfetto. The reference has no tracing at all
        (SURVEY.md section 5); this is the TPU-native replacement for its
        per-RPC Prometheus histograms when you need to see *inside* a
        batch."""
        import asyncio

        import os.path

        if request.query.get("list") in ("1", "true"):
            # served artifact dir (r16): enumerate captured profiles so
            # an operator can find what to pull into TensorBoard/
            # Perfetto without shelling into the box
            base = "/tmp/guber-profile"
            out = []
            try:
                for name in sorted(os.listdir(base)):
                    d = os.path.join(base, name)
                    if not os.path.isdir(d):
                        continue
                    files = size = 0
                    for dp, _, fs in os.walk(d):
                        files += len(fs)
                        size += sum(
                            os.path.getsize(os.path.join(dp, f))
                            for f in fs
                        )
                    out.append(
                        {"name": name, "files": files, "bytes": size}
                    )
            except FileNotFoundError:
                pass
            return web.json_response(
                {"base_dir": base, "profiles": out}
            )
        try:
            ms = int(request.query.get("ms", "1000"))
        except ValueError:
            return web.json_response(
                {"error": "'ms' must be an integer"}, status=400
            )
        ms = max(0, min(ms, 60_000))  # reported below as actually captured
        # `name` is a single path component under a fixed base — this is
        # the only write-capable endpoint on the HTTP surface, so clients
        # must not be able to aim it at arbitrary paths
        name = request.query.get("name", "trace")
        if os.path.basename(name) != name or name in ("", ".", ".."):
            return web.json_response(
                {"error": "'name' must be a bare directory name"},
                status=400,
            )
        out_dir = os.path.join("/tmp/guber-profile", name)
        if self._profiling:
            return web.json_response(
                {"error": "profile already in progress"}, status=409
            )
        self._profiling = True
        started = False
        try:
            import jax

            jax.profiler.start_trace(out_dir)
            started = True
            await asyncio.sleep(ms / 1000.0)
        except Exception as e:  # tunnel backends may not support tracing
            return web.json_response(
                {"error": f"profiler unavailable: {e}"}, status=501
            )
        finally:
            # stop even on client disconnect (CancelledError) so the
            # endpoint is usable again without a restart
            if started:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:
                    log.exception("stop_trace failed")
            self._profiling = False
        return web.json_response({"trace_dir": out_dir, "captured_ms": ms})

    # -- discovery ----------------------------------------------------------

    async def _start_discovery(self) -> None:
        advertise = self.conf.resolved_advertise()
        if self.conf.etcd_endpoints:
            from gubernator_tpu.serve.discovery import EtcdPool

            self._pool = EtcdPool(
                endpoints=self.conf.etcd_endpoints,
                prefix=self.conf.etcd_prefix,
                advertise=advertise,
                on_update=self._on_peers,
                tls_cert=self.conf.etcd_tls_cert,
                tls_key=self.conf.etcd_tls_key,
                tls_ca=self.conf.etcd_tls_ca,
            )
            await self._pool.start()
        elif self.conf.k8s_endpoints_selector:
            from gubernator_tpu.serve.discovery import K8sPool

            self._pool = K8sPool(
                namespace=self.conf.k8s_namespace,
                selector=self.conf.k8s_endpoints_selector,
                pod_ip=self.conf.k8s_pod_ip,
                pod_port=self.conf.k8s_pod_port,
                on_update=self._on_peers,
            )
            await self._pool.start()
        else:
            from gubernator_tpu.serve.discovery import StaticPool

            self._pool = StaticPool(
                peers=self.conf.peers or [advertise],
                advertise=advertise,
                on_update=self._on_peers,
            )
            await self._pool.start()

    async def _on_peers(self, peers) -> None:
        await self.instance.set_peers(peers)


def _enum_val(enum_pb, v):
    if isinstance(v, str):
        return enum_pb.Value(v)
    return int(v)


async def run_daemon(conf: ServerConfig) -> None:
    """Start a server and run until SIGINT/SIGTERM (reference
    cmd/gubernator/main.go:127-139). SIGTERM (the orchestrated-shutdown
    signal) drains gracefully — deregister, refuse new edge frames,
    finish in-flight work, flush GLOBAL + batcher queues — bounded by
    GUBER_DRAIN_TIMEOUT_MS; SIGINT stops immediately."""
    import signal

    server = Server(conf)
    await server.start()
    stop = asyncio.Event()
    graceful: list = []
    drain_task: list = []
    loop = asyncio.get_running_loop()

    def on_term():
        # second SIGTERM = the supervisor is impatient: abandon the
        # drain and hard-stop now
        if graceful:
            graceful.clear()
            for t in drain_task:
                t.cancel()
        graceful.append(True)
        stop.set()

    loop.add_signal_handler(signal.SIGINT, stop.set)
    loop.add_signal_handler(signal.SIGTERM, on_term)
    await stop.wait()
    # shutdown watchdog on a plain THREAD (immune to a wedged event
    # loop): a signalled daemon must exit within a bound, full stop.
    # The drain itself is budgeted, but a teardown await that never
    # returns (e.g. a client-library close wedging under load) would
    # otherwise leave a zombie the supervisor has to SIGKILL minutes
    # later — observed in the full-suite soak as daemons outliving
    # their test's kill timeout.
    import os
    import threading

    def _force_exit():
        log.error(
            "shutdown watchdog fired (teardown wedged); forcing exit"
        )
        logging.shutdown()
        os._exit(1)

    watchdog = threading.Timer(
        2 * getattr(conf, "drain_timeout", 5.0) + 10.0, _force_exit
    )
    watchdog.daemon = True
    watchdog.start()
    if graceful:
        log.info("SIGTERM: draining")
        drain_task.append(asyncio.ensure_future(server.drain()))
        try:
            await drain_task[0]
        except asyncio.CancelledError:
            log.warning("drain aborted (second SIGTERM)")
    log.info("shutting down")
    await server.stop()
    watchdog.cancel()
