"""Host-prep pipeline: sorted-run merge combine (r9).

The device batcher's submit thread used to pay the whole host prep for
a batch at flush time: flatten every caller group, concatenate, and
argsort the flattened batch by (owner, bucket, fingerprint) before
dispatch. With arrival-time prep (serve/batcher.py), each group is
converted, clipped, and PRE-SORTED on a small prep pool when it is
enqueued — so by flush time the batch is a set of sorted runs, and the
only serialized work left is stitching them together.

This module is that stitch: a stable k-way merge of pre-sorted uint64
key runs, O(n log k) instead of the O(n log n) full sort, built from
`np.searchsorted` passes (two binary-search gathers per merge level).
The merge is exactly equivalent to `np.argsort(concat, kind="stable")`
over the concatenated un-sorted batch — equal keys keep run order, and
runs arrive in caller order — which is what makes the merged device
fields byte-identical to the flush-time concat+argsort path
(tests/test_prep_pipeline.py pins this).

Pure numpy (plus the optional native lib) on purpose: importing this
module never pulls jax. merge_runs dispatches to the fused native
merge (guber_merge_runs, one GIL-free pass) when the library is built;
the engines (core/engine.py, parallel/sharded.py) consume the merged
output through their `merge_prepped` / `decide_submit_presorted`
entry points.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

#: field order of a prepped run's `fields` dict — matches
#: backends._ArrayOps.ARRAY_FIELDS
RUN_FIELDS = ("key_hash", "hits", "limit", "duration", "algo", "gnp")

try:  # fused native merge (guberhash.cc guber_merge_runs): one GIL-free
    # pass instead of ~30 small numpy ops — under a contended host the
    # numpy form's wall time amplifies ~10x from GIL preemption alone
    from gubernator_tpu.native import hashlib_native as _hn

    if not getattr(_hn, "_HAS_MERGE", False):
        raise AttributeError("guber_merge_runs missing")
except (ImportError, AttributeError, OSError):  # pragma: no cover
    _hn = None


def _merge2(
    a: Tuple[np.ndarray, np.ndarray], b: Tuple[np.ndarray, np.ndarray]
) -> Tuple[np.ndarray, np.ndarray]:
    """Stable two-way merge of (sorted_keys, payload) pairs: equal keys
    from `a` land before equal keys from `b` (searchsorted sides left/
    right), matching a stable sort of their concatenation."""
    sa, ta = a
    sb, tb = b
    na, nb = sa.shape[0], sb.shape[0]
    if na == 0:
        return b
    if nb == 0:
        return a
    pos_a = np.searchsorted(sb, sa, side="left") + np.arange(
        na, dtype=np.int64
    )
    pos_b = np.searchsorted(sa, sb, side="right") + np.arange(
        nb, dtype=np.int64
    )
    s = np.empty(na + nb, sa.dtype)
    t = np.empty(na + nb, ta.dtype)
    s[pos_a] = sa
    s[pos_b] = sb
    t[pos_a] = ta
    t[pos_b] = tb
    return s, t


def merge_sorted_runs(
    skeys: Sequence[np.ndarray],
) -> Tuple[np.ndarray, np.ndarray]:
    """Stable k-way merge of pre-sorted key runs.

    Returns `(skey, take)` where `skey` is the merged sorted stream and
    `take[i]` indexes the VIRTUAL concatenation of the runs:
    `skey == np.concatenate(skeys)[take]`. Because each run is
    stable-sorted and ties across runs resolve in run order, `take` is
    exactly `np.argsort(np.concatenate(skeys_unsorted), kind="stable")`
    composed with the per-run sorts — the property the merge-combine
    equivalence contract rests on."""
    offsets = np.zeros(len(skeys) + 1, np.int64)
    np.cumsum([s.shape[0] for s in skeys], out=offsets[1:])
    nodes = [
        (np.asarray(s, np.uint64),
         np.arange(offsets[i], offsets[i + 1], dtype=np.int64))
        for i, s in enumerate(skeys)
    ]
    if not nodes:
        return np.empty(0, np.uint64), np.empty(0, np.int64)
    # pairwise tree merge in run order: log2(k) levels, each one linear
    # pass + two binary-search gathers; adjacent pairing preserves run
    # order, which _merge2's left/right sides turn into tie stability
    while len(nodes) > 1:
        nxt = [
            _merge2(nodes[i], nodes[i + 1])
            if i + 1 < len(nodes)
            else nodes[i]
            for i in range(0, len(nodes), 2)
        ]
        nodes = nxt
    return nodes[0]


def merge_runs(runs: List[dict]) -> Dict[str, np.ndarray]:
    """Merge per-group prepped runs (engine `prep_run` output) into one
    batch-level sorted field set for `decide_submit_presorted`.

    Each run carries `n`, sorted `skey`, within-group `order` (caller
    index of sorted row j), per-shard `counts`, and device-dtype
    `fields` in sorted order. The merged `order` maps each merged row
    to its index in the FLATTENED batch (groups concatenated in caller
    order) — the permutation `decide_wait` unpermutes responses with.
    """
    if len(runs) == 1:
        r = runs[0]
        return dict(
            skey=r["skey"],
            order=np.asarray(r["order"], np.int32),
            counts=r["counts"],
            fields=r["fields"],
        )
    counts = runs[0]["counts"].copy()
    for r in runs[1:]:
        counts += r["counts"]
    if _hn is not None:
        n = int(sum(r["n"] for r in runs))
        m = _hn.merge_runs_native(runs, n)  # flat (B == n)
        return dict(
            skey=m["skey"],
            order=m["order"],
            counts=counts,
            fields={k: m[k] for k in RUN_FIELDS},
        )
    skey, take = merge_sorted_runs([r["skey"] for r in runs])
    base = 0
    gorders = []
    for r in runs:
        gorders.append(np.asarray(r["order"], np.int64) + base)
        base += r["n"]
    order = np.concatenate(gorders)[take].astype(np.int32)
    fields = {
        k: np.concatenate([r["fields"][k] for r in runs])[take]
        for k in RUN_FIELDS
    }
    return dict(skey=skey, order=order, counts=counts, fields=fields)
