"""First-class GEB client protocol (r12): the windowed binary frame
protocol as a PUBLIC client surface.

r10's profiling found the doors clients could actually reach (gRPC
protobuf, HTTP JSON) ceiling at ~110k dec/s on this class of box while
the internal windowed GEB framing sustains 340-560k on the same
hardware — the serialization/RPC tier, not the engine, was the
front-door bottleneck. This module closes that gap from the client
side: a JAX-free client (like `gubernator_tpu.client`) that speaks the
bridge wire protocol directly to

  - a daemon's GEB listener (`GUBER_GEB_PORT`, serve/edge_bridge.py
    GebListener) — 'host:port',
  - a co-located bridge socket — '/path.sock' or 'unix:/path.sock',

with hello/version negotiation, credit-windowed pipelining (up to the
server's advertised window of frames in flight per connection,
completed out of order), reconnect, and the GEBR drain/stale-ring
refusal semantics of r7/r8 honored.

Framing choice (`mode`):

  - 'string' — GEB2 windowed string frames (GEB1 against a pre-r7
    server). Items carry name/key; the daemon validates, routes, and
    forwards exactly as the gRPC door does. Correct on ANY topology.
  - 'fast' — GEB7 windowed pre-hashed frames (GEB6 legacy). The client
    hashes `name_key` itself and the daemon's array path decides the
    items locally with no per-item Python — the edge binary's fast
    path, from a library. Requires the client and the store to run the
    SAME slot hash (the hello's HELLO_XXH64 bit advertises the
    server's implementation) and, because fast frames bypass instance
    routing, keys this node actually owns.
  - 'auto' (default) — fast when the hello advertises it, the hash
    implementations agree, and the ring is single-node (where every
    key is owned by construction); string otherwise, and per batch for
    requests fast framing cannot carry (GLOBAL/NO_BATCHING behaviors,
    empty name/key). Multi-node fast routing remains the compiled
    edge's job.

Delivery semantics: a frame refused by GEBR (stale ring or drain) was
NOT served — retrying it (elsewhere) is safe, and the raised error
says so. A connection lost mid-flight leaves in-doubt frames
(`GebConnectionError`); whether their hits were applied is unknown,
the same at-most-once stance as the peer-forwarding tier.

The wire constants here are deliberate duplicates of
serve/edge_bridge.py's (this module must not import the serving tier);
tests/test_geb_client.py pins them equal.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import logging
import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.endpoints import parse_endpoint

__all__ = [
    "AsyncGebClient",
    "GebClient",
    "AsyncHttpGebClient",
    "GebError",
    "GebStaleRingError",
    "GebDrainingError",
    "GebConnectionError",
    "GEB_CONTENT_TYPE",
    "GEB_HTTP_PATH",
]

# -- wire constants (mirrors of serve/edge_bridge.py, test-pinned) ----------

MAGIC_REQ = 0x31424547  # 'GEB1'
MAGIC_RESP = 0x33424547  # 'GEB3'
MAGIC_HELLO = 0x49424547  # 'GEBI'
MAGIC_FAST_REQ = 0x36424547  # 'GEB6'
MAGIC_FAST_RESP = 0x35424547  # 'GEB5'
MAGIC_STALE = 0x52424547  # 'GEBR'
MAGIC_WREQ = 0x32424547  # 'GEB2'
MAGIC_WRESP = 0x34424547  # 'GEB4'
MAGIC_WFAST_REQ = 0x37424547  # 'GEB7'
MAGIC_WFAST_RESP = 0x38424547  # 'GEB8'
MAGIC_WCHAIN = 0x43424547  # 'GEBC' — chain-extended string req (r15)
MAGIC_WTRACE = 0x54424547  # 'GEBT' — trace-extended string req (r16)
MAGIC_SHM_REQ = 0x4D424547  # 'GEBM' — map a shared-memory lane (r18)
MAGIC_SHM_OK = 0x4E424547  # 'GEBN' — lane reply (path_len 0 = refused)

HELLO_FAST = 1
HELLO_WINDOWED = 2
HELLO_XXH64 = 4
HELLO_CHAIN = 8  # server accepts GEBC chain-extended frames (r15)
HELLO_TRACE = 16  # server accepts GEBT trace-extended frames (r16)
HELLO_SHM = 32  # this connection may negotiate the shm lane (r18)

log = logging.getLogger("gubernator_tpu.client_geb")

DRAIN_FRAME_ID = 0xFFFFFFFF

_HDR = struct.Struct("<II")
_ITEM_FIX = struct.Struct("<qqqBB")
_RESP_FIX = struct.Struct("<Bqqq")
_WFAST_HDR = struct.Struct("<IIQ")  # frame_id | ring_hash | t_sent_us
_WREQ_HDR = struct.Struct("<IQ")  # frame_id | t_sent_us
# GEBT trace extension after _WREQ_HDR (r16): 16B big-endian trace id,
# u64 span id, u8 flags (bit 0 = sampled)
_WTRACE_EXT = struct.Struct("<16sQB")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_FAST_REQ = struct.Struct("<QqqqB")  # key_hash|hits|limit|duration|algo

#: content type gating the HTTP gateway's binary door (POST /v1/geb)
GEB_CONTENT_TYPE = "application/x-guber-geb"
GEB_HTTP_PATH = "/v1/geb"

#: frames beyond this refuse client-side: the daemon chunks at its own
#: batch ladder, but an unbounded frame is an unbounded host alloc
MAX_FRAME_ITEMS = 65536

#: hard cap on one frame's payload bytes, mirroring the server's
#: read-side bound (edge_bridge.MAX_FRAME_PAYLOAD, test-pinned): the
#: server kills any connection advertising more before buffering it,
#: so refuse loudly here instead of dying with a dropped connection
MAX_FRAME_PAYLOAD = 8 << 20


def _check_wire_count(n: int) -> int:
    """Bound a server-supplied response item count BEFORE sizing a
    read from it — the mirror of the server's lying-length defense: a
    byzantine or desynced peer advertising a ~4G count must raise, not
    buffer gigabytes toward readexactly."""
    if n > MAX_FRAME_ITEMS:
        raise GebError(
            f"response item count {n} exceeds the "
            f"{MAX_FRAME_ITEMS}-item frame bound"
        )
    return n


class GebError(Exception):
    """Protocol-level client error."""


class GebStaleRingError(GebError):
    """The server refused the frame: routed under a stale membership
    view (GEBR). The frame was NOT served; reconnecting re-reads the
    hello (fresh ring) and retrying is safe."""


class GebDrainingError(GebError):
    """The server is draining (GEBR drain code): this frame was NOT
    served and the listener is closing. Retry against another node."""


class GebConnectionError(GebError):
    """Connection lost with frames in flight: whether their hits were
    applied is unknown (at-most-once ambiguity, like a failed peer
    forward). Peek-only batches are always safe to retry."""


# -- client-side slot hashing (fast framing) --------------------------------

_hash_batch = None
_hash_checked = False


def _load_hasher() -> None:
    global _hash_batch, _hash_checked
    if _hash_checked:
        return
    _hash_checked = True
    try:
        # ctypes + numpy only — no JAX (gubernator_tpu.native)
        from gubernator_tpu.native import hashlib_native

        _hash_batch = hashlib_native.hash_batch
    except Exception:
        _hash_batch = None


def client_hash_is_native() -> bool:
    """True when this process hashes with the native XXH64 library —
    must match the server's HELLO_XXH64 bit for fast framing."""
    _load_hasher()
    return _hash_batch is not None


def client_hash_batch(keys: Sequence[str]):
    """uint64 slot hashes, identical to the daemon's
    core.hashing.slot_hash_batch for the same implementation tier:
    native XXH64 when the shared library loads, else the blake2b-8
    fallback (byte-identical to core.hashing._slot_hash_batch_py).
    Kept here, not imported, because `gubernator_tpu.core` enables
    JAX x64 at import and this client must stay JAX-free."""
    import numpy as np

    _load_hasher()
    if _hash_batch is not None:
        return _hash_batch(list(keys))
    return np.array(
        [
            int.from_bytes(
                hashlib.blake2b(
                    k.encode("utf-8"), digest_size=8
                ).digest(),
                "little",
            )
            for k in keys
        ],
        dtype=np.uint64,
    )


# -- hello ------------------------------------------------------------------


@dataclass
class Hello:
    """Parsed GEBI hello: capability flags, credit window, ring
    fingerprint, and the live membership (grpc address, that node's
    frame-door endpoint, is_self)."""

    flags: int = 0
    ring_hash: int = 0
    nodes: List[Tuple[bool, str, str]] = field(default_factory=list)

    @property
    def windowed(self) -> bool:
        return bool(self.flags & HELLO_WINDOWED)

    @property
    def fast(self) -> bool:
        return bool(self.flags & HELLO_FAST)

    @property
    def xxh64(self) -> bool:
        return bool(self.flags & HELLO_XXH64)

    @property
    def chain(self) -> bool:
        return bool(self.flags & HELLO_CHAIN)

    @property
    def trace(self) -> bool:
        return bool(self.flags & HELLO_TRACE)

    @property
    def shm(self) -> bool:
        return bool(self.flags & HELLO_SHM)

    @property
    def window(self) -> int:
        return max(1, self.flags >> 16) if self.windowed else 1


async def read_hello(reader: asyncio.StreamReader) -> Hello:
    magic, flags, rhash, n_nodes = struct.unpack(
        "<IIII", await reader.readexactly(16)
    )
    if magic != MAGIC_HELLO:
        raise GebError(
            f"endpoint did not speak GEB (hello magic {magic:#x})"
        )
    if n_nodes > 4096:
        raise GebError(f"implausible hello node count {n_nodes}")
    nodes = []
    for _ in range(n_nodes):
        is_self, glen = struct.unpack(
            "<BH", await reader.readexactly(3)
        )
        grpc = (await reader.readexactly(glen)).decode()
        (blen,) = _U16.unpack(await reader.readexactly(2))
        bridge = (await reader.readexactly(blen)).decode()
        nodes.append((bool(is_self), grpc, bridge))
    return Hello(flags=flags, ring_hash=rhash, nodes=nodes)


def parse_hello_bytes(buf: bytes) -> Hello:
    """Parse one complete hello from a byte buffer (the HTTP door's
    GET /v1/geb response)."""
    if len(buf) < 16:
        raise GebError("short hello")
    magic, flags, rhash, n_nodes = struct.unpack_from("<IIII", buf, 0)
    if magic != MAGIC_HELLO:
        raise GebError(f"bad hello magic {magic:#x}")
    if n_nodes > 4096:
        raise GebError(f"implausible hello node count {n_nodes}")
    off = 16
    nodes = []
    try:
        for _ in range(n_nodes):
            is_self, glen = struct.unpack_from("<BH", buf, off)
            off += 3
            grpc = buf[off : off + glen].decode()
            off += glen
            (blen,) = _U16.unpack_from(buf, off)
            off += 2
            bridge = buf[off : off + blen].decode()
            off += blen
            nodes.append((bool(is_self), grpc, bridge))
    except (struct.error, UnicodeDecodeError) as e:
        raise GebError(f"malformed hello: {e}") from None
    return Hello(flags=flags, ring_hash=rhash, nodes=nodes)


# -- frame codec ------------------------------------------------------------


def _fast_eligible_item(r: RateLimitReq) -> bool:
    """Per-item fast-framing eligibility (the ring router partitions
    on this): BATCHING behavior, non-empty name/key, no quota chain."""
    return bool(
        r.behavior == Behavior.BATCHING
        and r.name
        and r.unique_key
        and not r.chain
    )


def _fast_eligible(reqs: Sequence[RateLimitReq]) -> bool:
    """Fast records carry (hash, hits, limit, duration, algo) only: no
    behavior, no validation-error channel, no quota-chain levels.
    GLOBAL/NO_BATCHING items, empty names/keys, and chained requests
    (r15 — the 33-byte record has no varlen room) must ride string
    frames."""
    return all(_fast_eligible_item(r) for r in reqs)


def encode_fast_payload(reqs: Sequence[RateLimitReq]) -> bytes:
    """n x 33-byte pre-hashed records (the edge binary's encoding)."""
    import numpy as np

    hashes = client_hash_batch([r.hash_key() for r in reqs])
    rec = np.zeros(
        len(reqs),
        dtype=np.dtype(
            [
                ("key_hash", "<u8"),
                ("hits", "<i8"),
                ("limit", "<i8"),
                ("duration", "<i8"),
                ("algo", "u1"),
            ]
        ),
    )
    rec["key_hash"] = hashes
    rec["hits"] = [r.hits for r in reqs]
    rec["limit"] = [r.limit for r in reqs]
    rec["duration"] = [r.duration for r in reqs]
    rec["algo"] = [int(r.algorithm) for r in reqs]
    return rec.tobytes()


def encode_string_payload(reqs: Sequence[RateLimitReq]) -> bytes:
    parts = []
    for r in reqs:
        name = r.name.encode()
        key = r.unique_key.encode()
        if len(name) > 0xFFFF or len(key) > 0xFFFF:
            raise GebError("name/unique_key exceed 65535 bytes")
        parts.append(_U16.pack(len(name)))
        parts.append(name)
        parts.append(_U16.pack(len(key)))
        parts.append(key)
        parts.append(
            _ITEM_FIX.pack(
                r.hits,
                r.limit,
                r.duration,
                int(r.algorithm),
                int(r.behavior),
            )
        )
    return b"".join(parts)


def encode_chain_payload(reqs: Sequence[RateLimitReq]) -> bytes:
    """GEBC chain-extended items (r15): each string item followed by a
    u8 level count and that many (u16 key_len | key | i64 limit |
    i64 duration) ancestor levels, shallow to deep. Plain items ride
    with a 0 count, so one mixed batch stays one frame."""
    parts = []
    for r in reqs:
        name = r.name.encode()
        key = r.unique_key.encode()
        if len(name) > 0xFFFF or len(key) > 0xFFFF:
            raise GebError("name/unique_key exceed 65535 bytes")
        chain = getattr(r, "chain", None) or []
        if len(chain) > 0xFF:
            raise GebError("chain exceeds 255 levels")
        parts.append(_U16.pack(len(name)))
        parts.append(name)
        parts.append(_U16.pack(len(key)))
        parts.append(key)
        parts.append(
            _ITEM_FIX.pack(
                r.hits,
                r.limit,
                r.duration,
                int(r.algorithm),
                int(r.behavior),
            )
        )
        parts.append(struct.pack("<B", len(chain)))
        for lv in chain:
            lk = lv.unique_key.encode()
            if len(lk) > 0xFFFF:
                raise GebError("chain level key exceeds 65535 bytes")
            parts.append(_U16.pack(len(lk)))
            parts.append(lk)
            parts.append(struct.pack("<qq", lv.limit, lv.duration))
    return b"".join(parts)


def decode_fast_body(body: bytes, n: int) -> List[RateLimitResp]:
    if len(body) != n * 25:
        raise GebError("fast response length mismatch")
    out = []
    off = 0
    for _ in range(n):
        st, limit, rem, reset = _RESP_FIX.unpack_from(body, off)
        off += _RESP_FIX.size
        if st not in (0, 1):
            # a corrupted or future-version status must fail loudly,
            # never decode fail-open as "allowed"
            raise GebError(f"bad status byte {st:#x} in fast response")
        out.append(
            RateLimitResp(
                status=Status(st),
                limit=limit,
                remaining=rem,
                reset_time=reset,
            )
        )
    return out


def decode_string_body(body: bytes, n: int) -> List[RateLimitResp]:
    """Parse n GEB3/GEB4 response items (varlen error/owner) from a
    complete buffer."""
    out = []
    off = 0
    try:
        for _ in range(n):
            st, limit, rem, reset = _RESP_FIX.unpack_from(body, off)
            off += _RESP_FIX.size
            (elen,) = _U16.unpack_from(body, off)
            off += 2
            err = body[off : off + elen].decode()
            off += elen
            (olen,) = _U16.unpack_from(body, off)
            off += 2
            owner = body[off : off + olen].decode()
            off += olen
            out.append(_string_resp(st, limit, rem, reset, err, owner))
    except (struct.error, UnicodeDecodeError) as e:
        raise GebError(f"malformed string response: {e}") from None
    if off != len(body):
        raise GebError("trailing bytes in string response")
    return out


def _string_resp(st, limit, rem, reset, err, owner) -> RateLimitResp:
    if st not in (0, 1):
        # fail loudly, never fail-open as "allowed" (see decode_fast_body)
        raise GebError(f"bad status byte {st:#x} in string response")
    r = RateLimitResp(
        status=Status(st),
        limit=limit,
        remaining=rem,
        reset_time=reset,
        error=err,
    )
    if owner:
        r.metadata["owner"] = owner
    return r


def build_frame(
    reqs: Sequence[RateLimitReq],
    fast: bool,
    windowed: bool,
    frame_id: int = 0,
    ring_hash: int = 0,
    t_sent_us: int = 0,
    trace_ctx=None,
) -> Tuple[bytes, bool]:
    """Encode one request frame; returns (bytes, is_fast).

    `trace_ctx` (r16, a serve/tracing.TraceContext) emits the GEBT
    trace-extended framing — windowed string frames only. It is
    silently dropped for fast frames (the 33-byte records are
    trace-free by design; the server head-samples those bridge-side)
    and for chained frames (GEBC has no trace slot — documented scope
    limit)."""
    if not reqs:
        raise GebError("empty request batch")
    if len(reqs) > MAX_FRAME_ITEMS:
        raise GebError(
            f"batch of {len(reqs)} exceeds the {MAX_FRAME_ITEMS}-item "
            f"frame bound; split it"
        )
    chained = any(getattr(r, "chain", None) for r in reqs)
    if chained and not windowed:
        raise GebError(
            "quota chains need the windowed GEBC framing; this server "
            "negotiated the legacy single-frame protocol (pre-r7)"
        )
    use_fast = fast and not chained and _fast_eligible(reqs)
    payload = (
        encode_fast_payload(reqs)
        if use_fast
        else encode_chain_payload(reqs)
        if chained
        else encode_string_payload(reqs)
    )
    if len(payload) > MAX_FRAME_PAYLOAD:
        # in practice only string frames with very long names/keys get
        # here (a max-item fast frame is ~2.1 MiB), but both framings
        # are bounded: the server refuses anything beyond the cap by
        # killing the connection, so fail loudly before the wire
        raise GebError(
            f"frame payload of {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_PAYLOAD}-byte bound; split the batch"
        )
    if use_fast:
        if windowed:
            hdr = _HDR.pack(MAGIC_WFAST_REQ, len(reqs)) + _WFAST_HDR.pack(
                frame_id, ring_hash, t_sent_us
            )
        else:
            hdr = _HDR.pack(MAGIC_FAST_REQ, len(reqs)) + _U32.pack(
                ring_hash
            )
        return hdr + _U32.pack(len(payload)) + payload, True
    if windowed:
        if trace_ctx is not None and not chained:
            hdr = (
                _HDR.pack(MAGIC_WTRACE, len(reqs))
                + _WREQ_HDR.pack(frame_id, t_sent_us)
                + _WTRACE_EXT.pack(
                    (trace_ctx.trace_id & ((1 << 128) - 1)).to_bytes(
                        16, "big"
                    ),
                    trace_ctx.span_id & ((1 << 64) - 1),
                    1 if trace_ctx.sampled else 0,
                )
            )
        else:
            hdr = _HDR.pack(
                MAGIC_WCHAIN if chained else MAGIC_WREQ, len(reqs)
            ) + _WREQ_HDR.pack(frame_id, t_sent_us)
    else:
        hdr = _HDR.pack(MAGIC_REQ, len(reqs))
    return hdr + _U32.pack(len(payload)) + payload, use_fast


# -- async client -----------------------------------------------------------


class AsyncGebClient:
    """Asyncio GEB client: one connection, up to the negotiated credit
    window of frames in flight, completed out of order. Concurrent
    `get_rate_limits` calls pipeline onto the same connection — that
    is the throughput model (the r7 windowed protocol); one call alone
    still pays a single round trip."""

    def __init__(
        self,
        endpoint: str,
        window: int = 0,
        mode: str = "auto",
        timeout: Optional[float] = None,
        shm: str = "auto",
        ring_route: Optional[bool] = None,
    ):
        """`shm` (r18): 'auto' maps the shared-memory lane when the
        endpoint is a unix socket and the hello advertises HELLO_SHM
        (frames fall back to the control socket transparently when the
        ring is full or torn); 'off' never negotiates; 'require' raises
        at connect() unless the lane maps. `ring_route` (r18): on a
        multi-node ring, shard fast frames per owner across per-node
        connections instead of downgrading to string frames — default
        from GUBER_CLIENT_RING_ROUTE (off). Ignored when routing can't
        be sound (no fast capability, hash mismatch, missing peer
        doors); stats() says why."""
        if mode not in ("auto", "fast", "string"):
            raise ValueError("mode must be 'auto', 'fast', or 'string'")
        if shm not in ("auto", "off", "require"):
            raise ValueError("shm must be 'auto', 'off', or 'require'")
        self._kind, self._addr = parse_endpoint(
            endpoint, "GEB endpoint"
        )
        self.endpoint = endpoint
        self.mode = mode
        self.timeout = timeout
        self.shm = shm
        if ring_route is None:
            ring_route = os.environ.get(
                "GUBER_CLIENT_RING_ROUTE", "0"
            ).lower() not in ("0", "false", "no", "off", "")
        self.ring_route = bool(ring_route)
        self._want_window = window
        self.hello: Optional[Hello] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer = None
        self._read_task: Optional[asyncio.Task] = None
        self._sem: Optional[asyncio.Semaphore] = None
        self._inflight: dict = {}
        self._next_id = 1
        self._use_fast = False
        self._windowed = True
        self._window = 1
        self._legacy_lock: Optional[asyncio.Lock] = None
        self._conn_lock: Optional[asyncio.Lock] = None
        self._closed = False
        # r18 satellite: auto-mode downgrades to string frames were
        # silent — count them, log once, and surface the reason
        self._downgrades = 0
        self._downgrade_reason: Optional[str] = None
        self._downgrade_logged = False
        # r18 shm lane + ring router state
        self._lane = None
        self._ring_hash_override: Optional[int] = None
        self._router: Optional["_RingRouter"] = None
        self._frames_socket = 0
        self._frames_shm = 0

    # -- connection ---------------------------------------------------------

    async def connect(self) -> Hello:
        """Open (or reuse) the connection and return the parsed hello.
        Reconnecting after a failure re-reads the hello — a GEBR
        stale-ring refusal is healed exactly this way."""
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None:
                return self.hello
            if self._closed:
                raise GebError("client is closed")
            if self._kind == "unix":
                reader, writer = await asyncio.open_unix_connection(
                    self._addr
                )
            else:
                host, port = self._addr
                reader, writer = await asyncio.open_connection(host, port)
            try:
                hello = await read_hello(reader)
            except Exception:
                writer.close()
                raise
            self._negotiate(hello)
            self.hello = hello
            self._reader, self._writer = reader, writer
            self._inflight = {}
            self._sem = asyncio.Semaphore(self._window)
            self._legacy_lock = asyncio.Lock()
            if self.shm != "off":
                # negotiate BEFORE the read loop owns the reader: the
                # GEBN reply is the only frame read inline post-hello
                mapped = False
                if (
                    self._kind == "unix"
                    and self._windowed
                    and hello.shm
                ):
                    try:
                        mapped = await self._negotiate_shm(
                            reader, writer
                        )
                    except Exception:
                        writer.close()
                        self._reader = self._writer = None
                        raise
                if self.shm == "require" and not mapped:
                    writer.close()
                    self._reader = self._writer = None
                    raise GebError(
                        "shm='require' but no lane mapped (endpoint "
                        "not a unix socket, server without HELLO_SHM, "
                        "or the server refused the ring)"
                    )
            if self._windowed:
                self._read_task = asyncio.ensure_future(
                    self._read_loop(reader, writer)
                )
        if self.ring_route and self._router is None:
            # outside _conn_lock: the router opens more AsyncGebClients
            # whose own connect() must not deadlock on re-entry
            self._maybe_start_router()
        return self.hello

    def _negotiate(self, hello: Hello) -> None:
        self._windowed = hello.windowed
        self._window = hello.window
        if self._want_window > 0:
            self._window = max(1, min(self._window, self._want_window))
        if self.mode == "string":
            self._use_fast = False
            return
        if self.mode == "fast":
            if not hello.fast:
                raise GebError(
                    "mode='fast' but the server does not advertise the "
                    "pre-hashed fast path (non-array backend or "
                    "GUBER_EDGE_FAST=0)"
                )
            # forced: the caller asserts topology/hash agreement
            self._use_fast = True
            return
        # auto: fast only when provably sound — hash implementations
        # agree and the ring is single-node (fast frames bypass
        # instance routing; multi-node fast routing is the edge's job,
        # or — with ring_route (r18) — the router's below)
        self._use_fast = (
            hello.fast
            and hello.xxh64 == client_hash_is_native()
            and len(hello.nodes) <= 1
        )
        if not self._use_fast:
            if not hello.fast:
                reason = "hello capability (no fast path advertised)"
            elif hello.xxh64 != client_hash_is_native():
                reason = "hash mismatch (server/client XXH64 tiers)"
            else:
                reason = "multi-node ring (fast frames bypass routing)"
            if self.ring_route and reason.startswith("multi-node"):
                # the router rescues exactly this case — not a
                # downgrade; _maybe_start_router records if it can't
                return
            self._downgrades += 1
            self._downgrade_reason = reason
            if not self._downgrade_logged:
                self._downgrade_logged = True
                log.info(
                    "geb client %s: auto mode downgraded to string "
                    "frames — %s (logged once; see stats())",
                    self.endpoint,
                    reason,
                )

    async def _negotiate_shm(self, reader, writer) -> bool:
        """Map the shared-memory lane (r18): send GEBM, read the GEBN
        reply inline (the windowed read loop is not running yet), open
        and start the lane. False = server refused (path_len 0) — the
        connection simply continues on the socket."""
        writer.write(_HDR.pack(MAGIC_SHM_REQ, 0))
        await writer.drain()
        magic, plen = _HDR.unpack(await reader.readexactly(8))
        if magic != MAGIC_SHM_OK:
            raise GebError(
                f"bad shm negotiation reply magic {magic:#x}"
            )
        if plen == 0:
            return False
        if plen > 4096:
            raise GebError(f"implausible shm path length {plen}")
        await reader.readexactly(16)  # ring caps; the header governs
        path = (await reader.readexactly(plen)).decode()
        # stdlib-only module (no JAX); lazy so socket-only clients
        # never touch it
        from gubernator_tpu.serve.shm import ShmClientLane

        poll_us = int(
            os.environ.get("GUBER_SHM_POLL_US", "0") or 0
        )
        lane = ShmClientLane(path, poll_us=poll_us)
        lane.start(
            asyncio.get_running_loop(),
            self._on_ring_frame,
            self._on_ring_torn,
            max_resp_len=MAX_FRAME_PAYLOAD + 64,
        )
        self._lane = lane
        return True

    def _on_ring_frame(self, data: bytes) -> None:
        """One complete response frame popped from the s2c ring
        (event-loop thread). The lane carries the exact socket frame
        bytes, so this is `_read_loop`'s parse over a buffer."""
        try:
            magic, n = _HDR.unpack_from(data, 0)
            if magic == MAGIC_STALE:
                if n == DRAIN_FRAME_ID:
                    exc: GebError = GebDrainingError(
                        f"{self.endpoint} is draining; frame not "
                        f"served (safe to retry elsewhere)"
                    )
                else:
                    exc = GebStaleRingError(
                        "frame refused: routed under a stale ring "
                        "(GEBR); reconnect re-reads the hello"
                    )
                self._conn_lost(exc)
                return
            (fid,) = _U32.unpack_from(data, 8)
            _check_wire_count(n)
            if magic == MAGIC_WFAST_RESP:
                resps = decode_fast_body(data[12:], n)
            elif magic == MAGIC_WRESP:
                resps = decode_string_body(data[12:], n)
            else:
                raise GebError(f"bad response magic {magic:#x}")
        except (GebError, struct.error) as e:
            self._conn_lost(
                e if isinstance(e, GebError) else GebError(str(e))
            )
            return
        fut = self._inflight.pop(fid, None)
        if fut is not None and not fut.done():
            fut.set_result(resps)

    def _on_ring_torn(self, exc: Exception) -> None:
        """The lane died under us (server teardown, drain, protocol
        violation). Frames in flight on the ring are in-doubt — the
        module's at-most-once stance is a connection loss; the next
        call reconnects over the socket and may re-map."""
        if self._lane is None:
            return
        self._conn_lost(exc)

    def _maybe_start_router(self) -> None:
        """Activate per-owner fast routing (r18) when it is provably
        sound: multi-node ring, fast capability, matching hash tiers,
        and a routable frame door for every peer. Records why not,
        otherwise swaps get_rate_limits onto the router."""
        hello = self.hello
        if hello is None or self.mode == "string":
            return
        if len(hello.nodes) <= 1:
            return  # single node: the direct fast path already won
        reason = None
        if not hello.fast:
            reason = "hello capability (no fast path advertised)"
        elif hello.xxh64 != client_hash_is_native():
            reason = "hash mismatch (server/client XXH64 tiers)"
        elif any(
            not is_self and not door
            for is_self, _, door in hello.nodes
        ):
            reason = "peer door unknown (GUBER_GEB_PEER_DOORS unset?)"
        if reason is not None:
            self._downgrades += 1
            self._downgrade_reason = reason
            if not self._downgrade_logged:
                self._downgrade_logged = True
                log.info(
                    "geb client %s: ring routing unavailable — %s; "
                    "staying on string frames (logged once)",
                    self.endpoint,
                    reason,
                )
            return
        self._router = _RingRouter(self, hello)

    def _conn_lost(self, exc: Optional[BaseException]) -> None:
        """Fail everything still in flight and reset so the next call
        reconnects fresh (new hello, new ring)."""
        lane, self._lane = self._lane, None
        if lane is not None:
            lane.close()
        inflight, self._inflight = self._inflight, {}
        self._reader = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        # cancel the reader so a stale loop can't outlive its
        # connection (its own teardown is writer-identity-guarded, so
        # even an uncancellable straggler cannot touch a successor)
        task = self._read_task
        self._read_task = None
        if task is not None and task is not asyncio.current_task():
            task.cancel()
        for fut in inflight.values():
            if not fut.done():
                # only GEBR refusals carry per-frame semantics that
                # hold for EVERY frame in flight (the server refused
                # them all un-served — retry is safe); any other
                # failure, including a decode error on one response,
                # leaves the others' delivery unknown and must surface
                # as the connection-loss type, not the trigger's
                fut.set_exception(
                    exc
                    if isinstance(
                        exc, (GebStaleRingError, GebDrainingError)
                    )
                    else GebConnectionError(
                        f"connection to {self.endpoint} lost with "
                        f"frames in flight ({exc!r}); delivery unknown"
                    )
                )

    async def close(self) -> None:
        self._closed = True
        router, self._router = self._router, None
        if router is not None:
            await router.close()
        task = self._read_task
        self._conn_lost(GebError("client closed"))
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    def stats(self) -> dict:
        """Operator-facing counters (r18 satellite): which transport
        and framing this client actually negotiated, and whether auto
        mode silently downgraded to string frames (and why)."""
        transport = self._kind
        if self._lane is not None:
            transport = "shm"
        return {
            "endpoint": self.endpoint,
            "mode": self.mode,
            "transport": transport,
            "use_fast": self._use_fast,
            "ring_routed": self._router is not None,
            "downgrades": self._downgrades,
            "downgrade_reason": self._downgrade_reason,
            "frames_socket": self._frames_socket,
            "frames_shm": self._frames_shm,
        }

    async def __aenter__(self):
        await self.connect()
        return self

    async def __aexit__(self, *a):
        await self.close()

    # -- request path -------------------------------------------------------

    async def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = None,
        trace=None,
    ) -> List[RateLimitResp]:
        """Serve one batch as one frame. Under concurrency, calls
        pipeline up to the credit window; responses match by frame id
        regardless of completion order.

        `trace` (r16): a serve/tracing.TraceContext to carry in-band
        over the GEBT framing — or, by default, the caller's active
        SAMPLED trace context (serve.tracing, stdlib-only) when the
        server advertises HELLO_TRACE. Fast and chained frames drop
        the context (trace-free by design / no GEBC slot); pre-r16
        servers never see GEBT.

        With ring routing active (r18), fast-eligible items shard per
        owner across per-node connections; the rest ride this
        connection's string frames. Responses return in input order."""
        await self.connect()
        if self._router is not None:
            return await self._router.get_rate_limits(
                reqs, timeout, trace
            )
        return await self._get_direct(reqs, timeout, trace)

    async def _get_direct(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = None,
        trace=None,
    ) -> List[RateLimitResp]:
        """One batch -> one frame on THIS connection (the pre-r18
        get_rate_limits body; the router calls it per shard)."""
        await self.connect()
        if (
            any(getattr(r, "chain", None) for r in reqs)
            and not self.hello.chain
        ):
            # sending GEBC at a pre-r15 server would poison the
            # connection (bad magic) — refuse client-side instead
            raise GebError(
                "server does not accept quota-chain frames "
                "(no HELLO_CHAIN capability; pre-r15?)"
            )
        trace_ctx = None
        if self.hello.trace and self._windowed:
            if trace is not None:
                trace_ctx = trace
            else:
                from gubernator_tpu.serve import tracing as _tracing

                tr = _tracing.active()
                if tr is not None and tr.sampled:
                    trace_ctx = tr.context()
        if not self._windowed:
            return await self._legacy_roundtrip(reqs, timeout)
        loop = asyncio.get_running_loop()
        fid = self._next_id
        self._next_id = (self._next_id + 1) & 0x7FFFFFFF or 1
        frame, is_fast = build_frame(
            reqs,
            fast=self._use_fast,
            windowed=True,
            frame_id=fid,
            ring_hash=(
                self._ring_hash_override
                if self._ring_hash_override is not None
                else self.hello.ring_hash
            ),
            t_sent_us=int(loop.time() * 1e6),
            trace_ctx=trace_ctx,
        )
        fut = loop.create_future()
        sem = self._sem
        await sem.acquire()
        writer = self._writer
        if writer is None:
            sem.release()
            raise GebConnectionError("connection lost before send")
        self._inflight[fid] = fut
        # shm lane first (r18): False means no room right now or the
        # frame outgrows the ring's bound — that frame (only) falls
        # back to the control socket, same connection, same window
        lane = self._lane
        if lane is not None and lane.try_send(frame):
            self._frames_shm += 1
        else:
            try:
                writer.write(frame)
                await writer.drain()
                self._frames_socket += 1
            except (ConnectionError, OSError) as e:
                self._inflight.pop(fid, None)
                sem.release()
                self._conn_lost(e)
                raise GebConnectionError(
                    f"send to {self.endpoint} failed: {e}"
                ) from e
        try:
            resps = await asyncio.wait_for(
                fut, timeout if timeout is not None else self.timeout
            )
        except asyncio.TimeoutError:
            # the window slot is wedged (frame may still be in service
            # server-side): the connection is no longer accountable —
            # drop it so state can't leak into later calls
            self._conn_lost(
                GebConnectionError("frame timed out; connection reset")
            )
            raise
        finally:
            sem.release()
        if len(resps) != len(reqs):
            raise GebError(
                f"response count {len(resps)} != request {len(reqs)}"
            )
        return resps

    async def _legacy_roundtrip(self, reqs, timeout):
        """Pre-r7 server: one frame in flight per connection
        (GEB1/GEB6 framings, version-skew fallback)."""
        frame, is_fast = build_frame(
            reqs,
            fast=self._use_fast,
            windowed=False,
            ring_hash=self.hello.ring_hash,
        )

        async def roundtrip():
            async with self._legacy_lock:
                writer, reader = self._writer, self._reader
                if writer is None:
                    raise GebConnectionError("connection lost")
                writer.write(frame)
                await writer.drain()
                magic, n = _HDR.unpack(await reader.readexactly(8))
                if magic == MAGIC_STALE:
                    raise GebStaleRingError(
                        "frame refused: stale ring (GEBR)"
                    )
                _check_wire_count(n)
                if is_fast:
                    if magic != MAGIC_FAST_RESP:
                        raise GebError(f"bad response magic {magic:#x}")
                    return decode_fast_body(
                        await reader.readexactly(n * 25), n
                    )
                if magic != MAGIC_RESP:
                    raise GebError(f"bad response magic {magic:#x}")
                return await _read_string_items(reader, n)

        try:
            return await asyncio.wait_for(
                roundtrip(),
                timeout if timeout is not None else self.timeout,
            )
        except (
            GebError,
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            asyncio.TimeoutError,
        ) as e:
            # ANY failure here leaves the one-frame-in-flight stream
            # unaccountable (response half-read or never read): drop
            # the connection so leftover bytes can't be parsed as the
            # next call's response header
            self._conn_lost(None if isinstance(e, GebError) else e)
            if isinstance(e, (GebError, asyncio.TimeoutError)):
                raise
            raise GebConnectionError(
                f"round trip to {self.endpoint} failed: {e}"
            ) from e

    # -- response reader ----------------------------------------------------

    async def _read_loop(self, reader, writer):
        exc: Optional[BaseException] = None
        try:
            while True:
                magic, n = _HDR.unpack(await reader.readexactly(8))
                if magic == MAGIC_STALE:
                    # GEBR: second word is the refused frame id; every
                    # frame still in flight was refused un-served too
                    # (the server closes the connection behind it)
                    if n == DRAIN_FRAME_ID:
                        exc = GebDrainingError(
                            f"{self.endpoint} is draining; frame not "
                            f"served (safe to retry elsewhere)"
                        )
                    else:
                        exc = GebStaleRingError(
                            "frame refused: routed under a stale ring "
                            "(GEBR); reconnect re-reads the hello"
                        )
                    return
                (fid,) = _U32.unpack(await reader.readexactly(4))
                _check_wire_count(n)
                if magic == MAGIC_WFAST_RESP:
                    resps = decode_fast_body(
                        await reader.readexactly(n * 25), n
                    )
                elif magic == MAGIC_WRESP:
                    resps = await _read_string_items(reader, n)
                else:
                    raise GebError(f"bad response magic {magic:#x}")
                fut = self._inflight.pop(fid, None)
                if fut is not None and not fut.done():
                    fut.set_result(resps)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
        ) as e:
            exc = e
        except asyncio.CancelledError:
            return
        except Exception as e:  # protocol desync
            exc = e
        finally:
            # identity guard: only tear down the connection THIS loop
            # was reading. After a timeout/reconnect, self._writer is
            # a successor connection with its own loop and in-flight
            # table — a stale loop's exit must not fail it.
            if self._writer is writer or self._writer is None:
                if self._lane is not None and self._inflight:
                    # the socket EOF raced the lane: frames already
                    # PUBLISHED to the ring (responses, or the GEBR
                    # that explains this close) are ordered on the
                    # ring but not against the socket — bounded grace
                    # for the lane to deliver them before declaring
                    # delivery unknown (a ring GEBR lands its own
                    # _conn_lost with the refusal semantics)
                    loop = asyncio.get_running_loop()
                    deadline = loop.time() + 1.0
                    try:
                        while (
                            self._lane is not None
                            and self._inflight
                            and loop.time() < deadline
                        ):
                            await asyncio.sleep(0.005)
                    except asyncio.CancelledError:
                        pass
                if self._writer is writer or self._writer is None:
                    self._conn_lost(exc)


async def _read_string_items(reader, n: int) -> List[RateLimitResp]:
    out = []
    for _ in range(n):
        st, limit, rem, reset = _RESP_FIX.unpack(
            await reader.readexactly(_RESP_FIX.size)
        )
        (elen,) = _U16.unpack(await reader.readexactly(2))
        err = (await reader.readexactly(elen)).decode()
        (olen,) = _U16.unpack(await reader.readexactly(2))
        owner = (await reader.readexactly(olen)).decode()
        out.append(_string_resp(st, limit, rem, reset, err, owner))
    return out


# -- client-side per-owner fast routing (r18) -------------------------------


def _ring_point(key: str) -> int:
    """crc32 ring point, byte-identical to core.hashing.ring_hash /
    reference hash.go:40-42 (duplicated: this module stays JAX-free)."""
    return zlib.crc32(key.encode("utf-8")) & 0xFFFFFFFF


async def _fetch_hello(kind: str, addr) -> Hello:
    """Read one fresh hello over a throwaway connection (GEBR healing:
    the PRIMARY connection stays up while the ring view refreshes)."""
    if kind == "unix":
        reader, writer = await asyncio.open_unix_connection(addr)
    else:
        host, port = addr
        reader, writer = await asyncio.open_connection(host, port)
    try:
        return await read_hello(reader)
    finally:
        writer.close()


class _RingRouter:
    """Shards fast-eligible items per owner across per-node GEB
    connections — the compiled edge's routing, client-side.

    The table is the picker's ring exactly (crc32 point per grpc
    address, sorted, binary-search successor with wraparound on the
    item's `name_key`), built from the hello's membership rows; each
    node's frame door comes from the same rows (self = the primary
    endpoint, peers = their advertised door). Every child connection
    echoes the ROUTER's membership fingerprint — the hello this table
    was built from, NOT the child's own fresher hello — so a server
    whose ring moved refuses with GEBR instead of silently serving a
    mis-routed frame. A GEBR refusal re-fetches the hello over a
    throwaway connection, rebuilds the table, and retries the REFUSED
    shards only (refused = un-served, so the retry is safe), bounded
    at MAX_ATTEMPTS. Connection losses propagate (at-most-once).
    Items fast framing cannot carry (GLOBAL/NO_BATCHING, empty
    name/key, chains) ride the primary connection's string frames."""

    MAX_ATTEMPTS = 3

    def __init__(self, owner: "AsyncGebClient", hello: Hello):
        self._owner = owner
        self._children: Dict[str, AsyncGebClient] = {}
        self._points: List[int] = []
        self._hosts: List[str] = []
        self._endpoints: Dict[str, str] = {}
        self._ring_hash = 0
        self.refreshes = 0
        stale = self._install(hello)
        assert not stale  # no children exist yet

    def _install(self, hello: Hello) -> List["AsyncGebClient"]:
        """(Re)build the table from a hello; returns the children the
        new membership obsoletes (the caller closes them — this method
        stays synchronous)."""
        endpoints: Dict[str, str] = {}
        points: List[Tuple[int, str]] = []
        for is_self, grpc_addr, door in hello.nodes:
            if is_self:
                endpoints[grpc_addr] = self._owner.endpoint
            elif door:
                endpoints[grpc_addr] = door
            else:
                raise GebError(
                    f"ring node {grpc_addr} advertises no frame door; "
                    f"cannot route (GUBER_GEB_PEER_DOORS unset?)"
                )
            points.append((_ring_point(grpc_addr), grpc_addr))
        points.sort()
        if len({p for p, _ in points}) != len(points):
            # mirror of the picker's collision refusal: placement
            # would silently diverge between this table and the ring
            raise GebError("ring point collision between peer addresses")
        self._points = [p for p, _ in points]
        self._hosts = [h for _, h in points]
        self._ring_hash = hello.ring_hash
        stale = []
        for host, child in list(self._children.items()):
            if endpoints.get(host) != child.endpoint:
                stale.append(self._children.pop(host))
            else:
                child._ring_hash_override = self._ring_hash
        self._endpoints = endpoints
        return stale

    def _child(self, host: str) -> "AsyncGebClient":
        child = self._children.get(host)
        if child is None:
            child = AsyncGebClient(
                self._endpoints[host],
                window=self._owner._want_window,
                mode="fast",
                timeout=self._owner.timeout,
                shm=self._owner.shm if self._owner.shm != "require"
                else "auto",
                ring_route=False,
            )
            child._ring_hash_override = self._ring_hash
            self._children[host] = child
        return child

    def owner_of(self, key: str) -> str:
        point = _ring_point(key)
        i = bisect.bisect_left(self._points, point)
        if i == len(self._points):
            i = 0
        return self._hosts[i]

    async def _refresh(self) -> None:
        hello = await _fetch_hello(
            self._owner._kind, self._owner._addr
        )
        stale = self._install(hello)
        self.refreshes += 1
        for child in stale:
            try:
                await child.close()
            except Exception:
                pass

    async def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = None,
        trace=None,
    ) -> List[RateLimitResp]:
        if not reqs:
            # parity with the direct path's empty-batch refusal
            return await self._owner._get_direct(reqs, timeout, trace)
        results: List[Optional[RateLimitResp]] = [None] * len(reqs)
        fast_items: List[Tuple[int, RateLimitReq]] = []
        string_items: List[Tuple[int, RateLimitReq]] = []
        for i, r in enumerate(reqs):
            (fast_items if _fast_eligible_item(r) else
             string_items).append((i, r))

        async def run_string() -> None:
            resps = await self._owner._get_direct(
                [r for _, r in string_items], timeout, trace
            )
            for (i, _), resp in zip(string_items, resps):
                results[i] = resp

        string_task = (
            asyncio.ensure_future(run_string())
            if string_items
            else None
        )
        try:
            pending = fast_items
            last_refusal: Optional[GebError] = None
            for _attempt in range(self.MAX_ATTEMPTS):
                if not pending:
                    break
                groups: Dict[str, List[Tuple[int, RateLimitReq]]] = {}
                for i, r in pending:
                    groups.setdefault(
                        self.owner_of(r.hash_key()), []
                    ).append((i, r))
                hosts = list(groups)
                outs = await asyncio.gather(
                    *[
                        self._child(h)._get_direct(
                            [r for _, r in groups[h]], timeout
                        )
                        for h in hosts
                    ],
                    return_exceptions=True,
                )
                refused: List[Tuple[int, RateLimitReq]] = []
                hard: Optional[BaseException] = None
                for host, out in zip(hosts, outs):
                    if isinstance(
                        out, (GebStaleRingError, GebDrainingError)
                    ):
                        # refused = NOT served; retrying (against a
                        # refreshed ring) is safe by the GEBR contract
                        refused.extend(groups[host])
                        last_refusal = out
                    elif isinstance(out, BaseException):
                        hard = out  # delivery unknown: propagate
                    else:
                        for (i, _), resp in zip(groups[host], out):
                            results[i] = resp
                if hard is not None:
                    raise hard
                pending = refused
                if pending:
                    await self._refresh()
            if pending:
                raise last_refusal or GebError(
                    "ring routing exhausted retries"
                )
        except BaseException:
            if string_task is not None:
                string_task.cancel()
                await asyncio.gather(
                    string_task, return_exceptions=True
                )
            raise
        if string_task is not None:
            await string_task
        return results  # type: ignore[return-value]

    async def close(self) -> None:
        children, self._children = self._children, {}
        for child in children.values():
            try:
                await child.close()
            except Exception:
                pass


# -- sync client ------------------------------------------------------------


class GebClient:
    """Blocking GEB client: the async client on a dedicated event-loop
    thread, so `get_rate_limits` is a plain call (the V1Client shape)
    while the connection underneath still pipelines — concurrent calls
    from several threads share the credit window."""

    def __init__(
        self,
        endpoint: str,
        window: int = 0,
        mode: str = "auto",
        timeout: Optional[float] = 30.0,
        shm: str = "auto",
        ring_route: Optional[bool] = None,
    ):
        self._client = AsyncGebClient(
            endpoint,
            window=window,
            mode=mode,
            timeout=timeout,
            shm=shm,
            ring_route=ring_route,
        )
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever,
            name="guber-geb-client",
            daemon=True,
        )
        self._thread.start()

    def _run(self, coro, timeout: Optional[float] = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        try:
            return fut.result(timeout)
        except TimeoutError:
            fut.cancel()
            raise

    def connect(self) -> Hello:
        return self._run(self._client.connect())

    @property
    def hello(self) -> Optional[Hello]:
        return self._client.hello

    def stats(self) -> dict:
        return self._client.stats()

    def get_rate_limits(
        self,
        reqs: Sequence[RateLimitReq],
        timeout: Optional[float] = None,
    ) -> List[RateLimitResp]:
        return self._run(self._client.get_rate_limits(reqs, timeout))

    def get_rate_limits_pipelined(
        self, batches: Sequence[Sequence[RateLimitReq]]
    ) -> List[List[RateLimitResp]]:
        """Serve many batches as concurrently pipelined frames (up to
        the credit window in flight); results in input order."""

        async def run_all():
            return await asyncio.gather(
                *[self._client.get_rate_limits(b) for b in batches]
            )

        return self._run(run_all())

    def close(self) -> None:
        try:
            self._run(self._client.close(), timeout=5.0)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5.0)
            self._loop.close()

    def __enter__(self):
        self.connect()
        return self

    def __exit__(self, *a):
        self.close()


# -- HTTP binary door -------------------------------------------------------


class AsyncHttpGebClient:
    """Binary GEB frames over the HTTP gateway (POST /v1/geb,
    content-type gated) for clients behind HTTP-only infrastructure:
    no protobuf, no JSON — one legacy-framed GEB payload per request
    body. GET /v1/geb returns the hello (ring + capability flags), so
    fast framing negotiates exactly like the socket client; a GEBR
    body heals by re-reading the hello and retrying once."""

    def __init__(
        self, base_url: str, mode: str = "auto", timeout: float = 30.0
    ):
        if mode not in ("auto", "fast", "string"):
            raise ValueError("mode must be 'auto', 'fast', or 'string'")
        self.base_url = base_url.rstrip("/")
        self.mode = mode
        self.timeout = timeout
        self.hello: Optional[Hello] = None
        self._use_fast = False
        self._session = None

    async def _ensure(self) -> None:
        if self._session is None:
            import aiohttp  # lazy: server-side dep, not a client one

            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout)
            )
        if self.hello is None:
            async with self._session.get(
                self.base_url + GEB_HTTP_PATH
            ) as resp:
                if resp.status != 200:
                    raise GebError(
                        f"GET {GEB_HTTP_PATH} -> {resp.status} (no "
                        f"binary door on this gateway?)"
                    )
                hello = parse_hello_bytes(await resp.read())
            self.hello = hello
            if self.mode == "string":
                self._use_fast = False
            elif self.mode == "fast":
                if not hello.fast:
                    raise GebError(
                        "mode='fast' but the gateway does not "
                        "advertise the fast path"
                    )
                self._use_fast = True
            else:
                self._use_fast = (
                    hello.fast
                    and hello.xxh64 == client_hash_is_native()
                    and len(hello.nodes) <= 1
                )

    async def get_rate_limits(
        self, reqs: Sequence[RateLimitReq], _retried: bool = False
    ) -> List[RateLimitResp]:
        await self._ensure()
        chained = any(getattr(r, "chain", None) for r in reqs)
        if chained and not self.hello.chain:
            raise GebError(
                "gateway does not accept quota-chain frames "
                "(no HELLO_CHAIN capability; pre-r15?)"
            )
        # chains need the GEBC framing, which is windowed-shaped; the
        # gateway echoes the frame id without pipelining semantics
        frame, is_fast = build_frame(
            reqs,
            fast=self._use_fast,
            windowed=chained,
            ring_hash=self.hello.ring_hash,
        )
        async with self._session.post(
            self.base_url + GEB_HTTP_PATH,
            data=frame,
            headers={"Content-Type": GEB_CONTENT_TYPE},
        ) as resp:
            if resp.status != 200:
                raise GebError(
                    f"POST {GEB_HTTP_PATH} -> {resp.status}: "
                    f"{(await resp.read())[:200]!r}"
                )
            body = await resp.read()
        if len(body) < _HDR.size:
            # a truncating proxy or empty 200 body stays inside the
            # module's GebError contract, not a raw struct.error
            raise GebError(
                f"short response frame ({len(body)} bytes)"
            )
        magic, n = _HDR.unpack_from(body, 0)
        if magic == MAGIC_STALE:
            if n == DRAIN_FRAME_ID:
                raise GebDrainingError("gateway draining; frame not served")
            if _retried:
                raise GebStaleRingError("stale ring after hello refresh")
            self.hello = None  # re-read the ring, retry once
            return await self.get_rate_limits(reqs, _retried=True)
        if is_fast:
            if magic != MAGIC_FAST_RESP:
                raise GebError(f"bad response magic {magic:#x}")
            out = decode_fast_body(body[8:], n)
        elif chained:
            # GEBC is answered with a GEB4 frame: u32 frame_id (echoed,
            # meaningless over HTTP) precedes the items
            if magic != MAGIC_WRESP:
                raise GebError(f"bad response magic {magic:#x}")
            out = decode_string_body(body[12:], n)
        else:
            if magic != MAGIC_RESP:
                raise GebError(f"bad response magic {magic:#x}")
            out = decode_string_body(body[8:], n)
        if len(out) != len(reqs):
            # positional pairing downstream: a truncating proxy or
            # miscounting server must fail loudly, never misattribute
            raise GebError(
                f"response count {len(out)} != request {len(reqs)}"
            )
        return out

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def __aenter__(self):
        await self._ensure()
        return self

    async def __aexit__(self, *a):
        await self.close()
