from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    Status,
    RateLimitReq,
    RateLimitResp,
    HealthCheckResp,
    hash_key,
)

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitReq",
    "RateLimitResp",
    "HealthCheckResp",
    "hash_key",
]
