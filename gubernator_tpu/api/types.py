"""Core data model: the wire-level types of the rate-limit API.

Mirrors the reference proto contract (reference proto/gubernator.proto:57-153,
proto/peers.proto:36-56) as plain Python dataclasses used throughout the
host-side code. The actual protobuf classes (for gRPC) are generated from our
own .proto files and converted to/from these types at the serving edge.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Duration constants in milliseconds (reference client.go:27-31).
MILLISECOND = 1
SECOND = 1000 * MILLISECOND
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE


class Algorithm(enum.IntEnum):
    """Rate-limit algorithm (reference proto/gubernator.proto:57-62;
    SLIDING_WINDOW/GCRA are the r15 suite — core/algorithms.py)."""

    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1
    SLIDING_WINDOW = 2
    GCRA = 3


class Behavior(enum.IntEnum):
    """Request routing behavior (reference proto/gubernator.proto:64-95).

    BATCHING    — forward to the owning peer through the micro-batching queue.
    NO_BATCHING — forward with a direct unary RPC (lowest latency).
    GLOBAL      — answer from the local replica cache; hits are aggregated and
                  pushed to the owner asynchronously, and the owner broadcasts
                  authoritative status back to all peers.
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2


class Status(enum.IntEnum):
    """Decision status (reference proto/gubernator.proto:125-128)."""

    UNDER_LIMIT = 0
    OVER_LIMIT = 1


@dataclass
class ChainLevel:
    """One ancestor level of a hierarchical quota chain (r15).

    A chained request debits `chain[0] -> chain[1] -> ... -> leaf`
    (shallow to deep: global first, the request's own key last) in ONE
    device pass with most-restrictive-wins semantics and the
    no-partial-debit contract (a refused level consumes quota at no
    other level). Each level is a real counter under the request's
    `name` namespace, shared by every chain with the same HEAD (and by
    plain requests for the head's own key): the tenant level IS the
    tenant's limit. Ancestor levels always decide as TOKEN buckets —
    only the leaf uses the request's algorithm — so one hierarchy
    serves callers with different leaf algorithms without the shared
    counters mismatch-recreating. On sharded topologies a chain's levels live on the
    head's owner shard (the consolidation contract,
    parallel/sharded.py pad_request_chained), so well-formed
    hierarchies keep one head per subtree. `duration=0` inherits the
    request's duration."""

    unique_key: str = ""
    limit: int = 0
    duration: int = 0


@dataclass
class RateLimitReq:
    """One rate-limit request (reference proto/gubernator.proto:97-123).

    duration is in milliseconds. hits == 0 is a read-only peek.
    `chain` (r15) lists ancestor quota levels, shallow to deep; empty =
    a plain single-level request. Chained requests are routed by the
    chain HEAD's key so one owner debits the whole chain atomically,
    and are incompatible with Behavior.GLOBAL (validated serving-side).
    """

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0
    algorithm: Algorithm = Algorithm.TOKEN_BUCKET
    behavior: Behavior = Behavior.BATCHING
    chain: List["ChainLevel"] = field(default_factory=list)

    def hash_key(self) -> str:
        return hash_key(self.name, self.unique_key)

    def routing_key(self) -> str:
        """The ring/ownership key: the chain head's for chained
        requests (the whole chain lands on one owner), else the
        request's own."""
        if self.chain:
            return hash_key(self.name, self.chain[0].unique_key)
        return self.hash_key()


@dataclass
class RateLimitResp:
    """One rate-limit decision (reference proto/gubernator.proto:130-143)."""

    status: Status = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class HealthCheckResp:
    """Server health (reference proto/gubernator.proto:146-153)."""

    status: str = ""
    message: str = ""
    peer_count: int = 0


@dataclass
class GetRateLimitsReq:
    requests: List[RateLimitReq] = field(default_factory=list)


@dataclass
class GetRateLimitsResp:
    responses: List[RateLimitResp] = field(default_factory=list)


@dataclass
class UpdatePeerGlobal:
    """One GLOBAL status broadcast entry (reference proto/peers.proto:52-55)."""

    key: str = ""
    status: Optional[RateLimitResp] = None


@dataclass
class PeerInfo:
    """Cluster membership entry (reference etcd.go:34 usage / cluster.go)."""

    address: str = ""
    is_owner: bool = False
    #: this peer's replica state lives in THIS node's mesh (a lockstep
    #: follower process or a co-scheduled server sharing the device
    #: store): replica-install broadcasts to it collapse into one local
    #: mesh install instead of a per-peer RPC (r21, global_mgr.py)
    mesh_local: bool = False


def hash_key(name: str, unique_key: str) -> str:
    """The canonical cache/ring key: `name + "_" + unique_key`
    (reference client.go:33-35)."""
    return name + "_" + unique_key


def millisecond_now() -> int:
    """Wall clock in unix milliseconds (reference cache/lru.go MillisecondNow)."""
    return time.time_ns() // 1_000_000


def over_limit_resp(limit: int, reset_time: int) -> RateLimitResp:
    """The frozen token-bucket refusal: status OVER_LIMIT, remaining 0.
    This is the exact response an existing zero-remaining token window
    returns for every hit-carrying request until it expires (the
    verdict the over-limit shed cache serves host-side,
    serve/shedcache.py)."""
    return RateLimitResp(
        status=Status.OVER_LIMIT,
        limit=limit,
        remaining=0,
        reset_time=reset_time,
    )


def resps_from_columns(status, limit, remaining, reset) -> List[RateLimitResp]:
    """RateLimitResp list from four parallel numpy response columns —
    the single device-array -> object seam (engine response fetch,
    serving backends). Batch ndarray->list conversion (one C pass per
    column, Python ints out) instead of 4n numpy scalar extractions:
    the int(arr[i]) loop was the response side's dominant per-item
    cost at 1000-item groups."""
    return [
        RateLimitResp(
            status=Status(s), limit=li, remaining=r, reset_time=t
        )
        for s, li, r, t in zip(
            status.tolist(), limit.tolist(), remaining.tolist(),
            reset.tolist(),
        )
    ]
