"""Conversions between wire protobuf messages and the internal dataclasses."""

from __future__ import annotations

from gubernator_tpu.api.proto.gen import gubernator_pb2
from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    ChainLevel,
    RateLimitReq,
    RateLimitResp,
    Status,
)


def req_from_pb(pb) -> RateLimitReq:
    return RateLimitReq(
        name=pb.name,
        unique_key=pb.unique_key,
        hits=pb.hits,
        limit=pb.limit,
        duration=pb.duration,
        algorithm=Algorithm(pb.algorithm),
        behavior=Behavior(pb.behavior),
        chain=[
            ChainLevel(
                unique_key=lv.unique_key,
                limit=lv.limit,
                duration=lv.duration,
            )
            for lv in pb.chain
        ],
    )


def req_to_pb(r: RateLimitReq):
    pb = gubernator_pb2.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=r.hits,
        limit=r.limit,
        duration=r.duration,
        algorithm=int(r.algorithm),
        behavior=int(r.behavior),
    )
    for lv in r.chain:
        pb.chain.add(
            unique_key=lv.unique_key,
            limit=lv.limit,
            duration=lv.duration,
        )
    return pb


def resp_from_pb(pb) -> RateLimitResp:
    return RateLimitResp(
        status=Status(pb.status),
        limit=pb.limit,
        remaining=pb.remaining,
        reset_time=pb.reset_time,
        error=pb.error,
        metadata=dict(pb.metadata),
    )


def resp_to_pb(r: RateLimitResp):
    pb = gubernator_pb2.RateLimitResp(
        status=int(r.status),
        limit=r.limit,
        remaining=r.remaining,
        reset_time=r.reset_time,
        error=r.error,
    )
    for k, v in r.metadata.items():
        pb.metadata[k] = v
    return pb
