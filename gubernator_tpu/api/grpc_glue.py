"""Hand-written gRPC service glue.

grpc_tools (the protoc gRPC python plugin) is not available in this image,
so the servicer registration and client stubs for the two services
(V1, PeersV1 — reference proto/gubernator.proto:27-45, proto/peers.proto:28-34)
are written out by hand against the generated message classes. Works with
both sync and asyncio grpc channels/servers.
"""

from __future__ import annotations

import grpc

from gubernator_tpu.api.proto.gen import gubernator_pb2, peers_pb2

V1_SERVICE = "pb.gubernator.V1"
PEERS_SERVICE = "pb.gubernator.PeersV1"


def add_v1_servicer(server: grpc.Server, servicer) -> None:
    """servicer must expose GetRateLimits(req, ctx) and HealthCheck(req, ctx)
    (sync or async depending on the server flavor)."""
    handlers = {
        "GetRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetRateLimits,
            request_deserializer=gubernator_pb2.GetRateLimitsReq.FromString,
            response_serializer=gubernator_pb2.GetRateLimitsResp.SerializeToString,
        ),
        "HealthCheck": grpc.unary_unary_rpc_method_handler(
            servicer.HealthCheck,
            request_deserializer=gubernator_pb2.HealthCheckReq.FromString,
            response_serializer=gubernator_pb2.HealthCheckResp.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(V1_SERVICE, handlers),)
    )


def add_peers_servicer(server: grpc.Server, servicer) -> None:
    """servicer must expose GetPeerRateLimits(req, ctx),
    UpdatePeerGlobals(req, ctx) and ReplicateBuckets(req, ctx)."""
    handlers = {
        "GetPeerRateLimits": grpc.unary_unary_rpc_method_handler(
            servicer.GetPeerRateLimits,
            request_deserializer=peers_pb2.GetPeerRateLimitsReq.FromString,
            response_serializer=peers_pb2.GetPeerRateLimitsResp.SerializeToString,
        ),
        "UpdatePeerGlobals": grpc.unary_unary_rpc_method_handler(
            servicer.UpdatePeerGlobals,
            request_deserializer=peers_pb2.UpdatePeerGlobalsReq.FromString,
            response_serializer=peers_pb2.UpdatePeerGlobalsResp.SerializeToString,
        ),
        "ReplicateBuckets": grpc.unary_unary_rpc_method_handler(
            servicer.ReplicateBuckets,
            request_deserializer=peers_pb2.ReplicateBucketsReq.FromString,
            response_serializer=peers_pb2.ReplicateBucketsResp.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(PEERS_SERVICE, handlers),)
    )


class V1Stub:
    """Client stub for the public service."""

    def __init__(self, channel):
        self.GetRateLimits = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=gubernator_pb2.GetRateLimitsReq.SerializeToString,
            response_deserializer=gubernator_pb2.GetRateLimitsResp.FromString,
        )
        self.HealthCheck = channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=gubernator_pb2.HealthCheckReq.SerializeToString,
            response_deserializer=gubernator_pb2.HealthCheckResp.FromString,
        )


class PeersV1Stub:
    """Client stub for the peer-to-peer service."""

    def __init__(self, channel):
        self.GetPeerRateLimits = channel.unary_unary(
            f"/{PEERS_SERVICE}/GetPeerRateLimits",
            request_serializer=peers_pb2.GetPeerRateLimitsReq.SerializeToString,
            response_deserializer=peers_pb2.GetPeerRateLimitsResp.FromString,
        )
        self.UpdatePeerGlobals = channel.unary_unary(
            f"/{PEERS_SERVICE}/UpdatePeerGlobals",
            request_serializer=peers_pb2.UpdatePeerGlobalsReq.SerializeToString,
            response_deserializer=peers_pb2.UpdatePeerGlobalsResp.FromString,
        )
        self.ReplicateBuckets = channel.unary_unary(
            f"/{PEERS_SERVICE}/ReplicateBuckets",
            request_serializer=peers_pb2.ReplicateBucketsReq.SerializeToString,
            response_deserializer=peers_pb2.ReplicateBucketsResp.FromString,
        )
