"""Generated protobuf modules.

protoc emits flat imports (`import gubernator_pb2`), so this package puts
its own directory on sys.path before importing them; consumers should use
`from gubernator_tpu.api.proto.gen import gubernator_pb2, peers_pb2`.
Regenerate with scripts/gen_protos.sh.
"""

import pathlib
import sys

_here = str(pathlib.Path(__file__).resolve().parent)
if _here not in sys.path:
    sys.path.insert(0, _here)

import gubernator_pb2  # noqa: E402
import peers_pb2  # noqa: E402

__all__ = ["gubernator_pb2", "peers_pb2"]
