"""Mesh-sharded rate limiting: the consistent-hash ring mapped onto a
`jax.sharding.Mesh`.

The reference distributes keys across peers with a consistent-hash ring and
forwards requests over gRPC (reference hash.go:80-96, peers.go:111-127).
Inside one host, this framework distributes keys across TPU chips instead:

- The slot store gains a leading `shard` axis, laid out over the mesh's
  "shard" axis — every chip owns `1/n` of the key space, the moral
  equivalent of one ring peer per chip, with ownership decided by a cheap
  hash (`owner = mix64(key_hash) mod n`) instead of a sorted ring search:
  with homogeneous chips there is no reason to pay the ring's lookup cost
  or its imbalance (the reference places one point per peer, hash.go:62-67).
- The request BATCH is sharded too: the host presorts each batch by
  (owner_shard, bucket, fingerprint) — one native radix pass — slices the
  contiguous per-shard runs into per-chip sub-batches, and lays the
  [n_shards, B_sub] request arrays out over the mesh's batch axis. Each
  chip evaluates ONLY the ~B/n rows it owns, so aggregate decisions/s
  scales with chip count — the same economy as the reference forwarding
  each key only to its owner peer (reference peers.go:111-207). The decide
  path needs NO collective at all: responses come back per-shard and the
  host unpermutes them into request order (it already owns the
  permutation).
- GLOBAL mode's owner->replica broadcast (reference global.go:158-232)
  becomes `sync_globals`: owners peek authoritative status, one psum
  replicates it mesh-wide, and every non-owner installs replica entries —
  the async gossip loop collapsed into a single collective step.

Multi-host scaling composes: each host runs one mesh-sharded engine over
its chips, and hosts peer with each other over gRPC exactly like reference
nodes (serve/peers.py), so ICI carries intra-host traffic and DCN only
carries the host-level ring's.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.core.engine import (
    EngineStats,
    EpochClock,
    _sat_i32,
    extend_ladder,
    pad_request_sorted,
    pad_to_bucket,
)
from gubernator_tpu.core.kernels import (
    BatchGroups,
    BatchRequest,
    BatchResponse,
    decide_presorted,
    pack_outputs,
    rebase_jit,
    upsert_globals,
    upsert_globals_jit,
    upsert_windows_jit,
)
from gubernator_tpu.core.store import Store, StoreConfig, mix64, new_store
from gubernator_tpu.parallel.policy import ShardingPolicy, shard_map_compat

# wall-clock reads go through the api.types MODULE attribute: the test
# suites pin the serving clock by patching millisecond_now there (and on
# core.engine/core.oracle), and a from-import frozen at import time
# would leak real time into fake-clock differential fuzzes
from gubernator_tpu.api import types as api_types

_SHARD_SALT = np.uint64(0xA24BAED4963EE407)

_log = logging.getLogger("gubernator.sharded")
_warned_ladder_overflow = False


def _warn_ladder_overflow(top: int, n: int) -> None:
    """One-time attribution for the multi-second stall a first oversized
    batch causes: extending the ladder compiles a fresh XLA program
    mid-call (library-only path — the serving batcher caps batches at
    the ladder top, so it never gets here)."""
    global _warned_ladder_overflow
    if not _warned_ladder_overflow:
        _warned_ladder_overflow = True
        _log.warning(
            "batch of %d exceeds the configured ladder top %d: extending "
            "the rung ladder triggers a fresh XLA compilation (tens of "
            "seconds on TPU) for this and each new overflow size — size "
            "the `buckets` ladder to your peak batch to avoid the stall",
            n,
            top,
        )


def owner_of(key_hash: jax.Array, n_shards: int) -> jax.Array:
    """Owning shard index for each key hash (device-side)."""
    return (mix64(key_hash ^ _SHARD_SALT) % jnp.uint64(n_shards)).astype(
        jnp.int32
    )


def owner_of_np(key_hash: np.ndarray, n_shards: int) -> np.ndarray:
    """Host-side twin of owner_of (numpy)."""
    from gubernator_tpu.core import hashing

    return (hashing.mix64(key_hash ^ _SHARD_SALT) % np.uint64(n_shards)).astype(
        np.int32
    )


def _axis_me(axes: tuple) -> jax.Array:
    """Flattened shard index under a 1-D ("shard",) or 2-D
    ("host", "chip") mesh — the 2-D form is host-major, matching the
    process-major device order the mesh is built with, so owner_of's
    `mod n_shards` placement is identical under both layouts."""
    me = jax.lax.axis_index(axes[0])
    for ax in axes[1:]:
        me = me * jax.lax.psum(1, ax) + jax.lax.axis_index(ax)
    return me


def _hier_psum(x: jax.Array, axes: tuple) -> jax.Array:
    """Hierarchical all-reduce (BASELINE config 5): innermost axis
    first. On a multi-slice mesh with axes ("host", "chip") this stages
    the reduction — chips within a host combine over ICI, then ONE
    pre-reduced vector per host crosses DCN — instead of a flat psum
    whose ring spans DCN on every leg. Mathematically identical to
    `psum(x, axes)`; the staging is the point."""
    for ax in reversed(axes):
        x = jax.lax.psum(x, ax)
    return x


def _local_decide(store: Store, req: BatchRequest, groups, now):
    """Per-device body under shard_map: store AND batch are this device's
    shards. The host routed every request row to its owner chip
    (pad_request_sharded), so each chip runs the plain single-device
    kernel on its own sub-batch — no collective on the decide path, the
    mesh analogue of the reference forwarding only owned keys to a peer
    (reference peers.go:111-207) — with its own per-shard duplicate-key
    group structure (store I/O at unique-key granularity, see
    kernels.BatchGroups). Responses + stats pack into one int32 row per
    shard (one host transfer total)."""
    store = jax.tree.map(lambda x: x[0], store)  # [1, r, s] -> [r, s]
    req = jax.tree.map(lambda x: x[0], req)  # [1, B_sub] -> [B_sub]
    groups = jax.tree.map(lambda x: x[0], groups)
    new_store_shard, resp, stats = decide_presorted(store, req, now, groups)
    packed = pack_outputs(resp, stats)
    return jax.tree.map(lambda x: x[None], new_store_shard), packed[None]


def _local_decide_gathered(store: Store, req: BatchRequest, groups, now,
                           axes=("shard",)):
    """_local_decide + one all_gather of the packed response rows: when
    the mesh spans processes the serving host cannot fetch follower
    shards directly, so the responses ride the compiled collective path
    (ICI within a host, DCN between hosts) and come out replicated. On
    the 2-D mesh the gather names both axes host-major, so the gathered
    row order equals the flattened shard order."""
    store, packed = _local_decide(store, req, groups, now)
    out = packed[0]
    if len(axes) == 1:
        return store, jax.lax.all_gather(out, axes[0])
    # gather chips within a host over ICI first, then hosts over DCN,
    # then flatten [host, chip, ...] -> [shard, ...]
    out = jax.lax.all_gather(out, axes[-1])
    out = jax.lax.all_gather(out, axes[0])
    return store, out.reshape((-1,) + out.shape[2:])


def _local_decide_sketch(store: Store, sketch, req: BatchRequest, groups,
                         now):
    """Two-tier twin of _local_decide (r14): each shard carries its own
    count-min SUB-SKETCH next to its store shard. The host routes every
    key to its owner chip, so a key's sketch charges land only in its
    owner's sub-sketch — the sketch identity is (shard, key, window),
    and the per-key error bound is the CLASSIC bound over that shard's
    charged total N_s <= N (sharding can only tighten it; see
    docs/operations.md "Partitioned engine (r14)")."""
    from gubernator_tpu.core.kernels import decide_presorted_sketch

    store = jax.tree.map(lambda x: x[0], store)
    sketch = jax.tree.map(lambda x: x[0], sketch)
    req = jax.tree.map(lambda x: x[0], req)
    groups = jax.tree.map(lambda x: x[0], groups)
    new_store, new_sketch, resp, stats = decide_presorted_sketch(
        store, sketch, req, now, groups
    )
    packed = pack_outputs(resp, stats)
    return (
        jax.tree.map(lambda x: x[None], new_store),
        jax.tree.map(lambda x: x[None], new_sketch),
        packed[None],
    )


def _local_decide_sketch_gathered(store: Store, sketch, req: BatchRequest,
                                  groups, now, axes=("shard",)):
    """_local_decide_sketch + the _local_decide_gathered all_gather
    (r20): the two-tier step's replicated-response form for meshes that
    span processes — the serving leader cannot fetch follower shards'
    packed rows, so they ride the compiled collective path and come out
    replicated, exactly like the exact-only step."""
    store, sketch, packed = _local_decide_sketch(
        store, sketch, req, groups, now
    )
    out = packed[0]
    if len(axes) == 1:
        return store, sketch, jax.lax.all_gather(out, axes[0])
    out = jax.lax.all_gather(out, axes[-1])
    out = jax.lax.all_gather(out, axes[0])
    return store, sketch, out.reshape((-1,) + out.shape[2:])


def _shard_sketch_min(data, owner, idx, axes):
    """Owner-masked collective count-min read (r20): each shard row-mins
    its LOCAL sub-sketch at the probe indices, zeroes the keys it does
    not own, and a hierarchical psum leaves every key's owner estimate
    replicated on all shards — the collective twin of _sketch_min_sharded
    for meshes whose shards the reading host cannot address (multihost:
    the promoter's estimate gathers become lockstep device programs
    instead of leader-only host indexing). Exactly one shard contributes
    per key, so the psum IS the owner's row-min."""
    local = data[0]  # [1, rows, width] -> [rows, width]
    est = None
    for r in range(idx.shape[0]):
        c = jnp.take(local[r], idx[r])
        est = c if est is None else jnp.minimum(est, c)
    me = _axis_me(axes)
    est = jnp.where(owner == me, est, 0)
    return _hier_psum(est, axes)


def _shard_rows(data, owner, b, axes):
    """Owner-masked collective bucket-row gather (r20): the collective
    twin of _rows_sharded — each shard gathers the requested bucket rows
    from its local store shard, zeroes rows for keys it does not own,
    and the psum replicates the owner's rows everywhere. Non-mutating;
    backs _gather_entries (live_mask / snapshot_read) on process-
    spanning meshes."""
    local = data[0]  # [1, buckets, lanes] -> [buckets, lanes]
    rows = jnp.take(local, b, axis=0)
    me = _axis_me(axes)
    rows = jnp.where((owner == me)[:, None], rows, 0)
    return _hier_psum(rows, axes)


def _np_presort_sharded(
    key_hash: np.ndarray, store_buckets: int, n_shards: int
):
    """Numpy fallback for the native sharded presort: stable argsort by
    (owner_shard, bucket, fingerprint) + per-shard counts."""
    from gubernator_tpu.core.store import group_sort_key_np

    owner = owner_of_np(key_hash, n_shards)
    # owner bits sit just above the (bucket << 32 | fp) group key, like
    # the native sort key (guberhash.cc guber_presort_sharded)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    comp = (
        owner.astype(np.uint64) << np.uint64(32 + bucket_bits)
    ) | group_sort_key_np(key_hash, store_buckets)
    order = np.argsort(comp, kind="stable").astype(np.int32)
    counts = np.bincount(owner, minlength=n_shards).astype(np.int64)
    return order, counts


def _np_presort_sharded_grouped(
    key_hash: np.ndarray, store_buckets: int, n_shards: int
):
    """Numpy fallback for the native sharded+grouped presort."""
    from gubernator_tpu.core.store import group_sort_key_np

    owner = owner_of_np(key_hash, n_shards)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    comp = (
        owner.astype(np.uint64) << np.uint64(32 + bucket_bits)
    ) | group_sort_key_np(key_hash, store_buckets)
    order = np.argsort(comp, kind="stable").astype(np.int32)
    counts = np.bincount(owner, minlength=n_shards).astype(np.int64)
    s = comp[order]
    n = s.shape[0]
    is_leader = np.empty(n, bool)
    if n:
        is_leader[0] = True
        np.not_equal(s[1:], s[:-1], out=is_leader[1:])
    group_id = np.cumsum(is_leader).astype(np.int32) - 1
    leader_pos = np.flatnonzero(is_leader).astype(np.int32)
    g_owner = (s[leader_pos] >> np.uint64(32 + bucket_bits)).astype(np.int64)
    group_counts = np.bincount(g_owner, minlength=n_shards).astype(np.int64)
    return order, counts, group_id, leader_pos, group_counts


try:  # native radix presort with shard partitioning (guberhash.cc)
    from gubernator_tpu.native import hashlib_native as _hn

    if not _hn._HAS_PRESORT_SHARDED:
        raise AttributeError("guber_presort_sharded missing")
    _presort_sharded = _hn.presort_sharded
    _presort_sharded_grouped = (
        _hn.presort_sharded_grouped
        if _hn._HAS_PRESORT_SHARDED_GROUPED
        else _np_presort_sharded_grouped
    )
    _prep_native = _hn.prep_sharded if _hn._HAS_PREP else None
except (ImportError, AttributeError, OSError):  # pragma: no cover
    _hn = None
    _presort_sharded = _np_presort_sharded
    _presort_sharded_grouped = _np_presort_sharded_grouped
    _prep_native = None


def sub_batch_ladder(buckets: Sequence[int]) -> tuple:
    """Padding rungs for per-shard sub-batches: the host ladder densified
    with 1.5x midpoints (64, 96, 128, 192, ... between min and max rung).
    Shard counts concentrate at ~B/n_shards + multinomial jitter, so the
    coarse 4x host ladder would pad a shard's rows up to 4x (measured:
    total mesh work grew instead of staying flat); midpoints cap padding
    waste at 1.5x for one extra compile per octave at warmup."""
    lo, hi = min(buckets), max(buckets)
    rungs = set(buckets)
    p = lo
    while p < hi:
        rungs.add(p)
        rungs.add(min(p * 3 // 2, hi))
        p *= 2
    rungs.add(hi)
    return tuple(sorted(rungs))


def pad_request_sharded(
    buckets: Sequence[int],
    store_buckets: int,
    n_shards: int,
    key_hash: np.ndarray,
    hits: np.ndarray,
    limit: np.ndarray,
    duration: np.ndarray,
    algo: np.ndarray,
    gnp: np.ndarray,
    with_groups: bool = False,
    group_rung: Optional[int] = None,
):
    """Partition a batch into per-shard sub-batches: the mesh sibling of
    engine.pad_request_sorted. One (owner, bucket, fp) radix sort makes
    each shard's rows a contiguous presorted run; every field becomes a
    [n_shards, B_sub] array (B_sub = bucket fitting the LARGEST shard's
    count) whose row s is shard s's sub-batch padded by repeating its
    last row with valid=False (preserving the monotonic bucket stream).

    Returns (req, order, take_idx) — plus `groups` when with_groups:
    - req: BatchRequest of [n_shards, B_sub] arrays, batch-axis shardable
      P("shard") — row s belongs on chip s.
    - order[k]: caller index of the k-th row in global sorted order.
    - take_idx[k]: flattened [n_shards*B_sub] device position of that row.
    - groups: BatchGroups of [n_shards, ...] arrays (per-shard
      duplicate-key structure, indices LOCAL to each shard's sub-batch)
      so each chip's store I/O runs at unique-key granularity.
    `group_rung` overrides the G rung choice (must hold every shard's
    group count) — callers staging SEVERAL batches into one stacked
    array pass a shared rung so the BatchGroups shapes line up.
    Unpermute responses with `out[order] = resp_flat[take_idx]`.
    """
    from gubernator_tpu.core.engine import (
        _sat_duration as sat_dur,
        _sat_i32 as sat_i32,
        choose_bucket,
        group_rungs,
    )

    n = key_hash.shape[0]
    if n == 0:
        # empty batch: one all-invalid row per shard (smallest rung)
        B0 = buckets[0] if hasattr(buckets, "__getitem__") else min(buckets)
        req = BatchRequest(
            key_hash=np.zeros((n_shards, B0), np.uint64),
            hits=np.zeros((n_shards, B0), np.int32),
            limit=np.zeros((n_shards, B0), np.int32),
            duration=np.zeros((n_shards, B0), np.int32),
            algo=np.zeros((n_shards, B0), np.int32),
            gnp=np.zeros((n_shards, B0), bool),
            valid=np.zeros((n_shards, B0), bool),
        )
        empty = (req, np.empty(0, np.int32), np.empty(0, np.int64))
        if with_groups:
            G0 = group_rungs(B0)[0]
            return (*empty, BatchGroups(
                key_hash=np.zeros((n_shards, G0), np.uint64),
                leader_pos=np.full((n_shards, G0), B0, np.int32),
                end_pos=np.full((n_shards, G0), B0 - 1, np.int32),
                valid=np.zeros((n_shards, G0), bool),
                group_id=np.zeros((n_shards, B0), np.int32),
            ))
        return empty
    if _prep_native is not None and with_groups:
        # one-call native prep: presort + groups + marshal fused (3.6x
        # the numpy path on one core, thread-parallel on real hosts —
        # guberhash.cc guber_prep_sharded). Bit-identical to the numpy
        # path below (pinned by tests/test_prep_native.py). Gated to
        # with_groups (the decide path): only it owns the two-in-flight
        # contract the flip-flopped prep buffers rely on.
        from gubernator_tpu.core.engine import dense_ladder_extension
        from gubernator_tpu.core.store import (
            COUNTER_MAX,
            MAX_DURATION_MS,
            TIME_FLOOR,
        )

        rungs = np.asarray(dense_ladder_extension(buckets, n), np.int64)
        order, counts, take_idx, fields, groups_d, B_sub, _G = (
            _prep_native(
                key_hash, hits, limit, duration, algo, gnp,
                store_buckets, n_shards, rungs,
                int(group_rung) if group_rung else 0,
                -COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS,
            )
        )
        if int(counts.max()) > max(buckets):
            _warn_ladder_overflow(max(buckets), int(counts.max()))
        req = BatchRequest(**fields)
        return req, order, take_idx, BatchGroups(
            key_hash=groups_d["key_hash"],
            leader_pos=groups_d["leader_pos"],
            end_pos=groups_d["end_pos"],
            valid=groups_d["valid"],
            group_id=groups_d["group_id"],
        )

    if with_groups:
        order, counts, gid_g, lp_g, gcounts = _presort_sharded_grouped(
            key_hash, store_buckets, n_shards
        )
    else:
        order, counts = _presort_sharded(key_hash, store_buckets, n_shards)
    counts32 = counts.astype(np.int64)
    starts = np.zeros(n_shards + 1, np.int64)
    np.cumsum(counts32, out=starts[1:])
    maxc = max(int(counts32.max()), 1)
    # a shard can draw more rows than the ladder's top rung when the
    # caller's batch exceeds max(buckets) — unreachable through the
    # serving tier (the batcher caps batches at the ladder top) but
    # supported for library callers: extend, don't raise
    if maxc > max(buckets):
        _warn_ladder_overflow(max(buckets), maxc)
    B_sub = choose_bucket(extend_ladder(buckets, maxc), maxc)

    # src[s, j]: index into the sorted arrays for padded cell (s, j) —
    # clamped to the shard's last real row (repeat-pad); empty shards
    # clamp to a neighbouring row, masked invalid below.
    j = np.arange(B_sub, dtype=np.int64)[None, :]
    src = starts[:-1, None] + np.minimum(
        j, np.maximum(counts32[:, None] - 1, 0)
    )
    np.clip(src, 0, max(n - 1, 0), out=src)
    valid = j < counts32[:, None]
    idx = order[src]  # compose once: caller index per padded cell

    def shard_field(x, dtype, sat=None):
        x = sat(x) if sat is not None else np.asarray(x, dtype)
        return x[idx]  # [n_shards, B_sub]

    req = BatchRequest(
        key_hash=shard_field(key_hash, np.uint64),
        hits=shard_field(hits, np.int32, sat_i32),
        limit=shard_field(limit, np.int32, sat_i32),
        duration=shard_field(duration, np.int32, sat_dur),
        algo=shard_field(algo, np.int32),
        gnp=shard_field(gnp, bool),
        valid=valid,
    )
    # global sorted position k lives at device cell (shard_of_k, k-start)
    shard_of_k = np.repeat(np.arange(n_shards, dtype=np.int64), counts32)
    take_idx = shard_of_k * B_sub + (np.arange(n, dtype=np.int64) - starts[shard_of_k])
    if not with_groups:
        return req, order, take_idx

    groups = stack_shard_groups(
        req.key_hash, gid_g, lp_g, gcounts, counts32, starts, n_shards,
        B_sub, group_rung,
    )
    return req, order, take_idx, groups


def stack_shard_groups(
    req_kh: np.ndarray,
    gid_g: np.ndarray,
    lp_g: np.ndarray,
    gcounts: np.ndarray,
    counts32: np.ndarray,
    starts: np.ndarray,
    n_shards: int,
    B_sub: int,
    group_rung: Optional[int] = None,
) -> BatchGroups:
    """Per-shard group structure with LOCAL indices (each shard's kernel
    sees only its own [B_sub] sub-batch); padding conventions come from
    the single source of truth, engine.build_groups, called per shard.
    Global group ids are contiguous in shard order (shard boundaries
    break groups), so shard s's groups are exactly
    gstarts[s]..gstarts[s+1] and its first group id IS gstarts[s].
    Shared by the flush-time presort path (pad_request_sharded) and the
    merge-combine path (MeshEngine.decide_submit_presorted) so the two
    can never drift."""
    from gubernator_tpu.core.engine import (
        build_groups,
        choose_bucket,
        group_rungs,
    )

    gstarts = np.zeros(n_shards + 1, np.int64)
    np.cumsum(gcounts, out=gstarts[1:])
    if group_rung is not None:
        if group_rung < int(gcounts.max()):
            raise ValueError(
                f"group_rung {group_rung} < max shard group count "
                f"{int(gcounts.max())}"
            )
        G_sub = group_rung
    else:
        G_sub = choose_bucket(
            group_rungs(B_sub), max(int(gcounts.max()), 1)
        )
    per_shard = []
    for s in range(n_shards):
        gc = int(gcounts[s])
        cs = int(counts32[s])
        per_shard.append(
            build_groups(
                req_kh[s],
                gid_g[starts[s] : starts[s] + cs] - int(gstarts[s]),
                lp_g[gstarts[s] : gstarts[s] + gc] - int(starts[s]),
                gc,
                cs,
                B_sub,
                G_sub,
            )
        )
    return BatchGroups(
        *(np.stack(leaves) for leaves in zip(*per_shard))
    )


def sharded_sort_keys_np(
    key_hash: np.ndarray, store_buckets: int, n_shards: int
) -> np.ndarray:
    """Composite host sort key of the sharded presort order —
    (owner_shard | bucket | fingerprint), the same packing
    _np_presort_sharded and guber_presort_sharded order by."""
    from gubernator_tpu.core.store import group_sort_key_np

    kh = np.asarray(key_hash, np.uint64)
    owner = owner_of_np(kh, n_shards)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    return (
        owner.astype(np.uint64) << np.uint64(32 + bucket_bits)
    ) | group_sort_key_np(kh, store_buckets)


def prep_run_sharded(
    fields: dict, store_buckets: int, n_shards: int
) -> dict:
    """Arrival-time per-group prep for the mesh engine: presort one
    group by (owner, bucket, fingerprint), clip fields to device
    dtypes, and count rows per shard — a sorted run the flush-time
    merge combine (serve/prep.py) stitches into one sharded batch.
    One fused native call when built (guber_prep_run); the numpy
    fallback below is bit-identical."""
    from gubernator_tpu.core.engine import _gather_clip_sorted

    if _hn is not None and getattr(_hn, "_HAS_PREP_RUN", False):
        from gubernator_tpu.core.store import (
            COUNTER_MAX,
            MAX_DURATION_MS,
            TIME_FLOOR,
        )

        return _hn.prep_run(
            fields, store_buckets, n_shards, -COUNTER_MAX, COUNTER_MAX,
            TIME_FLOOR, MAX_DURATION_MS,
        )
    kh = np.ascontiguousarray(fields["key_hash"], np.uint64)
    n = kh.shape[0]
    order, counts = _presort_sharded(kh, store_buckets, n_shards)
    sorted_fields = _gather_clip_sorted(fields, order, n)
    return dict(
        n=n,
        # elementwise in the key hash, so computed on the sorted hashes
        skey=sharded_sort_keys_np(
            sorted_fields["key_hash"], store_buckets, n_shards
        ),
        order=order,
        counts=np.asarray(counts, np.int64),
        fields=sorted_fields,
    )


def build_presorted_sharded(
    sub_buckets: Sequence[int],
    store_buckets: int,
    n_shards: int,
    fields: dict,
    skey: np.ndarray,
    counts: np.ndarray,
):
    """(req, take_idx, groups, B_sub) for an already-sorted sharded
    batch — the merge-combine twin of pad_request_sharded
    (with_groups=True), minus the argsort it no longer needs.
    Byte-identical outputs are pinned by tests/test_prep_pipeline.py.
    """
    from gubernator_tpu.core.engine import choose_bucket

    n = skey.shape[0]
    counts32 = np.asarray(counts, np.int64)
    starts = np.zeros(n_shards + 1, np.int64)
    np.cumsum(counts32, out=starts[1:])
    maxc = max(int(counts32.max()), 1)
    if maxc > max(sub_buckets):
        _warn_ladder_overflow(max(sub_buckets), maxc)
    B_sub = choose_bucket(extend_ladder(sub_buckets, maxc), maxc)
    # padded cell (s, j) reads merged sorted row starts[s]+min(j,
    # count-1) — the same repeat-pad/clamp pad_request_sharded
    # applies, but gathering from the sorted stream directly
    # (sorted_x[src] == x[order][src] == x[idx])
    j = np.arange(B_sub, dtype=np.int64)[None, :]
    src = starts[:-1, None] + np.minimum(
        j, np.maximum(counts32[:, None] - 1, 0)
    )
    np.clip(src, 0, max(n - 1, 0), out=src)
    valid = j < counts32[:, None]
    req = BatchRequest(
        key_hash=fields["key_hash"][src],
        hits=fields["hits"][src],
        limit=fields["limit"][src],
        duration=fields["duration"][src],
        algo=fields["algo"][src],
        gnp=fields["gnp"][src],
        valid=valid,
    )
    # group structure straight off the sorted key stream (skey ties ==
    # comp ties of _np_presort_sharded_grouped): one diff pass replaces
    # the grouped argsort
    is_leader = np.empty(n, bool)
    is_leader[0] = True
    np.not_equal(skey[1:], skey[:-1], out=is_leader[1:])
    gid_g = np.cumsum(is_leader).astype(np.int32) - 1
    lp_g = np.flatnonzero(is_leader).astype(np.int32)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    g_owner = (skey[lp_g] >> np.uint64(32 + bucket_bits)).astype(
        np.int64
    )
    gcounts = np.bincount(g_owner, minlength=n_shards).astype(np.int64)
    groups = stack_shard_groups(
        req.key_hash, gid_g, lp_g, gcounts, counts32, starts, n_shards,
        B_sub,
    )
    shard_of_k = np.repeat(np.arange(n_shards, dtype=np.int64), counts32)
    take_idx = shard_of_k * B_sub + (
        np.arange(n, dtype=np.int64) - starts[shard_of_k]
    )
    return req, take_idx, groups, B_sub


def _local_decide_chain(store: Store, req: BatchRequest, groups, chain_id,
                        now):
    """Per-device chain decide under shard_map (r15): the host routed
    every CHAIN whole to its head-key owner shard (pad_request_chained),
    so the chain AND-reduce runs entirely shard-local — the decide path
    keeps its no-collective property even with coupled rows."""
    from gubernator_tpu.core.kernels import decide_presorted_chain

    store = jax.tree.map(lambda x: x[0], store)
    req = jax.tree.map(lambda x: x[0], req)
    groups = jax.tree.map(lambda x: x[0], groups)
    chain_id = chain_id[0]
    new_store, resp, stats = decide_presorted_chain(
        store, req, now, chain_id, groups
    )
    packed = pack_outputs(resp, stats)
    return jax.tree.map(lambda x: x[None], new_store), packed[None]


def pad_request_chained(
    buckets: Sequence[int],
    store_buckets: int,
    n_shards: int,
    key_hash: np.ndarray,
    hits: np.ndarray,
    limit: np.ndarray,
    duration: np.ndarray,
    algo: np.ndarray,
    chain_ids: np.ndarray,
    route_hash: np.ndarray,
):
    """Presort + pad one CHAINED batch (r15): rows whose `chain_ids`
    match are one hierarchical request's levels and must decide in the
    same kernel invocation (the no-partial-debit AND-reduce is
    shard-local). Ownership therefore follows `route_hash` — the chain
    HEAD's key hash, identical for every row of a chain — while bucket
    addressing keeps each row's OWN key hash, so a chain's levels land
    whole on one shard yet store state in their own buckets. numpy-only
    (the native prep has no chain column; chain batches ride a
    dedicated lane, serve/batcher.py).

    Returns (req, order, take_idx, groups, chain_local) where
    chain_local carries kernel-ready per-shard-local chain slots
    (int32, values < the sub-batch rung; padding rows are singleton
    chains). take_idx is None on the flat (n_shards == 1) layout.

    Consolidation contract: a level key shared by chains with
    DIFFERENT heads lands on each head's owner shard separately, so
    its quota would be tracked per shard. Well-formed hierarchies
    (every child under one parent) never do this; the serving tier
    routes by chain head for the same reason (serve/instance.py).
    """
    from gubernator_tpu.core.engine import (
        _gather_clip_sorted,
        build_presorted_request,
    )
    from gubernator_tpu.core.store import group_sort_key_np

    kh = np.ascontiguousarray(key_hash, np.uint64)
    n = kh.shape[0]
    skey = group_sort_key_np(kh, store_buckets)
    if n_shards > 1:
        owner = owner_of_np(
            np.ascontiguousarray(route_hash, np.uint64), n_shards
        )
        bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
        comp = (
            owner.astype(np.uint64) << np.uint64(32 + bucket_bits)
        ) | skey
    else:
        owner = np.zeros(n, np.int32)
        comp = skey
    order = np.argsort(comp, kind="stable").astype(np.int32)
    s = comp[order]
    sorted_fields = _gather_clip_sorted(
        dict(
            key_hash=kh, hits=hits, limit=limit, duration=duration,
            algo=algo, gnp=np.zeros(n, bool),
        ),
        order,
        n,
    )
    chain_sorted = np.asarray(chain_ids, np.int64)[order]
    # pad/group/take_idx machinery is the merge-combine twins' —
    # delegated so the owner bit-packing, ladder-overflow, and
    # clamp-pad invariants cannot drift between the chain and plain
    # sharded paths; only the chain-slot localization is chain-specific

    if n_shards == 1:
        req, groups, B = build_presorted_request(
            buckets, sorted_fields, s, n
        )
        chain_local = np.arange(B, dtype=np.int32)
        if n:
            _, inv = np.unique(chain_sorted, return_inverse=True)
            chain_local[:n] = inv  # values < n <= B
        return req, order, None, groups, chain_local

    counts = np.bincount(owner, minlength=n_shards).astype(np.int64)
    req, take_idx, groups, B_sub = build_presorted_sharded(
        buckets, store_buckets, n_shards, sorted_fields, s, counts
    )
    starts = np.zeros(n_shards + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    chain_local = np.broadcast_to(
        np.arange(B_sub, dtype=np.int32), (n_shards, B_sub)
    ).copy()
    for sh in range(n_shards):
        c = int(counts[sh])
        if c:
            _, inv = np.unique(
                chain_sorted[starts[sh] : starts[sh] + c],
                return_inverse=True,
            )
            chain_local[sh, :c] = inv  # values < c <= B_sub
    return req, order, take_idx, groups, chain_local


def _shard_sync_globals(
    store: Store,
    key_hash: jax.Array,  # uint64[B] global keys to broadcast
    hits: jax.Array,  # int32[B] aggregated GLOBAL hits to charge on the
    # owner shard BEFORE broadcasting (0 = pure peek, the classic
    # sync_globals gossip step; nonzero = apply_global_hits, the
    # in-mesh psum replacing the owner->replica gossip round trip)
    limit: jax.Array,  # int32[B] request limit (for owner-side peek of misses)
    duration: jax.Array,
    algo: jax.Array,  # int32[B]: must match the stored algorithm, or the
    # peek would take the mismatch-recreate path and wipe owner state
    valid: jax.Array,
    now,
    n_shards: int,
    axes: tuple = ("shard",),
):
    """Owner charges+peeks authoritative status; psum replicates;
    others upsert. On a 2-D ("host", "chip") mesh the replication is
    the hierarchical ICI-then-DCN reduction of BASELINE config 5 (see
    _hier_psum)."""
    me = _axis_me(axes)
    store = jax.tree.map(lambda x: x[0], store)
    mine = owner_of(key_hash, n_shards) == me

    peek = BatchRequest(
        key_hash=key_hash,
        hits=hits,
        limit=limit,
        duration=duration,
        algo=algo,
        gnp=jnp.zeros(key_hash.shape[0], bool),
        valid=valid & mine,
    )
    store2, resp, _ = decide_presorted(store, peek, now)

    mask = mine & valid

    def combine(x):
        return _hier_psum(jnp.where(mask, x, 0), axes)

    status = combine(resp.status)
    r_limit = combine(resp.limit)
    remaining = combine(resp.remaining)
    reset = combine(resp.reset_time)

    # install replicas everywhere except the owner shard
    store3 = upsert_globals(
        store2,
        key_hash,
        r_limit,
        remaining,
        reset,
        status == 1,
        valid & ~mine,
    )
    return jax.tree.map(lambda x: x[None], store3), BatchResponse(
        status=status, limit=r_limit, remaining=remaining, reset_time=reset
    )


def _shard_upsert(
    store: Store,
    key_hash: jax.Array,
    limit: jax.Array,
    remaining: jax.Array,
    reset_time: jax.Array,
    is_over: jax.Array,
    valid: jax.Array,
    n_shards: int,
    axes: tuple = ("shard",),
):
    """Install GLOBAL replica statuses on each key's owning shard."""
    me = _axis_me(axes)
    store = jax.tree.map(lambda x: x[0], store)
    mine = owner_of(key_hash, n_shards) == me
    out = upsert_globals(
        store, key_hash, limit, remaining, reset_time, is_over, valid & mine
    )
    return jax.tree.map(lambda x: x[None], out)


def _shard_upsert_full(
    store: Store,
    key_hash: jax.Array,
    limit: jax.Array,
    remaining: jax.Array,
    reset_time: jax.Array,
    duration: jax.Array,
    ts: jax.Array,
    flags: jax.Array,
    valid: jax.Array,
    n_shards: int,
    axes: tuple = ("shard",),
):
    """Full-lane window install on each key's owning shard (r19): the
    mesh twin of upsert_windows_jit, carrying the raw L_DURATION/L_TS/
    L_FLAGS words so restored/re-partitioned entries of any algorithm
    land byte-exact."""
    from gubernator_tpu.core.store import FLAG_STICKY_OVER

    me = _axis_me(axes)
    store = jax.tree.map(lambda x: x[0], store)
    mine = owner_of(key_hash, n_shards) == me
    out = upsert_globals(
        store, key_hash, limit, remaining, reset_time,
        (flags & FLAG_STICKY_OVER) != 0, valid & mine,
        duration=duration, ts=ts, flags=flags,
    )
    return jax.tree.map(lambda x: x[None], out)


class PartitionedEngine:
    """ONE engine, every topology (r14): host glue + device programs
    for the slot store (and the r13 sketch cold tier), parameterized by
    a ShardingPolicy instead of being forked per topology.

    The policy decides the layout; the engine's host-side surfaces are
    layout-independent and SHARED, so decide/upsert/snapshot/sketch
    paths cannot drift between topologies (the r9 stack_shard_groups
    seam, finished):

    - flat (ShardingPolicy.single, the degenerate case): batches are
      flat [B] arrays, dispatch is a plain jit with the store donated —
      byte-identical to the historical single-device TpuEngine,
      including every padding and presort convention
      (tests/test_prep_pipeline.py pins them).
    - mesh (ShardingPolicy.over_mesh): the store (and sketch) gain a
      leading shard axis laid out over the mesh; batches become
      [n_shards, B_sub] per-shard sub-batches routed host-side by
      `owner = mix64(key_hash) mod n` (the consistent-hash ring mapped
      onto the mesh axis); dispatch is a jitted shard_map where each
      chip runs the SAME single-device kernel on its own sub-batch —
      no collective on the decide path. GLOBAL sync/upsert ride
      collectives (psum / owner-masked upsert) whose structure the
      policy picks (hierarchical ICI-then-DCN on 2-D meshes).

    TpuEngine and MeshEngine below are thin constructor shims over
    this class; parallel/multihost.py wraps it with the lockstep step
    pipe for multi-controller SPMD.
    """

    def __init__(
        self,
        config: StoreConfig = StoreConfig(),
        policy: Optional[ShardingPolicy] = None,
        buckets: Sequence[int] = (64, 256, 1024, 4096),
        sketch=None,
    ):
        self.policy = (
            policy if policy is not None else ShardingPolicy.single()
        )
        self.flat = self.policy.flat
        self.config = config
        self.buckets = sorted(buckets)
        self.device = self.policy.device
        self.clock = EpochClock()
        self.stats = EngineStats()
        # bumped by every reset(): the store-wipe epoch the over-limit
        # shed cache checks (serve/shedcache.py)
        self.reset_generation = 0
        # serve-tier hot-key observer (serve/promoter.py): called with
        # every dispatched BatchRequest (numpy, pre-device, flat [B] or
        # sharded [n_shards, B_sub] — the observer masks by `valid`
        # either way) so the streaming top-K candidate source sees all
        # traffic regardless of door or topology. Must never raise into
        # the dispatch path.
        self.observe_hook = None
        # sketch cold tier (r13; sharded over the mesh axis since r14):
        # `sketch_on` is the runtime A/B flag (scripts/perf_gate.py
        # flips it between paired rounds; both variants compile lazily)
        self.sketch_config = sketch
        self.sketch = None
        self.sketch_on = sketch is not None
        # r20: process-spanning meshes carry the sketch tier too — the
        # promoter's host reads (estimates, live rows) compile to
        # owner-masked psum collectives (_shard_sketch_min/_shard_rows)
        # instead of leader-only sharded-array indexing, and the
        # multihost wrapper broadcasts promote/ghits as lockstep
        # messages so every process issues the identical programs. The
        # pre-r20 GUBER_SKETCH multihost refusal is lifted.

        if self.flat:
            self.n = 1
            self.mesh = None
            self.axes: tuple = ()
        else:
            self.n = self.policy.n_shards
            self.mesh = self.policy.mesh
            self.axes = self.policy.axes
            self.sub_buckets = sub_batch_ladder(self.buckets)
            self.store_sharding = self.policy.store_sharding()
            self._build_mesh_programs()
        self.store: Store = self._fresh_store()
        if sketch is not None:
            self.sketch = self._fresh_sketch()

    # -- state construction -------------------------------------------------

    def _build_mesh_programs(self) -> None:
        Ps = self.policy.request_spec()
        P0 = self.policy.replicated_spec()
        span = self.policy.spans_processes
        step_fn = (
            functools.partial(_local_decide_gathered, axes=self.axes)
            if span
            else _local_decide
        )
        self._step = jax.jit(
            shard_map_compat(
                step_fn,
                mesh=self.mesh,
                in_specs=(Ps, Ps, Ps, P0),
                out_specs=(Ps, P0 if span else Ps),
                # the all_gather output IS replicated, but the static
                # varying-axis check can't prove it — disable just there
                check=not span,
            ),
            donate_argnums=(0,),
        )
        # quota-chain program (r15): chain-coupled rows, shard-local
        # AND-reduce (chains are routed whole to their head's owner).
        # jit is lazy, so deployments that never see a chain pay only
        # this wrapper construction. Multi-process meshes don't carry
        # it: the lockstep step pipe has no chain message (documented
        # scope limit; decide_chain_submit refuses loudly).
        self._step_chain = None
        if not span:
            self._step_chain = jax.jit(
                shard_map_compat(
                    _local_decide_chain,
                    mesh=self.mesh,
                    in_specs=(Ps, Ps, Ps, Ps, P0),
                    out_specs=(Ps, Ps),
                ),
                donate_argnums=(0,),
            )
        self._step_sketch = None
        if self.sketch_config is not None:
            sketch_step_fn = (
                functools.partial(
                    _local_decide_sketch_gathered, axes=self.axes
                )
                if span
                else _local_decide_sketch
            )
            self._step_sketch = jax.jit(
                shard_map_compat(
                    sketch_step_fn,
                    mesh=self.mesh,
                    in_specs=(Ps, Ps, Ps, Ps, P0),
                    out_specs=(Ps, Ps, P0 if span else Ps),
                    check=not span,
                ),
                donate_argnums=(0, 1),
            )
        # collective host-read programs (r20): when the mesh spans
        # processes the serving host cannot index follower shards, so
        # the promoter-surface reads (_gather_entries row gathers,
        # sketch_estimates row-mins) run as owner-masked psum
        # collectives with replicated outputs instead
        self._rows_coll = None
        self._sketch_min_coll = None
        if span:
            self._rows_coll = jax.jit(
                shard_map_compat(
                    functools.partial(_shard_rows, axes=self.axes),
                    mesh=self.mesh,
                    in_specs=(Ps, P0, P0),
                    out_specs=P0,
                    check=False,
                )
            )
            if self.sketch_config is not None:
                self._sketch_min_coll = jax.jit(
                    shard_map_compat(
                        functools.partial(
                            _shard_sketch_min, axes=self.axes
                        ),
                        mesh=self.mesh,
                        in_specs=(Ps, P0, P0),
                        out_specs=P0,
                        check=False,
                    )
                )
        sync_fn = functools.partial(
            _shard_sync_globals, n_shards=self.n, axes=self.axes
        )
        self._sync = jax.jit(
            shard_map_compat(
                sync_fn,
                mesh=self.mesh,
                in_specs=(Ps,) + (P0,) * 7,
                out_specs=(Ps, P0),
            ),
            donate_argnums=(0,),
        )
        upsert_fn = functools.partial(
            _shard_upsert, n_shards=self.n, axes=self.axes
        )
        self._upsert = jax.jit(
            shard_map_compat(
                upsert_fn,
                mesh=self.mesh,
                in_specs=(Ps,) + (P0,) * 6,
                out_specs=Ps,
            ),
            donate_argnums=(0,),
        )
        upsert_full_fn = functools.partial(
            _shard_upsert_full, n_shards=self.n, axes=self.axes
        )
        self._upsert_full = jax.jit(
            shard_map_compat(
                upsert_full_fn,
                mesh=self.mesh,
                in_specs=(Ps,) + (P0,) * 8,
                out_specs=Ps,
            ),
            donate_argnums=(0,),
        )

    def _replicate(self, x):
        """Stack a per-shard leaf to [n_shards, ...] laid over the
        policy's store sharding."""
        stacked = jnp.broadcast_to(x[None], (self.n,) + x.shape)
        return jax.device_put(stacked, self.store_sharding)

    def _fresh_store(self) -> Store:
        base = new_store(self.config)
        if self.flat:
            if self.device is not None:
                base = jax.device_put(base, self.device)
            return base
        return jax.tree.map(self._replicate, base)

    def _fresh_sketch(self):
        from gubernator_tpu.core.sketches import new_sketch

        sk = new_sketch(self.sketch_config)
        if self.flat:
            if self.device is not None:
                sk = jax.device_put(sk, self.device)
            return sk
        return jax.tree.map(self._replicate, sk)

    def reset(self) -> None:
        self.store = self._fresh_store()
        if self.sketch_config is not None:
            self.sketch = self._fresh_sketch()
        self.reset_generation += 1

    def _engine_now(self, now: int) -> np.int32:
        e, delta, reset_required = self.clock.advance(now)
        if reset_required:
            self.reset()
        elif delta is not None:
            # rebase is elementwise, so it runs shard-local with the
            # store's sharding preserved — no collective needed
            self.store = rebase_jit(self.store, np.int32(delta))
            if self.sketch is not None:
                # sketch windows are keyed by engine-ms // duration, so
                # a rebase shifts every window id: clear rather than
                # carry counts into wrong windows. Rare (~12-day
                # cadence) and one-sided-safe in the fail-open
                # direction for at most one window per key — the same
                # class of loss as the reference's restart contract.
                self.sketch = self._fresh_sketch()
        return e

    # -- the one dispatch funnel --------------------------------------------

    def _dispatch(self, req, groups, e_now):
        """Every submit path — flat or sharded, flush-prep, arrival-
        prep or merged — ends here: feed the serve-tier hot-key
        observer (numpy fields, pre-device) and pick the exact-only or
        two-tier program for this engine's layout."""
        hook = self.observe_hook
        if hook is not None:
            try:
                hook(req)
            except Exception:  # pragma: no cover - defensive
                pass  # observability must never fail a dispatch
        two_tier = self.sketch is not None and self.sketch_on
        if self.flat:
            from gubernator_tpu.core.engine import (
                _decide_packed_jit,
                _decide_packed_sketch_jit,
            )

            if two_tier:
                self.store, self.sketch, packed = (
                    _decide_packed_sketch_jit(
                        self.store, self.sketch, req, e_now, groups
                    )
                )
                return packed
            self.store, packed = _decide_packed_jit(
                self.store, req, e_now, groups
            )
            return packed
        if two_tier:
            self.store, self.sketch, packed = self._step_sketch(
                self.store, self.sketch, req, groups, e_now
            )
            return packed
        self.store, packed = self._step(self.store, req, groups, e_now)
        return packed

    # -- request-object API --------------------------------------------------

    def get_rate_limits_submit(
        self,
        reqs: Sequence["RateLimitReq"],
        now: Optional[int] = None,
        gnp: Optional[Sequence[bool]] = None,
    ):
        """Request-object sibling of decide_submit: convert + presort +
        dispatch one batch without waiting. Returns an opaque handle for
        get_rate_limits_wait, or None for an empty batch."""
        from gubernator_tpu.core.hashing import slot_hash_batch

        n = len(reqs)
        if n == 0:
            return None
        if now is None:
            now = api_types.millisecond_now()
        keys = [r.hash_key() for r in reqs]
        hashes = slot_hash_batch(keys)
        hits = np.fromiter((r.hits for r in reqs), np.int64, n)
        limit = np.fromiter((r.limit for r in reqs), np.int64, n)
        duration = np.fromiter((r.duration for r in reqs), np.int64, n)
        algo = np.fromiter((int(r.algorithm) for r in reqs), np.int32, n)
        gnp_arr = (
            np.asarray(gnp, bool) if gnp is not None else np.zeros(n, bool)
        )
        return self.decide_submit(
            hashes, hits, limit, duration, algo, gnp_arr, now
        )

    def get_rate_limits_wait(self, handle):
        """Fetch + convert the responses for a get_rate_limits_submit
        handle."""
        from gubernator_tpu.api.types import resps_from_columns

        if handle is None:
            return []
        return resps_from_columns(*self.decide_wait(handle))

    def get_rate_limits(
        self,
        reqs: Sequence["RateLimitReq"],
        now: Optional[int] = None,
        gnp: Optional[Sequence[bool]] = None,
    ):
        """Decide a batch. `gnp[i]` marks GLOBAL non-owner replica reads."""
        return self.get_rate_limits_wait(
            self.get_rate_limits_submit(reqs, now=now, gnp=gnp)
        )

    # -- array decide paths --------------------------------------------------

    def decide_submit(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        algo: np.ndarray,
        gnp: np.ndarray,
        now: int,
    ):
        """Presort(/shard) + dispatch one batch WITHOUT waiting.

        The store update is effective immediately (the jitted call
        threads the donated store), so the next submit may follow at
        once; jax dispatch is async, which lets the caller presort
        batch i+1 while the device computes batch i — the pipelining
        the serving batcher relies on. Returns an opaque handle for
        decide_wait; the handle captures the submit-time epoch so a
        later rebase cannot skew an in-flight batch's reset_times."""
        n = key_hash.shape[0]
        e_now = self._engine_now(now)
        if self.flat:
            req, order, groups = pad_request_sorted(
                self.buckets,
                self.config.slots,
                key_hash,
                hits,
                limit,
                duration,
                algo,
                gnp,
                with_groups=True,
            )
            packed = self._dispatch(req, groups, e_now)
            return (
                packed, order, None, n, req.key_hash.shape[0],
                self.clock.epoch,
            )
        req, order, take_idx, groups = pad_request_sharded(
            self.sub_buckets,
            self.config.slots,
            self.n,
            key_hash,
            hits,
            limit,
            duration,
            algo,
            gnp,
            with_groups=True,
        )
        B_sub = req.key_hash.shape[1]
        packed = self._dispatch(req, groups, e_now)
        if _prep_native is not None:
            # the native prep returns order/take_idx as VIEWS into its
            # reusable buffer ring; this handle can outlive any fixed
            # ring depth under the batcher's out-of-order fetch
            # pipeline, so keep copies (device-field views need none:
            # dispatch commits host inputs before the step returns)
            order = order.copy()
            take_idx = take_idx.copy()
        return (packed, order, take_idx, n, B_sub, self.clock.epoch)

    def decide_chain_submit(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        algo: np.ndarray,
        chain_ids: np.ndarray,
        route_hash: np.ndarray,
        now: int,
    ):
        """Dispatch one CHAINED batch (r15) without waiting: rows
        sharing a `chain_ids` value are one hierarchical request's
        levels, decided atomically under the no-partial-debit contract
        (kernels.decide_presorted_chain); `route_hash` (the chain
        head's key hash per row) picks the owning shard so chains stay
        whole. Handle format is decide_wait's. Chain batches run
        exact-only (no sketch tier) and take the numpy prep path — a
        dedicated lane, not the native-prep pipeline."""
        if self.policy.spans_processes:
            raise ValueError(
                "quota chains are not supported on the multihost "
                "lockstep engine (no chain step message); route chains "
                "to single-host backends"
            )
        n = key_hash.shape[0]
        e_now = self._engine_now(now)
        req, order, take_idx, groups, chain_local = pad_request_chained(
            self.buckets if self.flat else self.sub_buckets,
            self.config.slots,
            self.n,
            key_hash,
            hits,
            limit,
            duration,
            algo,
            chain_ids,
            route_hash,
        )
        hook = self.observe_hook
        if hook is not None:
            try:
                hook(req)
            except Exception:  # pragma: no cover - defensive
                pass
        if self.flat:
            from gubernator_tpu.core.engine import _decide_packed_chain_jit

            B = req.key_hash.shape[0]
            self.store, packed = _decide_packed_chain_jit(
                self.store, req, e_now, groups, chain_local
            )
            order_p = np.empty(B, np.int32)
            order_p[:n] = order
            order_p[n:] = np.arange(n, B, dtype=np.int32)
            return (packed, order_p, None, n, B, self.clock.epoch)
        B_sub = req.key_hash.shape[1]
        self.store, packed = self._step_chain(
            self.store, req, groups, chain_local, e_now
        )
        return (packed, order, take_idx, n, B_sub, self.clock.epoch)

    def decide_chain_arrays(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        algo: np.ndarray,
        chain_ids: np.ndarray,
        route_hash: np.ndarray,
        now: int,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Array-level chained decide: submit + wait (times int64
        unix-ms in/out, like decide_arrays)."""
        return self.decide_wait(
            self.decide_chain_submit(
                key_hash, hits, limit, duration, algo, chain_ids,
                route_hash, now,
            )
        )

    def prep_run(self, fields: dict) -> dict:
        """Arrival-time per-group prep (serve/batcher.py): one sorted,
        device-dtype run the flush-time merge combine stitches. The
        sort key is the policy's — (bucket, fp) flat, (owner, bucket,
        fp) sharded — so runs merge without re-sorting either way."""
        from gubernator_tpu.core.engine import prep_run_single

        if self.flat:
            return prep_run_single(fields, self.config.slots)
        return prep_run_sharded(fields, self.config.slots, self.n)

    def merge_prepped(self, runs):
        """Merge pre-sorted per-group runs into one dispatch-ready
        batch (the submit thread's `merge` stage)."""
        from gubernator_tpu.serve.prep import merge_runs

        if self.flat:
            from gubernator_tpu.core.engine import (
                _hn as _ce_hn,
                build_presorted_request,
                choose_bucket,
                group_rungs,
            )

            n = int(sum(r["n"] for r in runs))
            B = choose_bucket(self.buckets, n)
            if (
                _ce_hn is not None
                and getattr(_ce_hn, "_HAS_MERGE", False)
                and n
            ):
                m = _ce_hn.merge_runs_native(
                    runs, B, g_rungs=group_rungs(B)
                )
                req = BatchRequest(
                    key_hash=m["key_hash"], hits=m["hits"],
                    limit=m["limit"], duration=m["duration"],
                    algo=m["algo"], gnp=m["gnp"], valid=m["valid"],
                )
                groups = BatchGroups(
                    key_hash=m["group_key_hash"],
                    leader_pos=m["leader_pos"],
                    end_pos=m["group_end"],
                    valid=m["group_valid"],
                    group_id=m["group_id"],
                )
                return dict(
                    req=req, groups=groups, order=m["order"], n=n, B=B
                )
            m = merge_runs(runs)
            req, groups, B = build_presorted_request(
                self.buckets, m["fields"], m["skey"], n
            )
            order_p = np.empty(B, np.int32)
            order_p[:n] = m["order"]
            order_p[n:] = np.arange(n, B, dtype=np.int32)
            return dict(req=req, groups=groups, order=order_p, n=n, B=B)
        m = merge_runs(runs)
        req, take_idx, groups, B_sub = build_presorted_sharded(
            self.sub_buckets, self.config.slots, self.n, m["fields"],
            m["skey"], m["counts"],
        )
        return dict(
            req=req, groups=groups, order=m["order"],
            take_idx=take_idx, n=m["order"].shape[0], B_sub=B_sub,
        )

    def decide_submit_merged(self, merged: dict, now: int):
        """Dispatch a merge_prepped batch: epoch bookkeeping + the one
        jitted call — the submit thread's `dispatch` stage."""
        e_now = self._engine_now(now)
        packed = self._dispatch(merged["req"], merged["groups"], e_now)
        if self.flat:
            return (
                packed, merged["order"], None, merged["n"], merged["B"],
                self.clock.epoch,
            )
        return (
            packed, merged["order"], merged["take_idx"], merged["n"],
            merged["B_sub"], self.clock.epoch,
        )

    def decide_submit_presorted(
        self,
        fields: dict,
        skey: np.ndarray,
        order: Optional[np.ndarray],
        counts: np.ndarray,
        now: int,
    ):
        """Dispatch a batch whose host presort already happened
        (arrival-time prep + merge combine): `fields` are device-dtype
        request arrays in the policy's sorted order, `skey` the
        matching sorted composite keys, `order[k]` the caller index of
        sorted row k (None = identity, the lockstep-follower path),
        `counts` the per-shard row counts ([n] on the flat policy).
        Pads + derives the duplicate-key group structure in O(n) and
        dispatches — no argsort anywhere."""
        n = skey.shape[0]
        if n == 0:
            return None
        e_now = self._engine_now(now)
        if self.flat:
            from gubernator_tpu.core.engine import build_presorted_request

            req, groups, B = build_presorted_request(
                self.buckets, fields, skey, n
            )
            order_p = np.empty(B, np.int32)
            order_p[:n] = (
                order
                if order is not None
                else np.arange(n, dtype=np.int32)
            )
            order_p[n:] = np.arange(n, B, dtype=np.int32)
            packed = self._dispatch(req, groups, e_now)
            return (packed, order_p, None, n, B, self.clock.epoch)
        req, take_idx, groups, B_sub = build_presorted_sharded(
            self.sub_buckets, self.config.slots, self.n, fields, skey,
            counts,
        )
        if order is None:
            order = np.arange(n, dtype=np.int32)
        packed = self._dispatch(req, groups, e_now)
        return (packed, order, take_idx, n, B_sub, self.clock.epoch)

    def decide_wait(
        self, handle
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Fetch + unpermute the responses for a decide_submit handle.
        One handle format for every policy: (packed, order, take_idx,
        n, B, epoch) with take_idx None on the flat layout."""
        packed, order, take_idx, n, B, epoch = handle
        packed = np.asarray(jax.device_get(packed))
        if take_idx is None:
            from gubernator_tpu.core.engine import (
                _marshal,
                unpermute_responses,
            )
            from gubernator_tpu.core.kernels import unpack_outputs

            self.stats.add_batch(
                int(packed[4 * B]),
                int(packed[4 * B + 1]),
                int(packed[4 * B + 2]),
                int(packed[4 * B + 3]),
            )
            if _marshal is not None:
                u = _marshal.unpermute_i32(
                    packed[: 4 * B].reshape(4, B), order, n
                )
                status, rlimit, remaining, reset = u[0], u[1], u[2], u[3]
            else:
                s_st, s_lim, s_rem, s_reset = unpack_outputs(packed, B)[:4]
                status, rlimit, remaining, reset = unpermute_responses(
                    order, (s_st, s_lim, s_rem, s_reset)
                )
            r = np.asarray(reset[:n], np.int64)
            reset = np.where(r == 0, 0, r + epoch)
            return status[:n], rlimit[:n], remaining[:n], reset
        B_sub = B
        # [n_shards, 4*B_sub+PACKED_STATS]
        self.stats.add_batch(
            int(packed[:, 4 * B_sub].sum()),
            int(packed[:, 4 * B_sub + 1].sum()),
            int(packed[:, 4 * B_sub + 2].sum()),
            int(packed[:, 4 * B_sub + 3].sum()),
        )
        if _prep_native is not None and n > 0:
            # native one-pass unflatten of all four response columns
            from gubernator_tpu.native.hashlib_native import unflatten_resp

            bounds = np.searchsorted(
                take_idx, np.arange(1, self.n + 1) * B_sub, side="left"
            )
            counts = np.diff(np.concatenate(([0], bounds))).astype(
                np.int64
            )
            u = unflatten_resp(packed, order, counts, n, B_sub)
            status, rlimit, remaining, reset = u[0], u[1], u[2], u[3]
        else:

            def unflatten(col0):
                flat = packed[
                    :, col0 * B_sub : (col0 + 1) * B_sub
                ].reshape(-1)
                out = np.empty(n, flat.dtype)
                out[order] = flat[take_idx]
                return out

            status, rlimit, remaining, reset = (
                unflatten(c) for c in range(4)
            )
        r = np.asarray(reset, np.int64)
        reset = np.where(r == 0, 0, r + epoch)
        return status, rlimit, remaining, reset

    def decide_arrays(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        algo: np.ndarray,
        gnp: np.ndarray,
        now: int,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Array-level entry point (also the benchmark harness's).
        Times in/out are int64 unix-ms; conversion happens here."""
        return self.decide_wait(
            self.decide_submit(
                key_hash, hits, limit, duration, algo, gnp, now
            )
        )

    # -- shared host-side state reads ---------------------------------------

    @staticmethod
    def _pad_keys_pow2(key_hash: np.ndarray, *cols):
        """Pad key hashes (+ parallel int64 columns) to a power-of-two
        length (floor 64) by repeating the last row: un-jitted device
        gathers compile one kernel PER SHAPE, and the promoter's
        candidate count changes every tick (~500ms/tick of eager
        recompiles unpadded). Returns (kh, cols..., n)."""
        n = int(key_hash.shape[0])
        B = 1 << max(6, (n - 1).bit_length())
        kh = np.empty(B, np.uint64)
        kh[:n] = key_hash
        kh[n:] = kh[n - 1] if n else 0
        out = [kh]
        for c in cols:
            p = np.empty(B, np.int64)
            p[:n] = c
            p[n:] = p[n - 1] if n else 0
            out.append(p)
        out.append(n)
        return tuple(out)

    def _gather_entries(self, kh_padded: np.ndarray) -> np.ndarray:
        """Host np int32[B, ways, LANES]: each key's candidate bucket
        row, gathered from the key's owning shard's store — THE one
        lookup every non-mutating host read (snapshot_read, live_mask)
        shares, so the addressed row can never drift between
        topologies. Non-mutating; same thread contract as
        snapshot_read."""
        from gubernator_tpu.core.store import LANES, bucket_index

        kh = jnp.asarray(kh_padded)
        b = bucket_index(kh, self.config.slots)
        if self.flat:
            rows = _rows_flat(self.store.data, b)
        elif self.policy.spans_processes:
            # follower-process shards are not host-addressable: ride
            # the owner-masked psum collective (replicated output)
            owner = jnp.asarray(owner_of_np(kh_padded, self.n))
            rows = self._rows_coll(self.store.data, owner, b)
        else:
            owner = jnp.asarray(owner_of_np(kh_padded, self.n))
            rows = _rows_sharded(self.store.data, owner, b)
        return np.asarray(rows).reshape(kh_padded.shape[0], -1, LANES)

    def snapshot_read(
        self, key_hash: np.ndarray, now: Optional[int] = None
    ):
        """NON-MUTATING host read of the store rows for these uint64
        key hashes: per key, (limit, duration, remaining,
        reset_time_unix, over) for a live token window, or None
        (missing, expired, or leaky — leaky state refills continuously
        and is out of the replication scope). Nothing is written: no
        eviction, no expiry deletion, no stats — which is what makes
        bucket replication provably invisible to the decision stream.

        Thread contract: call from the batcher's single submit thread
        (DeviceBatcher.run_serialized) so the gather can never race a
        store-donating dispatch."""
        from gubernator_tpu.core.store import (
            FLAG_ALGO_LEAKY,
            FLAG_STICKY_OVER,
            L_DURATION,
            L_EXPIRE,
            L_FLAGS,
            L_LIMIT,
            L_REMAINING,
            L_TAG,
            fingerprints,
        )
        from gubernator_tpu.core import hashing

        n = int(key_hash.shape[0])
        if n == 0:
            return []
        if self.clock.epoch is None:
            return [None] * n  # nothing ever decided
        if now is None:
            now = api_types.millisecond_now()
        kh_p, _n = self._pad_keys_pow2(
            np.ascontiguousarray(key_hash, dtype=np.uint64)
        )
        ent_rows = self._gather_entries(kh_p)[:n]
        # fingerprint the PADDED pow2 shape and slice: eager per-n
        # shapes would recompile every distinct snapshot batch size
        fp = np.asarray(
            jax.device_get(fingerprints(jnp.asarray(kh_p)))
        )[:n]
        match = ent_rows[:, :, L_TAG] == fp[:, None]
        found = match.any(axis=1)
        way = np.argmax(match, axis=1)
        ent = ent_rows[np.arange(n), way]
        e_now = int(self.clock.to_engine(now))
        out = []
        flags_col = ent[:, L_FLAGS]
        for i in range(n):
            if not found[i] or int(ent[i, L_EXPIRE]) < e_now:
                out.append(None)  # miss, or entry past its reset
                continue
            flags = int(flags_col[i])
            if flags & FLAG_ALGO_LEAKY:
                out.append(None)
                continue
            remaining = int(ent[i, L_REMAINING])
            reset_time = int(
                self.clock.from_engine(np.int64(ent[i, L_EXPIRE]))
            )
            out.append((
                int(ent[i, L_LIMIT]),
                int(ent[i, L_DURATION]),
                remaining,
                reset_time,
                bool(flags & FLAG_STICKY_OVER) or remaining == 0,
            ))
        return out

    def live_mask(
        self, key_hash: np.ndarray, now: Optional[int] = None
    ) -> np.ndarray:
        """bool[n]: key currently holds a LIVE exact-tier entry (tag
        match, not expired) on its owning shard. Non-mutating; same
        thread contract as snapshot_read. The promoter screens
        candidates with this so an install can never clobber live
        exact state."""
        from gubernator_tpu.core.store import L_EXPIRE, L_TAG, fingerprints

        n = int(key_hash.shape[0])
        if n == 0 or self.clock.epoch is None:
            return np.zeros(n, bool)
        if now is None:
            now = api_types.millisecond_now()
        kh_p, _n = self._pad_keys_pow2(
            np.ascontiguousarray(key_hash, np.uint64)
        )
        rows = self._gather_entries(kh_p)
        fp = np.asarray(jax.device_get(fingerprints(jnp.asarray(kh_p))))
        match = rows[:, :, L_TAG] == fp[:, None]
        e_now = int(self.clock.to_engine(now))
        live = match & (rows[:, :, L_EXPIRE] >= e_now)
        return live.any(axis=1)[:n]

    # -- elastic re-partition (r17) ------------------------------------------

    def export_windows(self, now: Optional[int] = None) -> dict:
        """Host-side read of EVERY live window in the store:
        {key_hash uint64[m], limit, remaining, reset_time (unix-ms),
        is_over, duration, ts, flags} — the full-store twin of
        snapshot_read, enumerating entries instead of looking keys up.
        Each entry's key hash is reconstructed from its L_TAG|L_KEYLOW
        lanes (the r14 layout keeps the full 64 bits precisely so
        store state stays re-addressable); the one lossy case is a
        hash whose high 32 bits were zero (fingerprints() coerces the
        tag to 1, ~2^-32 per key). `is_over` carries the
        FLAG_STICKY_OVER bit ONLY — an exhausted-but-not-sticky window
        must reinstall as exactly that (a sticky bit added in transit
        would flip its peek answers from UNDER to OVER).

        r19 widened the export from token-only to flag-aware: the raw
        `duration` (L_DURATION), `ts` (L_TS: the leaky leak clock /
        sliding previous-subwindow count) and `flags` (L_FLAGS: the
        algo bits + sticky) lanes ride along, so leaky, sliding-window
        and GCRA entries — and chain-level rows, which are ordinary
        token rows keyed by level — round-trip byte-exact through
        install_windows under ANY ShardingPolicy ("restore is also a
        re-partition"). `reset_time` is the L_EXPIRE lane in unix-ms
        whatever the algorithm encodes there (expiry, window anchor,
        or GCRA theoretical-arrival time); the same engine-clock
        conversion inverts it on install. Non-mutating; submit-thread
        contract like snapshot_read."""
        from gubernator_tpu.core.store import (
            FLAG_STICKY_OVER,
            L_DURATION,
            L_EXPIRE,
            L_FLAGS,
            L_KEYLOW,
            L_LIMIT,
            L_REMAINING,
            L_TAG,
            L_TS,
            LANES,
        )

        empty = dict(
            key_hash=np.empty(0, np.uint64),
            limit=np.empty(0, np.int64),
            remaining=np.empty(0, np.int64),
            reset_time=np.empty(0, np.int64),
            is_over=np.empty(0, bool),
            duration=np.empty(0, np.int64),
            ts=np.empty(0, np.int64),
            flags=np.empty(0, np.int64),
        )
        if self.clock.epoch is None:
            return empty  # nothing ever decided
        if now is None:
            now = api_types.millisecond_now()
        e_now = int(self.clock.to_engine(now))
        ent = np.asarray(jax.device_get(self.store.data)).reshape(
            -1, LANES
        )
        live = (ent[:, L_TAG] != 0) & (ent[:, L_EXPIRE] >= e_now)
        ent = ent[live]
        if not ent.shape[0]:
            return empty
        hi = ent[:, L_TAG].astype(np.int64).view(np.uint64) & np.uint64(
            0xFFFFFFFF
        )
        lo = ent[:, L_KEYLOW].astype(np.int64).view(
            np.uint64
        ) & np.uint64(0xFFFFFFFF)
        return dict(
            key_hash=(hi << np.uint64(32)) | lo,
            limit=ent[:, L_LIMIT].astype(np.int64),
            remaining=ent[:, L_REMAINING].astype(np.int64),
            reset_time=np.asarray(
                self.clock.from_engine(ent[:, L_EXPIRE]), np.int64
            ),
            is_over=(ent[:, L_FLAGS] & FLAG_STICKY_OVER) != 0,
            duration=ent[:, L_DURATION].astype(np.int64),
            ts=ent[:, L_TS].astype(np.int64),
            flags=ent[:, L_FLAGS].astype(np.int64),
        )

    def repartition(
        self, policy: ShardingPolicy, now: Optional[int] = None
    ) -> "PartitionedEngine":
        """A NEW engine under `policy` carrying every live window of
        this one: export_windows -> install_windows under the new
        ShardingPolicy — the store re-partition path a GUBER_SHARDS
        change drives (serve/backends.py MeshBackend.repartition), and
        since r19 also the checkpoint-restore-across-a-shard-change
        path ("restore is also a re-partition"). The full-lane
        round-trip carries every algorithm's state (token, leaky,
        sliding, GCRA, chain-level rows) byte-exact. Same geometry/
        ladder/sketch config; sketch-tier counts do NOT migrate
        (window-keyed, transient — the loss direction is a one-window
        over-admission in the cold tier, same as a store reset, and
        the hot exact tier moves losslessly). Call with the batcher
        idle or on its serialized submit thread; warm the new engine
        before serving."""
        if now is None:
            now = api_types.millisecond_now()
        eng = PartitionedEngine(
            self.config,
            policy=policy,
            buckets=self.buckets,
            sketch=self.sketch_config,
        )
        w = self.export_windows(now)
        if w["key_hash"].shape[0]:
            eng.install_windows(
                w["key_hash"], w["limit"], w["remaining"],
                w["reset_time"], w["is_over"], now=now,
                duration=w["duration"], ts=w["ts"], flags=w["flags"],
            )
        return eng

    # -- GLOBAL install / sync ----------------------------------------------

    def _upsert_padded(self, hashes, lim, rem, reset, over, valid):
        """One padded replica-install call against this policy's
        layout: flat = the donated single-store upsert jit; mesh = the
        owner-masked shard_map upsert collective."""
        if self.flat:
            self.store = upsert_globals_jit(
                self.store, hashes, lim, rem, reset, over, valid
            )
        else:
            self.store = self._upsert(
                self.store, hashes, lim, rem, reset, over, valid
            )

    def _upsert_full_padded(self, hashes, lim, rem, reset, dur, ts,
                            flags, valid):
        """One padded full-lane install call (r19): the flag-aware twin
        of _upsert_padded, carrying duration/ts/flags through to the
        store so any algorithm's entry reinstalls byte-exact."""
        if self.flat:
            self.store = upsert_windows_jit(
                self.store, hashes, lim, rem, reset, dur, ts, flags,
                valid,
            )
        else:
            self.store = self._upsert_full(
                self.store, hashes, lim, rem, reset, dur, ts, flags,
                valid,
            )

    def install_windows(
        self,
        key_hash: np.ndarray,
        limit: np.ndarray,
        remaining: np.ndarray,
        reset_time: np.ndarray,
        is_over: np.ndarray,
        now: Optional[int] = None,
        duration: Optional[np.ndarray] = None,
        ts: Optional[np.ndarray] = None,
        flags: Optional[np.ndarray] = None,
    ) -> None:
        """Install windows for pre-hashed keys — the array-level
        GLOBAL replica install (UpdatePeerGlobals receive path) and the
        sketch promoter's migration surface. Batches larger than the
        bucket ladder's top rung are CHUNKED (installs are per-key
        upserts; chunk order preserves last-wins for duplicates), so
        callers never hit a choose_bucket refusal.

        Without the optional lanes the install is the historical
        token-replica form (zero duration/ts, sticky-only flags). With
        `duration`/`ts`/`flags` (r19: export_windows round-trip), the
        raw lanes land verbatim, so leaky/sliding/GCRA entries — and
        sticky bits — survive a restore or re-partition byte-exact;
        `is_over` is then ignored (the sticky bit lives in `flags`)."""
        kh = np.ascontiguousarray(key_hash, np.uint64)
        n = int(kh.shape[0])
        if n == 0:
            return
        if now is None:
            now = api_types.millisecond_now()
        self._engine_now(now)  # pin/refresh the epoch
        top = max(self.buckets)
        limit = np.asarray(limit)
        remaining = np.asarray(remaining)
        reset_time = np.asarray(reset_time)
        full = flags is not None
        if full:
            duration = np.asarray(duration)
            ts = (
                np.zeros(n, np.int64) if ts is None else np.asarray(ts)
            )
            flags = np.asarray(flags)
        else:
            is_over = np.asarray(is_over, bool)
        for s in range(0, n, top):
            e = min(s + top, n)
            if full:
                hashes, lim, rem, reset, dur, tss, flg, valid = (
                    pad_to_bucket(
                        self.buckets,
                        e - s,
                        (kh[s:e], np.uint64),
                        (_sat_i32(limit[s:e]), np.int32),
                        (_sat_i32(remaining[s:e]), np.int32),
                        (self.clock.to_engine(reset_time[s:e]),
                         np.int32),
                        (_sat_i32(duration[s:e]), np.int32),
                        (_sat_i32(ts[s:e]), np.int32),
                        (_sat_i32(flags[s:e]), np.int32),
                    )
                )
                self._upsert_full_padded(
                    hashes, lim, rem, reset, dur, tss, flg, valid
                )
                continue
            hashes, lim, rem, reset, over, valid = pad_to_bucket(
                self.buckets,
                e - s,
                (kh[s:e], np.uint64),
                (_sat_i32(limit[s:e]), np.int32),
                (_sat_i32(remaining[s:e]), np.int32),
                (self.clock.to_engine(reset_time[s:e]), np.int32),
                (is_over[s:e], bool),
            )
            self._upsert_padded(hashes, lim, rem, reset, over, valid)

    def update_globals(self, *args, now: Optional[int] = None, **kw):
        """Install owner-broadcast GLOBAL statuses (UpdatePeerGlobals
        receive path). Two call forms, ONE install path (both funnel
        into install_windows, so the replica-install semantics cannot
        drift between the serving tiers):

        - object form: update_globals([(key, RateLimitResp), ...])
        - array form:  update_globals(key_hash=..., limit=...,
          remaining=..., reset_time=..., is_over=...) — positional
          ndarrays accepted for the historical MeshEngine signature.
        """
        updates_kw = kw.pop("updates", None)
        if updates_kw is not None:
            if args or kw:
                raise TypeError(
                    "update_globals(updates=...) excludes other args"
                )
            args = (updates_kw,)
        if kw or len(args) > 1 or (
            args and isinstance(args[0], np.ndarray)
        ):
            names = ("key_hash", "limit", "remaining", "reset_time",
                     "is_over")
            vals = dict(zip(names, args))
            vals.update(kw)
            return self.install_windows(
                vals["key_hash"], vals["limit"], vals["remaining"],
                vals["reset_time"], vals["is_over"], now=now,
            )
        from gubernator_tpu.api.types import Status
        from gubernator_tpu.core.hashing import slot_hash_batch

        updates = list(args[0]) if args else []
        n = len(updates)
        if n == 0:
            return
        return self.install_windows(
            slot_hash_batch([k for k, _ in updates]),
            np.fromiter((s.limit for _, s in updates), np.int64, n),
            np.fromiter((s.remaining for _, s in updates), np.int64, n),
            np.fromiter((s.reset_time for _, s in updates), np.int64, n),
            np.fromiter(
                (s.status == Status.OVER_LIMIT for _, s in updates),
                bool, n,
            ),
            now=now,
        )

    def _sync_padded(self, key_hash, hits, limit, duration, algo, now):
        """One padded owner-charge + psum-replicate + replica-install
        collective step; returns the padded sorted-order responses and
        the pad order. Flat degenerate case: the owner leg IS the whole
        mesh, so the same semantics are one local decide (identical
        kernel; the replica-install leg is empty)."""
        n = key_hash.shape[0]
        if algo is None:
            algo = np.zeros(n, np.int32)
        e_now = self._engine_now(now)
        if self.flat:
            # gossip traffic must not heat the promoter's top-K or
            # count as decide batches in EngineStats — the mesh
            # branch's collective records neither, and the two
            # policies may not drift (runs on the serialized submit
            # thread, so the swap-out is not racy)
            hook, self.observe_hook = self.observe_hook, None
            stats, self.stats = self.stats, EngineStats()
            try:
                # sync batches are gossip accumulations with no upper
                # bound; the flat ladder tops out at max(buckets), so
                # chunk (like install_windows) rather than refuse —
                # the mesh branch handles the same overflow by
                # extending its ladder
                top = max(self.buckets)
                if n <= top:
                    h = self.decide_submit(
                        key_hash, hits, limit, duration, algo,
                        np.zeros(n, bool), now,
                    )
                    return self.decide_wait(h), None
                cols = ([], [], [], [])
                for s in range(0, n, top):
                    e = min(s + top, n)
                    h = self.decide_submit(
                        key_hash[s:e], hits[s:e], limit[s:e],
                        duration[s:e], algo[s:e],
                        np.zeros(e - s, bool), now,
                    )
                    for c, v in zip(cols, self.decide_wait(h)):
                        c.append(v)
                return tuple(np.concatenate(c) for c in cols), None
            finally:
                self.observe_hook = hook
                self.stats = stats
        if n > max(self.buckets):
            _warn_ladder_overflow(max(self.buckets), n)
        req, order = pad_request_sorted(
            extend_ladder(self.buckets, n),
            self.config.slots,
            key_hash,
            hits,
            limit,
            duration,
            algo,
            np.zeros(n, bool),
        )
        self.store, resp = self._sync(
            self.store,
            req.key_hash,
            req.hits,
            req.limit,
            req.duration,
            req.algo,
            req.valid,
            e_now,
        )
        return resp, order

    def sync_globals(
        self,
        key_hash: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        now: int,
        algo: Optional[np.ndarray] = None,
    ) -> None:
        """One collective gossip step for the given GLOBAL keys: owner
        peeks authoritative status (hits=0), a psum replicates it
        mesh-wide, every non-owner installs replica entries. `algo`
        must carry each key's algorithm (defaults to token bucket)."""
        n = key_hash.shape[0]
        if n == 0:
            return
        self._sync_padded(
            key_hash, np.zeros(n, np.int64), limit, duration, algo, now
        )

    def apply_global_hits(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        now: int,
        algo: Optional[np.ndarray] = None,
    ):
        """In-mesh GLOBAL hit aggregation (r14 prototype, the SNIPPETS
        brief's psum): charge each key's aggregated GLOBAL hits on its
        OWNER shard and replicate the post-charge status to every other
        shard in ONE collective step — the owner->replica gossip loop
        (queue hits -> owner applies -> broadcast -> replicas install)
        collapsed into a single device program when the "peers" are
        shards of one mesh. Returns (status, limit, remaining,
        reset_time_unix) per key in caller order — the authoritative
        post-charge windows, ready for a cross-NODE broadcast when the
        mesh is one node of a wider ring."""
        n = key_hash.shape[0]
        if n == 0:
            z = np.empty(0, np.int64)
            return z, z, z, z
        resp, order = self._sync_padded(
            key_hash, hits, limit, duration, algo, now
        )
        if order is None:  # flat: decide_wait already unpermuted
            return resp
        epoch = self.clock.epoch

        def unpad(a):
            a = np.asarray(a)
            out = np.empty(a.shape[0], a.dtype)
            out[order] = a
            return out[:n]

        status = unpad(resp.status)
        rlimit = unpad(resp.limit)
        remaining = unpad(resp.remaining)
        r = unpad(resp.reset_time).astype(np.int64)
        reset = np.where(r == 0, 0, r + epoch)
        return status, rlimit, remaining, reset

    # -- sketch cold tier (r13, sharded r14) --------------------------------

    def _sketch_windows(self, durations: np.ndarray, now: int):
        """(window_id int64[n], window_end_unix int64[n]) for the
        current fixed windows of these durations."""
        from gubernator_tpu.core.sketches import window_id_np

        e_now = int(self.clock.to_engine(now))
        wid = window_id_np(e_now, durations)
        d = np.maximum(np.asarray(durations, np.int64), 1)
        wend_engine = (wid + 1) * d
        return wid, np.asarray(self.clock.from_engine(wend_engine))

    def sketch_estimates(
        self,
        key_hash: np.ndarray,
        durations: np.ndarray,
        now: Optional[int] = None,
    ) -> np.ndarray:
        """NON-MUTATING current-window count-min estimates int64[n]
        for these keys (0 when the tier is off or nothing was ever
        decided), read from each key's OWNING shard's sub-sketch —
        the same addressing the decide kernel charges, so host and
        device views cannot drift. Narrow gathers only; submit-thread
        contract like snapshot_read."""
        n = int(key_hash.shape[0])
        if self.sketch is None or self.clock.epoch is None or n == 0:
            return np.zeros(n, np.int64)
        if now is None:
            now = api_types.millisecond_now()
        from gubernator_tpu.core.sketches import sketch_indices_np

        kh, dur, _n = self._pad_keys_pow2(
            np.ascontiguousarray(key_hash, np.uint64),
            np.asarray(durations, np.int64),
        )
        wid, _ = self._sketch_windows(dur, now)
        idx = sketch_indices_np(kh, wid, self.sketch_config)
        if self.flat:
            est = _sketch_min_flat(self.sketch.data, jnp.asarray(idx))
        elif self.policy.spans_processes:
            owner = jnp.asarray(owner_of_np(kh, self.n))
            est = self._sketch_min_coll(
                self.sketch.data, owner, jnp.asarray(idx)
            )
        else:
            owner = jnp.asarray(owner_of_np(kh, self.n))
            est = _sketch_min_sharded(
                self.sketch.data, owner, jnp.asarray(idx)
            )
        return np.asarray(est, np.int64)[:n]

    def promote_from_sketch(
        self,
        key_hash: np.ndarray,
        limits: np.ndarray,
        durations: np.ndarray,
        now: Optional[int] = None,
    ):
        """Migrate hot sketch-tier keys into exact buckets: read each
        key's current-window estimate (an all-shards gather on the
        mesh) and install a token window with remaining = max(limit -
        estimate, 0) and reset = the window's end on the key's owning
        shard — the key then decides exactly for the rest of the
        window and re-creates exactly in the next one. Keys already
        holding a LIVE exact entry are skipped (their state is
        authoritative). Returns (installed bool[n], estimate int64[n],
        reset_unix int64[n], over bool[n]). Thread contract: submit-
        thread only (DeviceBatcher.run_serialized) — this reads AND
        upserts the store."""
        n = int(key_hash.shape[0])
        if n == 0 or self.sketch is None:
            z = np.zeros(n, np.int64)
            return np.zeros(n, bool), z, z, np.zeros(n, bool)
        if now is None:
            now = api_types.millisecond_now()
        self._engine_now(now)  # pin the epoch before window math
        kh = np.ascontiguousarray(key_hash, np.uint64)
        limits = np.asarray(limits, np.int64)
        est = self.sketch_estimates(kh, durations, now)
        _, reset_unix = self._sketch_windows(durations, now)
        over = est >= limits
        remaining = np.maximum(limits - est, 0)
        todo = ~self.live_mask(kh, now)
        if todo.any():
            self.install_windows(
                kh[todo], limits[todo], remaining[todo],
                reset_unix[todo], over[todo], now,
            )
        return todo, est, reset_unix, over

    # -- warmup --------------------------------------------------------------

    def _warmup_sketch_reads(self, now: int) -> None:
        """Compile the promoter's host-read surfaces at their pow2
        rungs so the first flush ticks don't pay eager compiles on the
        serving submit thread."""
        if self.sketch is None:
            return
        for B in (64, 128, 256, 512, 1024):
            kh = np.arange(1, B + 1, dtype=np.uint64) << np.uint64(32)
            durs = np.full(B, 1000, np.int64)
            self.sketch_estimates(kh, durs, now)
            self.live_mask(kh, now)

    def warmup(self, now: Optional[int] = None) -> None:
        """Pre-compile every (batch rung, group rung) program plus the
        GLOBAL install/sync programs (first TPU jit is ~20-40s; none of
        it may land inside a serving RPC deadline), then reset the
        state the warmup traffic dirtied. Mesh policies additionally
        walk the per-shard sub-rung ladder with batches crafted so
        every shard hits every rung. NOTE: this drives the engine's own
        methods — the multihost lockstep wrapper must run its own
        warmup through its broadcasting public surface
        (parallel/multihost.py)."""
        from gubernator_tpu.api.types import RateLimitResp

        if now is None:
            now = api_types.millisecond_now()
        if self.flat:
            from gubernator_tpu.core.engine import group_rungs

            for b in self.buckets:
                # one XLA program per (request rung, group rung) pair:
                # craft batches whose unique-key count hits each group
                # rung, with distinct FINGERPRINTS (value << 32)
                for g in group_rungs(b):
                    k = np.resize(
                        np.arange(1, g + 1, dtype=np.uint64)
                        << np.uint64(32),
                        b,
                    )
                    ones = np.ones(b, np.int64)
                    self.decide_arrays(
                        k, ones, ones * 10, ones * 1000,
                        np.zeros(b, np.int32), np.zeros(b, bool), now,
                    )
                # the GLOBAL replica-install path is a separate XLA
                # program and must not pay jit time inside a broadcast
                # RPC deadline either
                self.update_globals(
                    [
                        (f"warmup:{i}", RateLimitResp(limit=1))
                        for i in range(b)
                    ],
                    now=now,
                )
            self._warmup_sketch_reads(now)
            self.reset()
            self.stats = EngineStats()
            return
        warmup_public(self, now)


def warmup_public(engine, now: Optional[int] = None) -> None:
    """Mesh warmup through an engine-like object's PUBLIC surface
    (decide_arrays / update_globals / sync_globals / reset): compiles
    every (sub-batch rung, group rung) program plus the collective
    GLOBAL programs. Driving only the public surface is what makes it
    lockstep-safe for the multihost wrapper — every call broadcasts,
    so followers replay the identical compile sequence. The ONE warmup
    body for PartitionedEngine's mesh branch and the serving
    MeshBackend/MultiHostBackend (serve/backends.py), so the compile
    coverage cannot drift between the library and serving tiers."""
    from gubernator_tpu.core.engine import group_rungs

    if now is None:
        now = api_types.millisecond_now()
    n = engine.n
    rungs = engine.sub_buckets
    rng = np.random.default_rng(0xB007)
    pool = rng.integers(1, 2**63, 4 * n * max(rungs), np.int64).astype(
        np.uint64
    )
    owners = owner_of_np(pool, n)
    per_shard = [pool[owners == s] for s in range(n)]
    for r in rungs:
        # one XLA program per (sub-batch rung, group rung) pair: craft
        # per-shard batches whose unique-key count hits each group rung
        # (g == r is the all-unique case)
        for g in group_rungs(r):
            k = np.concatenate([np.resize(p[:g], r) for p in per_shard])
            ones = np.ones(k.shape[0], np.int64)
            engine.decide_arrays(
                key_hash=k, hits=ones, limit=ones * 10,
                duration=ones * 1000,
                algo=np.zeros(k.shape[0], np.int32),
                gnp=np.zeros(k.shape[0], bool),
                now=now,
            )
    # broadcast-receive + gossip collective programs per host rung
    for b in engine.buckets:
        k = np.arange(1, b + 1, dtype=np.uint64)
        ones = np.ones(b, np.int64)
        engine.update_globals(
            key_hash=k,
            limit=ones,
            remaining=ones,
            reset_time=ones * now,
            is_over=np.zeros(b, bool),
            now=now,
        )
        engine.sync_globals(k, ones, ones * 1000, now=now)
    if getattr(engine, "sketch", None) is not None:
        engine._warmup_sketch_reads(now)
    # clear state and counters dirtied by warmup traffic (the stats
    # object is shared through the multihost wrapper's property, so
    # mutate in place rather than rebinding)
    engine.reset()
    engine.stats.__init__()


# narrow jitted gathers shared by the host-side state reads: jit keeps
# sharded-array indexing off the eager path (whole-array materialization)
# and makes the per-shape compile explicit (warmup pre-pays the pow2
# rungs the promoter/replication loops use)
@jax.jit
def _rows_flat(data, b):
    return jnp.take(data, b, axis=0)


@jax.jit
def _rows_sharded(data, owner, b):
    return data[owner, b]


@jax.jit
def _sketch_min_flat(data, idx):
    est = None
    for r in range(idx.shape[0]):
        c = jnp.take(data[r], idx[r])
        est = c if est is None else jnp.minimum(est, c)
    return est


@jax.jit
def _sketch_min_sharded(data, owner, idx):
    est = None
    for r in range(idx.shape[0]):
        c = data[owner, r, idx[r]]
        est = c if est is None else jnp.minimum(est, c)
    return est


class TpuEngine(PartitionedEngine):
    """Single-device engine: PartitionedEngine under the degenerate
    flat policy (one shard, no mesh, plain-jit dispatch). The
    historical name and constructor, kept because "one chip" remains
    the most common deployment; every code path is the shared
    partitioned implementation."""

    def __init__(
        self,
        config: StoreConfig = StoreConfig(),
        buckets: Sequence[int] = (64, 256, 1024, 4096),
        device: Optional[jax.Device] = None,
        sketch=None,
    ):
        super().__init__(
            config,
            policy=ShardingPolicy.single(device),
            buckets=buckets,
            sketch=sketch,
        )


class MeshEngine(PartitionedEngine):
    """Mesh-sharded engine: PartitionedEngine over a device mesh
    (key-space sharding with collective GLOBAL sync). The historical
    name and constructor; see PartitionedEngine for the shared
    implementation."""

    def __init__(
        self,
        config: StoreConfig = StoreConfig(),
        devices: Optional[Sequence[jax.Device]] = None,
        buckets: Sequence[int] = (64, 256, 1024, 4096),
        mesh_shape: Optional[Tuple[int, int]] = None,
        sketch=None,
    ):
        super().__init__(
            config,
            policy=ShardingPolicy.over_mesh(devices, mesh_shape),
            buckets=buckets,
            sketch=sketch,
        )
