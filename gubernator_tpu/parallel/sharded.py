"""Mesh-sharded rate limiting: the consistent-hash ring mapped onto a
`jax.sharding.Mesh`.

The reference distributes keys across peers with a consistent-hash ring and
forwards requests over gRPC (reference hash.go:80-96, peers.go:111-127).
Inside one host, this framework distributes keys across TPU chips instead:

- The slot store gains a leading `shard` axis, laid out over the mesh's
  "shard" axis — every chip owns `1/n` of the key space, the moral
  equivalent of one ring peer per chip, with ownership decided by a cheap
  hash (`owner = mix64(key_hash) mod n`) instead of a sorted ring search:
  with homogeneous chips there is no reason to pay the ring's lookup cost
  or its imbalance (the reference places one point per peer, hash.go:62-67).
- A request batch is replicated to all chips (`shard_map`); each chip
  evaluates the full batch against its own store shard with non-owned rows
  masked invalid, and the per-chip decisions are combined with one
  `jax.lax.psum` over ICI — the collective plays the role of the
  peer-to-peer forwarding RPCs (reference peers.go) with zero host hops.
- GLOBAL mode's owner->replica broadcast (reference global.go:158-232)
  becomes `sync_globals`: owners peek authoritative status, one psum
  replicates it mesh-wide, and every non-owner installs replica entries —
  the async gossip loop collapsed into a single collective step.

Multi-host scaling composes: each host runs one mesh-sharded engine over
its chips, and hosts peer with each other over gRPC exactly like reference
nodes (serve/peers.py), so ICI carries intra-host traffic and DCN only
carries the host-level ring's.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.core.engine import (
    EpochClock,
    _sat_i32,
    pad_request_sorted,
    pad_to_bucket,
    unpermute_responses,
)
from gubernator_tpu.core.kernels import (
    BatchRequest,
    BatchResponse,
    BatchStats,
    decide_presorted,
    pack_outputs,
    rebase_jit,
    unpack_outputs,
    upsert_globals,
)
from gubernator_tpu.core.store import Store, StoreConfig, mix64, new_store

_SHARD_SALT = np.uint64(0xA24BAED4963EE407)


def owner_of(key_hash: jax.Array, n_shards: int) -> jax.Array:
    """Owning shard index for each key hash (device-side)."""
    return (mix64(key_hash ^ _SHARD_SALT) % jnp.uint64(n_shards)).astype(
        jnp.int32
    )


def owner_of_np(key_hash: np.ndarray, n_shards: int) -> np.ndarray:
    """Host-side twin of owner_of (numpy)."""
    from gubernator_tpu.core import hashing

    return (hashing.mix64(key_hash ^ _SHARD_SALT) % np.uint64(n_shards)).astype(
        np.int32
    )


def _shard_decide(store: Store, req: BatchRequest, now, n_shards: int):
    """Per-device body under shard_map: store is this device's shard."""
    me = jax.lax.axis_index("shard")
    store = jax.tree.map(lambda x: x[0], store)  # [1, r, s] -> [r, s]
    mine = owner_of(req.key_hash, n_shards) == me
    # masking non-owned rows leaves them interspersed; decide_presorted's
    # key-based grouping handles that (ownership is per-key, so groups
    # stay uniformly valid or invalid)
    local_req = req._replace(valid=req.valid & mine)
    new_store_shard, resp, stats = decide_presorted(store, local_req, now)

    # Non-owners contribute zeros; one psum combines the mesh's answers.
    mask = mine & req.valid

    def combine(x):
        return jax.lax.psum(jnp.where(mask, x, 0), "shard")

    resp = BatchResponse(
        status=combine(resp.status),
        limit=combine(resp.limit),
        remaining=combine(resp.remaining),
        reset_time=combine(resp.reset_time),
    )
    stats = BatchStats(
        hits=jax.lax.psum(stats.hits, "shard"),
        misses=jax.lax.psum(stats.misses, "shard"),
    )
    return jax.tree.map(lambda x: x[None], new_store_shard), resp, stats


def _packed_shard_decide(store, req, now, n_shards: int):
    """_shard_decide with responses + stats packed into one int32 array —
    one host transfer instead of six (see engine._decide_packed_jit)."""
    store, resp, stats = _shard_decide(store, req, now, n_shards)
    return store, pack_outputs(resp, stats)


def _shard_sync_globals(
    store: Store,
    key_hash: jax.Array,  # uint64[B] global keys to broadcast
    limit: jax.Array,  # int32[B] request limit (for owner-side peek of misses)
    duration: jax.Array,
    algo: jax.Array,  # int32[B]: must match the stored algorithm, or the
    # peek would take the mismatch-recreate path and wipe owner state
    valid: jax.Array,
    now,
    n_shards: int,
):
    """Owner peeks authoritative status; psum replicates; others upsert."""
    me = jax.lax.axis_index("shard")
    store = jax.tree.map(lambda x: x[0], store)
    mine = owner_of(key_hash, n_shards) == me

    B = key_hash.shape[0]
    peek = BatchRequest(
        key_hash=key_hash,
        hits=jnp.zeros(B, jnp.int32),
        limit=limit,
        duration=duration,
        algo=algo,
        gnp=jnp.zeros(B, bool),
        valid=valid & mine,
    )
    store2, resp, _ = decide_presorted(store, peek, now)

    mask = mine & valid

    def combine(x):
        return jax.lax.psum(jnp.where(mask, x, 0), "shard")

    status = combine(resp.status)
    r_limit = combine(resp.limit)
    remaining = combine(resp.remaining)
    reset = combine(resp.reset_time)

    # install replicas everywhere except the owner shard
    store3 = upsert_globals(
        store2,
        key_hash,
        r_limit,
        remaining,
        reset,
        status == 1,
        valid & ~mine,
    )
    return jax.tree.map(lambda x: x[None], store3), BatchResponse(
        status=status, limit=r_limit, remaining=remaining, reset_time=reset
    )


def _shard_upsert(
    store: Store,
    key_hash: jax.Array,
    limit: jax.Array,
    remaining: jax.Array,
    reset_time: jax.Array,
    is_over: jax.Array,
    valid: jax.Array,
    n_shards: int,
):
    """Install GLOBAL replica statuses on each key's owning shard."""
    me = jax.lax.axis_index("shard")
    store = jax.tree.map(lambda x: x[0], store)
    mine = owner_of(key_hash, n_shards) == me
    out = upsert_globals(
        store, key_hash, limit, remaining, reset_time, is_over, valid & mine
    )
    return jax.tree.map(lambda x: x[None], out)


class MeshEngine:
    """Drop-in sibling of core.engine.TpuEngine, sharded over a mesh.

    decide_arrays() has the same contract; GLOBAL requests served on
    non-owner shards never leave the mesh — replicas answer locally after
    each sync_globals() collective.
    """

    def __init__(
        self,
        config: StoreConfig = StoreConfig(),
        devices: Optional[Sequence[jax.Device]] = None,
        buckets: Sequence[int] = (64, 256, 1024, 4096),
    ):
        if devices is None:
            devices = jax.devices()
        self.mesh = Mesh(np.asarray(devices), ("shard",))
        self.n = len(devices)
        self.config = config
        self.buckets = sorted(buckets)
        self.clock = EpochClock()

        sharding = NamedSharding(self.mesh, P("shard"))
        self.store_sharding = sharding
        self.store = self._fresh_store()

        decide_fn = functools.partial(_packed_shard_decide, n_shards=self.n)
        self._step = jax.jit(
            jax.shard_map(
                decide_fn,
                mesh=self.mesh,
                in_specs=(P("shard"), P(), P()),
                out_specs=(P("shard"), P()),
            ),
            donate_argnums=(0,),
        )
        sync_fn = functools.partial(_shard_sync_globals, n_shards=self.n)
        self._sync = jax.jit(
            jax.shard_map(
                sync_fn,
                mesh=self.mesh,
                in_specs=(P("shard"), P(), P(), P(), P(), P(), P()),
                out_specs=(P("shard"), P()),
            ),
            donate_argnums=(0,),
        )
        upsert_fn = functools.partial(_shard_upsert, n_shards=self.n)
        self._upsert = jax.jit(
            jax.shard_map(
                upsert_fn,
                mesh=self.mesh,
                in_specs=(P("shard"),) + (P(),) * 6,
                out_specs=P("shard"),
            ),
            donate_argnums=(0,),
        )

    def _fresh_store(self) -> Store:
        base = new_store(self.config)

        def rep(x):
            stacked = jnp.broadcast_to(x[None], (self.n,) + x.shape)
            return jax.device_put(stacked, self.store_sharding)

        return jax.tree.map(rep, base)

    def reset(self) -> None:
        self.store = self._fresh_store()

    def _engine_now(self, now: int) -> np.int32:
        e, delta, reset_required = self.clock.advance(now)
        if reset_required:
            self.reset()
        elif delta is not None:
            # rebase is elementwise, so it runs shard-local with the
            # store's sharding preserved — no collective needed
            self.store = rebase_jit(self.store, np.int32(delta))
        return e

    def decide_arrays(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        algo: np.ndarray,
        gnp: np.ndarray,
        now: int,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        n = key_hash.shape[0]
        e_now = self._engine_now(now)
        req, order = pad_request_sorted(
            self.buckets,
            self.config.slots,
            key_hash,
            hits,
            limit,
            duration,
            algo,
            gnp,
        )
        self.store, packed = self._step(self.store, req, e_now)
        packed = np.asarray(jax.device_get(packed))
        s_status, s_lim, s_rem, s_reset, _h, _m = unpack_outputs(
            packed, req.key_hash.shape[0]
        )
        status, rlimit, remaining, reset = unpermute_responses(
            order, (s_status, s_lim, s_rem, s_reset)
        )
        reset = self.clock.from_engine(reset)
        return status[:n], rlimit[:n], remaining[:n], reset[:n]

    def update_globals(
        self,
        key_hash: np.ndarray,
        limit: np.ndarray,
        remaining: np.ndarray,
        reset_time: np.ndarray,
        is_over: np.ndarray,
        now: Optional[int] = None,
    ) -> None:
        """Install broadcast GLOBAL statuses on their owning shards — the
        receive side of UpdatePeerGlobals (reference gubernator.go:199-207)
        for a mesh-backed host. reset_time is int64 unix-ms."""
        n = key_hash.shape[0]
        if n == 0:
            return
        from gubernator_tpu.api.types import millisecond_now

        self._engine_now(millisecond_now() if now is None else now)
        kh, lim, rem, rst, over, valid = pad_to_bucket(
            self.buckets,
            n,
            (key_hash, np.uint64),
            (_sat_i32(limit), np.int32),
            (_sat_i32(remaining), np.int32),
            (self.clock.to_engine(reset_time), np.int32),
            (is_over, bool),
        )
        self.store = self._upsert(
            self.store, kh, lim, rem, rst, over, valid
        )

    def sync_globals(
        self,
        key_hash: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        now: int,
        algo: Optional[np.ndarray] = None,
    ) -> None:
        """One collective gossip step for the given GLOBAL keys. `algo`
        must carry each key's algorithm (defaults to token bucket)."""
        n = key_hash.shape[0]
        if n == 0:
            return
        if algo is None:
            algo = np.zeros(n, np.int32)
        e_now = self._engine_now(now)
        req, _order = pad_request_sorted(
            self.buckets,
            self.config.slots,
            key_hash,
            np.zeros(n, np.int64),
            limit,
            duration,
            algo,
            np.zeros(n, bool),
        )
        self.store, _resp = self._sync(
            self.store,
            req.key_hash,
            req.limit,
            req.duration,
            req.algo,
            req.valid,
            e_now,
        )
