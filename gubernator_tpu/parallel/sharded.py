"""Mesh-sharded rate limiting: the consistent-hash ring mapped onto a
`jax.sharding.Mesh`.

The reference distributes keys across peers with a consistent-hash ring and
forwards requests over gRPC (reference hash.go:80-96, peers.go:111-127).
Inside one host, this framework distributes keys across TPU chips instead:

- The slot store gains a leading `shard` axis, laid out over the mesh's
  "shard" axis — every chip owns `1/n` of the key space, the moral
  equivalent of one ring peer per chip, with ownership decided by a cheap
  hash (`owner = mix64(key_hash) mod n`) instead of a sorted ring search:
  with homogeneous chips there is no reason to pay the ring's lookup cost
  or its imbalance (the reference places one point per peer, hash.go:62-67).
- The request BATCH is sharded too: the host presorts each batch by
  (owner_shard, bucket, fingerprint) — one native radix pass — slices the
  contiguous per-shard runs into per-chip sub-batches, and lays the
  [n_shards, B_sub] request arrays out over the mesh's batch axis. Each
  chip evaluates ONLY the ~B/n rows it owns, so aggregate decisions/s
  scales with chip count — the same economy as the reference forwarding
  each key only to its owner peer (reference peers.go:111-207). The decide
  path needs NO collective at all: responses come back per-shard and the
  host unpermutes them into request order (it already owns the
  permutation).
- GLOBAL mode's owner->replica broadcast (reference global.go:158-232)
  becomes `sync_globals`: owners peek authoritative status, one psum
  replicates it mesh-wide, and every non-owner installs replica entries —
  the async gossip loop collapsed into a single collective step.

Multi-host scaling composes: each host runs one mesh-sharded engine over
its chips, and hosts peer with each other over gRPC exactly like reference
nodes (serve/peers.py), so ICI carries intra-host traffic and DCN only
carries the host-level ring's.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.core.engine import (
    EngineStats,
    EpochClock,
    _sat_i32,
    extend_ladder,
    pad_request_sorted,
    pad_to_bucket,
)
from gubernator_tpu.core.kernels import (
    BatchGroups,
    BatchRequest,
    BatchResponse,
    decide_presorted,
    pack_outputs,
    rebase_jit,
    upsert_globals,
)
from gubernator_tpu.core.store import Store, StoreConfig, mix64, new_store

_SHARD_SALT = np.uint64(0xA24BAED4963EE407)

_log = logging.getLogger("gubernator.sharded")
_warned_ladder_overflow = False


def _warn_ladder_overflow(top: int, n: int) -> None:
    """One-time attribution for the multi-second stall a first oversized
    batch causes: extending the ladder compiles a fresh XLA program
    mid-call (library-only path — the serving batcher caps batches at
    the ladder top, so it never gets here)."""
    global _warned_ladder_overflow
    if not _warned_ladder_overflow:
        _warned_ladder_overflow = True
        _log.warning(
            "batch of %d exceeds the configured ladder top %d: extending "
            "the rung ladder triggers a fresh XLA compilation (tens of "
            "seconds on TPU) for this and each new overflow size — size "
            "the `buckets` ladder to your peak batch to avoid the stall",
            n,
            top,
        )


def owner_of(key_hash: jax.Array, n_shards: int) -> jax.Array:
    """Owning shard index for each key hash (device-side)."""
    return (mix64(key_hash ^ _SHARD_SALT) % jnp.uint64(n_shards)).astype(
        jnp.int32
    )


def owner_of_np(key_hash: np.ndarray, n_shards: int) -> np.ndarray:
    """Host-side twin of owner_of (numpy)."""
    from gubernator_tpu.core import hashing

    return (hashing.mix64(key_hash ^ _SHARD_SALT) % np.uint64(n_shards)).astype(
        np.int32
    )


def _axis_me(axes: tuple) -> jax.Array:
    """Flattened shard index under a 1-D ("shard",) or 2-D
    ("host", "chip") mesh — the 2-D form is host-major, matching the
    process-major device order the mesh is built with, so owner_of's
    `mod n_shards` placement is identical under both layouts."""
    me = jax.lax.axis_index(axes[0])
    for ax in axes[1:]:
        me = me * jax.lax.psum(1, ax) + jax.lax.axis_index(ax)
    return me


def _hier_psum(x: jax.Array, axes: tuple) -> jax.Array:
    """Hierarchical all-reduce (BASELINE config 5): innermost axis
    first. On a multi-slice mesh with axes ("host", "chip") this stages
    the reduction — chips within a host combine over ICI, then ONE
    pre-reduced vector per host crosses DCN — instead of a flat psum
    whose ring spans DCN on every leg. Mathematically identical to
    `psum(x, axes)`; the staging is the point."""
    for ax in reversed(axes):
        x = jax.lax.psum(x, ax)
    return x


def _local_decide(store: Store, req: BatchRequest, groups, now):
    """Per-device body under shard_map: store AND batch are this device's
    shards. The host routed every request row to its owner chip
    (pad_request_sharded), so each chip runs the plain single-device
    kernel on its own sub-batch — no collective on the decide path, the
    mesh analogue of the reference forwarding only owned keys to a peer
    (reference peers.go:111-207) — with its own per-shard duplicate-key
    group structure (store I/O at unique-key granularity, see
    kernels.BatchGroups). Responses + stats pack into one int32 row per
    shard (one host transfer total)."""
    store = jax.tree.map(lambda x: x[0], store)  # [1, r, s] -> [r, s]
    req = jax.tree.map(lambda x: x[0], req)  # [1, B_sub] -> [B_sub]
    groups = jax.tree.map(lambda x: x[0], groups)
    new_store_shard, resp, stats = decide_presorted(store, req, now, groups)
    packed = pack_outputs(resp, stats)
    return jax.tree.map(lambda x: x[None], new_store_shard), packed[None]


def _local_decide_gathered(store: Store, req: BatchRequest, groups, now,
                           axes=("shard",)):
    """_local_decide + one all_gather of the packed response rows: when
    the mesh spans processes the serving host cannot fetch follower
    shards directly, so the responses ride the compiled collective path
    (ICI within a host, DCN between hosts) and come out replicated. On
    the 2-D mesh the gather names both axes host-major, so the gathered
    row order equals the flattened shard order."""
    store, packed = _local_decide(store, req, groups, now)
    out = packed[0]
    if len(axes) == 1:
        return store, jax.lax.all_gather(out, axes[0])
    # gather chips within a host over ICI first, then hosts over DCN,
    # then flatten [host, chip, ...] -> [shard, ...]
    out = jax.lax.all_gather(out, axes[-1])
    out = jax.lax.all_gather(out, axes[0])
    return store, out.reshape((-1,) + out.shape[2:])


def _np_presort_sharded(
    key_hash: np.ndarray, store_buckets: int, n_shards: int
):
    """Numpy fallback for the native sharded presort: stable argsort by
    (owner_shard, bucket, fingerprint) + per-shard counts."""
    from gubernator_tpu.core.store import group_sort_key_np

    owner = owner_of_np(key_hash, n_shards)
    # owner bits sit just above the (bucket << 32 | fp) group key, like
    # the native sort key (guberhash.cc guber_presort_sharded)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    comp = (
        owner.astype(np.uint64) << np.uint64(32 + bucket_bits)
    ) | group_sort_key_np(key_hash, store_buckets)
    order = np.argsort(comp, kind="stable").astype(np.int32)
    counts = np.bincount(owner, minlength=n_shards).astype(np.int64)
    return order, counts


def _np_presort_sharded_grouped(
    key_hash: np.ndarray, store_buckets: int, n_shards: int
):
    """Numpy fallback for the native sharded+grouped presort."""
    from gubernator_tpu.core.store import group_sort_key_np

    owner = owner_of_np(key_hash, n_shards)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    comp = (
        owner.astype(np.uint64) << np.uint64(32 + bucket_bits)
    ) | group_sort_key_np(key_hash, store_buckets)
    order = np.argsort(comp, kind="stable").astype(np.int32)
    counts = np.bincount(owner, minlength=n_shards).astype(np.int64)
    s = comp[order]
    n = s.shape[0]
    is_leader = np.empty(n, bool)
    if n:
        is_leader[0] = True
        np.not_equal(s[1:], s[:-1], out=is_leader[1:])
    group_id = np.cumsum(is_leader).astype(np.int32) - 1
    leader_pos = np.flatnonzero(is_leader).astype(np.int32)
    g_owner = (s[leader_pos] >> np.uint64(32 + bucket_bits)).astype(np.int64)
    group_counts = np.bincount(g_owner, minlength=n_shards).astype(np.int64)
    return order, counts, group_id, leader_pos, group_counts


try:  # native radix presort with shard partitioning (guberhash.cc)
    from gubernator_tpu.native import hashlib_native as _hn

    if not _hn._HAS_PRESORT_SHARDED:
        raise AttributeError("guber_presort_sharded missing")
    _presort_sharded = _hn.presort_sharded
    _presort_sharded_grouped = (
        _hn.presort_sharded_grouped
        if _hn._HAS_PRESORT_SHARDED_GROUPED
        else _np_presort_sharded_grouped
    )
    _prep_native = _hn.prep_sharded if _hn._HAS_PREP else None
except (ImportError, AttributeError, OSError):  # pragma: no cover
    _hn = None
    _presort_sharded = _np_presort_sharded
    _presort_sharded_grouped = _np_presort_sharded_grouped
    _prep_native = None


def sub_batch_ladder(buckets: Sequence[int]) -> tuple:
    """Padding rungs for per-shard sub-batches: the host ladder densified
    with 1.5x midpoints (64, 96, 128, 192, ... between min and max rung).
    Shard counts concentrate at ~B/n_shards + multinomial jitter, so the
    coarse 4x host ladder would pad a shard's rows up to 4x (measured:
    total mesh work grew instead of staying flat); midpoints cap padding
    waste at 1.5x for one extra compile per octave at warmup."""
    lo, hi = min(buckets), max(buckets)
    rungs = set(buckets)
    p = lo
    while p < hi:
        rungs.add(p)
        rungs.add(min(p * 3 // 2, hi))
        p *= 2
    rungs.add(hi)
    return tuple(sorted(rungs))


def pad_request_sharded(
    buckets: Sequence[int],
    store_buckets: int,
    n_shards: int,
    key_hash: np.ndarray,
    hits: np.ndarray,
    limit: np.ndarray,
    duration: np.ndarray,
    algo: np.ndarray,
    gnp: np.ndarray,
    with_groups: bool = False,
    group_rung: Optional[int] = None,
):
    """Partition a batch into per-shard sub-batches: the mesh sibling of
    engine.pad_request_sorted. One (owner, bucket, fp) radix sort makes
    each shard's rows a contiguous presorted run; every field becomes a
    [n_shards, B_sub] array (B_sub = bucket fitting the LARGEST shard's
    count) whose row s is shard s's sub-batch padded by repeating its
    last row with valid=False (preserving the monotonic bucket stream).

    Returns (req, order, take_idx) — plus `groups` when with_groups:
    - req: BatchRequest of [n_shards, B_sub] arrays, batch-axis shardable
      P("shard") — row s belongs on chip s.
    - order[k]: caller index of the k-th row in global sorted order.
    - take_idx[k]: flattened [n_shards*B_sub] device position of that row.
    - groups: BatchGroups of [n_shards, ...] arrays (per-shard
      duplicate-key structure, indices LOCAL to each shard's sub-batch)
      so each chip's store I/O runs at unique-key granularity.
    `group_rung` overrides the G rung choice (must hold every shard's
    group count) — callers staging SEVERAL batches into one stacked
    array pass a shared rung so the BatchGroups shapes line up.
    Unpermute responses with `out[order] = resp_flat[take_idx]`.
    """
    from gubernator_tpu.core.engine import (
        _sat_duration as sat_dur,
        _sat_i32 as sat_i32,
        choose_bucket,
        group_rungs,
    )

    n = key_hash.shape[0]
    if n == 0:
        # empty batch: one all-invalid row per shard (smallest rung)
        B0 = buckets[0] if hasattr(buckets, "__getitem__") else min(buckets)
        req = BatchRequest(
            key_hash=np.zeros((n_shards, B0), np.uint64),
            hits=np.zeros((n_shards, B0), np.int32),
            limit=np.zeros((n_shards, B0), np.int32),
            duration=np.zeros((n_shards, B0), np.int32),
            algo=np.zeros((n_shards, B0), np.int32),
            gnp=np.zeros((n_shards, B0), bool),
            valid=np.zeros((n_shards, B0), bool),
        )
        empty = (req, np.empty(0, np.int32), np.empty(0, np.int64))
        if with_groups:
            G0 = group_rungs(B0)[0]
            return (*empty, BatchGroups(
                key_hash=np.zeros((n_shards, G0), np.uint64),
                leader_pos=np.full((n_shards, G0), B0, np.int32),
                end_pos=np.full((n_shards, G0), B0 - 1, np.int32),
                valid=np.zeros((n_shards, G0), bool),
                group_id=np.zeros((n_shards, B0), np.int32),
            ))
        return empty
    if _prep_native is not None and with_groups:
        # one-call native prep: presort + groups + marshal fused (3.6x
        # the numpy path on one core, thread-parallel on real hosts —
        # guberhash.cc guber_prep_sharded). Bit-identical to the numpy
        # path below (pinned by tests/test_prep_native.py). Gated to
        # with_groups (the decide path): only it owns the two-in-flight
        # contract the flip-flopped prep buffers rely on.
        from gubernator_tpu.core.engine import dense_ladder_extension
        from gubernator_tpu.core.store import (
            COUNTER_MAX,
            MAX_DURATION_MS,
            TIME_FLOOR,
        )

        rungs = np.asarray(dense_ladder_extension(buckets, n), np.int64)
        order, counts, take_idx, fields, groups_d, B_sub, _G = (
            _prep_native(
                key_hash, hits, limit, duration, algo, gnp,
                store_buckets, n_shards, rungs,
                int(group_rung) if group_rung else 0,
                -COUNTER_MAX, COUNTER_MAX, TIME_FLOOR, MAX_DURATION_MS,
            )
        )
        if int(counts.max()) > max(buckets):
            _warn_ladder_overflow(max(buckets), int(counts.max()))
        req = BatchRequest(**fields)
        return req, order, take_idx, BatchGroups(
            key_hash=groups_d["key_hash"],
            leader_pos=groups_d["leader_pos"],
            end_pos=groups_d["end_pos"],
            valid=groups_d["valid"],
            group_id=groups_d["group_id"],
        )

    if with_groups:
        order, counts, gid_g, lp_g, gcounts = _presort_sharded_grouped(
            key_hash, store_buckets, n_shards
        )
    else:
        order, counts = _presort_sharded(key_hash, store_buckets, n_shards)
    counts32 = counts.astype(np.int64)
    starts = np.zeros(n_shards + 1, np.int64)
    np.cumsum(counts32, out=starts[1:])
    maxc = max(int(counts32.max()), 1)
    # a shard can draw more rows than the ladder's top rung when the
    # caller's batch exceeds max(buckets) — unreachable through the
    # serving tier (the batcher caps batches at the ladder top) but
    # supported for library callers: extend, don't raise
    if maxc > max(buckets):
        _warn_ladder_overflow(max(buckets), maxc)
    B_sub = choose_bucket(extend_ladder(buckets, maxc), maxc)

    # src[s, j]: index into the sorted arrays for padded cell (s, j) —
    # clamped to the shard's last real row (repeat-pad); empty shards
    # clamp to a neighbouring row, masked invalid below.
    j = np.arange(B_sub, dtype=np.int64)[None, :]
    src = starts[:-1, None] + np.minimum(
        j, np.maximum(counts32[:, None] - 1, 0)
    )
    np.clip(src, 0, max(n - 1, 0), out=src)
    valid = j < counts32[:, None]
    idx = order[src]  # compose once: caller index per padded cell

    def shard_field(x, dtype, sat=None):
        x = sat(x) if sat is not None else np.asarray(x, dtype)
        return x[idx]  # [n_shards, B_sub]

    req = BatchRequest(
        key_hash=shard_field(key_hash, np.uint64),
        hits=shard_field(hits, np.int32, sat_i32),
        limit=shard_field(limit, np.int32, sat_i32),
        duration=shard_field(duration, np.int32, sat_dur),
        algo=shard_field(algo, np.int32),
        gnp=shard_field(gnp, bool),
        valid=valid,
    )
    # global sorted position k lives at device cell (shard_of_k, k-start)
    shard_of_k = np.repeat(np.arange(n_shards, dtype=np.int64), counts32)
    take_idx = shard_of_k * B_sub + (np.arange(n, dtype=np.int64) - starts[shard_of_k])
    if not with_groups:
        return req, order, take_idx

    groups = stack_shard_groups(
        req.key_hash, gid_g, lp_g, gcounts, counts32, starts, n_shards,
        B_sub, group_rung,
    )
    return req, order, take_idx, groups


def stack_shard_groups(
    req_kh: np.ndarray,
    gid_g: np.ndarray,
    lp_g: np.ndarray,
    gcounts: np.ndarray,
    counts32: np.ndarray,
    starts: np.ndarray,
    n_shards: int,
    B_sub: int,
    group_rung: Optional[int] = None,
) -> BatchGroups:
    """Per-shard group structure with LOCAL indices (each shard's kernel
    sees only its own [B_sub] sub-batch); padding conventions come from
    the single source of truth, engine.build_groups, called per shard.
    Global group ids are contiguous in shard order (shard boundaries
    break groups), so shard s's groups are exactly
    gstarts[s]..gstarts[s+1] and its first group id IS gstarts[s].
    Shared by the flush-time presort path (pad_request_sharded) and the
    merge-combine path (MeshEngine.decide_submit_presorted) so the two
    can never drift."""
    from gubernator_tpu.core.engine import (
        build_groups,
        choose_bucket,
        group_rungs,
    )

    gstarts = np.zeros(n_shards + 1, np.int64)
    np.cumsum(gcounts, out=gstarts[1:])
    if group_rung is not None:
        if group_rung < int(gcounts.max()):
            raise ValueError(
                f"group_rung {group_rung} < max shard group count "
                f"{int(gcounts.max())}"
            )
        G_sub = group_rung
    else:
        G_sub = choose_bucket(
            group_rungs(B_sub), max(int(gcounts.max()), 1)
        )
    per_shard = []
    for s in range(n_shards):
        gc = int(gcounts[s])
        cs = int(counts32[s])
        per_shard.append(
            build_groups(
                req_kh[s],
                gid_g[starts[s] : starts[s] + cs] - int(gstarts[s]),
                lp_g[gstarts[s] : gstarts[s] + gc] - int(starts[s]),
                gc,
                cs,
                B_sub,
                G_sub,
            )
        )
    return BatchGroups(
        *(np.stack(leaves) for leaves in zip(*per_shard))
    )


def sharded_sort_keys_np(
    key_hash: np.ndarray, store_buckets: int, n_shards: int
) -> np.ndarray:
    """Composite host sort key of the sharded presort order —
    (owner_shard | bucket | fingerprint), the same packing
    _np_presort_sharded and guber_presort_sharded order by."""
    from gubernator_tpu.core.store import group_sort_key_np

    kh = np.asarray(key_hash, np.uint64)
    owner = owner_of_np(kh, n_shards)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    return (
        owner.astype(np.uint64) << np.uint64(32 + bucket_bits)
    ) | group_sort_key_np(kh, store_buckets)


def prep_run_sharded(
    fields: dict, store_buckets: int, n_shards: int
) -> dict:
    """Arrival-time per-group prep for the mesh engine: presort one
    group by (owner, bucket, fingerprint), clip fields to device
    dtypes, and count rows per shard — a sorted run the flush-time
    merge combine (serve/prep.py) stitches into one sharded batch.
    One fused native call when built (guber_prep_run); the numpy
    fallback below is bit-identical."""
    from gubernator_tpu.core.engine import _gather_clip_sorted

    if _hn is not None and getattr(_hn, "_HAS_PREP_RUN", False):
        from gubernator_tpu.core.store import (
            COUNTER_MAX,
            MAX_DURATION_MS,
            TIME_FLOOR,
        )

        return _hn.prep_run(
            fields, store_buckets, n_shards, -COUNTER_MAX, COUNTER_MAX,
            TIME_FLOOR, MAX_DURATION_MS,
        )
    kh = np.ascontiguousarray(fields["key_hash"], np.uint64)
    n = kh.shape[0]
    order, counts = _presort_sharded(kh, store_buckets, n_shards)
    sorted_fields = _gather_clip_sorted(fields, order, n)
    return dict(
        n=n,
        # elementwise in the key hash, so computed on the sorted hashes
        skey=sharded_sort_keys_np(
            sorted_fields["key_hash"], store_buckets, n_shards
        ),
        order=order,
        counts=np.asarray(counts, np.int64),
        fields=sorted_fields,
    )


def build_presorted_sharded(
    sub_buckets: Sequence[int],
    store_buckets: int,
    n_shards: int,
    fields: dict,
    skey: np.ndarray,
    counts: np.ndarray,
):
    """(req, take_idx, groups, B_sub) for an already-sorted sharded
    batch — the merge-combine twin of pad_request_sharded
    (with_groups=True), minus the argsort it no longer needs.
    Byte-identical outputs are pinned by tests/test_prep_pipeline.py.
    """
    from gubernator_tpu.core.engine import choose_bucket

    n = skey.shape[0]
    counts32 = np.asarray(counts, np.int64)
    starts = np.zeros(n_shards + 1, np.int64)
    np.cumsum(counts32, out=starts[1:])
    maxc = max(int(counts32.max()), 1)
    if maxc > max(sub_buckets):
        _warn_ladder_overflow(max(sub_buckets), maxc)
    B_sub = choose_bucket(extend_ladder(sub_buckets, maxc), maxc)
    # padded cell (s, j) reads merged sorted row starts[s]+min(j,
    # count-1) — the same repeat-pad/clamp pad_request_sharded
    # applies, but gathering from the sorted stream directly
    # (sorted_x[src] == x[order][src] == x[idx])
    j = np.arange(B_sub, dtype=np.int64)[None, :]
    src = starts[:-1, None] + np.minimum(
        j, np.maximum(counts32[:, None] - 1, 0)
    )
    np.clip(src, 0, max(n - 1, 0), out=src)
    valid = j < counts32[:, None]
    req = BatchRequest(
        key_hash=fields["key_hash"][src],
        hits=fields["hits"][src],
        limit=fields["limit"][src],
        duration=fields["duration"][src],
        algo=fields["algo"][src],
        gnp=fields["gnp"][src],
        valid=valid,
    )
    # group structure straight off the sorted key stream (skey ties ==
    # comp ties of _np_presort_sharded_grouped): one diff pass replaces
    # the grouped argsort
    is_leader = np.empty(n, bool)
    is_leader[0] = True
    np.not_equal(skey[1:], skey[:-1], out=is_leader[1:])
    gid_g = np.cumsum(is_leader).astype(np.int32) - 1
    lp_g = np.flatnonzero(is_leader).astype(np.int32)
    bucket_bits = max(int(store_buckets).bit_length() - 1, 1)
    g_owner = (skey[lp_g] >> np.uint64(32 + bucket_bits)).astype(
        np.int64
    )
    gcounts = np.bincount(g_owner, minlength=n_shards).astype(np.int64)
    groups = stack_shard_groups(
        req.key_hash, gid_g, lp_g, gcounts, counts32, starts, n_shards,
        B_sub,
    )
    shard_of_k = np.repeat(np.arange(n_shards, dtype=np.int64), counts32)
    take_idx = shard_of_k * B_sub + (
        np.arange(n, dtype=np.int64) - starts[shard_of_k]
    )
    return req, take_idx, groups, B_sub


def _shard_sync_globals(
    store: Store,
    key_hash: jax.Array,  # uint64[B] global keys to broadcast
    limit: jax.Array,  # int32[B] request limit (for owner-side peek of misses)
    duration: jax.Array,
    algo: jax.Array,  # int32[B]: must match the stored algorithm, or the
    # peek would take the mismatch-recreate path and wipe owner state
    valid: jax.Array,
    now,
    n_shards: int,
    axes: tuple = ("shard",),
):
    """Owner peeks authoritative status; psum replicates; others upsert.
    On a 2-D ("host", "chip") mesh the replication is the hierarchical
    ICI-then-DCN reduction of BASELINE config 5 (see _hier_psum)."""
    me = _axis_me(axes)
    store = jax.tree.map(lambda x: x[0], store)
    mine = owner_of(key_hash, n_shards) == me

    B = key_hash.shape[0]
    peek = BatchRequest(
        key_hash=key_hash,
        hits=jnp.zeros(B, jnp.int32),
        limit=limit,
        duration=duration,
        algo=algo,
        gnp=jnp.zeros(B, bool),
        valid=valid & mine,
    )
    store2, resp, _ = decide_presorted(store, peek, now)

    mask = mine & valid

    def combine(x):
        return _hier_psum(jnp.where(mask, x, 0), axes)

    status = combine(resp.status)
    r_limit = combine(resp.limit)
    remaining = combine(resp.remaining)
    reset = combine(resp.reset_time)

    # install replicas everywhere except the owner shard
    store3 = upsert_globals(
        store2,
        key_hash,
        r_limit,
        remaining,
        reset,
        status == 1,
        valid & ~mine,
    )
    return jax.tree.map(lambda x: x[None], store3), BatchResponse(
        status=status, limit=r_limit, remaining=remaining, reset_time=reset
    )


def _shard_upsert(
    store: Store,
    key_hash: jax.Array,
    limit: jax.Array,
    remaining: jax.Array,
    reset_time: jax.Array,
    is_over: jax.Array,
    valid: jax.Array,
    n_shards: int,
    axes: tuple = ("shard",),
):
    """Install GLOBAL replica statuses on each key's owning shard."""
    me = _axis_me(axes)
    store = jax.tree.map(lambda x: x[0], store)
    mine = owner_of(key_hash, n_shards) == me
    out = upsert_globals(
        store, key_hash, limit, remaining, reset_time, is_over, valid & mine
    )
    return jax.tree.map(lambda x: x[None], out)


class MeshEngine:
    """Drop-in sibling of core.engine.TpuEngine, sharded over a mesh.

    decide_arrays() has the same contract; GLOBAL requests served on
    non-owner shards never leave the mesh — replicas answer locally after
    each sync_globals() collective.
    """

    def __init__(
        self,
        config: StoreConfig = StoreConfig(),
        devices: Optional[Sequence[jax.Device]] = None,
        buckets: Sequence[int] = (64, 256, 1024, 4096),
        mesh_shape: Optional[Tuple[int, int]] = None,
    ):
        if devices is None:
            devices = jax.devices()
        self.n = len(devices)
        # a single-process mesh host can fetch every response shard
        # directly; a multi-process mesh must all_gather them (the serving
        # leader cannot address follower-process shards)
        procs = {d.process_index for d in devices}
        span = len(procs) > 1
        if mesh_shape is None and span and self.n % len(procs) == 0:
            # The auto 2-D shape assumes the device list is process-major
            # with EQUAL per-process counts. Validate that before
            # committing: with unequal contributions (n still divisible
            # by len(procs)) the reshape would group chips of different
            # hosts under one 'host' row — numerically correct, but the
            # "ICI within a row, DCN across rows" staging would silently
            # cross DCN inside a row. Fall back to the flat ('shard',)
            # mesh when any row mixes processes (ADVICE r5 #1).
            grid = np.asarray(devices).reshape(
                len(procs), self.n // len(procs)
            )
            if all(
                len({d.process_index for d in row}) == 1 for row in grid
            ):
                mesh_shape = (len(procs), self.n // len(procs))
        if mesh_shape is not None:
            # 2-D ("host", "chip") mesh: the GLOBAL-sync reduction runs
            # hierarchically — chips combine within a host over ICI,
            # then hosts combine over DCN (BASELINE config 5's
            # "hierarchical psum"). Device order is process-major
            # (host-major), so the reshape groups each host's chips and
            # the flattened (host, chip) index equals the 1-D shard
            # index — placement is layout-independent.
            n_hosts, per_host = mesh_shape
            if n_hosts * per_host != self.n:
                raise ValueError(
                    f"mesh_shape {mesh_shape} != {self.n} devices"
                )
            dev_grid = np.asarray(devices).reshape(n_hosts, per_host)
            self.mesh = Mesh(dev_grid, ("host", "chip"))
            self.axes: tuple = ("host", "chip")
        else:
            self.mesh = Mesh(np.asarray(devices), ("shard",))
            self.axes = ("shard",)
        self.config = config
        self.buckets = sorted(buckets)
        self.sub_buckets = sub_batch_ladder(self.buckets)
        self.clock = EpochClock()
        self.stats = EngineStats()
        # store-wipe epoch for the over-limit shed cache (see
        # core/engine.py reset_generation)
        self.reset_generation = 0

        Ps = P(self.axes)  # leading dim over all mesh axes, host-major
        sharding = NamedSharding(self.mesh, Ps)
        self.store_sharding = sharding
        self.store = self._fresh_store()

        step_fn = (
            functools.partial(_local_decide_gathered, axes=self.axes)
            if span
            else _local_decide
        )
        self._step = jax.jit(
            jax.shard_map(
                step_fn,
                mesh=self.mesh,
                in_specs=(Ps, Ps, Ps, P()),
                out_specs=(Ps, P() if span else Ps),
                # the all_gather output IS replicated, but the static
                # varying-axis check can't prove it — disable just there
                check_vma=not span,
            ),
            donate_argnums=(0,),
        )
        sync_fn = functools.partial(
            _shard_sync_globals, n_shards=self.n, axes=self.axes
        )
        self._sync = jax.jit(
            jax.shard_map(
                sync_fn,
                mesh=self.mesh,
                in_specs=(Ps, P(), P(), P(), P(), P(), P()),
                out_specs=(Ps, P()),
            ),
            donate_argnums=(0,),
        )
        upsert_fn = functools.partial(
            _shard_upsert, n_shards=self.n, axes=self.axes
        )
        self._upsert = jax.jit(
            jax.shard_map(
                upsert_fn,
                mesh=self.mesh,
                in_specs=(Ps,) + (P(),) * 6,
                out_specs=Ps,
            ),
            donate_argnums=(0,),
        )

    def _fresh_store(self) -> Store:
        base = new_store(self.config)

        def rep(x):
            stacked = jnp.broadcast_to(x[None], (self.n,) + x.shape)
            return jax.device_put(stacked, self.store_sharding)

        return jax.tree.map(rep, base)

    def reset(self) -> None:
        self.store = self._fresh_store()
        self.reset_generation += 1

    def _engine_now(self, now: int) -> np.int32:
        e, delta, reset_required = self.clock.advance(now)
        if reset_required:
            self.reset()
        elif delta is not None:
            # rebase is elementwise, so it runs shard-local with the
            # store's sharding preserved — no collective needed
            self.store = rebase_jit(self.store, np.int32(delta))
        return e

    def decide_submit(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        algo: np.ndarray,
        gnp: np.ndarray,
        now: int,
    ):
        """Presort/shard + dispatch one batch WITHOUT waiting — the mesh
        sibling of TpuEngine.decide_submit. The store update threads
        through the jitted step immediately, so the caller may prep the
        next batch while every chip computes this one (the serving
        batcher's pipelining; MeshBackend exposes this split). Returns
        an opaque handle for decide_wait."""
        n = key_hash.shape[0]
        e_now = self._engine_now(now)
        req, order, take_idx, groups = pad_request_sharded(
            self.sub_buckets,
            self.config.slots,
            self.n,
            key_hash,
            hits,
            limit,
            duration,
            algo,
            gnp,
            with_groups=True,
        )
        B_sub = req.key_hash.shape[1]
        self.store, packed = self._step(self.store, req, groups, e_now)
        if _prep_native is not None:
            # the native prep returns order/take_idx as VIEWS into its
            # reusable buffer ring. This handle outlives any fixed ring
            # depth under the batcher's out-of-order fetch pipeline (a
            # stalled fetch can be outrun by later submits without
            # bound), so the handle keeps copies. The device-field views
            # need no copy: dispatch commits host inputs before _step
            # returns (verified by mutate-after-dispatch on the tunnel
            # backend; jax never exposes numpy inputs to later writes).
            order = order.copy()
            take_idx = take_idx.copy()
        # epoch captured at submit: a later submit may rebase before this
        # batch's wait (same contract as TpuEngine.decide_submit)
        return (packed, order, take_idx, n, B_sub, self.clock.epoch)

    def prep_run(self, fields: dict) -> dict:
        """Arrival-time per-group prep (serve/batcher.py): see
        prep_run_sharded."""
        return prep_run_sharded(fields, self.config.slots, self.n)

    def merge_prepped(self, runs):
        """Merge pre-sorted per-group runs into one dispatch-ready
        sharded batch (the submit thread's `merge` stage): a flat
        fused native merge when available (serve/prep.py dispatches to
        guber_merge_runs), then the per-shard [n_shards, B_sub] layout
        + group structure via build_presorted_sharded. Output feeds
        decide_submit_merged."""
        from gubernator_tpu.serve.prep import merge_runs

        m = merge_runs(runs)
        req, take_idx, groups, B_sub = build_presorted_sharded(
            self.sub_buckets, self.config.slots, self.n, m["fields"],
            m["skey"], m["counts"],
        )
        return dict(
            req=req, groups=groups, order=m["order"],
            take_idx=take_idx, n=m["order"].shape[0], B_sub=B_sub,
        )

    def decide_submit_merged(self, merged: dict, now: int):
        """Dispatch a merge_prepped batch (mesh): epoch bookkeeping +
        the jitted shard_map call. Returns the standard decide_wait
        handle."""
        e_now = self._engine_now(now)
        self.store, packed = self._step(
            self.store, merged["req"], merged["groups"], e_now
        )
        return (
            packed, merged["order"], merged["take_idx"], merged["n"],
            merged["B_sub"], self.clock.epoch,
        )

    def decide_submit_presorted(
        self,
        fields: dict,
        skey: np.ndarray,
        order: Optional[np.ndarray],
        counts: np.ndarray,
        now: int,
    ):
        """Mesh sibling of TpuEngine.decide_submit_presorted: dispatch a
        batch whose (owner, bucket, fingerprint) presort already
        happened at arrival time. Slices the merged sorted stream into
        contiguous per-shard sub-batches ([n_shards, B_sub] repeat-pad,
        identical to pad_request_sharded's layout), derives the
        per-shard duplicate-key group structure from the sorted key
        stream in O(n), and dispatches. `order` may be None (identity)
        for callers that discard the handle — the lockstep follower
        path. Returns the standard decide_wait handle."""
        n = skey.shape[0]
        if n == 0:
            return None
        e_now = self._engine_now(now)
        req, take_idx, groups, B_sub = build_presorted_sharded(
            self.sub_buckets, self.config.slots, self.n, fields, skey,
            counts,
        )
        if order is None:
            order = np.arange(n, dtype=np.int32)
        self.store, packed = self._step(self.store, req, groups, e_now)
        return (packed, order, take_idx, n, B_sub, self.clock.epoch)

    def decide_wait(
        self, handle
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Fetch + unflatten the responses for a decide_submit handle."""
        packed, order, take_idx, n, B_sub, epoch = handle
        # [n_shards, 4*B_sub+PACKED_STATS]
        packed = np.asarray(jax.device_get(packed))
        self.stats.add_batch(
            int(packed[:, 4 * B_sub].sum()),
            int(packed[:, 4 * B_sub + 1].sum()),
            int(packed[:, 4 * B_sub + 2].sum()),
            int(packed[:, 4 * B_sub + 3].sum()),
        )

        if _prep_native is not None and n > 0:
            # native one-pass unflatten of all four response columns
            from gubernator_tpu.native.hashlib_native import unflatten_resp

            # per-shard counts fall out of take_idx: it is strictly
            # increasing and cell (s, j) flattens to s*B_sub + j, so
            # shard boundaries are one binary search each
            bounds = np.searchsorted(
                take_idx, np.arange(1, self.n + 1) * B_sub, side="left"
            )
            counts = np.diff(np.concatenate(([0], bounds))).astype(
                np.int64
            )
            u = unflatten_resp(packed, order, counts, n, B_sub)
            status, rlimit, remaining, reset = u[0], u[1], u[2], u[3]
        else:

            def unflatten(col0):
                flat = packed[
                    :, col0 * B_sub : (col0 + 1) * B_sub
                ].reshape(-1)
                out = np.empty(n, flat.dtype)
                out[order] = flat[take_idx]
                return out

            status, rlimit, remaining, reset = (
                unflatten(c) for c in range(4)
            )
        r = np.asarray(reset, np.int64)
        reset = np.where(r == 0, 0, r + epoch)
        return status, rlimit, remaining, reset

    def decide_arrays(
        self,
        key_hash: np.ndarray,
        hits: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        algo: np.ndarray,
        gnp: np.ndarray,
        now: int,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        return self.decide_wait(
            self.decide_submit(
                key_hash, hits, limit, duration, algo, gnp, now
            )
        )

    def update_globals(
        self,
        key_hash: np.ndarray,
        limit: np.ndarray,
        remaining: np.ndarray,
        reset_time: np.ndarray,
        is_over: np.ndarray,
        now: Optional[int] = None,
    ) -> None:
        """Install broadcast GLOBAL statuses on their owning shards — the
        receive side of UpdatePeerGlobals (reference gubernator.go:199-207)
        for a mesh-backed host. reset_time is int64 unix-ms."""
        n = key_hash.shape[0]
        if n == 0:
            return
        from gubernator_tpu.api.types import millisecond_now

        self._engine_now(millisecond_now() if now is None else now)
        if n > max(self.buckets):
            _warn_ladder_overflow(max(self.buckets), n)
        kh, lim, rem, rst, over, valid = pad_to_bucket(
            extend_ladder(self.buckets, n),
            n,
            (key_hash, np.uint64),
            (_sat_i32(limit), np.int32),
            (_sat_i32(remaining), np.int32),
            (self.clock.to_engine(reset_time), np.int32),
            (is_over, bool),
        )
        self.store = self._upsert(
            self.store, kh, lim, rem, rst, over, valid
        )

    def sync_globals(
        self,
        key_hash: np.ndarray,
        limit: np.ndarray,
        duration: np.ndarray,
        now: int,
        algo: Optional[np.ndarray] = None,
    ) -> None:
        """One collective gossip step for the given GLOBAL keys. `algo`
        must carry each key's algorithm (defaults to token bucket)."""
        n = key_hash.shape[0]
        if n == 0:
            return
        if algo is None:
            algo = np.zeros(n, np.int32)
        e_now = self._engine_now(now)
        if n > max(self.buckets):
            _warn_ladder_overflow(max(self.buckets), n)
        req, _order = pad_request_sorted(
            extend_ladder(self.buckets, n),
            self.config.slots,
            key_hash,
            np.zeros(n, np.int64),
            limit,
            duration,
            algo,
            np.zeros(n, bool),
        )
        self.store, _resp = self._sync(
            self.store,
            req.key_hash,
            req.limit,
            req.duration,
            req.algo,
            req.valid,
            e_now,
        )
