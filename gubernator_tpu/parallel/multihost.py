"""Multi-host mesh: one logical device mesh spanning several processes.

`parallel/sharded.py` shards the key space over the chips of ONE host
(single-controller). This module extends the same engine across hosts the
way JAX scales: `jax.distributed` turns N processes into one SPMD program
over a global `Mesh`, and the psum that combines per-shard decisions rides
ICI within a host and DCN (gloo/TCP on CPU, ICI/DCN collectives on TPU
pods) between hosts — the moral equivalent of the reference wiring more
peers into its gossip mesh (reference peers.go/global.go), except the
"gossip" is a compiler-scheduled collective.

Multi-controller SPMD requires every process to issue the SAME jitted
calls in the same order. Serving is request-driven on the leader
(process 0), so followers run a lockstep loop fed by a step pipe: before
each device call the leader broadcasts (kind, now, arrays) over plain
length-prefixed TCP; every process then issues the identical call. The
pipe is a trusted-cluster side channel exactly like the reference's
insecure peer gRPC (reference peers.go:130-139); a follower failure
surfaces as a broken pipe and the cluster restarts fresh — the documented
state-loss contract (reference architecture.md:5-11).

Scaling model (BASELINE config 5, v5e-32 = 4 hosts x 8 chips): each chip
owns 1/32 of the key space. A multi-process mesh is built 2-D as
("host", "chip") — process-major device order groups each host's chips —
and the GLOBAL-sync reduction is HIERARCHICAL (sharded._hier_psum):
chips combine within a host over ICI first, then one pre-reduced vector
per host crosses DCN, instead of a flat 32-way all-reduce whose ring
spans DCN on every leg. Collective structure is asserted from the
compiled module in tests/test_sharded.py; the multi-process topologies
in tests/test_multihost.py run it end to end.
"""

from __future__ import annotations

import logging
import socket
import struct
from typing import List, Optional, Sequence

import numpy as np

log = logging.getLogger("gubernator_tpu.multihost")

# Wire format GMH2: a typed, gadget-free codec. GMH1 framed pickle, which
# hands arbitrary code execution to anything that can reach a follower's
# listen port — a strictly worse trust posture than the reference's
# insecure-but-parse-safe protobuf peer channel (reference
# peers.go:130-139). Step messages are only flat dicts of scalars, strings,
# int tuples, one nested config dict, and dense numpy arrays, so a
# six-tag TLV encoding covers the whole surface with no deserialization
# gadget: decode constructs nothing but bytes, ints, str, tuple, dict and
# whitelisted-dtype ndarrays.
_MAGIC = b"GMH2"

_T_NONE, _T_INT, _T_STR, _T_ARR, _T_TUPLE, _T_DICT = range(6)

# dtype whitelist — everything the step pipe ever carries. Explicit
# little-endian codes so a mixed-endian cluster fails loudly at the
# codec, not silently in the kernels.
_DTYPES = {
    0: np.dtype("<u8"),  # key_hash
    1: np.dtype("<i8"),  # hits/limit/duration/remaining/reset_time
    2: np.dtype("<i4"),  # algo
    3: np.dtype("|b1"),  # gnp/is_over
}
_DTYPE_CODES = {dt: code for code, dt in _DTYPES.items()}

_MAX_DEPTH = 4  # message dict -> config dict -> tuples; headroom of one
_MAX_ITEMS = 4096  # fields per dict / elements per tuple
_MAX_STR = 1 << 20
_MAX_ARR_BYTES = 1 << 31


def _encode_value(out: bytearray, v, depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("step message nests too deep to encode")
    if v is None:
        out.append(_T_NONE)
    elif isinstance(v, (int, np.integer)) and not isinstance(v, bool):
        out.append(_T_INT)
        out += struct.pack("<q", int(v))
    elif isinstance(v, str):
        b = v.encode()
        if len(b) > _MAX_STR:
            raise ValueError("string field too large for step pipe")
        out.append(_T_STR)
        out += struct.pack("<I", len(b))
        out += b
    elif isinstance(v, np.ndarray):
        dt = v.dtype.newbyteorder("<") if v.dtype.byteorder == ">" else v.dtype
        arr = np.ascontiguousarray(v, dtype=dt)
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            raise ValueError(f"dtype {arr.dtype} not in step-pipe whitelist")
        if arr.ndim > 4:
            raise ValueError("array rank > 4 on step pipe")
        out.append(_T_ARR)
        out.append(code)
        out.append(arr.ndim)
        for d in arr.shape:
            out += struct.pack("<I", d)
        out += arr.tobytes()
    elif isinstance(v, tuple):
        if len(v) > _MAX_ITEMS:
            raise ValueError("tuple too long for step pipe")
        out.append(_T_TUPLE)
        out += struct.pack("<I", len(v))
        for e in v:
            _encode_value(out, e, depth + 1)
    elif isinstance(v, dict):
        if len(v) > _MAX_ITEMS:
            raise ValueError("dict too large for step pipe")
        out.append(_T_DICT)
        out += struct.pack("<I", len(v))
        for k, e in v.items():
            kb = str(k).encode()
            out += struct.pack("<H", len(kb))
            out += kb
            _encode_value(out, e, depth + 1)
    else:
        raise ValueError(f"type {type(v).__name__} not encodable on step pipe")


def _utf8(raw) -> str:
    # keep the "hostile frame -> ConnectionError" contract airtight
    try:
        return str(raw, "utf-8")
    except UnicodeDecodeError as e:
        raise ConnectionError(f"invalid utf-8 in step pipe frame: {e}")


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = memoryview(buf)
        self.pos = 0

    def take(self, n: int) -> memoryview:
        if n < 0 or self.pos + n > len(self.buf):
            raise ConnectionError("step pipe frame truncated")
        v = self.buf[self.pos : self.pos + n]
        self.pos += n
        return v

    def u8(self) -> int:
        return self.take(1)[0]

    def unpack(self, fmt: str):
        (v,) = struct.unpack("<" + fmt, self.take(struct.calcsize(fmt)))
        return v


def _decode_value(r: _Reader, depth: int = 0):
    if depth > _MAX_DEPTH:
        raise ConnectionError("step pipe frame nests too deep")
    tag = r.u8()
    if tag == _T_NONE:
        return None
    if tag == _T_INT:
        return r.unpack("q")
    if tag == _T_STR:
        n = r.unpack("I")
        if n > _MAX_STR:
            raise ConnectionError("oversized string in step pipe frame")
        return _utf8(r.take(n))
    if tag == _T_ARR:
        dt = _DTYPES.get(r.u8())
        if dt is None:
            raise ConnectionError("unknown dtype in step pipe frame")
        ndim = r.u8()
        if ndim > 4:
            raise ConnectionError("array rank > 4 in step pipe frame")
        shape = tuple(r.unpack("I") for _ in range(ndim))
        n_elem = 1
        for d in shape:
            n_elem *= d
        if n_elem * dt.itemsize > _MAX_ARR_BYTES:
            raise ConnectionError("oversized array in step pipe frame")
        raw = r.take(n_elem * dt.itemsize)
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag == _T_TUPLE:
        n = r.unpack("I")
        if n > _MAX_ITEMS:
            raise ConnectionError("oversized tuple in step pipe frame")
        return tuple(_decode_value(r, depth + 1) for _ in range(n))
    if tag == _T_DICT:
        n = r.unpack("I")
        if n > _MAX_ITEMS:
            raise ConnectionError("oversized dict in step pipe frame")
        d = {}
        for _ in range(n):
            klen = r.unpack("H")
            k = _utf8(r.take(klen))
            d[k] = _decode_value(r, depth + 1)
        return d
    raise ConnectionError(f"unknown tag {tag} in step pipe frame")


def _encode_msg(obj: dict) -> bytes:
    out = bytearray()
    _encode_value(out, obj)
    return _MAGIC + struct.pack("<Q", len(out)) + bytes(out)


def _send_msg(sock: socket.socket, obj: dict) -> None:
    sock.sendall(_encode_msg(obj))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("step pipe closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket) -> dict:
    if _recv_exact(sock, 4) != _MAGIC:
        raise ConnectionError("step pipe desync")
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    if n > _MAX_ARR_BYTES:
        raise ConnectionError("oversized step pipe frame")
    r = _Reader(_recv_exact(sock, n))
    msg = _decode_value(r)
    if not isinstance(msg, dict):
        raise ConnectionError("step pipe frame is not a message dict")
    if r.pos != len(r.buf):
        raise ConnectionError("trailing bytes in step pipe frame")
    return msg


class StepPipe:
    """Leader side: broadcast each device step to every follower and wait
    for acks (the ack keeps processes in lockstep so no follower falls
    more than one collective behind)."""

    def __init__(self, follower_addrs: Sequence[str], timeout_s: float = 30.0):
        import time

        self.socks: List[socket.socket] = []
        for addr in follower_addrs:
            host, _, port = addr.rpartition(":")
            deadline = time.monotonic() + timeout_s
            while True:  # follower binds its listener after the jax
                # rendezvous; retry until it is up
                try:
                    s = socket.create_connection((host, int(port)), timeout=5)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
            s.settimeout(None)  # connect timeout must not cap step acks
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.socks.append(s)

    def broadcast(self, msg: dict) -> None:
        wire = _encode_msg(msg)  # serialize once for every follower
        for s in self.socks:
            s.sendall(wire)

    def await_acks(self) -> None:
        for s in self.socks:
            m = _recv_msg(s)
            if m.get("kind") == "nack":
                raise RuntimeError(f"follower rejected step: {m.get('error')}")
            if m.get("kind") != "ack":
                raise RuntimeError(f"unexpected follower reply: {m}")

    def close(self) -> None:
        for s in self.socks:
            try:
                _send_msg(s, {"kind": "shutdown"})
                s.close()
            except OSError:
                pass


def initialize_distributed(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """jax.distributed.initialize with the platform this image needs
    forced first (the TPU tunnel pre-registers itself). On the CPU
    backend the cross-process collectives implementation must be
    selected BEFORE the client initializes: without it this jaxlib's
    CPU client refuses multi-process computations outright
    ("Multiprocess computations aren't implemented on the CPU
    backend") — the error that kept the multihost suite in the
    permanent failure set. gloo-over-TCP is the CPU stand-in for DCN
    (multi-process TPU/GPU backends ignore the knob)."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - knob absent on newer jax
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


class MultiHostMeshEngine:
    """The ONE partitioned engine (parallel/sharded.PartitionedEngine,
    r14) over the GLOBAL device mesh, plus the leader-side lockstep
    step pipe — this wrapper owns only the multi-controller choreography
    (broadcast each device call so every process issues the identical
    program); every decide/upsert/sync code path is the shared engine's.
    Construct identically in every process; only the leader calls the
    public decide/update/sync methods (followers run follower_loop).
    """

    def __init__(
        self,
        store_config,
        followers: Optional[Sequence[str]] = None,
        buckets: Sequence[int] = (64, 256, 1024, 4096),
        sketch=None,
    ):
        import jax

        from gubernator_tpu.parallel.sharded import MeshEngine

        self.is_leader = jax.process_index() == 0
        self.inner = MeshEngine(
            store_config, devices=jax.devices(), buckets=buckets,
            sketch=sketch,
        )
        self.pipe = (
            StepPipe(followers) if (self.is_leader and followers) else None
        )
        if self.pipe:
            # Config handshake: every process derives batch-padding shapes
            # independently from its own ladder, and the lockstep shard_map
            # requires those shapes to be IDENTICAL across processes. A
            # mismatch used to surface only as a distributed shape
            # divergence mid-serving (or, before the skew-overflow
            # fallback, an incidental choose_bucket error during warmup
            # replay); verify it explicitly at connect time instead.
            self.pipe.broadcast({"kind": "hello", "config": self._config()})
            self.pipe.await_acks()

    def _config(self) -> dict:
        skc = self.inner.sketch_config
        return {
            "buckets": tuple(self.inner.buckets),
            "sub_buckets": tuple(self.inner.sub_buckets),
            "store": (self.inner.config.rows, self.inner.config.slots),
            "n_shards": self.inner.n,
            # sketch geometry (r20; counter width since r21): a leader
            # with the cold tier on and a follower without it (or with
            # a different width or counter dtype) would diverge at the
            # first two-tier dispatch — verify at hello
            "sketch": (
                (skc.rows, skc.width, skc.counter_bytes)
                if skc is not None
                else None
            ),
        }

    @property
    def buckets(self):
        return self.inner.buckets

    @property
    def sub_buckets(self):
        return self.inner.sub_buckets

    @property
    def n(self):
        return self.inner.n

    @property
    def stats(self):
        return self.inner.stats

    @property
    def reset_generation(self):
        # store-wipe epoch for the over-limit shed cache; follower
        # stores reset in lockstep with the leader's, so the leader's
        # counter is authoritative for the whole mesh
        return self.inner.reset_generation

    # -- sketch cold tier surfaces (r20) ------------------------------------
    # The backend tier probes `sketch` (tier present?) and sets
    # `observe_hook` (the promoter's hot-key observer — leader-local by
    # construction: only the leader dispatches request batches, so only
    # its hook ever fires). `sketch_on` is the runtime A/B flag; both
    # sides of a lockstep dispatch must pick the same two-tier-or-not
    # program, so the leader's flag rides every decide message ("sk")
    # and followers adopt it before dispatching — flipping it here can
    # never diverge the fleet.

    @property
    def sketch(self):
        return self.inner.sketch

    @property
    def sketch_config(self):
        return self.inner.sketch_config

    @property
    def sketch_on(self):
        return self.inner.sketch_on

    @sketch_on.setter
    def sketch_on(self, value):
        self.inner.sketch_on = value

    @property
    def observe_hook(self):
        return self.inner.observe_hook

    @observe_hook.setter
    def observe_hook(self, fn):
        self.inner.observe_hook = fn

    # -- leader API ---------------------------------------------------------

    def _lockstep(self, msg: dict) -> None:
        if self.pipe:
            self.pipe.broadcast(msg)

    def _done(self) -> None:
        if self.pipe:
            self.pipe.await_acks()

    def decide_arrays(self, key_hash, hits, limit, duration, algo, gnp, now):
        assert self.is_leader
        return self.decide_wait(
            self.decide_submit(
                key_hash, hits, limit, duration, algo, gnp, now
            )
        )

    def decide_submit(self, key_hash, hits, limit, duration, algo, gnp,
                      now):
        """Pipelined split for the multihost leader: followers only need
        to ISSUE the identical jitted call (their psum legs run inside
        the device program) — they never fetch results, so the leader
        may submit batch N+1 while batch N's fetch is in flight, exactly
        like the single-host engines. The ack still bounds skew at one
        collective."""
        assert self.is_leader
        self._lockstep(
            {
                "kind": "decide",
                "key_hash": key_hash,
                "hits": hits,
                "limit": limit,
                "duration": duration,
                "algo": algo,
                "gnp": gnp,
                "now": now,
                "sk": int(self.inner.sketch_on),
            }
        )
        try:
            return self.inner.decide_submit(
                key_hash, hits, limit, duration, algo, gnp, now
            )
        finally:
            self._done()

    def decide_wait(self, handle):
        """Leader-local: fetching the packed outputs involves no
        collective, so no lockstep message is needed (followers already
        moved on at submit time)."""
        assert self.is_leader
        return self.inner.decide_wait(handle)

    def prep_run(self, fields: dict) -> dict:
        """Leader-local arrival-time prep (serve/batcher.py): pure host
        work, no collective — followers receive the already-sorted run
        via decide_submit_presorted's lockstep message and never
        re-sort, so the prep cost is paid once per cluster."""
        assert self.is_leader
        return self.inner.prep_run(fields)

    def merge_prepped(self, runs):
        """Leader-side merge of pre-sorted runs. Returns the FLAT
        merged form (serve/prep.py) — deliberately not the padded
        per-shard layout, because it doubles as the lockstep wire
        format decide_submit_merged broadcasts; each process derives
        its identical [n_shards, B_sub] layout locally."""
        assert self.is_leader
        from gubernator_tpu.serve.prep import merge_runs

        return merge_runs(runs)

    def decide_submit_merged(self, merged, now):
        """Dispatch a merge_prepped batch across the lockstep fleet."""
        return self.decide_submit_presorted(
            merged["fields"], merged["skey"], merged["order"],
            merged["counts"], now,
        )

    def decide_submit_presorted(self, fields, skey, order, counts, now):
        """Merge-combine sibling of decide_submit: broadcasts the
        SORTED batch (fields + sort keys + per-shard counts), so
        followers skip the presort entirely and only issue the
        identical jitted call. `order` stays leader-local — it exists
        only to unpermute responses, which followers never fetch."""
        assert self.is_leader
        msg = {"kind": "decide_p", "skey": skey, "counts": counts,
               "now": now, "sk": int(self.inner.sketch_on)}
        msg.update(fields)
        self._lockstep(msg)
        try:
            return self.inner.decide_submit_presorted(
                fields, skey, order, counts, now
            )
        finally:
            self._done()

    def update_globals(self, key_hash, limit, remaining, reset_time, is_over,
                       now=None):
        assert self.is_leader
        from gubernator_tpu.api.types import millisecond_now

        now = millisecond_now() if now is None else now
        self._lockstep(
            {
                "kind": "upsert",
                "key_hash": key_hash,
                "limit": limit,
                "remaining": remaining,
                "reset_time": reset_time,
                "is_over": is_over,
                "now": now,
            }
        )
        try:
            return self.inner.update_globals(
                key_hash, limit, remaining, reset_time, is_over, now=now
            )
        finally:
            self._done()

    def reset(self) -> None:
        assert self.is_leader
        self._lockstep({"kind": "reset"})
        try:
            self.inner.reset()
        finally:
            self._done()

    def sync_globals(self, key_hash, limit, duration, now, algo=None):
        assert self.is_leader
        self._lockstep(
            {
                "kind": "sync",
                "key_hash": key_hash,
                "limit": limit,
                "duration": duration,
                "algo": algo,
                "now": now,
            }
        )
        try:
            return self.inner.sync_globals(
                key_hash, limit, duration, now, algo=algo
            )
        finally:
            self._done()

    def apply_global_hits(self, key_hash, hits, limit, duration, now,
                          algo=None):
        """Mesh-native GLOBAL flush (r20): aggregate gossip hits charge
        their owner shards + replicate post-charge windows in ONE
        collective step across the whole multi-process mesh. The step's
        response legs are psum outputs (replicated), so the leader
        fetches them host-side while followers dispatch-and-discard."""
        assert self.is_leader
        n = key_hash.shape[0]
        if n == 0:
            z = np.empty(0, np.int64)
            return z, z, z, z
        self._lockstep(
            {
                "kind": "ghits",
                "key_hash": key_hash,
                "hits": hits,
                "limit": limit,
                "duration": duration,
                "algo": algo,
                "now": now,
            }
        )
        try:
            return self.inner.apply_global_hits(
                key_hash, hits, limit, duration, now, algo=algo
            )
        finally:
            self._done()

    def promote_from_sketch(self, key_hash, limits, durations, now=None):
        """decide_p-style lockstep promotion (r20): the serving-tier
        promoter stays a host loop on the leader, but its device
        surfaces (collective estimate/live-row reads + the conditional
        window install) broadcast so every process issues the identical
        programs. The branch on `todo.any()` cannot diverge: both reads
        return psum-replicated arrays, so all processes see the same
        values."""
        assert self.is_leader
        from gubernator_tpu.api.types import millisecond_now

        now = millisecond_now() if now is None else now
        kh = np.ascontiguousarray(key_hash, np.uint64)
        limits = np.asarray(limits, np.int64)
        durations = np.asarray(durations, np.int64)
        if kh.shape[0] == 0 or self.inner.sketch is None:
            return self.inner.promote_from_sketch(kh, limits, durations, now)
        self._lockstep(
            {
                "kind": "promote",
                "key_hash": kh,
                "limits": limits,
                "durations": durations,
                "now": now,
            }
        )
        try:
            return self.inner.promote_from_sketch(kh, limits, durations, now)
        finally:
            self._done()

    def _warmup_sketch_reads(self, now) -> None:
        """Lockstep-safe twin of the engine's promoter-surface warmup
        (warmup_public calls this by name): each pow2 rung rides a
        `promote` broadcast so followers compile the identical
        collective read + install programs. The installs dirty the
        store, but warmup_public ends with a (broadcast) reset()."""
        if self.inner.sketch is None:
            return
        for B in (64, 128, 256, 512, 1024):
            kh = np.arange(1, B + 1, dtype=np.uint64) << np.uint64(32)
            self.promote_from_sketch(
                kh, np.full(B, 10, np.int64), np.full(B, 1000, np.int64),
                now,
            )

    def close(self) -> None:
        if self.pipe:
            self.pipe.close()

    # -- follower API -------------------------------------------------------

    def follower_loop(self, listen_addr: str, ready_cb=None) -> None:
        """Serve lockstep steps until the leader shuts the pipe. Each
        message triggers the identical jitted call the leader makes, so
        the global-mesh collectives line up."""
        assert not self.is_leader
        host, _, port = listen_addr.rpartition(":")
        srv = socket.create_server((host, int(port)))
        if ready_cb:
            ready_cb()
        conn, peer = srv.accept()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        log.info("step pipe connected from %s", peer)
        while True:
            msg = _recv_msg(conn)
            kind = msg.pop("kind")
            if kind == "shutdown":
                break
            if kind == "hello":
                want, have = msg["config"], self._config()
                if want != have:
                    err = (
                        "leader/follower config mismatch (batch shapes "
                        f"would diverge in lockstep): leader={want} "
                        f"follower={have}"
                    )
                    # nack first so the leader's await_acks surfaces the
                    # diagnostic instead of an opaque closed-pipe error
                    _send_msg(conn, {"kind": "nack", "error": err})
                    raise RuntimeError(err)
            elif kind == "decide":
                # submit only: the follower's psum legs execute inside
                # the dispatched device program; fetching the packed
                # outputs here would buy nothing and cost a device->host
                # transfer per step (plus it would serialize the
                # leader's fetch pipeline through follower acks).
                # "sk" carries the leader's sketch_on so the two-tier
                # program choice can never diverge across processes.
                sk = msg.pop("sk", None)
                if sk is not None:
                    self.inner.sketch_on = bool(sk)
                self.inner.decide_submit(**msg)
            elif kind == "decide_p":
                # merge-combined batch: already sorted + clipped on the
                # leader; order=None (identity) — the handle is
                # discarded, responses are leader-only
                sk = msg.get("sk")
                if sk is not None:
                    self.inner.sketch_on = bool(sk)
                self.inner.decide_submit_presorted(
                    {
                        k: msg[k]
                        for k in ("key_hash", "hits", "limit",
                                  "duration", "algo", "gnp")
                    },
                    msg["skey"],
                    None,
                    msg["counts"],
                    msg["now"],
                )
            elif kind == "reset":
                self.inner.reset()
            elif kind == "upsert":
                self.inner.update_globals(
                    msg["key_hash"],
                    msg["limit"],
                    msg["remaining"],
                    msg["reset_time"],
                    msg["is_over"],
                    now=msg["now"],
                )
            elif kind == "sync":
                self.inner.sync_globals(
                    msg["key_hash"],
                    msg["limit"],
                    msg["duration"],
                    msg["now"],
                    algo=msg["algo"],
                )
            elif kind == "ghits":
                # mesh-native GLOBAL flush: dispatch the identical sync
                # collective and discard — post-charge responses are
                # leader-only (replicated psum outputs), so fetching
                # them here would only serialize the leader behind a
                # follower device->host transfer
                self.inner._sync_padded(
                    msg["key_hash"],
                    msg["hits"],
                    msg["limit"],
                    msg["duration"],
                    msg["algo"],
                    msg["now"],
                )
            elif kind == "promote":
                # sketch-tier promotion: the collective reads return
                # replicated arrays, so this process's todo/install
                # control flow is byte-identical to the leader's
                self.inner.promote_from_sketch(
                    msg["key_hash"],
                    msg["limits"],
                    msg["durations"],
                    msg["now"],
                )
            else:
                raise RuntimeError(f"unknown step kind {kind!r}")
            _send_msg(conn, {"kind": "ack"})
        conn.close()
        srv.close()
