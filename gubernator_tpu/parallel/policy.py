"""Sharding policy: the one object that decides how the partitioned
engine lays state and batches over devices (r14).

The paper's thesis maps Gubernator's consistent-hash ring onto mesh
axes; before r14 that mapping was smeared across three engine variants
(TpuEngine / MeshEngine / MultiHostMeshEngine) whose decide/upsert/
snapshot paths could drift independently — and did: the mesh variants
sat unverified in the permanent failure set. A `ShardingPolicy` now
carries everything topology-specific — devices, mesh axes, the
NamedSharding specs for store rows and request columns, the collective
choice for GLOBAL sync — and ONE engine (parallel/sharded.py
PartitionedEngine) consumes it, with the single-device policy as the
degenerate case (no mesh, flat [B] batches, plain jit: byte-identical
to the historical TpuEngine fast path).

jax compat: this tree pins jax 0.4.x, where `shard_map` lives at
`jax.experimental.shard_map.shard_map` with the replication check
spelled `check_rep`; jax >= 0.5 promotes it to `jax.shard_map` with
`check_vma`. `shard_map_compat` papers over both so the sharded paths
run (and are TESTED, on simulated host devices) on either — the
version skew that kept the mesh suite in the failure set since seed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_compat(f, mesh, in_specs, out_specs, check=True):
    """jax.shard_map across the 0.4/0.5 API rename (see module
    docstring). `check` maps to check_vma (new) / check_rep (old)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


@dataclass(frozen=True)
class ShardingPolicy:
    """How one engine's state and batches map onto devices.

    - `devices`: the shards, in shard-index order (None on the flat
      single-device policy, where placement is jax's default or the
      one pinned `device`).
    - `axes`: mesh axis names, host-major — ("shard",) flat 1-D, or
      ("host", "chip") when the reduction should stage ICI-then-DCN
      (BASELINE config 5's hierarchical psum). Empty for single.
    - `mesh`: the jax Mesh (None => no mesh: the degenerate policy).
    - `spans_processes`: True when the mesh crosses process boundaries
      (multi-controller SPMD): responses must all_gather back to the
      serving leader, and host-side state reads (snapshot/sketch
      gathers for replication and the promoter) are unavailable — the
      follower processes would have to issue matching programs.
    """

    device: Optional[jax.Device] = None
    devices: Optional[Tuple[jax.Device, ...]] = None
    axes: Tuple[str, ...] = ()
    mesh: Optional[Mesh] = field(default=None, compare=False)
    spans_processes: bool = False

    # -- factories ----------------------------------------------------------

    @classmethod
    def single(cls, device: Optional[jax.Device] = None) -> "ShardingPolicy":
        """The degenerate policy: one shard, no mesh, flat [B] batches,
        plain jit dispatch — the historical TpuEngine layout."""
        return cls(device=device)

    @classmethod
    def over_mesh(
        cls,
        devices: Optional[Sequence[jax.Device]] = None,
        mesh_shape: Optional[Tuple[int, int]] = None,
    ) -> "ShardingPolicy":
        """Key-space sharding over a device mesh. `mesh_shape` forces a
        2-D ("host", "chip") layout; by default a multi-process device
        list with equal per-process counts auto-selects it, after
        validating each reshaped row is single-process (else the
        "ICI within a row, DCN across rows" staging would silently
        cross DCN inside a row — ADVICE r5 #1) — the flat ("shard",)
        mesh is the fallback."""
        if devices is None:
            devices = jax.devices()
        devices = tuple(devices)
        n = len(devices)
        procs = {d.process_index for d in devices}
        span = len(procs) > 1
        if mesh_shape is None and span and n % len(procs) == 0:
            grid = np.asarray(devices).reshape(len(procs), n // len(procs))
            if all(
                len({d.process_index for d in row}) == 1 for row in grid
            ):
                mesh_shape = (len(procs), n // len(procs))
        if mesh_shape is not None:
            n_hosts, per_host = mesh_shape
            if n_hosts * per_host != n:
                raise ValueError(
                    f"mesh_shape {mesh_shape} != {n} devices"
                )
            mesh = Mesh(
                np.asarray(devices).reshape(n_hosts, per_host),
                ("host", "chip"),
            )
            axes: Tuple[str, ...] = ("host", "chip")
        else:
            mesh = Mesh(np.asarray(devices), ("shard",))
            axes = ("shard",)
        return cls(
            devices=devices, axes=axes, mesh=mesh, spans_processes=span
        )

    # -- derived properties -------------------------------------------------

    @property
    def flat(self) -> bool:
        """True for the degenerate single-device policy."""
        return self.mesh is None

    @property
    def n_shards(self) -> int:
        return 1 if self.flat else len(self.devices)

    @property
    def hierarchical(self) -> bool:
        """Stage the GLOBAL-sync reduction ICI-then-DCN (2-D mesh)."""
        return len(self.axes) > 1

    def store_spec(self) -> P:
        """PartitionSpec for state rows: leading shard axis over every
        mesh axis, host-major (store [n_shards, buckets, W], sketch
        [n_shards, rows, width])."""
        assert not self.flat
        return P(self.axes)

    def request_spec(self) -> P:
        """PartitionSpec for request columns: per-shard sub-batches
        [n_shards, B_sub] laid over the same axes as the store, so row
        s of every field sits on the chip owning key-space shard s."""
        return self.store_spec()

    def replicated_spec(self) -> P:
        return P()

    def store_sharding(self) -> NamedSharding:
        assert not self.flat
        return NamedSharding(self.mesh, self.store_spec())

    def describe(self) -> str:
        if self.flat:
            return "single-device (flat, degenerate policy)"
        shape = dict(self.mesh.shape)
        return (
            f"{self.n_shards}-shard mesh {shape} axes={self.axes} "
            f"collective={'hierarchical' if self.hierarchical else 'flat'}"
            f"{' multi-process' if self.spans_processes else ''}"
        )
