// gubernator-tpu native serving edge.
//
// The latency-critical front door the reference implements in compiled Go
// (its gRPC/JSON gateway): this C++ process terminates client HTTP/1.1
// JSON connections, validates + parses requests, coalesces them across
// ALL connections into micro-batches (the reference's BatchWait /
// BatchLimit semantics, config.go:59-62), and forwards each batch to the
// Python serving daemon as ONE binary frame over a unix-domain socket
// (serve/edge_bridge.py). The daemon pays one read + one decode per
// batch; all per-request parse/serialize cost stays here, outside the
// Python process. Responses fan back to the originating connections.
//
// Scope: POST /v1/GetRateLimits (the hot path). Everything else
// (HealthCheck, metrics, debug) is served by the daemon's own HTTP
// listener; GET /v1/HealthCheck here reports edge liveness only.
//
// Build: make -C gubernator_tpu/native/edge
// Run:   guber-edge --listen 8080 --backend /tmp/guber-edge.sock
//                   [--batch-wait-us 500] [--batch-limit 1000]

#include <arpa/inet.h>
#include <csignal>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- protocol

constexpr uint32_t kMagicReq = 0x31424547;       // 'GEB1'
constexpr uint32_t kMagicResp = 0x33424547;      // 'GEB3'
constexpr uint32_t kMagicHello = 0x49424547;     // 'GEBI' ring hello (r5)
constexpr uint32_t kMagicFastReq = 0x36424547;   // 'GEB6' pre-hashed (r5)
constexpr uint32_t kMagicFastResp = 0x35424547;  // 'GEB5'
constexpr uint32_t kMagicStale = 0x52424547;     // 'GEBR' stale ring
// windowed framing (r7): per-frame ids + a bridge-advertised credit
// window, so N frames ride one connection and responses complete out
// of order (serve/edge_bridge.py module docstring for the layouts)
constexpr uint32_t kMagicWReq = 0x32424547;       // 'GEB2' string req
constexpr uint32_t kMagicWResp = 0x34424547;      // 'GEB4' string resp
constexpr uint32_t kMagicWFastReq = 0x37424547;   // 'GEB7' fast req
constexpr uint32_t kMagicWFastResp = 0x38424547;  // 'GEB8' fast resp

// CLOCK_MONOTONIC microseconds — the same clock domain as the
// daemon's time.monotonic(), so a frame stamp crosses the socket
// intact and the bridge can attribute edge->bridge transit
// (serve/stages.py edge_to_bridge)
uint64_t mono_us() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// IPv6 bridge endpoint specs are refused loudly (ADVICE r5 #2): the
// frame protocol splits host:port on the LAST colon, so '[::1]:9100'
// or a bare '::1' would misparse silently (bracketed host handed to
// getaddrinfo, or the address mistaken for a unix path).
bool endpoint_is_ipv6ish(const std::string& s) {
  if (s.find('[') != std::string::npos ||
      s.find(']') != std::string::npos)
    return true;
  return std::count(s.begin(), s.end(), ':') > 1;
}

struct Item {
  std::string name;
  std::string key;
  int64_t hits = 0;
  int64_t limit = 0;
  int64_t duration = 0;
  uint8_t algorithm = 0;
  uint8_t behavior = 0;
  uint64_t hash = 0;  // xxh64(name+"_"+key) for the GEB4 fast path
};

// ------------------------------------------------------------------ xxh64
// XXH64 (Yann Collet's public-domain algorithm), implemented from the
// spec — MUST produce bit-identical values to native/guberhash.cc's
// implementation with the daemon's seed, or the edge's pre-hashed keys
// would address different store slots than directly-served traffic
// (pinned e2e by tests/test_edge_fast_path.py shared-state assertions).
constexpr uint64_t kSlotHashSeed = 0x67756265726E6174ULL;  // "gubernat"

uint64_t xx_rotl(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

uint64_t xxh64(const uint8_t* data, size_t len, uint64_t seed) {
  constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
  constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
  constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
  constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
  constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  auto rd64 = [](const uint8_t* q) {
    uint64_t v;
    memcpy(&v, q, 8);
    return v;  // little-endian host assumed (x86/arm)
  };
  auto rd32 = [](const uint8_t* q) {
    uint32_t v;
    memcpy(&v, q, 4);
    return (uint64_t)v;
  };
  auto round = [](uint64_t acc, uint64_t input) {
    return xx_rotl(acc + input * P2, 31) * P1;
  };
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    do {
      v1 = round(v1, rd64(p)); p += 8;
      v2 = round(v2, rd64(p)); p += 8;
      v3 = round(v3, rd64(p)); p += 8;
      v4 = round(v4, rd64(p)); p += 8;
    } while (p + 32 <= end);
    h = xx_rotl(v1, 1) + xx_rotl(v2, 7) + xx_rotl(v3, 12) + xx_rotl(v4, 18);
    auto merge = [&](uint64_t acc, uint64_t val) {
      return (acc ^ round(0, val)) * P1 + P4;
    };
    h = merge(h, v1); h = merge(h, v2); h = merge(h, v3); h = merge(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h = xx_rotl(h ^ round(0, rd64(p)), 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h = xx_rotl(h ^ (rd32(p) * P1), 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h = xx_rotl(h ^ (*p++ * P5), 11) * P1;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

uint64_t slot_hash(const std::string& name, const std::string& key) {
  std::string joined;
  joined.reserve(name.size() + 1 + key.size());
  joined += name;
  joined += '_';
  joined += key;
  return xxh64((const uint8_t*)joined.data(), joined.size(), kSlotHashSeed);
}

// ------------------------------------------------------------------ crc32
// CRC-32 (IEEE 802.3, the zlib/Go crc32.ChecksumIEEE polynomial),
// table-driven, written from the spec. MUST match zlib.crc32: the ring
// places a key on the node whose point (crc32 of its gRPC address)
// succeeds crc32(name+"_"+key) — bit parity with the daemon's
// core/hashing.ring_hash (reference hash.go:40-42) is what makes the
// edge's routing agree with every daemon's picker (pinned e2e by
// tests/test_edge_cluster.py placement assertions).

struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
      t[i] = c;
    }
  }
};

uint32_t crc32_ieee(const uint8_t* p, size_t n) {
  static const Crc32Table tbl;
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = tbl.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

uint32_t crc32_str(const std::string& s) {
  return crc32_ieee((const uint8_t*)s.data(), s.size());
}

// ------------------------------------------------------------------- ring
// Consistent-hash view of the cluster, read from the bridge hello
// (serve/edge_bridge.py `_hello`). Placement-compatible with the
// daemon's picker (serve/peers.py ConsistentHashPicker / reference
// hash.go:80-96): one crc32 point per node, sorted, successor with
// wraparound.

struct Node {
  std::string grpc;    // the node's gRPC address (ring point + owner
                       // metadata string)
  std::string bridge;  // "host:port" of its edge bridge; empty = reach
                       // it through the slow path (string frames to the
                       // primary, which forwards over gRPC)
  bool self = false;   // the node behind our --backend endpoint
};

struct Ring {
  uint32_t hash = 0;  // membership fingerprint; echoed in fast frames
  bool fast = false;  // bridge advertises the pre-hashed path
  bool windowed = false;  // bridge accepts GEB2/GEB7 windowed frames
  uint32_t window = 0;    // credit window (frames in flight per conn)
  std::vector<Node> nodes;
  std::vector<std::pair<uint32_t, int>> points;  // sorted (point, node)

  void index() {
    points.clear();
    for (size_t i = 0; i < nodes.size(); ++i)
      points.emplace_back(crc32_str(nodes[i].grpc), (int)i);
    std::sort(points.begin(), points.end());
    // two addresses on one crc32 point (~2^-32/pair) would split
    // ownership between this sort-order tie-break and the picker's
    // last-add-wins — and the membership fingerprint cannot catch it.
    // The daemon's picker refuses the collision; surface it here too
    // in case a version-skewed daemon let it through (ADVICE r5 #3).
    for (size_t i = 1; i < points.size(); ++i)
      if (points[i].first == points[i - 1].first)
        fprintf(stderr,
                "guber-edge: ring point collision %#x between '%s' and "
                "'%s'; placement may diverge from the daemons\n",
                points[i].first, nodes[points[i - 1].second].grpc.c_str(),
                nodes[points[i].second].grpc.c_str());
  }

  // node index owning `name_key`, or -1 on an empty ring
  int owner(const std::string& name, const std::string& key) const {
    if (points.empty()) return -1;
    std::string joined;
    joined.reserve(name.size() + 1 + key.size());
    joined += name;
    joined += '_';
    joined += key;
    uint32_t point = crc32_str(joined);
    auto it = std::lower_bound(
        points.begin(), points.end(),
        std::make_pair(point, INT32_MIN));
    if (it == points.end()) it = points.begin();
    return it->second;
  }
};

struct Decision {
  uint8_t status = 0;
  int64_t limit = 0;
  int64_t remaining = 0;
  int64_t reset_time = 0;
  std::string error;
  std::string owner;  // metadata["owner"] for forwarded keys (parity
  // with the gRPC/gateway surface, reference gubernator.go:151)
};

void put_u16(std::string& b, uint16_t v) { b.append((char*)&v, 2); }
void put_u32(std::string& b, uint32_t v) { b.append((char*)&v, 4); }
void put_i64(std::string& b, int64_t v) { b.append((char*)&v, 8); }

// ------------------------------------------------------------- minimal JSON
// Parser for the fixed GetRateLimitsReq schema; tolerant of whitespace,
// field order, string/number duality for int64 fields (the JSON gateway
// emits int64 as strings), and unknown fields (skipped).

struct JsonCursor {
  const char* p;
  const char* end;
  bool fail = false;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool eat(char c) {
    ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    return false;
  }
  bool parse_string(std::string& out) {
    ws();
    if (p >= end || *p != '"') return false;
    ++p;
    out.clear();
    while (p < end && *p != '"') {
      char c = *p++;
      if (c == '\\' && p < end) {
        char e = *p++;
        switch (e) {
          case 'n': out.push_back('\n'); break;
          case 't': out.push_back('\t'); break;
          case 'r': out.push_back('\r'); break;
          case 'b': out.push_back('\b'); break;
          case 'f': out.push_back('\f'); break;
          case 'u': {
            if (end - p < 4) return false;
            unsigned cp = 0;
            for (int i = 0; i < 4; ++i) {
              char h = *p++;
              cp <<= 4;
              if (h >= '0' && h <= '9') cp |= h - '0';
              else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
              else return false;
            }
            // UTF-8 encode (BMP only; surrogate pairs unsupported — the
            // rate-limit key space in practice is ASCII)
            if (cp < 0x80) out.push_back((char)cp);
            else if (cp < 0x800) {
              out.push_back((char)(0xC0 | (cp >> 6)));
              out.push_back((char)(0x80 | (cp & 0x3F)));
            } else {
              out.push_back((char)(0xE0 | (cp >> 12)));
              out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
              out.push_back((char)(0x80 | (cp & 0x3F)));
            }
            break;
          }
          default: out.push_back(e);
        }
      } else {
        out.push_back(c);
      }
    }
    if (p >= end) return false;
    ++p;  // closing quote
    return true;
  }
  bool parse_i64(int64_t& out) {
    ws();
    if (p < end && *p == '"') {  // gateway-style string int64
      std::string s;
      if (!parse_string(s)) return false;
      out = strtoll(s.c_str(), nullptr, 10);  // NUL-bounded copy
      return true;
    }
    // Bounded manual scan: the buffer is only NUL-terminated at the end
    // of the whole pipelined stream, so a bare strtoll(p) on a body
    // whose Content-Length truncates mid-number would silently absorb
    // digits from the NEXT pipelined request. Saturates like strtoll.
    const char* q = p;
    bool neg = false;
    if (q < end && (*q == '-' || *q == '+')) {
      neg = (*q == '-');
      ++q;
    }
    if (q >= end || *q < '0' || *q > '9') return false;
    const uint64_t lim =
        neg ? (uint64_t)INT64_MAX + 1 : (uint64_t)INT64_MAX;
    uint64_t v = 0;
    for (; q < end && *q >= '0' && *q <= '9'; ++q) {
      if (v <= (lim - (uint64_t)(*q - '0')) / 10) {
        v = v * 10 + (uint64_t)(*q - '0');
      } else {
        v = lim;  // saturate, keep consuming digits
      }
    }
    out = neg ? (v >= (uint64_t)INT64_MAX + 1
                     ? INT64_MIN
                     : -(int64_t)v)
              : (int64_t)v;
    p = q;
    return true;
  }
  // skip any value (for unknown fields)
  bool skip_value() {
    ws();
    if (p >= end) return false;
    if (*p == '"') {
      std::string s;
      return parse_string(s);
    }
    if (*p == '{' || *p == '[') {
      char open = *p, close = (open == '{') ? '}' : ']';
      int depth = 0;
      bool in_str = false;
      while (p < end) {
        char c = *p++;
        if (in_str) {
          if (c == '\\') { if (p < end) ++p; }
          else if (c == '"') in_str = false;
        } else if (c == '"') in_str = true;
        else if (c == open) ++depth;
        else if (c == close && --depth == 0) return true;
      }
      return false;
    }
    while (p < end && *p != ',' && *p != '}' && *p != ']') ++p;
    return true;
  }
};

bool field_is(const std::string& f, const char* snake, const char* camel) {
  return f == snake || f == camel;
}

// algorithm / behavior accept both enum names and numbers
uint8_t parse_algorithm(JsonCursor& c, bool& ok) {
  c.ws();
  if (c.p < c.end && *c.p == '"') {
    std::string s;
    ok = c.parse_string(s);
    return s == "LEAKY_BUCKET" ? 1 : 0;
  }
  int64_t v = 0;
  ok = c.parse_i64(v);
  return (uint8_t)v;
}

uint8_t parse_behavior(JsonCursor& c, bool& ok) {
  c.ws();
  if (c.p < c.end && *c.p == '"') {
    std::string s;
    ok = c.parse_string(s);
    if (s == "NO_BATCHING") return 1;
    if (s == "GLOBAL") return 2;
    return 0;
  }
  int64_t v = 0;
  ok = c.parse_i64(v);
  return (uint8_t)v;
}

// returns false on malformed JSON
bool parse_get_rate_limits(const char* body, size_t len,
                           std::vector<Item>& out) {
  JsonCursor c{body, body + len};
  if (!c.eat('{')) return false;
  std::string field;
  while (true) {
    if (c.eat('}')) return true;
    if (!c.parse_string(field) || !c.eat(':')) return false;
    if (field_is(field, "requests", "requests")) {
      if (!c.eat('[')) return false;
      if (c.eat(']')) { /* empty */ }
      else {
        do {
          if (!c.eat('{')) return false;
          Item it;
          std::string f;
          while (true) {
            if (c.eat('}')) break;
            if (!c.parse_string(f) || !c.eat(':')) return false;
            bool ok = true;
            if (field_is(f, "name", "name")) ok = c.parse_string(it.name);
            else if (field_is(f, "unique_key", "uniqueKey"))
              ok = c.parse_string(it.key);
            else if (field_is(f, "hits", "hits")) ok = c.parse_i64(it.hits);
            else if (field_is(f, "limit", "limit")) ok = c.parse_i64(it.limit);
            else if (field_is(f, "duration", "duration"))
              ok = c.parse_i64(it.duration);
            else if (field_is(f, "algorithm", "algorithm"))
              it.algorithm = parse_algorithm(c, ok);
            else if (field_is(f, "behavior", "behavior"))
              it.behavior = parse_behavior(c, ok);
            else ok = c.skip_value();
            if (!ok) return false;
            c.eat(',');
          }
          out.push_back(std::move(it));
        } while (c.eat(','));
        if (!c.eat(']')) return false;
      }
    } else {
      if (!c.skip_value()) return false;
    }
    c.eat(',');
  }
}

const char* kStatusName[2] = {"UNDER_LIMIT", "OVER_LIMIT"};

void json_escape(std::string& out, const std::string& s) {
  for (char ch : s) {
    switch (ch) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if ((unsigned char)ch < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", ch);
          out += buf;
        } else out.push_back(ch);
    }
  }
}

std::string render_responses(const Decision* d, size_t n) {
  std::string out = "{\"responses\": [";
  char num[32];
  for (size_t i = 0; i < n; ++i) {
    if (i) out += ", ";
    out += "{\"status\": \"";
    out += kStatusName[d[i].status & 1];
    out += "\", \"limit\": \"";
    snprintf(num, sizeof num, "%lld", (long long)d[i].limit);
    out += num;
    out += "\", \"remaining\": \"";
    snprintf(num, sizeof num, "%lld", (long long)d[i].remaining);
    out += num;
    out += "\", \"resetTime\": \"";
    snprintf(num, sizeof num, "%lld", (long long)d[i].reset_time);
    out += num;
    out += "\", \"error\": \"";
    json_escape(out, d[i].error);
    if (d[i].owner.empty()) {
      out += "\", \"metadata\": {}}";
    } else {
      out += "\", \"metadata\": {\"owner\": \"";
      json_escape(out, d[i].owner);
      out += "\"}}";
    }
  }
  out += "]}";
  return out;
}

// SIGTERM/SIGINT: stop accepting, let in-flight requests drain (bounded),
// exit 0 — the same graceful contract as the daemon (reference
// cmd/gubernator/main.go:127-139 drains on SIGINT). The handler writes
// one byte into a self-pipe the accept loops poll() on: process-directed
// signals may be delivered to ANY thread, so waking a specific blocked
// accept() via EINTR is not reliable (and stripping SA_RESTART would
// instead abort in-flight reads everywhere else).
std::atomic<bool> g_shutdown{false};
int g_wake_pipe[2] = {-1, -1};
int g_peer_timeout_s = 30;  // peer-bridge round-trip deadline (see Lane)

void on_term(int) {
  g_shutdown.store(true);
  if (g_wake_pipe[1] >= 0) {
    char b = 1;
    // async-signal-safe; a full pipe just means a wakeup is already queued
    (void)!write(g_wake_pipe[1], &b, 1);
  }
}

// ----------------------------------------------------------- lanes/router
// r5 cluster shape: one request (Pending) splits into SHARDS — one
// per-owner pre-hashed (GEB6) shard per cluster node, plus one string
// (GEB1) shard for items that need the serving instance's full
// semantics (GLOBAL, validation errors, nodes without a reachable
// bridge). Each shard rides a Lane: a batching connection pool to one
// bridge endpoint (the local unix socket, or a peer's TCP bridge).
// This is the reference's every-compiled-node-routes shape
// (gubernator.go:114, hash.go:80-96) applied to the edge tier.

struct Pending {
  std::vector<Item> items;
  std::vector<Decision> decisions;  // sized by Router::execute
  int shards_left = 0;
  std::mutex m;
  std::condition_variable cv;
};

struct Shard {
  Pending* parent = nullptr;
  std::vector<uint32_t> idx;  // positions in parent->items
  bool fast = false;          // GEB6 vs GEB1 framing
  uint32_t ring_hash = 0;     // membership view this shard was routed
                              // with (echoed in GEB6 frames)
  std::string owner;          // non-self owner's gRPC addr: stamped as
                              // metadata.owner on success (parity with
                              // instance-side forwards, instance.py)
  bool failed = false;
  bool stale = false;         // failed because the bridge refused the
                              // ring view (GEBR)
};

enum class RtStatus { kOk, kFail, kStale };

// Mark a shard finished. Decision/field writes above happen-before the
// parent's wakeup via p->m. Notify while holding p->m: the waiter may
// destroy the stack Pending the instant shards_left hits zero.
void finish_shard(Shard* s, RtStatus st) {
  if (st != RtStatus::kOk) {
    s->failed = true;
    s->stale = (st == RtStatus::kStale);
  }
  Pending* p = s->parent;
  std::lock_guard<std::mutex> lk(p->m);
  if (--p->shards_left == 0) p->cv.notify_one();
}

// Bridge endpoint: a unix path (the co-located daemon) or host:port (a
// peer's TCP bridge listener).
struct Endpoint {
  bool is_unix = true;
  std::string path;  // unix path, or host
  uint16_t port = 0;
  std::string spec;  // the original string (lane registry key)
};

Endpoint parse_endpoint(const std::string& s) {
  Endpoint ep;
  ep.spec = s;
  size_t colon = s.rfind(':');
  if (colon != std::string::npos && colon + 1 < s.size()) {
    bool digits = true;
    for (size_t i = colon + 1; i < s.size(); ++i)
      if (s[i] < '0' || s[i] > '9') digits = false;
    if (digits) {
      ep.is_unix = false;
      ep.path = s.substr(0, colon);
      ep.port = (uint16_t)atoi(s.c_str() + colon + 1);
      return ep;
    }
  }
  ep.path = s;
  return ep;
}

// Connect with a bounded handshake: TCP connects are non-blocking with
// a 5s poll (a peer that fell off the network must cost one failed
// shard, not a 2-minute SYN timeout holding client requests hostage).
int connect_endpoint(const Endpoint& ep) {
  int fd;
  if (ep.is_unix) {
    fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    snprintf(addr.sun_path, sizeof addr.sun_path, "%s", ep.path.c_str());
    if (connect(fd, (sockaddr*)&addr, sizeof addr) != 0) {
      close(fd);
      return -1;
    }
    return fd;
  }
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portbuf[8];
  snprintf(portbuf, sizeof portbuf, "%u", (unsigned)ep.port);
  if (getaddrinfo(ep.path.c_str(), portbuf, &hints, &res) != 0 || !res)
    return -1;
  fd = socket(res->ai_family, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) {
    freeaddrinfo(res);
    return -1;
  }
  int rc = connect(fd, res->ai_addr, (socklen_t)res->ai_addrlen);
  freeaddrinfo(res);
  if (rc != 0 && errno == EINPROGRESS) {
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, 5000) <= 0) rc = -1;
    else {
      int err = 0;
      socklen_t elen = sizeof err;
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen);
      rc = err == 0 ? 0 : -1;
    }
  } else if (rc != 0) {
    rc = -1;
  }
  if (rc != 0) {
    close(fd);
    return -1;
  }
  int fl = fcntl(fd, F_GETFL);
  fcntl(fd, F_SETFL, fl & ~O_NONBLOCK);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  return fd;
}

bool send_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = write(fd, p, n);
    if (w < 0 && errno == EINTR) continue;  // signal mid-roundtrip
    if (w <= 0) return false;
    p += w;
    n -= (size_t)w;
  }
  return true;
}
bool recv_all(int fd, char* p, size_t n) {
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

// Ring-carrying hello ('GEBI', serve/edge_bridge.py `_hello`). The fd
// must already have a receive deadline set; on success the deadline is
// the caller's to clear.
bool read_hello(int fd, Ring* out) {
  char hdr[16];
  if (!recv_all(fd, hdr, 16)) return false;
  uint32_t magic, flags, rhash, n_nodes;
  memcpy(&magic, hdr, 4);
  memcpy(&flags, hdr + 4, 4);
  memcpy(&rhash, hdr + 8, 4);
  memcpy(&n_nodes, hdr + 12, 4);
  if (magic != kMagicHello || n_nodes > 65536) return false;
  out->fast = (flags & 1) != 0;
  out->windowed = (flags & 2) != 0;
  out->window = flags >> 16;
  if (out->windowed && out->window == 0) out->window = 1;
  if (out->window > 1024) out->window = 1024;
  out->hash = rhash;
  out->nodes.clear();
  for (uint32_t i = 0; i < n_nodes; ++i) {
    char fix[3];
    if (!recv_all(fd, fix, 3)) return false;
    Node nd;
    nd.self = fix[0] != 0;
    uint16_t glen;
    memcpy(&glen, fix + 1, 2);
    nd.grpc.resize(glen);
    if (glen && !recv_all(fd, nd.grpc.data(), glen)) return false;
    uint16_t blen;
    if (!recv_all(fd, (char*)&blen, 2)) return false;
    nd.bridge.resize(blen);
    if (blen && !recv_all(fd, nd.bridge.data(), blen)) return false;
    if (!nd.bridge.empty() && endpoint_is_ipv6ish(nd.bridge)) {
      // a misparsed endpoint would dial garbage; treat the node as
      // bridge-less (its items ride the string path) and say so once
      fprintf(stderr,
              "guber-edge: ignoring IPv6 bridge endpoint '%s' for node "
              "'%s' (bridge endpoints must be IPv4/hostname)\n",
              nd.bridge.c_str(), nd.grpc.c_str());
      nd.bridge.clear();
    }
    out->nodes.push_back(std::move(nd));
  }
  out->index();
  return true;
}

class Lane : public std::enable_shared_from_this<Lane> {
 public:
  // `workers` connections to ONE bridge endpoint pull batches from a
  // shared queue, so batch N+1 is in flight while N awaits its
  // response. Ordering across concurrent batches is no more defined
  // than the reference's concurrent goroutines — per-connection HTTP
  // pipelining stays FIFO.
  //
  // Windowed mode (r7): when the bridge's hello advertises a credit
  // window, each worker connection splits into this writer thread and
  // a detached reader thread. The writer streams frames (each stamped
  // with a frame id + send time) without waiting for responses, up to
  // `window` in flight; the reader matches responses by id — possibly
  // out of order — and finishes their shards. Edge encode/decode of
  // frame N+1 overlaps the bridge's device wait on frame N, which is
  // where the one-frame-per-roundtrip protocol burned its wall time.
  //
  // Lifetime: created through create() only. Worker threads are
  // detached and co-own the Lane via shared_ptr, so an evicted lane
  // (membership churn dropped its endpoint) is freed when its last
  // worker observes `stopping_` and exits — nobody ever joins a
  // thread that may be blocked on a wedged peer. Readers co-own the
  // Lane and their connection state the same way.
  using HelloFn = std::function<void(const Ring&)>;

  static std::shared_ptr<Lane> create(Endpoint ep, int batch_wait_us,
                                      int batch_limit, int workers,
                                      HelloFn on_hello,
                                      bool wait_connect) {
    std::shared_ptr<Lane> lane(new Lane(std::move(ep), batch_wait_us,
                                        batch_limit,
                                        std::move(on_hello)));
    for (int i = 0; i < workers; ++i)
      std::thread([lane] { lane->run(); }).detach();
    // primary lane: block until every worker attempted its eager
    // connect, so a readiness probe hitting HealthCheck right after
    // the listen port opens sees the true backend state. Peer lanes
    // skip the wait — a request must not stall on a peer's SYN.
    if (wait_connect)
      while (lane->started_.load() < workers)
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
    return lane;
  }

  // enqueue only; completion flows through finish_shard. Fast
  // (pre-hashed) and slow (string) shards ride separate queues: a
  // backend frame is all-GEB6 or all-GEB1. Returns false when the
  // lane is shutting down (the caller fails the shard).
  bool submit(Shard* s) {
    {
      std::lock_guard<std::mutex> lk(m_);
      if (stopping_) return false;
      (s->fast ? fast_queue_ : queue_).push_back(s);
      queued_items_ += s->idx.size();
    }
    cv_.notify_one();
    return true;
  }

  // Fail everything queued and tell the workers to exit after their
  // in-flight round-trips. Idempotent.
  void shutdown() {
    std::vector<Shard*> orphans;
    {
      std::lock_guard<std::mutex> lk(m_);
      if (stopping_) return;
      stopping_ = true;
      orphans.insert(orphans.end(), queue_.begin(), queue_.end());
      orphans.insert(orphans.end(), fast_queue_.begin(),
                     fast_queue_.end());
      queue_.clear();
      fast_queue_.clear();
      queued_items_ = 0;
    }
    cv_.notify_all();
    for (Shard* s : orphans) finish_shard(s, RtStatus::kFail);
  }

  bool backend_ok() const { return connected_.load() > 0; }
  // last hello's fast-path capability; false until the first connect
  bool fast_advertised() const { return fast_ok_.load(); }

 private:
  Lane(Endpoint ep, int batch_wait_us, int batch_limit,
       HelloFn on_hello)
      : ep_(std::move(ep)),
        wait_us_(batch_wait_us),
        limit_(batch_limit),
        on_hello_(std::move(on_hello)) {}
  int connect_backend() {
    int fd = connect_endpoint(ep_);
    if (fd < 0) return -1;
    // bounded hello read so a wedged bridge can't hang the worker
    timeval tv{};
    tv.tv_sec = 5;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    Ring ring;
    if (!read_hello(fd, &ring)) {
      close(fd);
      return -1;
    }
    fast_ok_.store(ring.fast);
    windowed_.store(ring.windowed);
    window_.store(ring.windowed ? (int)ring.window : 0);
    if (on_hello_) on_hello_(ring);
    if (ep_.is_unix) {
      // co-located daemon: no steady-state deadline (pre-r5 contract;
      // a wedged local daemon takes the whole node down regardless)
      tv.tv_sec = 0;
      tv.tv_usec = 0;
    } else {
      // PEER round-trips stay bounded: a peer that accepts a frame and
      // never answers (half-open connection, wedged process) must cost
      // one failed shard — not permanently absorb this worker while
      // Router::execute waits forever and client connections pile up
      // to the max-conns cap. Steady-state decides are milliseconds
      // (rungs precompile at boot), so the default 30s is generous;
      // --peer-timeout-s tunes it for slower device backends.
      tv.tv_sec = g_peer_timeout_s;
      tv.tv_usec = 0;
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    }
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    return fd;
  }

  // ---- frame builders / response fillers, shared by the one-frame
  // round-trip path (version-skewed bridges) and the windowed path ----

  static uint32_t build_fast_payload(const std::vector<Shard*>& batch,
                                     std::string& payload) {
    uint32_t n = 0;
    for (Shard* s : batch) {
      for (uint32_t i : s->idx) {
        const Item& it = s->parent->items[i];
        payload.append((const char*)&it.hash, 8);
        put_i64(payload, it.hits);
        put_i64(payload, it.limit);
        put_i64(payload, it.duration);
        payload.push_back((char)it.algorithm);
        ++n;
      }
    }
    return n;
  }

  static uint32_t build_string_payload(const std::vector<Shard*>& batch,
                                       std::string& payload) {
    uint32_t n = 0;
    for (Shard* s : batch) {
      for (uint32_t i : s->idx) {
        const Item& it = s->parent->items[i];
        put_u16(payload, (uint16_t)it.name.size());
        payload += it.name;
        put_u16(payload, (uint16_t)it.key.size());
        payload += it.key;
        put_i64(payload, it.hits);
        put_i64(payload, it.limit);
        put_i64(payload, it.duration);
        payload.push_back((char)it.algorithm);
        payload.push_back((char)it.behavior);
        ++n;
      }
    }
    return n;
  }

  static void fill_fast_decisions(std::vector<Shard*>& batch,
                                  const char* raw) {
    size_t off = 0;
    for (Shard* s : batch) {
      for (uint32_t i : s->idx) {
        Decision& d = s->parent->decisions[i];
        const char* rec = raw + off * 25;
        d.status = (uint8_t)rec[0];
        memcpy(&d.limit, rec + 1, 8);
        memcpy(&d.remaining, rec + 9, 8);
        memcpy(&d.reset_time, rec + 17, 8);
        if (!s->owner.empty()) d.owner = s->owner;
        ++off;
      }
    }
  }

  static bool read_string_decisions(int fd, uint32_t rn,
                                    std::vector<Decision>& all) {
    // wire count is attacker/desync-controlled on the windowed path
    // (the roundtrip caller checks rn==n first, kMagicWResp cannot
    // until the id lookup below the read): bound the allocation the
    // same way the GEB8 branch bounds its 25-byte records, else a
    // corrupt count bad_allocs a detached reader thread and
    // std::terminate takes the whole edge down. 29 = min bytes/record.
    if (rn > (64u << 20) / 29) return false;
    all.assign(rn, Decision());
    for (uint32_t i = 0; i < rn; ++i) {
      char fix[25];
      if (!recv_all(fd, fix, 25)) return false;
      all[i].status = (uint8_t)fix[0];
      memcpy(&all[i].limit, fix + 1, 8);
      memcpy(&all[i].remaining, fix + 9, 8);
      memcpy(&all[i].reset_time, fix + 17, 8);
      uint16_t elen;
      if (!recv_all(fd, (char*)&elen, 2)) return false;
      all[i].error.resize(elen);
      if (elen && !recv_all(fd, all[i].error.data(), elen)) return false;
      uint16_t olen;
      if (!recv_all(fd, (char*)&olen, 2)) return false;
      all[i].owner.resize(olen);
      if (olen && !recv_all(fd, all[i].owner.data(), olen)) return false;
    }
    return true;
  }

  static void fill_string_decisions(std::vector<Shard*>& batch,
                                    std::vector<Decision>& all) {
    size_t off = 0;
    for (Shard* s : batch) {
      for (uint32_t i : s->idx) {
        Decision& d = s->parent->decisions[i];
        d = std::move(all[off++]);
        // per-owner slow shards (r7): stamp the routed owner when the
        // serving node left it empty (it owned the key) — parity with
        // instance-side forwards and the fast path. A node that
        // re-forwarded a stale-routed item sets its own owner; keep it.
        if (d.owner.empty() && !s->owner.empty()) d.owner = s->owner;
      }
    }
  }

  // GEB6/GEB5: fixed 33-byte pre-hashed items out, 25-byte decisions
  // back — the daemon side is a single numpy structured-array view, so
  // per-item cost exists ONLY in this process. A GEBR reply means the
  // bridge's membership view differs from the one these shards were
  // routed with: fail them kStale (the router refreshes its ring).
  RtStatus roundtrip_fast(int fd, std::vector<Shard*>& batch) {
    std::string payload;
    uint32_t n = build_fast_payload(batch, payload);
    std::string frame;
    put_u32(frame, kMagicFastReq);
    put_u32(frame, n);
    put_u32(frame, batch[0]->ring_hash);  // batches share one view
    put_u32(frame, (uint32_t)payload.size());
    frame += payload;
    if (!send_all(fd, frame.data(), frame.size())) return RtStatus::kFail;

    char hdr[8];
    if (!recv_all(fd, hdr, 8)) return RtStatus::kFail;
    uint32_t magic, rn;
    memcpy(&magic, hdr, 4);
    memcpy(&rn, hdr + 4, 4);
    if (magic == kMagicStale) return RtStatus::kStale;
    if (magic != kMagicFastResp || rn != n) return RtStatus::kFail;
    std::vector<char> raw(25u * rn);
    if (rn && !recv_all(fd, raw.data(), raw.size()))
      return RtStatus::kFail;
    fill_fast_decisions(batch, raw.data());
    return RtStatus::kOk;
  }

  RtStatus roundtrip(int fd, std::vector<Shard*>& batch) {
    std::string payload;
    uint32_t n = build_string_payload(batch, payload);
    std::string frame;
    put_u32(frame, kMagicReq);
    put_u32(frame, n);
    put_u32(frame, (uint32_t)payload.size());
    frame += payload;
    if (!send_all(fd, frame.data(), frame.size())) return RtStatus::kFail;

    char hdr[8];
    if (!recv_all(fd, hdr, 8)) return RtStatus::kFail;
    uint32_t magic, rn;
    memcpy(&magic, hdr, 4);
    memcpy(&rn, hdr + 4, 4);
    if (magic != kMagicResp || rn != n) return RtStatus::kFail;
    std::vector<Decision> all;
    if (!read_string_decisions(fd, rn, all)) return RtStatus::kFail;
    fill_string_decisions(batch, all);
    return RtStatus::kOk;
  }

  // ---- windowed connection state (r7) ----
  // Co-owned by the writer worker and its detached reader thread; the
  // last owner's destructor closes the fd. kill() (shutdown both
  // directions) is safe to call while the other thread is blocked on
  // the fd — it unblocks reads without invalidating the descriptor.
  struct ConnState {
    int fd = -1;
    std::mutex m;
    std::condition_variable cv;
    struct Entry {
      std::vector<Shard*> batch;
      bool fast = false;
      uint32_t n = 0;
      // when the frame was registered: the reader's receive timeout
      // must measure the oldest frame's OWN wait, not an idle-parked
      // countdown a fresh frame happened to inherit
      std::chrono::steady_clock::time_point sent{};
    };
    std::unordered_map<uint32_t, Entry> inflight;  // frame_id -> entry
    bool dead = false;                             // guarded by m
    ~ConnState() {
      if (fd >= 0) close(fd);
    }
    void kill() { ::shutdown(fd, SHUT_RDWR); }
  };

  // Fail every frame still in flight (connection died, stream
  // desynced, or GEBR refused the routed view). Entries the writer
  // reclaimed on a failed send are already gone from the map, so no
  // shard is ever finished twice.
  static void drain_windowed(const std::shared_ptr<ConnState>& st,
                             RtStatus rst) {
    std::vector<ConnState::Entry> orphans;
    {
      std::lock_guard<std::mutex> lk(st->m);
      st->dead = true;
      for (auto& kv : st->inflight)
        orphans.push_back(std::move(kv.second));
      st->inflight.clear();
    }
    st->cv.notify_all();
    for (auto& e : orphans)
      for (Shard* s : e.batch) finish_shard(s, rst);
  }

  // Reader thread: match windowed responses to in-flight frames by id
  // (out-of-order completion is the point), finish their shards, and
  // release writer credit. Any protocol surprise or read failure kills
  // the connection and fails whatever is still outstanding. On peer
  // connections SO_RCVTIMEO bounds a wedged bridge; a timeout with
  // NOTHING in flight is just an idle connection and keeps waiting.
  void reader_loop(std::shared_ptr<ConnState> st) {
    auto recv_exact = [&](char* p, size_t nbytes, bool idle_ok) -> bool {
      size_t got = 0;
      while (got < nbytes) {
        ssize_t r = read(st->fd, p + got, nbytes - got);
        if (r > 0) {
          got += (size_t)r;
          continue;
        }
        if (r < 0 && errno == EINTR) continue;
        if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK) &&
            idle_ok && got == 0) {
          bool keep_waiting;
          {
            std::lock_guard<std::mutex> lk(st->m);
            if (st->dead) {
              keep_waiting = false;
            } else if (st->inflight.empty()) {
              keep_waiting = true;  // healthy idle conn: keep parking
            } else {
              // the SO_RCVTIMEO countdown that just expired mostly
              // measured idle time if a frame was sent moments ago —
              // only declare the bridge wedged once the OLDEST
              // in-flight frame has itself waited out the timeout
              auto oldest =
                  std::chrono::steady_clock::time_point::max();
              for (const auto& kv : st->inflight)
                if (kv.second.sent < oldest) oldest = kv.second.sent;
              keep_waiting = std::chrono::steady_clock::now() - oldest <
                             std::chrono::seconds(g_peer_timeout_s);
            }
          }
          if (keep_waiting) continue;
        }
        return false;
      }
      return true;
    };
    std::vector<char> raw;
    RtStatus fail_as = RtStatus::kFail;
    while (true) {
      char hdr[8];
      if (!recv_exact(hdr, 8, /*idle_ok=*/true)) break;
      uint32_t magic, second;
      memcpy(&magic, hdr, 4);
      memcpy(&second, hdr + 4, 4);
      if (magic == kMagicStale) {
        // second = the refused frame id; every outstanding frame was
        // routed with the same stale view, so they all fail kStale
        // (the router wakes its refresher)
        fail_as = RtStatus::kStale;
        break;
      }
      char fidb[4];
      uint32_t fid;
      if (magic == kMagicWFastResp) {
        if (!recv_exact(fidb, 4, false)) break;
        memcpy(&fid, fidb, 4);
        if (second > (uint32_t)(64 << 20) / 25) break;  // absurd count
        raw.resize((size_t)25 * second);
        if (second && !recv_exact(raw.data(), raw.size(), false)) break;
        ConnState::Entry e;
        bool ok = false;
        {
          std::lock_guard<std::mutex> lk(st->m);
          auto it = st->inflight.find(fid);
          if (it != st->inflight.end() && it->second.fast &&
              it->second.n == second) {
            e = std::move(it->second);
            st->inflight.erase(it);
            ok = true;
          }
        }
        if (!ok) break;  // unknown id / kind mismatch: stream desynced
        st->cv.notify_all();
        fill_fast_decisions(e.batch, raw.data());
        for (Shard* s : e.batch) finish_shard(s, RtStatus::kOk);
        continue;
      }
      if (magic == kMagicWResp) {
        if (!recv_exact(fidb, 4, false)) break;
        memcpy(&fid, fidb, 4);
        std::vector<Decision> all;
        if (!read_string_decisions(st->fd, second, all)) break;
        ConnState::Entry e;
        bool ok = false;
        {
          std::lock_guard<std::mutex> lk(st->m);
          auto it = st->inflight.find(fid);
          if (it != st->inflight.end() && !it->second.fast &&
              it->second.n == second) {
            e = std::move(it->second);
            st->inflight.erase(it);
            ok = true;
          }
        }
        if (!ok) break;
        st->cv.notify_all();
        fill_string_decisions(e.batch, all);
        for (Shard* s : e.batch) finish_shard(s, RtStatus::kOk);
        continue;
      }
      break;  // unknown magic: desynced
    }
    st->kill();  // unblock a writer mid-send; sends now fail fast
    drain_windowed(st, fail_as);
  }

  // Stream one batch as a windowed frame: register it in the in-flight
  // table (credit-gated), send, and return without waiting — the
  // reader finishes the shards whenever the response lands. Returns
  // false when the connection must be dropped; the batch's shards are
  // finished on every failure path.
  bool send_windowed(const std::shared_ptr<ConnState>& st,
                     std::vector<Shard*>& batch, bool fast,
                     uint32_t& next_frame_id) {
    std::string payload;
    uint32_t n = fast ? build_fast_payload(batch, payload)
                      : build_string_payload(batch, payload);
    uint32_t window = (uint32_t)std::max(1, window_.load());
    uint32_t fid;
    {
      std::unique_lock<std::mutex> lk(st->m);
      // credit gate: at most `window` frames in flight per connection
      // (the bridge advertises the window it is willing to serve
      // concurrently; beyond it frames would only queue in its socket)
      st->cv.wait(lk, [&] {
        return st->dead || st->inflight.size() < window;
      });
      if (st->dead) {
        lk.unlock();
        for (Shard* s : batch) finish_shard(s, RtStatus::kFail);
        return false;
      }
      fid = next_frame_id++;
      auto& e = st->inflight[fid];
      e.batch = batch;
      e.fast = fast;
      e.n = n;
      e.sent = std::chrono::steady_clock::now();
    }
    std::string frame;
    uint64_t t_sent = mono_us();
    if (fast) {
      put_u32(frame, kMagicWFastReq);
      put_u32(frame, n);
      put_u32(frame, fid);
      put_u32(frame, batch[0]->ring_hash);  // batches share one view
      frame.append((const char*)&t_sent, 8);
      put_u32(frame, (uint32_t)payload.size());
    } else {
      put_u32(frame, kMagicWReq);
      put_u32(frame, n);
      put_u32(frame, fid);
      frame.append((const char*)&t_sent, 8);
      put_u32(frame, (uint32_t)payload.size());
    }
    frame += payload;
    if (!send_all(st->fd, frame.data(), frame.size())) {
      // a partial write desyncs the stream: reclaim OUR frame if the
      // reader hasn't already drained it, then drop the connection
      bool mine;
      {
        std::lock_guard<std::mutex> lk(st->m);
        mine = st->inflight.erase(fid) > 0;
      }
      if (mine)
        for (Shard* s : batch) finish_shard(s, RtStatus::kFail);
      return false;
    }
    return true;
  }

  void run() {
    int fd = connect_backend();
    if (fd >= 0) connected_.fetch_add(1);
    std::shared_ptr<ConnState> st;  // non-null = windowed connection
    uint32_t next_frame_id = 1;
    auto adopt_windowed = [&] {
      if (fd >= 0 && windowed_.load()) {
        st = std::make_shared<ConnState>();
        st->fd = fd;
        auto self = shared_from_this();
        auto stc = st;
        std::thread([self, stc] { self->reader_loop(stc); }).detach();
      }
    };
    auto drop_conn = [&] {
      if (st) {
        st->kill();  // reader fails anything left in flight and exits
        st.reset();  // last ConnState owner closes the fd
      } else if (fd >= 0) {
        close(fd);
      }
      if (fd >= 0) connected_.fetch_sub(1);
      fd = -1;
    };
    adopt_windowed();
    started_.fetch_add(1);
    while (true) {
      std::vector<Shard*> batch;
      bool fast = false;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait(lk, [this] {
          return stopping_ || !queue_.empty() || !fast_queue_.empty();
        });
        if (stopping_) break;
        // batch window: flush at limit_ items or after wait_us_
        if ((int)queued_items_ < limit_ && wait_us_ > 0) {
          cv_.wait_for(lk, std::chrono::microseconds(wait_us_), [this] {
            return stopping_ || (int)queued_items_ >= limit_;
          });
        }
        // one frame kind per round-trip; drain the deeper queue first
        // (both nonempty alternates naturally as they drain)
        fast = fast_queue_.size() >= queue_.size() && !fast_queue_.empty();
        auto& q = fast ? fast_queue_ : queue_;
        size_t take_items = 0;
        while (!q.empty()) {
          Shard* head = q.front();
          size_t next = head->idx.size();
          if (!batch.empty() && (int)(take_items + next) > limit_) break;
          // a fast frame carries ONE ring fingerprint: shards routed
          // under different membership views never co-batch
          if (fast && !batch.empty() &&
              head->ring_hash != batch[0]->ring_hash)
            break;
          batch.push_back(head);
          take_items += next;
          q.pop_front();
          if ((int)take_items >= limit_) break;
        }
        queued_items_ -= take_items;
      }
      if (batch.empty()) continue;
      if (fd < 0) {
        fd = connect_backend();
        if (fd >= 0) {
          connected_.fetch_add(1);
          adopt_windowed();
        }
      }
      if (fast && fd >= 0 && !fast_ok_.load()) {
        // safety net (the router folds non-fast peers' items into the
        // slow path at routing time): never put a pre-hashed frame on
        // a bridge that didn't advertise it — and don't churn the
        // healthy connection either; nothing was sent
        for (Shard* s : batch) finish_shard(s, RtStatus::kFail);
        continue;
      }
      if (st) {
        // windowed: stream the frame and immediately collect the next
        // batch — the reader completes it whenever the bridge answers
        if (!send_windowed(st, batch, fast, next_frame_id)) drop_conn();
        continue;
      }
      RtStatus rst = RtStatus::kFail;
      if (fd >= 0) {
        rst = fast ? roundtrip_fast(fd, batch) : roundtrip(fd, batch);
        if (rst != RtStatus::kOk) {
          // GEBR also closes bridge-side; reconnecting re-reads the
          // hello, which (on the primary lane) republishes the ring
          close(fd);
          fd = -1;
          connected_.fetch_sub(1);
        }
      }
      for (Shard* s : batch) finish_shard(s, rst);
    }
    if (st) {
      // bounded drain: let in-flight windowed frames finish before the
      // kill, preserving shutdown()'s in-flight-completes contract
      std::unique_lock<std::mutex> lk(st->m);
      st->cv.wait_for(lk, std::chrono::seconds(5), [&] {
        return st->inflight.empty() || st->dead;
      });
    }
    drop_conn();
  }

  Endpoint ep_;
  int wait_us_;
  int limit_;
  std::atomic<int> connected_{0};
  std::atomic<int> started_{0};
  std::atomic<bool> fast_ok_{false};
  // windowed capability from the last hello (per-lane; connections made
  // before a bridge upgrade keep their negotiated mode)
  std::atomic<bool> windowed_{false};
  std::atomic<int> window_{0};
  HelloFn on_hello_;
  std::mutex m_;
  std::condition_variable cv_;
  bool stopping_ = false;  // guarded by m_
  std::deque<Shard*> queue_;
  std::deque<Shard*> fast_queue_;
  size_t queued_items_ = 0;
};

class Router {
 public:
  Router(const std::string& primary, int batch_wait_us, int batch_limit,
         int workers, int refresh_ms)
      : primary_ep_(parse_endpoint(primary)),
        wait_us_(batch_wait_us),
        limit_(batch_limit),
        workers_(workers),
        refresh_ms_(refresh_ms) {
    primary_ = Lane::create(
        primary_ep_, wait_us_, limit_, workers_,
        [this](const Ring& r) { publish_ring(r); },
        /*wait_connect=*/true);
  }

  void start_refresher() {
    // ONE long-lived refresher: re-reads the ring every refresh_ms_,
    // or immediately when request_refresh() wakes it (a stale frame
    // was refused). Keeps thread churn off the request path entirely.
    std::thread([this] {
      while (!g_shutdown.load()) {
        {
          std::unique_lock<std::mutex> lk(refresh_cv_m_);
          refresh_cv_.wait_for(
              lk, std::chrono::milliseconds(refresh_ms_),
              [this] { return refresh_asap_; });
          refresh_asap_ = false;
        }
        refresh_ring();
      }
    }).detach();
  }

  bool backend_ok() const { return primary_->backend_ok(); }

  // Split into shards, route, wait. Returns false only when EVERY
  // shard failed (callers answer 503/UNAVAILABLE, matching the
  // single-backend behavior); partial failures become per-item errors,
  // like instance-side peer forwards (serve/instance.py forward()).
  bool execute(Pending& p) {
    if (p.items.empty()) return true;
    p.decisions.assign(p.items.size(), Decision());
    std::shared_ptr<const Ring> ring = current_ring();

    Shard slow;
    slow.parent = &p;
    std::map<int, Shard> fast_by_node;
    // per-owner STRING shards (r7 slow-path owner batching): items
    // that fall off the pre-hashed path (fast kill switch, a peer
    // that doesn't advertise it, mixed fleets) but whose OWNER has a
    // reachable bridge ship as string frames straight to that owner —
    // the owner serves them locally through its full instance —
    // instead of funnelling through the primary's instance and a
    // second gRPC forwarding hop. String frames carry no ring
    // fingerprint: a stale-routed item is simply forwarded by its
    // receiver, so this path needs no GEBR machinery.
    std::map<int, Shard> slow_by_node;
    std::map<int, std::shared_ptr<Lane>> lane_by_node;
    auto lane_at = [&](int node) -> std::shared_ptr<Lane>& {
      auto lit = lane_by_node.find(node);
      if (lit == lane_by_node.end())
        lit = lane_by_node
                  .emplace(node, lane_for(ring->nodes[node].bridge))
                  .first;
      return lit->second;
    };
    for (uint32_t i = 0; i < p.items.size(); ++i) {
      Item& it = p.items[i];
      // GLOBAL needs the instance's replica/gossip path; empty fields
      // need its per-item validation errors — both stay on the
      // primary. Ownership itself only needs the ring (carried by the
      // hello regardless of the fast capability).
      bool routable = ring && it.behavior != 2 && !it.name.empty() &&
                      !it.key.empty();
      int node = -1;
      if (routable) {
        node = ring->owner(it.name, it.key);
        routable = node >= 0;
      }
      bool eligible = routable && ring->fast;
      if (eligible && !ring->nodes[node].self) {
        const Node& nd = ring->nodes[node];
        if (nd.bridge.empty()) {
          eligible = false;
        } else {
          // a departed endpoint (nullptr: this ring snapshot predates
          // an eviction) or a peer that hasn't advertised the fast
          // path (mixed fleet, or its lane hasn't completed the first
          // hello yet) gets its items over the slow path instead of a
          // doomed pre-hashed frame
          auto& lane = lane_at(node);
          if (!lane || !lane->fast_advertised()) eligible = false;
        }
      }
      if (eligible) {
        Shard& sh = fast_by_node[node];
        if (sh.parent == nullptr) {
          sh.parent = &p;
          sh.fast = true;
          sh.ring_hash = ring->hash;
          if (!ring->nodes[node].self)
            sh.owner = ring->nodes[node].grpc;
        }
        sh.idx.push_back(i);
        it.hash = slot_hash(it.name, it.key);
        continue;
      }
      // slow path: per-owner where the owner's bridge is reachable,
      // the primary's string frame otherwise
      if (routable && !ring->nodes[node].self &&
          !ring->nodes[node].bridge.empty() && lane_at(node)) {
        Shard& sh = slow_by_node[node];
        if (sh.parent == nullptr) {
          sh.parent = &p;
          sh.owner = ring->nodes[node].grpc;
        }
        sh.idx.push_back(i);
        continue;
      }
      slow.idx.push_back(i);
    }

    // Degraded-cluster heuristic: when the ONLY fast destination is
    // this node and most items fold to the string path anyway (peers
    // without reachable bridges — e.g. a cluster without
    // GUBER_EDGE_TCP), splitting buys one small array frame at the
    // cost of a second backend round-trip per request; measured on the
    // 6-node no-bridge topology that trade LOSES (~15% door
    // throughput), so fold the minority self-fast items into the slow
    // frame and send ONE frame, the pre-r5 shape. Single-node (slow
    // minority) and real clusters (remote fast shards exist) keep the
    // split.
    if (fast_by_node.size() == 1 && !slow.idx.empty()) {
      auto it = fast_by_node.begin();
      if (ring->nodes[it->first].self &&
          it->second.idx.size() < slow.idx.size()) {
        for (uint32_t i : it->second.idx) slow.idx.push_back(i);
        std::sort(slow.idx.begin(), slow.idx.end());
        fast_by_node.clear();
      }
    }

    int n_shards = (slow.idx.empty() ? 0 : 1) +
                   (int)slow_by_node.size() + (int)fast_by_node.size();
    {
      std::lock_guard<std::mutex> lk(p.m);
      p.shards_left = n_shards;
    }
    if (!slow.idx.empty() && !primary_->submit(&slow))
      finish_shard(&slow, RtStatus::kFail);
    for (auto& [node, sh] : slow_by_node) {
      if (!lane_by_node.at(node)->submit(&sh))
        finish_shard(&sh, RtStatus::kFail);
    }
    for (auto& [node, sh] : fast_by_node) {
      std::shared_ptr<Lane> lane = ring->nodes[node].self
                                       ? primary_
                                       : lane_by_node.at(node);
      if (!lane->submit(&sh)) finish_shard(&sh, RtStatus::kFail);
    }
    {
      std::unique_lock<std::mutex> lk(p.m);
      p.cv.wait(lk, [&p] { return p.shards_left == 0; });
    }

    bool any_ok = false, saw_stale = false;
    auto fill_errors = [&](const Shard& s, const std::string& why) {
      for (uint32_t i : s.idx) {
        Decision& d = p.decisions[i];
        d = Decision();
        d.error = "while fetching rate limit '" + p.items[i].name + "_" +
                  p.items[i].key + "' from peer - '" + why + "'";
      }
    };
    // string shards can fail kStale too (r7): a GEBR refusing a fast
    // frame drains EVERY frame in flight on that connection as stale,
    // string frames included — those must surface as the per-item
    // retry error and wake the refresher, not read as a dead backend
    if (!slow.idx.empty()) {
      if (slow.failed) {
        saw_stale |= slow.stale;
        fill_errors(slow, slow.stale
                              ? "edge: cluster membership changed; retry"
                              : "edge backend unavailable");
      } else {
        any_ok = true;
      }
    }
    for (auto& [node, sh] : slow_by_node) {
      (void)node;
      if (!sh.failed) {
        any_ok = true;
        continue;
      }
      saw_stale |= sh.stale;
      fill_errors(sh, sh.stale
                          ? "edge: cluster membership changed; retry"
                          : "edge: bridge " + sh.owner + " unreachable");
    }
    for (auto& [node, sh] : fast_by_node) {
      (void)node;
      if (!sh.failed) {
        any_ok = true;
        continue;
      }
      saw_stale |= sh.stale;
      fill_errors(sh, sh.stale
                          ? "edge: cluster membership changed; retry"
                          : "edge: bridge " +
                                (sh.owner.empty() ? primary_ep_.spec
                                                  : sh.owner) +
                                " unreachable");
    }
    if (saw_stale) {
      // refresh OFF the request path: connect_endpoint + hello can
      // block up to ~10s against a wedged primary, and the per-item
      // "membership changed; retry" errors are already composed — the
      // reply must not wait on the re-read. Waking the long-lived
      // refresher costs a notify, not a thread.
      {
        std::lock_guard<std::mutex> lk(refresh_cv_m_);
        refresh_asap_ = true;
      }
      refresh_cv_.notify_one();
    }
    // a stale ring is a transient routing miss, not a dead backend:
    // surface the per-item retry errors as a normal response instead
    // of a blanket 503
    return any_ok || saw_stale;
  }

 private:
  std::shared_ptr<const Ring> current_ring() {
    std::lock_guard<std::mutex> lk(ring_m_);
    return ring_;
  }

  void publish_ring(const Ring& r) {
    auto next = std::make_shared<Ring>(r);
    {
      std::lock_guard<std::mutex> lk(ring_m_);
      ring_ = next;
    }
    // Evict lanes whose endpoint left the membership: under pod-IP
    // discovery (k8s rollouts) endpoints are never reused, so an
    // unevicted lane strands its worker threads forever. In-flight
    // round-trips finish; queued shards fail; the Lane frees itself
    // when its last detached worker exits.
    std::vector<std::shared_ptr<Lane>> evicted;
    {
      std::lock_guard<std::mutex> lk(lanes_m_);
      for (auto it = lanes_.begin(); it != lanes_.end();) {
        bool live = false;
        for (const Node& nd : next->nodes)
          if (!nd.self && nd.bridge == it->first) live = true;
        if (live) {
          ++it;
        } else {
          evicted.push_back(it->second);
          it = lanes_.erase(it);
        }
      }
    }
    for (auto& lane : evicted) lane->shutdown();
    // pre-warm lanes for every peer bridge in the new membership so
    // the first request after a ring change doesn't ride the slow
    // path while the lane's first hello is still in flight
    for (const Node& nd : next->nodes)
      if (!nd.self && !nd.bridge.empty()) lane_for(nd.bridge);
  }

  // one short-lived hello round-trip to the primary bridge, debounced:
  // concurrent stale shards must not stampede the bridge with connects
  void refresh_ring() {
    auto now = std::chrono::steady_clock::now();
    {
      std::lock_guard<std::mutex> lk(refresh_m_);
      if (now - last_refresh_ < std::chrono::milliseconds(50)) return;
      last_refresh_ = now;
    }
    int fd = connect_endpoint(primary_ep_);
    if (fd < 0) return;
    timeval tv{};
    tv.tv_sec = 5;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    Ring r;
    if (read_hello(fd, &r)) publish_ring(r);
    close(fd);
  }

  // get-or-create the lane for a peer bridge endpoint; publish_ring
  // evicts lanes for departed endpoints. The returned shared_ptr keeps
  // a lane usable by an in-flight execute() even if eviction races it
  // (submit on a stopped lane fails cleanly instead of dangling).
  // Returns nullptr for an endpoint NOT in the CURRENT ring: an
  // in-flight execute() routing with a pre-eviction ring must not
  // resurrect a just-evicted lane — the recreated lane's detached
  // workers would sit on a dead peer until the next ring publish
  // (ADVICE r5 #1); the caller folds those items into the slow path.
  std::shared_ptr<Lane> lane_for(const std::string& spec) {
    // ring before lanes_m_ — current_ring() takes ring_m_, and
    // publish_ring never holds ring_m_ while taking lanes_m_
    std::shared_ptr<const Ring> ring = current_ring();
    std::lock_guard<std::mutex> lk(lanes_m_);
    auto it = lanes_.find(spec);
    if (it != lanes_.end()) return it->second;
    bool member = false;
    if (ring)
      for (const Node& nd : ring->nodes)
        if (!nd.self && nd.bridge == spec) member = true;
    if (!member) return nullptr;
    auto lane =
        Lane::create(parse_endpoint(spec), wait_us_, limit_, workers_,
                     nullptr, /*wait_connect=*/false);
    lanes_.emplace(spec, lane);
    return lane;
  }

  Endpoint primary_ep_;
  std::shared_ptr<Lane> primary_;
  int wait_us_;
  int limit_;
  int workers_;
  int refresh_ms_;
  std::mutex ring_m_;
  std::shared_ptr<const Ring> ring_;
  std::mutex lanes_m_;
  std::unordered_map<std::string, std::shared_ptr<Lane>> lanes_;
  std::mutex refresh_m_;
  std::chrono::steady_clock::time_point last_refresh_{};
  std::mutex refresh_cv_m_;
  std::condition_variable refresh_cv_;
  bool refresh_asap_ = false;  // guarded by refresh_cv_m_
};

// -------------------------------------------------------------- HTTP layer

// Returns false when the reply could not be fully written (e.g. the
// client stopped reading and SO_SNDTIMEO expired) — the caller must
// close the connection rather than let a non-reading client pin the
// thread or desync the stream.
bool http_reply(int fd, int code, const char* reason,
                const std::string& body) {
  char hdr[256];
  int n = snprintf(hdr, sizeof hdr,
                   "HTTP/1.1 %d %s\r\n"
                   "Content-Type: application/json\r\n"
                   "Content-Length: %zu\r\n\r\n",
                   code, reason, body.size());
  std::string out;
  out.reserve((size_t)n + body.size());
  out.append(hdr, (size_t)n);
  out.append(body);
  size_t off = 0;
  while (off < out.size()) {
    ssize_t w = write(fd, out.data() + off, out.size() - off);
    if (w <= 0) return false;
    off += (size_t)w;
  }
  return true;
}

// Thread-per-connection needs bounds or a slow-loris client pins OS
// threads forever: every accepted socket gets a receive timeout (read()
// returns EAGAIN and the connection closes) and the total connection
// count is capped (excess accepts are answered 503 and closed).
std::atomic<int> g_conns{0};
int g_max_conns = 4096;
int g_recv_timeout_s = 60;

struct ConnGuard {
  ~ConnGuard() { g_conns.fetch_sub(1, std::memory_order_relaxed); }
};

void serve_connection(int fd, Router* router) {
  ConnGuard guard;
  std::string buf;
  char tmp[16384];
  while (true) {
    // Per-request wall deadline: SO_RCVTIMEO alone only bounds a single
    // idle read — a client trickling one byte per interval would renew
    // it forever. A whole request (headers + body) must complete within
    // the budget or the connection closes.
    const auto req_deadline = std::chrono::steady_clock::now() +
                              std::chrono::seconds(g_recv_timeout_s);
    auto expired = [&] {
      return std::chrono::steady_clock::now() > req_deadline;
    };
    // read until end of headers
    size_t hdr_end;
    while ((hdr_end = buf.find("\r\n\r\n")) == std::string::npos) {
      ssize_t r = read(fd, tmp, sizeof tmp);
      if (r <= 0 || expired()) {
        close(fd);
        return;
      }
      buf.append(tmp, (size_t)r);
      if (buf.size() > (16u << 20)) { close(fd); return; }
    }
    std::string head = buf.substr(0, hdr_end);
    bool has_clen = false;
    size_t content_len = 0;
    {
      // case-insensitive content-length scan
      std::string lower = head;
      for (char& c : lower) c = (char)tolower(c);
      size_t pos = lower.find("content-length:");
      if (pos != std::string::npos) {
        has_clen = true;
        content_len = strtoull(lower.c_str() + pos + 15, nullptr, 10);
      }
    }
    bool is_post = head.rfind("POST", 0) == 0;
    if (is_post && !has_clen) {
      // no chunked support: fail clean and close (a desynced keep-alive
      // stream would mis-parse the chunk body as the next request)
      http_reply(fd, 411, "Length Required",
                 "{\"error\": \"Content-Length required\"}");
      close(fd);
      return;
    }
    if (content_len > (16u << 20)) {
      http_reply(fd, 413, "Payload Too Large",
                 "{\"error\": \"body exceeds 16 MiB\"}");
      close(fd);
      return;
    }
    size_t body_start = hdr_end + 4;
    while (buf.size() < body_start + content_len) {
      ssize_t r = read(fd, tmp, sizeof tmp);
      if (r <= 0 || expired()) { close(fd); return; }
      buf.append(tmp, (size_t)r);
    }

    bool is_post_grl = head.rfind("POST /v1/GetRateLimits", 0) == 0;
    bool is_health = head.rfind("GET /v1/HealthCheck", 0) == 0;
    bool sent;
    if (is_health) {
      sent = http_reply(fd, 200, "OK",
                        router->backend_ok()
                            ? "{\"status\": \"healthy\", \"message\": "
                              "\"edge\", \"peerCount\": 0}"
                            : "{\"status\": \"unhealthy\", \"message\": "
                              "\"backend unreachable\", \"peerCount\": 0}");
    } else if (!is_post_grl) {
      sent = http_reply(fd, 404, "Not Found", "{\"error\": \"not found\"}");
    } else {
      Pending p;
      if (!parse_get_rate_limits(buf.data() + body_start, content_len,
                                 p.items)) {
        sent = http_reply(fd, 400, "Bad Request",
                          "{\"error\": \"malformed JSON\"}");
      } else if ([&] {
                   for (const Item& it : p.items)
                     if (it.name.size() > 65535 || it.key.size() > 65535)
                       return true;
                   return false;
                 }()) {
        sent = http_reply(fd, 400, "Bad Request",
                          "{\"error\": \"name/unique_key exceeds 65535 "
                          "bytes\"}");
      } else if (p.items.empty()) {
        sent = http_reply(fd, 200, "OK", "{\"responses\": []}");
      } else {
        if (!router->execute(p)) {
          sent = http_reply(fd, 503, "Service Unavailable",
                            "{\"error\": \"backend unavailable\"}");
        } else {
          sent = http_reply(fd, 200, "OK",
                            render_responses(p.decisions.data(),
                                             p.decisions.size()));
        }
      }
    }
    if (!sent) {  // client stopped reading (SO_SNDTIMEO expired)
      close(fd);
      return;
    }
    buf.erase(0, body_start + content_len);
  }
}

// gRPC/HTTP2 terminator (serve_grpc_connection + HPACK + proto codec);
// shares Item/Decision/Router above, hence the in-namespace include
#include "h2_grpc.inc"

}  // namespace

static const char kUsage[] =
    "guber-edge: native HTTP/JSON + gRPC front door for gubernator-tpu\n"
    "  --listen PORT          TCP port to serve HTTP on (default 8080)\n"
    "  --grpc-listen PORT     TCP port to serve gRPC (h2c) on "
    "(default 0 = off)\n"
    "  --backend PATH         daemon's edge unix socket "
    "(default /tmp/guber-edge.sock)\n"
    "  --batch-wait-us N      cross-connection batch window (default 500)\n"
    "  --ring-refresh-ms N    cluster ring re-read period (default 1000)\n"
    "  --peer-timeout-s N     peer-bridge round-trip deadline "
    "(default 30)\n"
    "  --batch-limit N        max requests per backend frame (default 1000)\n"
    "  --workers N            pipelined backend connections (default 2)\n"
    "  --max-conns N          client connection cap (default 4096)\n"
    "  --recv-timeout-s N     per-read client timeout (default 60)\n";

// Strict non-negative integer parse: a typo'd VALUE ("80O0", "abc")
// must fail loudly, not atoi-truncate into serving the wrong port.
static bool parse_int_flag(const char* v, int* out) {
  char* end = nullptr;
  long x = strtol(v, &end, 10);
  if (end == v || *end != '\0' || x < 0 || x > (1L << 30)) return false;
  *out = static_cast<int>(x);
  return true;
}

int main(int argc, char** argv) {
  // a client that resets its connection mid-write must fail that write
  // (EPIPE), not SIGPIPE-kill the whole edge — e.g. the GOAWAY sent
  // while tearing down an h2 connection the peer already closed
  signal(SIGPIPE, SIG_IGN);
  if (pipe(g_wake_pipe) != 0) {
    perror("pipe");
    return 1;
  }
  // SA_RESTART kept: in-flight reads/writes on connection and batcher
  // threads must not be aborted by the shutdown signal; the self-pipe
  // wakes the accept loops regardless of which thread took delivery
  struct sigaction sa{};
  sa.sa_handler = on_term;
  sa.sa_flags = SA_RESTART;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);
  int port = 8080;
  int grpc_port = 0;
  std::string backend = "/tmp/guber-edge.sock";
  int batch_wait_us = 500;
  int batch_limit = 1000;
  int workers = 2;
  int ring_refresh_ms = 1000;
  for (int i = 1; i < argc; i += 2) {
    std::string a = argv[i];
    if (a == "--help" || a == "-h") {
      fputs(kUsage, stdout);
      return 0;
    }
    if (i + 1 >= argc) {
      fprintf(stderr, "missing value for %s\n%s", a.c_str(), kUsage);
      return 2;
    }
    const char* v = argv[i + 1];
    bool ok = true;
    if (a == "--listen") ok = parse_int_flag(v, &port);
    else if (a == "--grpc-listen") ok = parse_int_flag(v, &grpc_port);
    else if (a == "--backend") backend = v;
    else if (a == "--batch-wait-us") ok = parse_int_flag(v, &batch_wait_us);
    else if (a == "--ring-refresh-ms") {
      ok = parse_int_flag(v, &ring_refresh_ms);
      ring_refresh_ms = std::max(50, ring_refresh_ms);
    }
    else if (a == "--peer-timeout-s") {
      ok = parse_int_flag(v, &g_peer_timeout_s);
      g_peer_timeout_s = std::max(1, g_peer_timeout_s);
    }
    else if (a == "--batch-limit") ok = parse_int_flag(v, &batch_limit);
    else if (a == "--workers") {
      ok = parse_int_flag(v, &workers);
      workers = std::max(1, workers);
    } else if (a == "--max-conns") {
      ok = parse_int_flag(v, &g_max_conns);
      g_max_conns = std::max(1, g_max_conns);
    } else if (a == "--recv-timeout-s") {
      ok = parse_int_flag(v, &g_recv_timeout_s);
      g_recv_timeout_s = std::max(1, g_recv_timeout_s);
    } else {
      // a typo'd flag silently ignored would serve with defaults — fail
      fprintf(stderr, "unknown flag %s\n%s", a.c_str(), kUsage);
      return 2;
    }
    if (!ok) {
      fprintf(stderr, "bad value for %s: %s\n%s", a.c_str(), v, kUsage);
      return 2;
    }
  }

  // the frame protocol splits host:port on the LAST colon, so an IPv6
  // --backend ('[::1]:9100', bare '::1') would misparse silently
  // (bracketed host handed to the resolver, or the address mistaken
  // for a unix path). Refuse at config parse time (ADVICE r5 #2).
  if (endpoint_is_ipv6ish(backend)) {
    fprintf(stderr,
            "--backend '%s' looks like an IPv6 literal; the backend must "
            "be a unix socket path or an IPv4/hostname 'host:port'\n",
            backend.c_str());
    return 2;
  }

  // bind BEFORE constructing the router: its primary lane blocks on
  // eager worker connects, and a bind failure should exit before
  // spawning any detached lane threads
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) {
    perror("socket");
    return 1;
  }
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(srv, (sockaddr*)&addr, sizeof addr) != 0 || listen(srv, 512) != 0) {
    perror("bind/listen");
    return 1;
  }

  // gRPC listener binds up front too (fail fast on a taken port)
  int grpc_srv = -1;
  if (grpc_port > 0) {
    grpc_srv = socket(AF_INET, SOCK_STREAM, 0);
    if (grpc_srv < 0) {
      perror("socket");
      return 1;
    }
    setsockopt(grpc_srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in gaddr{};
    gaddr.sin_family = AF_INET;
    gaddr.sin_addr.s_addr = htonl(INADDR_ANY);
    gaddr.sin_port = htons((uint16_t)grpc_port);
    if (bind(grpc_srv, (sockaddr*)&gaddr, sizeof gaddr) != 0 ||
        listen(grpc_srv, 512) != 0) {
      perror("bind/listen (grpc)");
      return 1;
    }
  }

  Router router(backend, batch_wait_us, batch_limit, workers,
                ring_refresh_ms);
  router.start_refresher();
  fprintf(stderr, "guber-edge listening on :%d%s backend=%s\n", port,
          grpc_port > 0
              ? (" grpc=:" + std::to_string(grpc_port)).c_str()
              : "",
          backend.c_str());
  fflush(stderr);

  auto accept_loop = [&one](int lsrv, Router* b, bool grpc) {
    pollfd pfds[2] = {{lsrv, POLLIN, 0}, {g_wake_pipe[0], POLLIN, 0}};
    while (!g_shutdown.load()) {
      pfds[0].revents = pfds[1].revents = 0;
      if (poll(pfds, 2, -1) < 0) continue;  // EINTR etc: re-check flag
      if (g_shutdown.load() || (pfds[1].revents & POLLIN)) break;
      if (!(pfds[0].revents & POLLIN)) continue;
      int fd = accept(lsrv, nullptr, nullptr);
      if (fd < 0) continue;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      // receive timeout: a slow-loris / idle keep-alive client gets its
      // read() failed after --recv-timeout-s and the thread exits. The
      // same timeout bounds gRPC connections (gRPC clients keep
      // connections alive with PINGs well inside any sane timeout).
      timeval tv{};
      tv.tv_sec = g_recv_timeout_s;
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
      // send timeout: a client that stops reading its response must
      // fail the write, not block the thread forever
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
      if (g_conns.fetch_add(1, std::memory_order_relaxed) >= g_max_conns) {
        g_conns.fetch_sub(1, std::memory_order_relaxed);
        if (!grpc)
          http_reply(fd, 503, "Service Unavailable",
                     "{\"error\": \"connection limit reached\"}");
        close(fd);  // gRPC: plain close; client sees connection refused
        continue;
      }
      std::thread(grpc ? serve_grpc_connection : serve_connection, fd, b)
          .detach();
    }
  };

  if (grpc_srv >= 0) {
    std::thread(accept_loop, grpc_srv, &router, true).detach();
  }
  accept_loop(srv, &router, false);

  // graceful drain: stop taking connections, give in-flight requests a
  // bounded window to finish, then exit 0. Connection threads are
  // detached; g_conns counts the live ones.
  close(srv);
  if (grpc_srv >= 0) close(grpc_srv);
  fprintf(stderr, "guber-edge: shutdown signal; draining %d conns\n",
          g_conns.load());
  fflush(stderr);
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(10);
  while (g_conns.load() > 0 &&
         std::chrono::steady_clock::now() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  fprintf(stderr, "guber-edge: exiting (%d conns remained)\n",
          g_conns.load());
  fflush(nullptr);
  // _exit: detached lane workers and the refresher still reference the
  // stack Router; running destructors under them would be a
  // use-after-free. After the drain there is nothing left worth
  // running destructors for.
  _exit(0);
}
