"""Native (C++) components, loaded via ctypes with graceful fallback.

The reference has no native components (pure Go, CGO_ENABLED=0); the ones
here exist because Python — unlike Go — can't hash millions of keys per
second per core, and host-side hashing sits on the serving hot path.

`hashlib_native` exposes:
- hash_batch(keys: list[str]) -> np.ndarray[uint64]   (XXH64)
- crc32_batch(keys: list[str]) -> np.ndarray[uint32]  (ring points)

Build with `make -C gubernator_tpu/native` (repo Makefile does this).
Import fails cleanly when the .so is absent; callers
(core/hashing.slot_hash_batch) fall back to pure Python.
"""
