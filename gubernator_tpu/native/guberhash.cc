// Batch 64-bit key hashing for the serving hot path.
//
// The serving tier hashes every request key (name + "_" + unique_key) to a
// 64-bit slot hash before shipping the batch to the device. In Python this
// costs ~1us/key (hashlib call overhead); at millions of decisions per
// second host hashing would dominate, so the batch loop lives here. The
// Python side passes one concatenated byte buffer plus an offsets array and
// receives a uint64 array — one FFI call per batch, no per-key overhead.
//
// Hash: XXH64 (Yann Collet's public-domain algorithm, implemented from the
// spec). 64-bit avalanche quality is what the slot store needs: row
// indices and the fingerprint tag are all derived from this one value
// (gubernator_tpu/core/store.py slot_indices/fingerprints).
//
// Build: make -C gubernator_tpu/native   (or scripts in repo Makefile)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;

inline uint64_t rotl(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / arm64)
}

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t round1(uint64_t acc, uint64_t lane) {
  acc += lane * P2;
  acc = rotl(acc, 31);
  return acc * P1;
}

inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  acc ^= round1(0, val);
  return acc * P1 + P4;
}

uint64_t xxh64(const uint8_t* data, size_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* const end = data + len;
  uint64_t h;

  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* const limit = end - 32;
    do {
      v1 = round1(v1, read64(p));
      v2 = round1(v2, read64(p + 8));
      v3 = round1(v3, read64(p + 16));
      v4 = round1(v4, read64(p + 24));
      p += 32;
    } while (p <= limit);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }

  h += static_cast<uint64_t>(len);

  while (p + 8 <= end) {
    h ^= round1(0, read64(p));
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= static_cast<uint64_t>(read32(p)) * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= static_cast<uint64_t>(*p) * P5;
    h = rotl(h, 11) * P1;
    ++p;
  }

  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

}  // namespace

extern "C" {

// Hash n byte-slices of one concatenated buffer. offsets has n+1 entries;
// slice i is buf[offsets[i] : offsets[i+1]].
void guber_hash_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                      uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] =
        xxh64(buf + offsets[i],
              static_cast<size_t>(offsets[i + 1] - offsets[i]), seed);
  }
}

// crc32 (IEEE, reflected) batch — ring points for peer ownership, matching
// the reference picker's hash function (reference hash.go:40-42).
static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    }
    crc_table[i] = c;
  }
  crc_init_done = true;
}

void guber_crc32_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                       uint32_t* out) {
  if (!crc_init_done) crc_init();
  for (int64_t i = 0; i < n; ++i) {
    uint32_t c = 0xFFFFFFFFu;
    for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
      c = crc_table[(c ^ buf[j]) & 0xFF] ^ (c >> 8);
    }
    out[i] = c ^ 0xFFFFFFFFu;
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch presort: argsort by (bucket(key_hash), fingerprint(key_hash)) — the
// order decide_presorted requires (core/kernels.py). numpy's comparison
// argsort measured ~1.8ms for 16k keys, slower than the device batch it
// feeds; this LSD radix sort runs ~15x faster and keeps the host side of
// the pipeline off the critical path. Must stay bit-identical to
// core/store.py group_sort_key / bucket_index / fingerprints.

namespace {

constexpr uint64_t BUCKET_SALT = 0x9E3779B97F4A7C15ULL;
// must match gubernator_tpu/parallel/sharded.py _SHARD_SALT
constexpr uint64_t SHARD_SALT = 0xA24BAED4963EE407ULL;

inline uint64_t splitmix64(uint64_t x) {
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// Stable LSD radix argsort of `keys` (low `total_bits` bits meaningful);
// writes the permutation into order_out.
void radix_argsort(std::vector<uint64_t>& keys, int64_t n, int total_bits,
                   int32_t* order_out) {
  std::vector<int32_t> idx(n), idx2(n);
  for (int64_t i = 0; i < n; ++i) idx[i] = static_cast<int32_t>(i);
  std::vector<uint64_t> keys2(n);

  const int passes = (total_bits + 15) / 16;
  static thread_local std::vector<uint32_t> count(1 << 16);
  for (int pass = 0; pass < passes; ++pass) {
    const int shift = pass * 16;
    std::memset(count.data(), 0, count.size() * sizeof(uint32_t));
    for (int64_t i = 0; i < n; ++i) {
      ++count[(keys[i] >> shift) & 0xFFFF];
    }
    uint32_t sum = 0;
    for (uint32_t d = 0; d < (1u << 16); ++d) {
      uint32_t c = count[d];
      count[d] = sum;
      sum += c;
    }
    for (int64_t i = 0; i < n; ++i) {
      uint32_t pos = count[(keys[i] >> shift) & 0xFFFF]++;
      keys2[pos] = keys[i];
      idx2[pos] = idx[i];
    }
    keys.swap(keys2);
    idx.swap(idx2);
  }
  std::memcpy(order_out, idx.data(), n * sizeof(int32_t));
}

// Run-major counting argsort for composite run keys (shard/bucket bits)
// that fit a direct histogram: ONE stable counting pass on the run key,
// then a per-run stable fingerprint sort for the rare multi-key runs
// (store load factors keep mean keys/bucket around 1, and duplicate
// rows of ONE key share a fingerprint, so most runs are fp-uniform and
// skip the sort entirely). Output is bit-identical to the LSD radix on
// (run_key<<32 | fp) — fp ascending within a run, ties in input order —
// at ~3x less memory traffic for B=32k. skey/fp are per INPUT row;
// ends_out receives each run's END offset in the sorted order.
void counting_argsort_fp(const uint32_t* skey, const uint32_t* fp,
                         int64_t n, uint64_t space, int32_t* order_out,
                         std::vector<uint32_t>& ends_out) {
  ends_out.assign(space, 0);
  for (int64_t i = 0; i < n; ++i) ++ends_out[skey[i]];
  uint32_t sum = 0;
  for (uint64_t b = 0; b < space; ++b) {  // counts -> start offsets
    uint32_t c = ends_out[b];
    ends_out[b] = sum;
    sum += c;
  }
  for (int64_t i = 0; i < n; ++i) {  // stable scatter; starts -> ends
    order_out[ends_out[skey[i]]++] = static_cast<int32_t>(i);
  }
  int64_t s = 0;
  for (uint64_t b = 0; b < space; ++b) {
    const int64_t e = ends_out[b];
    if (e - s > 1) {
      const uint32_t f0 = fp[order_out[s]];
      bool uniform = true;
      for (int64_t i = s + 1; i < e; ++i) {
        if (fp[order_out[i]] != f0) {
          uniform = false;
          break;
        }
      }
      if (!uniform) {
        std::stable_sort(
            order_out + s, order_out + e,
            [&](int32_t a, int32_t c) { return fp[a] < fp[c]; });
      }
    }
    s = e;
  }
}

// Histograms above this are slower to zero than the radix passes save.
constexpr uint64_t COUNTING_SPACE_MAX = 1ULL << 16;
// The sharded composite (owner|bucket) key gets a larger cap: the bigger
// memset trades against skipping 3-4 radix passes instead of 2-3.
constexpr uint64_t SHARDED_COUNTING_SPACE_MAX = 1ULL << 18;

// Build the sharded run keys (owner << bucket_bits | bucket), per-row
// fingerprints, and per-shard row counts in one pass.
void build_sharded_keys(const uint64_t* key_hash, int64_t n, uint64_t bmask,
                        int bucket_bits, uint64_t n_shards,
                        int64_t* counts_out, std::vector<uint32_t>& sk,
                        std::vector<uint32_t>& fp) {
  sk.resize(n);
  fp.resize(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t owner = splitmix64(kh ^ SHARD_SALT) % n_shards;
    ++counts_out[owner];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    sk[i] = static_cast<uint32_t>((owner << bucket_bits) | bkt);
    uint32_t f = static_cast<uint32_t>(kh >> 32);
    if (f == 0) f = 1;
    fp[i] = f;
  }
}

// Walk the sorted runs emitting duplicate-key groups (fp-runs within a
// run key). When group_counts_out is non-null, each group also counts
// toward its owning shard (owner = run_key >> bucket_bits). Returns the
// group count.
int64_t emit_groups(const std::vector<uint32_t>& ends, uint64_t space,
                    const std::vector<uint32_t>& fp, const int32_t* order,
                    int32_t* group_id_out, int32_t* leader_pos_out,
                    int64_t* group_counts_out, int bucket_bits) {
  int64_t g = 0;
  int64_t s = 0;
  for (uint64_t r = 0; r < space; ++r) {
    const int64_t e = ends[r];
    int64_t i = s;
    while (i < e) {
      const uint32_t f = fp[order[i]];
      leader_pos_out[g] = static_cast<int32_t>(i);
      if (group_counts_out) ++group_counts_out[r >> bucket_bits];
      while (i < e && fp[order[i]] == f) {
        group_id_out[i] = static_cast<int32_t>(g);
        ++i;
      }
      ++g;
    }
    s = e;
  }
  return g;
}

// Single-device composite (bucket | fp) fast path; false -> radix.
bool counting_presort(const uint64_t* key_hash, int64_t n, uint64_t buckets,
                      int32_t* order_out, std::vector<uint32_t>& fp_out,
                      std::vector<uint32_t>& ends_out) {
  if (buckets > COUNTING_SPACE_MAX) return false;
  const uint64_t bmask = buckets - 1;
  fp_out.resize(n);
  static thread_local std::vector<uint32_t> bk;
  bk.resize(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    bk[i] = static_cast<uint32_t>(splitmix64(kh ^ BUCKET_SALT) & bmask);
    uint32_t f = static_cast<uint32_t>(kh >> 32);
    if (f == 0) f = 1;
    fp_out[i] = f;
  }
  counting_argsort_fp(bk.data(), fp_out.data(), n, buckets, order_out,
                      ends_out);
  return true;
}

}  // namespace

extern "C" {

// order_out[i] = index of the i-th row in (bucket, fingerprint) order.
// buckets must be a power of two. Stable (equal keys keep input order).
void guber_presort(const uint64_t* key_hash, int64_t n, uint64_t buckets,
                   int32_t* order_out) {
  {
    static thread_local std::vector<uint32_t> fp, ends;
    if (counting_presort(key_hash, n, buckets, order_out, fp, ends)) return;
  }
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;

  // sort key: (bucket << 32) | fingerprint  — 32 + bucket_bits bits
  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (bkt << 32) | fp;
  }
  radix_argsort(keys, n, 32 + bucket_bits, order_out);
}

// Batch marshalling: gather-with-permutation + pad in one C pass. The
// serving hot path must build the device request arrays (sorted by the
// presort permutation, clipped to the int32 envelope, padded by
// repeating the last sorted row) and unpermute the responses for every
// batch; the numpy version costs ~40ns/element across six fields
// (~630us/16k batch), this runs in one cache-friendly pass.

void guber_gather_pad_i64_clip(const int64_t* src, const int32_t* order,
                               int64_t n, int64_t b, int64_t lo, int64_t hi,
                               int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    int64_t v = src[order[i]];
    v = v < lo ? lo : (v > hi ? hi : v);
    out[i] = static_cast<int32_t>(v);
  }
  const int32_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

void guber_gather_pad_i32(const int32_t* src, const int32_t* order,
                          int64_t n, int64_t b, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[order[i]];
  const int32_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

void guber_gather_pad_u64(const uint64_t* src, const int32_t* order,
                          int64_t n, int64_t b, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[order[i]];
  const uint64_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

void guber_gather_pad_u8(const uint8_t* src, const int32_t* order,
                         int64_t n, int64_t b, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[order[i]];
  const uint8_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

// out[order[i]] = sorted[i] for the first n positions of each of `k`
// response arrays laid out back to back ([k, b] row-major), writing into
// k output arrays of length b back to back.
void guber_unpermute_i32(const int32_t* sorted, const int32_t* order,
                         int64_t n, int64_t b, int64_t k, int32_t* out) {
  for (int64_t a = 0; a < k; ++a) {
    const int32_t* s = sorted + a * b;
    int32_t* o = out + a * b;
    for (int64_t i = 0; i < n; ++i) o[order[i]] = s[i];
  }
}

// guber_presort + group structure from the sorted key stream: the runs
// of equal (bucket, fingerprint) ARE the duplicate-key groups whose
// store I/O the kernel compacts (core/kernels.py BatchGroups), and they
// fall out of the sort for one extra O(n) pass. group_id_out[i] = group
// slot of sorted row i; leader_pos_out[g] = first sorted row of group g
// (only the first *n_groups_out entries are written).
void guber_presort_grouped(const uint64_t* key_hash, int64_t n,
                           uint64_t buckets, int32_t* order_out,
                           int32_t* group_id_out, int32_t* leader_pos_out,
                           int64_t* n_groups_out) {
  {
    static thread_local std::vector<uint32_t> fp, ends;
    if (counting_presort(key_hash, n, buckets, order_out, fp, ends)) {
      // groups are runs of equal fp within a bucket run (two distinct
      // key hashes sharing (bucket, fp) merge into one group — exactly
      // the composite-key behavior of the radix path, and of the store,
      // whose tag IS the fp)
      *n_groups_out = emit_groups(ends, buckets, fp, order_out,
                                  group_id_out, leader_pos_out, nullptr, 0);
      return;
    }
  }
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;

  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (bkt << 32) | fp;
  }
  std::vector<uint64_t> sorted(keys);  // radix_argsort leaves keys sorted,
  // but the buffer identity depends on pass parity — copy for clarity
  radix_argsort(sorted, n, 32 + bucket_bits, order_out);

  int64_t g = 0;
  uint64_t prev = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t k = keys[order_out[i]];
    if (i == 0 || k != prev) {
      leader_pos_out[g] = static_cast<int32_t>(i);
      ++g;
      prev = k;
    }
    group_id_out[i] = static_cast<int32_t>(g - 1);
  }
  *n_groups_out = g;
}

// Mesh-sharded presort: argsort by (owner_shard, bucket, fingerprint) and
// per-shard row counts. owner = splitmix64(kh ^ SHARD_SALT) % n_shards —
// must stay bit-identical to parallel/sharded.py owner_of / owner_of_np.
// Rows of one shard come out contiguous, internally in the (bucket, fp)
// order decide_presorted requires, so the host can slice per-shard
// sub-batches straight out of the permutation (batch-axis sharding over
// the mesh: each chip gets only the rows it owns).
void guber_presort_sharded(const uint64_t* key_hash, int64_t n,
                           uint64_t buckets, uint64_t n_shards,
                           int32_t* order_out, int64_t* counts_out) {
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;
  int shard_bits = 1;
  while ((1ULL << shard_bits) < n_shards) ++shard_bits;

  for (uint64_t s = 0; s < n_shards; ++s) counts_out[s] = 0;

  if ((n_shards << bucket_bits) <= SHARDED_COUNTING_SPACE_MAX) {
    static thread_local std::vector<uint32_t> sk, fp, ends;
    build_sharded_keys(key_hash, n, bmask, bucket_bits, n_shards,
                       counts_out, sk, fp);
    counting_argsort_fp(sk.data(), fp.data(), n, n_shards << bucket_bits,
                        order_out, ends);
    return;
  }

  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t owner = splitmix64(kh ^ SHARD_SALT) % n_shards;
    ++counts_out[owner];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (owner << (32 + bucket_bits)) | (bkt << 32) | fp;
  }
  radix_argsort(keys, n, 32 + bucket_bits + shard_bits, order_out);
}

// guber_presort_sharded + per-shard group structure: groups are runs of
// equal (owner, bucket, fp) composite keys in the sorted stream (shard
// boundaries break groups automatically — the owner rides the top sort
// bits). group_id_out[i] = GLOBAL group index of sorted row i;
// leader_pos_out[g] = first sorted row of global group g;
// group_counts_out[s] = number of groups owned by shard s.
void guber_presort_sharded_grouped(
    const uint64_t* key_hash, int64_t n, uint64_t buckets,
    uint64_t n_shards, int32_t* order_out, int64_t* counts_out,
    int32_t* group_id_out, int32_t* leader_pos_out,
    int64_t* group_counts_out) {
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;
  int shard_bits = 1;
  while ((1ULL << shard_bits) < n_shards) ++shard_bits;

  for (uint64_t s = 0; s < n_shards; ++s) {
    counts_out[s] = 0;
    group_counts_out[s] = 0;
  }

  if ((n_shards << bucket_bits) <= SHARDED_COUNTING_SPACE_MAX) {
    static thread_local std::vector<uint32_t> sk, fp, ends;
    build_sharded_keys(key_hash, n, bmask, bucket_bits, n_shards,
                       counts_out, sk, fp);
    const uint64_t space = n_shards << bucket_bits;
    counting_argsort_fp(sk.data(), fp.data(), n, space, order_out, ends);
    emit_groups(ends, space, fp, order_out, group_id_out, leader_pos_out,
                group_counts_out, bucket_bits);
    return;
  }

  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t owner = splitmix64(kh ^ SHARD_SALT) % n_shards;
    ++counts_out[owner];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (owner << (32 + bucket_bits)) | (bkt << 32) | fp;
  }
  std::vector<uint64_t> sorted(keys);
  radix_argsort(sorted, n, 32 + bucket_bits + shard_bits, order_out);

  int64_t g = 0;
  uint64_t prev = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t k = keys[order_out[i]];
    if (i == 0 || k != prev) {
      leader_pos_out[g] = static_cast<int32_t>(i);
      ++group_counts_out[k >> (32 + bucket_bits)];
      ++g;
      prev = k;
    }
    group_id_out[i] = static_cast<int32_t>(g - 1);
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// One-call sharded batch prep: presort + duplicate-key groups + device-array
// marshal, optionally thread-parallel.
//
// The r2 mesh host path was numpy: a native sharded presort followed by
// per-field fancy-indexed gathers and a per-shard Python build_groups loop —
// measured ~4.3ms per 32k batch on one core, ~10x the presort itself, which
// capped a served mesh at a fraction of one chip's throughput (the r2
// verdict's "single-threaded host prep" ceiling). This entry point absorbs
// the whole pipeline into one pass:
//
//   phase A (parallel over row ranges): owner/bucket/fingerprint per row +
//           per-thread shard histograms
//   phase B (serial, O(threads*shards)): stable scatter offsets
//   phase C (parallel over row ranges): partition rows by owning shard
//   phase D (parallel over shards): per-shard stable LSD radix argsort by
//           (bucket, fingerprint) — 8-bit digits, skip-uniform passes —
//           then ONE fused walk emits the sorted permutation, the
//           duplicate-key group structure (engine.build_groups
//           conventions), all six clipped+padded device fields, and
//           take_idx.
//
// Thread count: GUBER_PREP_THREADS env, default hardware_concurrency
// (capped 32); 1 runs everything inline with zero pool overhead. Output is
// bit-identical to the numpy twin (parallel/sharded.py fallbacks) at every
// thread count: phases A/C preserve input order per shard (contiguous
// thread ranges, thread-minor offsets) and the per-shard radix is stable.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <functional>
#include <mutex>
#include <thread>
#include <pthread.h>

namespace {

// Forked children inherit lanes_ > 1 but ZERO worker threads (threads
// don't survive fork) — without this flag a child's first prep call
// would park in done_cv_.wait() forever. The atfork child handler flips
// it so children run every phase inline.
std::atomic<bool> g_pool_forked{false};

class PrepPool {
 public:
  static PrepPool& inst() {
    static PrepPool* p = new PrepPool();  // leaked: workers live for the
    // process; a static destructor would race threads parked in wait()
    return *p;
  }
  int lanes() const {
    return g_pool_forked.load(std::memory_order_relaxed) ? 1 : lanes_;
  }

  // Run fn(tid, lanes) on every lane; the caller runs lane 0.
  // Concurrent callers (K prep-worker threads, serve/batcher.py) are
  // serialized on caller_m_: each caller's pooled section runs alone —
  // worker-thread parallelism and in-call pool parallelism compose by
  // time-slicing rather than deadlocking. lanes==1 touches no shared
  // state and skips the lock entirely.
  void run(const std::function<void(int, int)>& fn) {
    if (lanes() == 1) {
      fn(0, 1);
      return;
    }
    std::lock_guard<std::mutex> caller_lock(caller_m_);
    {
      std::unique_lock<std::mutex> lk(m_);
      fn_ = &fn;
      pending_ = lanes_ - 1;
      ++gen_;
    }
    cv_.notify_all();
    fn(0, lanes_);
    std::unique_lock<std::mutex> lk(m_);
    done_cv_.wait(lk, [&] { return pending_ == 0; });
    fn_ = nullptr;
  }

 private:
  PrepPool() {
    long t = 0;
    if (const char* e = getenv("GUBER_PREP_THREADS")) t = atol(e);
    if (t <= 0) t = (long)std::thread::hardware_concurrency();
    if (t < 1) t = 1;
    if (t > 32) t = 32;
    lanes_ = (int)t;
    if (lanes_ > 1) {
      pthread_atfork(nullptr, nullptr, [] {
        g_pool_forked.store(true, std::memory_order_relaxed);
      });
    }
    for (int i = 1; i < lanes_; ++i) {
      std::thread th([this, i] { worker(i); });
      th.detach();
    }
  }
  void worker(int tid) {
    uint64_t seen = 0;
    for (;;) {
      const std::function<void(int, int)>* fn;
      {
        std::unique_lock<std::mutex> lk(m_);
        cv_.wait(lk, [&] { return gen_ != seen; });
        seen = gen_;
        fn = fn_;
      }
      (*fn)(tid, lanes_);
      {
        std::unique_lock<std::mutex> lk(m_);
        if (--pending_ == 0) done_cv_.notify_one();
      }
    }
  }

  std::mutex m_, caller_m_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(int, int)>* fn_ = nullptr;
  uint64_t gen_ = 0;
  int pending_ = 0;
  int lanes_ = 1;
};

// group_rungs twin (core/engine.py group_rungs): {15b/64, b/4, 3b/8, b}
// floored, min 64, deduped ascending. Returns count; writes into out[4].
// MUST stay in lockstep with the Python ladder — the native prep picks
// its G rung here and the bit-identity tests compare against Python.
inline int group_rungs_c(int64_t b, int64_t out[4]) {
  auto rung = [b](int64_t num, int64_t den) {
    int64_t r = b < 64 ? b : ((num * b) / den < 64 ? 64 : (num * b) / den);
    return r > b ? b : r;
  };
  int64_t v[4] = {rung(15, 64), rung(1, 4), rung(3, 8), b};
  // insertion sort + dedup (4 elements)
  for (int i = 1; i < 4; ++i)
    for (int j = i; j > 0 && v[j] < v[j - 1]; --j) std::swap(v[j], v[j - 1]);
  int k = 0;
  for (int i = 0; i < 4; ++i)
    if (k == 0 || v[i] != out[k - 1]) out[k++] = v[i];
  return k;
}

inline int64_t pick_rung(const int64_t* rungs, int64_t n_rungs,
                         int64_t need) {
  for (int64_t i = 0; i < n_rungs; ++i)
    if (rungs[i] >= need) return rungs[i];
  return -1;
}

inline int32_t clip_i64(int64_t v, int64_t lo, int64_t hi) {
  return (int32_t)(v < lo ? lo : (v > hi ? hi : v));
}

}  // namespace

extern "C" {

int64_t guber_prep_threads() { return PrepPool::inst().lanes(); }

namespace {
// GUBER_PREP_DEBUG=1: print per-phase microseconds to stderr
inline bool prep_debug() {
  static const bool on = [] {
    const char* e = getenv("GUBER_PREP_DEBUG");
    return e && *e && *e != '0';
  }();
  return on;
}
inline int64_t now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}
}  // namespace

// Returns 0 on success; -1 if a shard's row count exceeds the rung
// ladder top; -2 if g_override is given but smaller than a shard's group
// count. picked_out: {B_sub, G_sub}. Output buffers are caller-allocated
// for n_shards rows of the ladder-top rung; rows are written compactly
// with stride B_sub (fields / gid), G_sub (group arrays).
int64_t guber_prep_sharded(
    const uint64_t* key_hash, const int64_t* hits, const int64_t* limit,
    const int64_t* duration, const int32_t* algo, const uint8_t* gnp,
    int64_t n, uint64_t buckets, int64_t n_shards, const int64_t* rungs,
    int64_t n_rungs, int64_t g_override, int64_t lo, int64_t hi,
    int64_t dlo, int64_t dhi,
    // outputs
    int32_t* order_out, int64_t* counts_out, int64_t* picked_out,
    uint64_t* kh_out, int32_t* hits_out, int32_t* limit_out,
    int32_t* dur_out, int32_t* algo_out, uint8_t* gnp_out,
    uint8_t* valid_out, uint64_t* gkh_out, int32_t* glead_out,
    int32_t* gend_out, uint8_t* gvalid_out, int32_t* gid_out,
    int64_t* take_idx_out) {
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;

  PrepPool& pool = PrepPool::inst();
  const int T = pool.lanes();
  const bool dbg = prep_debug();
  int64_t t0 = dbg ? now_us() : 0, t1 = 0, t2 = 0, t3 = 0, t4 = 0;

  // phase A: per-row composite keys + per-thread shard histograms.
  // NOTE: scratch vectors are main-thread-owned; worker lambdas must
  // capture raw POINTERS — a `thread_local` referenced inside the
  // lambda body would resolve to each worker's own (empty) instance.
  static thread_local std::vector<uint64_t> key_arr_tl;
  static thread_local std::vector<int32_t> owner_arr_tl;
  key_arr_tl.resize(n);
  std::vector<std::vector<int64_t>> hist(T);
  const bool multi = n_shards > 1;
  if (multi) owner_arr_tl.resize(n);
  uint64_t* const key_arr = key_arr_tl.data();
  int32_t* const owner_arr = multi ? owner_arr_tl.data() : nullptr;
  // power-of-two shard counts (every real mesh) take a mask instead of
  // the ~30-90-cycle 64-bit modulo; both match owner_of / owner_of_np
  const bool ns_pow2 = (n_shards & (n_shards - 1)) == 0;
  const uint64_t ns_mask = (uint64_t)n_shards - 1;
  pool.run([&](int tid, int lanes) {
    hist[tid].assign(n_shards, 0);
    int64_t* const h = hist[tid].data();
    const int64_t s0 = n * tid / lanes, s1 = n * (tid + 1) / lanes;
    for (int64_t i = s0; i < s1; ++i) {
      const uint64_t kh = key_hash[i];
      const uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
      uint64_t fp = kh >> 32;
      if (fp == 0) fp = 1;
      key_arr[i] = (bkt << 32) | fp;
      if (multi) {
        const uint64_t mix = splitmix64(kh ^ SHARD_SALT);
        const int32_t o = (int32_t)(ns_pow2 ? (mix & ns_mask)
                                            : (mix % (uint64_t)n_shards));
        owner_arr[i] = o;
        ++h[o];
      } else {
        ++h[0];
      }
    }
  });

  // phase B: starts per shard + per-(shard, thread) scatter offsets
  std::vector<int64_t> starts(n_shards + 1, 0);
  std::vector<std::vector<int64_t>> off(T, std::vector<int64_t>(n_shards));
  {
    int64_t sum = 0;
    for (int64_t s = 0; s < n_shards; ++s) {
      starts[s] = sum;
      int64_t c = 0;
      for (int t = 0; t < T; ++t) {
        off[t][s] = sum + c;
        c += hist[t][s];
      }
      counts_out[s] = c;
      sum += c;
    }
    starts[n_shards] = sum;
  }

  if (dbg) t1 = now_us();
  int64_t maxc = 1;
  for (int64_t s = 0; s < n_shards; ++s)
    if (counts_out[s] > maxc) maxc = counts_out[s];
  const int64_t B = pick_rung(rungs, n_rungs, maxc);
  if (B < 0) return -1;

  // phase C: stable partition of row indices by owning shard
  static thread_local std::vector<int32_t> part_tl;
  part_tl.resize(n);
  int32_t* const part = part_tl.data();
  if (multi) {
    pool.run([&](int tid, int lanes) {
      const int64_t s0 = n * tid / lanes, s1 = n * (tid + 1) / lanes;
      int64_t* const o = off[tid].data();
      for (int64_t i = s0; i < s1; ++i) part[o[owner_arr[i]]++] = (int32_t)i;
    });
  } else {
    pool.run([&](int tid, int lanes) {
      const int64_t s0 = n * tid / lanes, s1 = n * (tid + 1) / lanes;
      for (int64_t i = s0; i < s1; ++i) part[i] = (int32_t)i;
    });
  }

  if (dbg) t2 = now_us();
  // phase D part 1: per-shard stable radix argsort (order_out) + group
  // counts (gcounts). G rung selection needs every shard's group count,
  // so the fused output walk is a second parallel phase.
  std::vector<int64_t> gcounts(n_shards, 0), gstarts(n_shards + 1, 0);
  // leader positions (shard-local j) found by the sort pass, consumed by
  // the marshal pass: at most one leader per row
  static thread_local std::vector<int32_t> lead_tl;
  lead_tl.resize(n);
  int32_t* const lead_scratch = lead_tl.data();
  // 12-bit digits over the BUCKET bits only (fp handled by per-run
  // fixups): ceil(15/12) = 2 passes at the default 32k-bucket store,
  // histogram small enough that the per-pass memset (32 KiB) is noise
  constexpr int DIGIT = 12;
  constexpr int64_t DMASK = (1 << DIGIT) - 1;
  const int passes = (bucket_bits + DIGIT - 1) / DIGIT;
  std::atomic<int64_t> next_shard{0};
  pool.run([&](int, int) {
    // (key, idx) pair radix: keys stream sequentially each pass and the
    // scatter partitions stay cache-resident (vs random key_arr[a[j]]
    // loads every pass in an index-only sort)
    static thread_local std::vector<uint64_t> ka, kb;
    static thread_local std::vector<int32_t> ia, ib;
    static thread_local std::vector<int64_t> h(1 << DIGIT);
    for (;;) {
      const int64_t s = next_shard.fetch_add(1);
      if (s >= n_shards) break;
      const int64_t cnt = counts_out[s], st = starts[s];
      if (cnt == 0) continue;
      ka.resize(cnt);
      kb.resize(cnt);
      ia.resize(cnt);
      ib.resize(cnt);
      for (int64_t j = 0; j < cnt; ++j) {
        const int32_t row = part[st + j];
        ia[j] = row;
        ka[j] = key_arr[row];
      }
      // radix ONLY the bucket bits (>= 32): full-fp passes would be
      // wasted work — fingerprint order matters only WITHIN a bucket
      // run, and at serving load factors (~1 key/bucket) almost every
      // run is a singleton or a single hot key's duplicates. The rare
      // multi-fp run gets a stable_sort fixup below. Halves the passes
      // at the default 15-bucket-bit store (2 vs 4).
      if (passes > 1 && cnt >= (int64_t)(buckets >> 2) &&
          bucket_bits <= 18) {
        // dense slice (single-device path: cnt == n vs 32k buckets):
        // ONE counting pass over the whole bucket space beats two
        // 12-bit passes — the histogram walk amortizes over enough rows
        static thread_local std::vector<int64_t> hb;
        hb.assign((size_t)buckets, 0);
        for (int64_t j = 0; j < cnt; ++j) ++hb[ka[j] >> 32];
        int64_t sum = 0;
        for (uint64_t d = 0; d < buckets; ++d) {
          const int64_t c = hb[d];
          hb[d] = sum;
          sum += c;
        }
        for (int64_t j = 0; j < cnt; ++j) {
          const int64_t pos = hb[ka[j] >> 32]++;
          kb[pos] = ka[j];
          ib[pos] = ia[j];
        }
        ka.swap(kb);
        ia.swap(ib);
      } else {
        for (int p = 0; p < passes; ++p) {
          const int shift = 32 + p * DIGIT;
          std::memset(h.data(), 0, h.size() * sizeof(int64_t));
          const uint32_t first = (ka[0] >> shift) & DMASK;
          bool uniform = true;
          for (int64_t j = 0; j < cnt; ++j) {
            const uint32_t d = (ka[j] >> shift) & DMASK;
            ++h[d];
            uniform &= (d == first);
          }
          if (uniform) continue;  // pass is a no-op permutation
          int64_t sum = 0;
          for (int64_t d = 0; d <= DMASK; ++d) {
            const int64_t c = h[d];
            h[d] = sum;
            sum += c;
          }
          for (int64_t j = 0; j < cnt; ++j) {
            const int64_t pos = h[(ka[j] >> shift) & DMASK]++;
            kb[pos] = ka[j];
            ib[pos] = ia[j];
          }
          ka.swap(kb);
          ia.swap(ib);
        }
      }
      // fixups + leaders in one walk: for each bucket run, if the fps
      // are not already non-decreasing, stable_sort the (key, idx)
      // pairs by full key (fp in the low bits; stability keeps input
      // order on ties). Leaders are full-key change positions.
      int32_t* ls = lead_scratch + st;
      int64_t g = 0;
      int64_t rs = 0;  // bucket-run start
      for (int64_t j = 0; j <= cnt; ++j) {
        const bool run_end =
            (j == cnt) || ((ka[j] >> 32) != (ka[rs] >> 32));
        if (!run_end) continue;
        if (j - rs > 1) {
          bool sorted = true;
          for (int64_t q = rs + 1; q < j; ++q)
            if (ka[q] < ka[q - 1]) {
              sorted = false;
              break;
            }
          if (!sorted) {
            // sort pairs by key, input-stable: indices ride along
            static thread_local std::vector<std::pair<uint64_t, int32_t>>
                tmp;
            tmp.resize(j - rs);
            for (int64_t q = rs; q < j; ++q)
              tmp[q - rs] = {ka[q], ia[q]};
            std::stable_sort(
                tmp.begin(), tmp.end(),
                [](const auto& x, const auto& y) {
                  return x.first < y.first;
                });
            for (int64_t q = rs; q < j; ++q) {
              ka[q] = tmp[q - rs].first;
              ia[q] = tmp[q - rs].second;
            }
          }
        }
        for (int64_t q = rs; q < j; ++q)
          if (q == rs || ka[q] != ka[q - 1]) ls[g++] = (int32_t)q;
        if (j < cnt) rs = j;
      }
      gcounts[s] = g;
      std::memcpy(order_out + st, ia.data(), cnt * sizeof(int32_t));
    }
  });

  if (dbg) t3 = now_us();
  int64_t maxg = 1;
  for (int64_t s = 0; s < n_shards; ++s) {
    gstarts[s + 1] = gstarts[s] + gcounts[s];
    if (gcounts[s] > maxg) maxg = gcounts[s];
  }
  int64_t G;
  if (g_override > 0) {
    if (g_override < maxg) return -2;
    G = g_override;
  } else {
    int64_t gr[4];
    const int ng = group_rungs_c(B, gr);
    G = pick_rung(gr, ng, maxg);
    if (G < 0) return -1;  // unreachable: top rung is B >= maxc >= maxg
  }
  picked_out[0] = B;
  picked_out[1] = G;

  // phase D part 2: per-shard marshal — one streaming loop PER FIELD
  // (interleaved 8-array writes per row defeat vectorization; per-field
  // loops make the padding tail a vectorized constant fill and the real
  // rows a single gather+store stream), then groups from the sort
  // pass's leader scratch with build_groups' padding conventions.
  std::atomic<int64_t> next_shard2{0};
  pool.run([&](int, int) {
    for (;;) {
      const int64_t s = next_shard2.fetch_add(1);
      if (s >= n_shards) break;
      const int64_t cnt = counts_out[s], st = starts[s];
      if (cnt == 0) continue;  // filled by the serial fixup below —
      // the fill row belongs to another shard whose order may not be
      // written yet
      const int32_t* ord = order_out + st;
      uint64_t* kh_o = kh_out + s * B;
      int32_t* hi_o = hits_out + s * B;
      int32_t* li_o = limit_out + s * B;
      int32_t* du_o = dur_out + s * B;
      int32_t* al_o = algo_out + s * B;
      uint8_t* gn_o = gnp_out + s * B;
      uint8_t* va_o = valid_out + s * B;
      int32_t* gi_o = gid_out + s * B;
      uint64_t* gk_o = gkh_out + s * G;
      int32_t* gl_o = glead_out + s * G;
      int32_t* ge_o = gend_out + s * G;
      uint8_t* gv_o = gvalid_out + s * G;

      for (int64_t j = 0; j < cnt; ++j) kh_o[j] = key_hash[ord[j]];
      std::fill(kh_o + cnt, kh_o + B, kh_o[cnt - 1]);
      for (int64_t j = 0; j < cnt; ++j)
        hi_o[j] = clip_i64(hits[ord[j]], lo, hi);
      std::fill(hi_o + cnt, hi_o + B, hi_o[cnt - 1]);
      for (int64_t j = 0; j < cnt; ++j)
        li_o[j] = clip_i64(limit[ord[j]], lo, hi);
      std::fill(li_o + cnt, li_o + B, li_o[cnt - 1]);
      for (int64_t j = 0; j < cnt; ++j)
        du_o[j] = clip_i64(duration[ord[j]], dlo, dhi);
      std::fill(du_o + cnt, du_o + B, du_o[cnt - 1]);
      for (int64_t j = 0; j < cnt; ++j) al_o[j] = algo[ord[j]];
      std::fill(al_o + cnt, al_o + B, al_o[cnt - 1]);
      for (int64_t j = 0; j < cnt; ++j) gn_o[j] = gnp[ord[j]];
      std::fill(gn_o + cnt, gn_o + B, gn_o[cnt - 1]);
      std::memset(va_o, 1, cnt);
      std::memset(va_o + cnt, 0, B - cnt);
      int64_t* tk = take_idx_out + st;
      const int64_t base = s * B;
      for (int64_t j = 0; j < cnt; ++j) tk[j] = base + j;

      // groups: leaders from the sort pass; run fills are sequential
      const int32_t* ls = lead_scratch + st;
      const int64_t gc = gcounts[s];
      for (int64_t g = 0; g < gc; ++g) {
        const int64_t lead = ls[g];
        const int64_t next = (g + 1 < gc) ? ls[g + 1] : cnt;
        gl_o[g] = (int32_t)lead;
        ge_o[g] = (int32_t)((g + 1 < gc) ? next - 1 : B - 1);
        gk_o[g] = kh_o[lead];
        gv_o[g] = 1;
        for (int64_t j = lead; j < next; ++j) gi_o[j] = (int32_t)g;
      }
      std::fill(gi_o + cnt, gi_o + B, (int32_t)(gc - 1));
      // padded group slots: leader=B, end=B-1, invalid, key of row B-1
      std::fill(gl_o + gc, gl_o + G, (int32_t)B);
      std::fill(ge_o + gc, ge_o + G, (int32_t)(B - 1));
      std::memset(gv_o + gc, 0, G - gc);
      std::fill(gk_o + gc, gk_o + G, kh_o[B - 1]);
    }
  });

  if (dbg) t4 = now_us();
  // serial fixup for empty shards: numpy twin semantics — padded cells
  // replicate order[clip(starts[s], 0, n-1)] (the next shard's first
  // sorted row), group ids 0, group keys kh_padded[B-1].
  for (int64_t s = 0; s < n_shards; ++s) {
    if (counts_out[s] != 0) continue;
    const int64_t src = starts[s] < n ? starts[s] : (n > 0 ? n - 1 : 0);
    const int32_t row = n > 0 ? order_out[src] : 0;
    const uint64_t kf = n > 0 ? key_hash[row] : 0;
    const int32_t hf = n > 0 ? clip_i64(hits[row], lo, hi) : 0;
    const int32_t lf = n > 0 ? clip_i64(limit[row], lo, hi) : 0;
    const int32_t df = n > 0 ? clip_i64(duration[row], dlo, dhi) : 0;
    const int32_t af = n > 0 ? algo[row] : 0;
    const uint8_t gf = n > 0 ? gnp[row] : 0;
    uint64_t* kh_o = kh_out + s * B;
    int32_t* hi_o = hits_out + s * B;
    int32_t* li_o = limit_out + s * B;
    int32_t* du_o = dur_out + s * B;
    int32_t* al_o = algo_out + s * B;
    uint8_t* gn_o = gnp_out + s * B;
    uint8_t* va_o = valid_out + s * B;
    int32_t* gi_o = gid_out + s * B;
    for (int64_t j = 0; j < B; ++j) {
      kh_o[j] = kf;
      hi_o[j] = hf;
      li_o[j] = lf;
      du_o[j] = df;
      al_o[j] = af;
      gn_o[j] = gf;
      va_o[j] = 0;
      gi_o[j] = 0;
    }
    uint64_t* gk_o = gkh_out + s * G;
    int32_t* gl_o = glead_out + s * G;
    int32_t* ge_o = gend_out + s * G;
    uint8_t* gv_o = gvalid_out + s * G;
    for (int64_t q = 0; q < G; ++q) {
      gk_o[q] = kf;
      gl_o[q] = (int32_t)B;
      ge_o[q] = (int32_t)(B - 1);
      gv_o[q] = 0;
    }
  }
  if (dbg) {
    const int64_t t5 = now_us();
    fprintf(stderr,
            "prep phases us: A+B=%ld C=%ld sort=%ld marshal=%ld fixup=%ld "
            "total=%ld (T=%d)\n",
            (long)(t1 - t0), (long)(t2 - t1), (long)(t3 - t2),
            (long)(t4 - t3), (long)(t5 - t4), (long)(t5 - t0), T);
  }
  return 0;
}

// Mesh response unflatten: out[c][order[st+j]] = packed[s][c*B_sub + j]
// for the n real rows — the native twin of MeshEngine.decide_arrays's
// per-column `out[order] = flat[take_idx]`, all four response columns in
// one pass. packed rows have `stride` int32s (4*B_sub + stats tail).
void guber_unflatten_resp(const int32_t* packed, const int32_t* order,
                          const int64_t* counts, int64_t n,
                          int64_t n_shards, int64_t b_sub, int64_t stride,
                          int32_t* out) {
  int64_t st = 0;
  for (int64_t s = 0; s < n_shards; ++s) {
    const int64_t cnt = counts[s];
    const int32_t* row = packed + s * stride;
    for (int64_t c = 0; c < 4; ++c) {
      const int32_t* col = row + c * b_sub;
      int32_t* o = out + c * n;
      for (int64_t j = 0; j < cnt; ++j) o[order[st + j]] = col[j];
    }
    st += cnt;
  }
}

// Sorted-run merge combine (r9): stable k-way merge of per-group
// PRE-SORTED runs (serve/batcher.py arrival-time prep), fused with the
// field materialization + request padding the flush path needs — one
// GIL-free pass replacing the flattened batch's concat + full radix
// sort + marshal. Stability contract (pinned python-side): equal sort
// keys resolve in run order, and runs arrive in caller order, so the
// merged permutation equals np.argsort(concat, kind="stable").
//
// Inputs are k parallel pointer tables (one entry per run) of the
// sorted skey / device-dtype fields / within-run caller order, plus
// per-run lengths ns[k] and flattened-batch base offsets bases[k].
// Outputs: merged skey[n] (group derivation + mesh slicing),
// order_out[B] (global caller index; tail = identity, the engine's
// padding convention), the six padded field arrays [B] (tail repeats
// the last merged row, valid=0 — pad_request_sorted's convention), and
// the duplicate-key group stream (group_id[n], leader_pos[n], g_real).
// Pass B == n to skip padding (the mesh path lays out per-shard
// sub-batches from the flat merged stream instead).
// When n_rungs > 0, the group stream is additionally PADDED to the
// smallest rung G >= max(g_real, 1) of g_rungs (engine.group_rungs'
// ladder, engine.build_groups' conventions): gkh/glead/gend/gvalid
// sized G (caller allocates g_rungs[n_rungs-1]), group_id_out sized B
// with the padding tail pointing at the last real group, and the
// picked G returned through g_pick_out — so the whole merge + pad +
// group build is one GIL-free call. n_rungs == 0 skips the padding
// (the mesh path lays out per-shard groups itself).
int64_t guber_merge_runs(
    const uint64_t* const* skeys, const uint64_t* const* khs,
    const int32_t* const* hits, const int32_t* const* limits,
    const int32_t* const* durs, const int32_t* const* algos,
    const uint8_t* const* gnps, const int32_t* const* orders,
    const int64_t* ns, const int64_t* bases, int64_t k, int64_t B,
    const int64_t* g_rungs, int64_t n_rungs, uint64_t* skey_out,
    int32_t* order_out, uint64_t* kh_out, int32_t* hits_out,
    int32_t* limit_out, int32_t* dur_out, int32_t* algo_out,
    uint8_t* gnp_out, uint8_t* valid_out, int32_t* group_id_out,
    int32_t* leader_pos_out, uint64_t* gkh_out, int32_t* gend_out,
    uint8_t* gvalid_out, int64_t* g_real_out, int64_t* g_pick_out) {
  int64_t n = 0;
  for (int64_t r = 0; r < k; ++r) n += ns[r];
  if (n > B) return -1;
  // binary min-heap of run heads ordered by (key, run index): the run
  // tie-break is what keeps equal keys in caller order (runs are
  // caller-ordered), matching a stable sort of the concatenation
  struct Head {
    uint64_t key;
    int64_t run;
  };
  std::vector<Head> heap;
  heap.reserve((size_t)k);
  std::vector<int64_t> pos((size_t)k, 0);
  auto lt = [](const Head& a, const Head& b) {
    return a.key < b.key || (a.key == b.key && a.run < b.run);
  };
  auto sift_down = [&](size_t i) {
    const size_t sz = heap.size();
    for (;;) {
      size_t s = i, l = 2 * i + 1, r2 = 2 * i + 2;
      if (l < sz && lt(heap[l], heap[s])) s = l;
      if (r2 < sz && lt(heap[r2], heap[s])) s = r2;
      if (s == i) return;
      std::swap(heap[i], heap[s]);
      i = s;
    }
  };
  for (int64_t r = 0; r < k; ++r)
    if (ns[r] > 0) heap.push_back({skeys[r][0], r});
  for (size_t i = heap.size(); i-- > 0;) sift_down(i);

  int64_t g = -1;
  uint64_t prev_key = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t r = heap[0].run;
    const int64_t j = pos[(size_t)r]++;
    const uint64_t key = heap[0].key;
    skey_out[i] = key;
    order_out[i] = (int32_t)(orders[r][j] + bases[r]);
    kh_out[i] = khs[r][j];
    hits_out[i] = hits[r][j];
    limit_out[i] = limits[r][j];
    dur_out[i] = durs[r][j];
    algo_out[i] = algos[r][j];
    gnp_out[i] = gnps[r][j];
    valid_out[i] = 1;
    if (i == 0 || key != prev_key) {
      leader_pos_out[++g] = (int32_t)i;
      prev_key = key;
    }
    group_id_out[i] = (int32_t)g;
    if (j + 1 < ns[r]) {
      heap[0].key = skeys[r][j + 1];
    } else {
      heap[0] = heap.back();
      heap.pop_back();
    }
    if (!heap.empty()) sift_down(0);
  }
  const int64_t g_real = g + 1;
  *g_real_out = g_real;
  // padding tail: repeat the last merged row with valid=0; order maps
  // padding rows to themselves (engine padding conventions)
  // (see guber_prep_run below for the arrival-side producer)
  for (int64_t i = n; i < B; ++i) {
    order_out[i] = (int32_t)i;
    kh_out[i] = n ? kh_out[n - 1] : 0;
    hits_out[i] = n ? hits_out[n - 1] : 0;
    limit_out[i] = n ? limit_out[n - 1] : 0;
    dur_out[i] = n ? dur_out[n - 1] : 0;
    algo_out[i] = n ? algo_out[n - 1] : 0;
    gnp_out[i] = n ? gnp_out[n - 1] : 0;
    valid_out[i] = 0;
  }
  if (n_rungs > 0) {
    // padded group build, engine.build_groups conventions: pick the
    // smallest rung holding the real groups, real slots get
    // leader/end/key, the final real group owns the request padding
    // tail, padded slots carry leader=B / end=B-1 / valid=0, and
    // padded request rows point at the last real group
    const int64_t g_need = g_real > 1 ? g_real : 1;
    int64_t G = 0;
    for (int64_t r = 0; r < n_rungs; ++r) {
      if (g_rungs[r] >= g_need) {
        G = g_rungs[r];
        break;
      }
    }
    if (G == 0) return -3;  // ladder cannot hold the group count
    *g_pick_out = G;
    for (int64_t q = 0; q < g_real; ++q) {
      const int32_t lead = leader_pos_out[q];
      gkh_out[q] = kh_out[lead];
      gend_out[q] =
          q + 1 < g_real ? leader_pos_out[q + 1] - 1 : (int32_t)(B - 1);
      gvalid_out[q] = 1;
    }
    const uint64_t k_pad = B ? kh_out[B - 1] : 0;
    for (int64_t q = g_real; q < G; ++q) {
      leader_pos_out[q] = (int32_t)B;
      gkh_out[q] = k_pad;
      gend_out[q] = (int32_t)(B - 1);
      gvalid_out[q] = 0;
    }
    const int32_t gid_pad = (int32_t)(g_real > 0 ? g_real - 1 : 0);
    for (int64_t i = n; i < B; ++i) group_id_out[i] = gid_pad;
  }
  return 0;
}

// Arrival-time per-group prep (r9): ONE call fusing the sharded
// presort (guber_presort_sharded), the device-dtype clip+gather of all
// six request fields, and the composite sort-key stream the merge
// orders by — the producer side of guber_merge_runs. One GIL-free
// call per enqueued group keeps the prep pool's threads off the
// interpreter while the serving loop is hot. n_shards == 1 degrades
// to the single-device (bucket, fingerprint) order: the owner bits
// are zero, so the composite key equals group_sort_key_np's.
int64_t guber_prep_run(const uint64_t* key_hash, const int64_t* hits,
                       const int64_t* limits, const int64_t* durs,
                       const int32_t* algos, const uint8_t* gnps,
                       int64_t n, uint64_t buckets, int64_t n_shards,
                       int64_t lo, int64_t hi, int64_t dlo, int64_t dhi,
                       int32_t* order_out, int64_t* counts_out,
                       uint64_t* skey_out, uint64_t* kh_out,
                       int32_t* hits_out, int32_t* limit_out,
                       int32_t* dur_out, int32_t* algo_out,
                       uint8_t* gnp_out) {
  guber_presort_sharded(key_hash, n, buckets, (uint64_t)n_shards,
                        order_out, counts_out);
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;
  if (bucket_bits < 1) bucket_bits = 1;  // python max(bit_length-1, 1)
  const uint64_t bmask = buckets - 1;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t j = order_out[i];
    const uint64_t kh = key_hash[j];
    kh_out[i] = kh;
    uint64_t owner =
        n_shards > 1 ? splitmix64(kh ^ SHARD_SALT) % (uint64_t)n_shards
                     : 0;
    const uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    skey_out[i] = (owner << (32 + bucket_bits)) | (bkt << 32) | fp;
    int64_t h = hits[j];
    hits_out[i] = (int32_t)(h < lo ? lo : (h > hi ? hi : h));
    int64_t l = limits[j];
    limit_out[i] = (int32_t)(l < lo ? lo : (l > hi ? hi : l));
    int64_t d = durs[j];
    dur_out[i] = (int32_t)(d < dlo ? dlo : (d > dhi ? dhi : d));
    algo_out[i] = algos[j];
    gnp_out[i] = gnps[j];
  }
  return 0;
}

}  // extern "C"
