// Batch 64-bit key hashing for the serving hot path.
//
// The serving tier hashes every request key (name + "_" + unique_key) to a
// 64-bit slot hash before shipping the batch to the device. In Python this
// costs ~1us/key (hashlib call overhead); at millions of decisions per
// second host hashing would dominate, so the batch loop lives here. The
// Python side passes one concatenated byte buffer plus an offsets array and
// receives a uint64 array — one FFI call per batch, no per-key overhead.
//
// Hash: XXH64 (Yann Collet's public-domain algorithm, implemented from the
// spec). 64-bit avalanche quality is what the slot store needs: row
// indices and the fingerprint tag are all derived from this one value
// (gubernator_tpu/core/store.py slot_indices/fingerprints).
//
// Build: make -C gubernator_tpu/native   (or scripts in repo Makefile)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;

inline uint64_t rotl(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / arm64)
}

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t round1(uint64_t acc, uint64_t lane) {
  acc += lane * P2;
  acc = rotl(acc, 31);
  return acc * P1;
}

inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  acc ^= round1(0, val);
  return acc * P1 + P4;
}

uint64_t xxh64(const uint8_t* data, size_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* const end = data + len;
  uint64_t h;

  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* const limit = end - 32;
    do {
      v1 = round1(v1, read64(p));
      v2 = round1(v2, read64(p + 8));
      v3 = round1(v3, read64(p + 16));
      v4 = round1(v4, read64(p + 24));
      p += 32;
    } while (p <= limit);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }

  h += static_cast<uint64_t>(len);

  while (p + 8 <= end) {
    h ^= round1(0, read64(p));
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= static_cast<uint64_t>(read32(p)) * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= static_cast<uint64_t>(*p) * P5;
    h = rotl(h, 11) * P1;
    ++p;
  }

  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

}  // namespace

extern "C" {

// Hash n byte-slices of one concatenated buffer. offsets has n+1 entries;
// slice i is buf[offsets[i] : offsets[i+1]].
void guber_hash_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                      uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] =
        xxh64(buf + offsets[i],
              static_cast<size_t>(offsets[i + 1] - offsets[i]), seed);
  }
}

// crc32 (IEEE, reflected) batch — ring points for peer ownership, matching
// the reference picker's hash function (reference hash.go:40-42).
static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    }
    crc_table[i] = c;
  }
  crc_init_done = true;
}

void guber_crc32_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                       uint32_t* out) {
  if (!crc_init_done) crc_init();
  for (int64_t i = 0; i < n; ++i) {
    uint32_t c = 0xFFFFFFFFu;
    for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
      c = crc_table[(c ^ buf[j]) & 0xFF] ^ (c >> 8);
    }
    out[i] = c ^ 0xFFFFFFFFu;
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch presort: argsort by (bucket(key_hash), fingerprint(key_hash)) — the
// order decide_presorted requires (core/kernels.py). numpy's comparison
// argsort measured ~1.8ms for 16k keys, slower than the device batch it
// feeds; this LSD radix sort runs ~15x faster and keeps the host side of
// the pipeline off the critical path. Must stay bit-identical to
// core/store.py group_sort_key / bucket_index / fingerprints.

namespace {

constexpr uint64_t BUCKET_SALT = 0x9E3779B97F4A7C15ULL;
// must match gubernator_tpu/parallel/sharded.py _SHARD_SALT
constexpr uint64_t SHARD_SALT = 0xA24BAED4963EE407ULL;

inline uint64_t splitmix64(uint64_t x) {
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// Stable LSD radix argsort of `keys` (low `total_bits` bits meaningful);
// writes the permutation into order_out.
void radix_argsort(std::vector<uint64_t>& keys, int64_t n, int total_bits,
                   int32_t* order_out) {
  std::vector<int32_t> idx(n), idx2(n);
  for (int64_t i = 0; i < n; ++i) idx[i] = static_cast<int32_t>(i);
  std::vector<uint64_t> keys2(n);

  const int passes = (total_bits + 15) / 16;
  static thread_local std::vector<uint32_t> count(1 << 16);
  for (int pass = 0; pass < passes; ++pass) {
    const int shift = pass * 16;
    std::memset(count.data(), 0, count.size() * sizeof(uint32_t));
    for (int64_t i = 0; i < n; ++i) {
      ++count[(keys[i] >> shift) & 0xFFFF];
    }
    uint32_t sum = 0;
    for (uint32_t d = 0; d < (1u << 16); ++d) {
      uint32_t c = count[d];
      count[d] = sum;
      sum += c;
    }
    for (int64_t i = 0; i < n; ++i) {
      uint32_t pos = count[(keys[i] >> shift) & 0xFFFF]++;
      keys2[pos] = keys[i];
      idx2[pos] = idx[i];
    }
    keys.swap(keys2);
    idx.swap(idx2);
  }
  std::memcpy(order_out, idx.data(), n * sizeof(int32_t));
}

// Run-major counting argsort for composite run keys (shard/bucket bits)
// that fit a direct histogram: ONE stable counting pass on the run key,
// then a per-run stable fingerprint sort for the rare multi-key runs
// (store load factors keep mean keys/bucket around 1, and duplicate
// rows of ONE key share a fingerprint, so most runs are fp-uniform and
// skip the sort entirely). Output is bit-identical to the LSD radix on
// (run_key<<32 | fp) — fp ascending within a run, ties in input order —
// at ~3x less memory traffic for B=32k. skey/fp are per INPUT row;
// ends_out receives each run's END offset in the sorted order.
void counting_argsort_fp(const uint32_t* skey, const uint32_t* fp,
                         int64_t n, uint64_t space, int32_t* order_out,
                         std::vector<uint32_t>& ends_out) {
  ends_out.assign(space, 0);
  for (int64_t i = 0; i < n; ++i) ++ends_out[skey[i]];
  uint32_t sum = 0;
  for (uint64_t b = 0; b < space; ++b) {  // counts -> start offsets
    uint32_t c = ends_out[b];
    ends_out[b] = sum;
    sum += c;
  }
  for (int64_t i = 0; i < n; ++i) {  // stable scatter; starts -> ends
    order_out[ends_out[skey[i]]++] = static_cast<int32_t>(i);
  }
  int64_t s = 0;
  for (uint64_t b = 0; b < space; ++b) {
    const int64_t e = ends_out[b];
    if (e - s > 1) {
      const uint32_t f0 = fp[order_out[s]];
      bool uniform = true;
      for (int64_t i = s + 1; i < e; ++i) {
        if (fp[order_out[i]] != f0) {
          uniform = false;
          break;
        }
      }
      if (!uniform) {
        std::stable_sort(
            order_out + s, order_out + e,
            [&](int32_t a, int32_t c) { return fp[a] < fp[c]; });
      }
    }
    s = e;
  }
}

// Histograms above this are slower to zero than the radix passes save.
constexpr uint64_t COUNTING_SPACE_MAX = 1ULL << 16;
// The sharded composite (owner|bucket) key gets a larger cap: the bigger
// memset trades against skipping 3-4 radix passes instead of 2-3.
constexpr uint64_t SHARDED_COUNTING_SPACE_MAX = 1ULL << 18;

// Build the sharded run keys (owner << bucket_bits | bucket), per-row
// fingerprints, and per-shard row counts in one pass.
void build_sharded_keys(const uint64_t* key_hash, int64_t n, uint64_t bmask,
                        int bucket_bits, uint64_t n_shards,
                        int64_t* counts_out, std::vector<uint32_t>& sk,
                        std::vector<uint32_t>& fp) {
  sk.resize(n);
  fp.resize(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t owner = splitmix64(kh ^ SHARD_SALT) % n_shards;
    ++counts_out[owner];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    sk[i] = static_cast<uint32_t>((owner << bucket_bits) | bkt);
    uint32_t f = static_cast<uint32_t>(kh >> 32);
    if (f == 0) f = 1;
    fp[i] = f;
  }
}

// Walk the sorted runs emitting duplicate-key groups (fp-runs within a
// run key). When group_counts_out is non-null, each group also counts
// toward its owning shard (owner = run_key >> bucket_bits). Returns the
// group count.
int64_t emit_groups(const std::vector<uint32_t>& ends, uint64_t space,
                    const std::vector<uint32_t>& fp, const int32_t* order,
                    int32_t* group_id_out, int32_t* leader_pos_out,
                    int64_t* group_counts_out, int bucket_bits) {
  int64_t g = 0;
  int64_t s = 0;
  for (uint64_t r = 0; r < space; ++r) {
    const int64_t e = ends[r];
    int64_t i = s;
    while (i < e) {
      const uint32_t f = fp[order[i]];
      leader_pos_out[g] = static_cast<int32_t>(i);
      if (group_counts_out) ++group_counts_out[r >> bucket_bits];
      while (i < e && fp[order[i]] == f) {
        group_id_out[i] = static_cast<int32_t>(g);
        ++i;
      }
      ++g;
    }
    s = e;
  }
  return g;
}

// Single-device composite (bucket | fp) fast path; false -> radix.
bool counting_presort(const uint64_t* key_hash, int64_t n, uint64_t buckets,
                      int32_t* order_out, std::vector<uint32_t>& fp_out,
                      std::vector<uint32_t>& ends_out) {
  if (buckets > COUNTING_SPACE_MAX) return false;
  const uint64_t bmask = buckets - 1;
  fp_out.resize(n);
  static thread_local std::vector<uint32_t> bk;
  bk.resize(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    bk[i] = static_cast<uint32_t>(splitmix64(kh ^ BUCKET_SALT) & bmask);
    uint32_t f = static_cast<uint32_t>(kh >> 32);
    if (f == 0) f = 1;
    fp_out[i] = f;
  }
  counting_argsort_fp(bk.data(), fp_out.data(), n, buckets, order_out,
                      ends_out);
  return true;
}

}  // namespace

extern "C" {

// order_out[i] = index of the i-th row in (bucket, fingerprint) order.
// buckets must be a power of two. Stable (equal keys keep input order).
void guber_presort(const uint64_t* key_hash, int64_t n, uint64_t buckets,
                   int32_t* order_out) {
  {
    static thread_local std::vector<uint32_t> fp, ends;
    if (counting_presort(key_hash, n, buckets, order_out, fp, ends)) return;
  }
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;

  // sort key: (bucket << 32) | fingerprint  — 32 + bucket_bits bits
  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (bkt << 32) | fp;
  }
  radix_argsort(keys, n, 32 + bucket_bits, order_out);
}

// Batch marshalling: gather-with-permutation + pad in one C pass. The
// serving hot path must build the device request arrays (sorted by the
// presort permutation, clipped to the int32 envelope, padded by
// repeating the last sorted row) and unpermute the responses for every
// batch; the numpy version costs ~40ns/element across six fields
// (~630us/16k batch), this runs in one cache-friendly pass.

void guber_gather_pad_i64_clip(const int64_t* src, const int32_t* order,
                               int64_t n, int64_t b, int64_t lo, int64_t hi,
                               int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    int64_t v = src[order[i]];
    v = v < lo ? lo : (v > hi ? hi : v);
    out[i] = static_cast<int32_t>(v);
  }
  const int32_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

void guber_gather_pad_i32(const int32_t* src, const int32_t* order,
                          int64_t n, int64_t b, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[order[i]];
  const int32_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

void guber_gather_pad_u64(const uint64_t* src, const int32_t* order,
                          int64_t n, int64_t b, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[order[i]];
  const uint64_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

void guber_gather_pad_u8(const uint8_t* src, const int32_t* order,
                         int64_t n, int64_t b, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[order[i]];
  const uint8_t fill = n ? out[n - 1] : 0;
  for (int64_t i = n; i < b; ++i) out[i] = fill;
}

// out[order[i]] = sorted[i] for the first n positions of each of `k`
// response arrays laid out back to back ([k, b] row-major), writing into
// k output arrays of length b back to back.
void guber_unpermute_i32(const int32_t* sorted, const int32_t* order,
                         int64_t n, int64_t b, int64_t k, int32_t* out) {
  for (int64_t a = 0; a < k; ++a) {
    const int32_t* s = sorted + a * b;
    int32_t* o = out + a * b;
    for (int64_t i = 0; i < n; ++i) o[order[i]] = s[i];
  }
}

// guber_presort + group structure from the sorted key stream: the runs
// of equal (bucket, fingerprint) ARE the duplicate-key groups whose
// store I/O the kernel compacts (core/kernels.py BatchGroups), and they
// fall out of the sort for one extra O(n) pass. group_id_out[i] = group
// slot of sorted row i; leader_pos_out[g] = first sorted row of group g
// (only the first *n_groups_out entries are written).
void guber_presort_grouped(const uint64_t* key_hash, int64_t n,
                           uint64_t buckets, int32_t* order_out,
                           int32_t* group_id_out, int32_t* leader_pos_out,
                           int64_t* n_groups_out) {
  {
    static thread_local std::vector<uint32_t> fp, ends;
    if (counting_presort(key_hash, n, buckets, order_out, fp, ends)) {
      // groups are runs of equal fp within a bucket run (two distinct
      // key hashes sharing (bucket, fp) merge into one group — exactly
      // the composite-key behavior of the radix path, and of the store,
      // whose tag IS the fp)
      *n_groups_out = emit_groups(ends, buckets, fp, order_out,
                                  group_id_out, leader_pos_out, nullptr, 0);
      return;
    }
  }
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;

  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (bkt << 32) | fp;
  }
  std::vector<uint64_t> sorted(keys);  // radix_argsort leaves keys sorted,
  // but the buffer identity depends on pass parity — copy for clarity
  radix_argsort(sorted, n, 32 + bucket_bits, order_out);

  int64_t g = 0;
  uint64_t prev = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t k = keys[order_out[i]];
    if (i == 0 || k != prev) {
      leader_pos_out[g] = static_cast<int32_t>(i);
      ++g;
      prev = k;
    }
    group_id_out[i] = static_cast<int32_t>(g - 1);
  }
  *n_groups_out = g;
}

// Mesh-sharded presort: argsort by (owner_shard, bucket, fingerprint) and
// per-shard row counts. owner = splitmix64(kh ^ SHARD_SALT) % n_shards —
// must stay bit-identical to parallel/sharded.py owner_of / owner_of_np.
// Rows of one shard come out contiguous, internally in the (bucket, fp)
// order decide_presorted requires, so the host can slice per-shard
// sub-batches straight out of the permutation (batch-axis sharding over
// the mesh: each chip gets only the rows it owns).
void guber_presort_sharded(const uint64_t* key_hash, int64_t n,
                           uint64_t buckets, uint64_t n_shards,
                           int32_t* order_out, int64_t* counts_out) {
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;
  int shard_bits = 1;
  while ((1ULL << shard_bits) < n_shards) ++shard_bits;

  for (uint64_t s = 0; s < n_shards; ++s) counts_out[s] = 0;

  if ((n_shards << bucket_bits) <= SHARDED_COUNTING_SPACE_MAX) {
    static thread_local std::vector<uint32_t> sk, fp, ends;
    build_sharded_keys(key_hash, n, bmask, bucket_bits, n_shards,
                       counts_out, sk, fp);
    counting_argsort_fp(sk.data(), fp.data(), n, n_shards << bucket_bits,
                        order_out, ends);
    return;
  }

  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t owner = splitmix64(kh ^ SHARD_SALT) % n_shards;
    ++counts_out[owner];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (owner << (32 + bucket_bits)) | (bkt << 32) | fp;
  }
  radix_argsort(keys, n, 32 + bucket_bits + shard_bits, order_out);
}

// guber_presort_sharded + per-shard group structure: groups are runs of
// equal (owner, bucket, fp) composite keys in the sorted stream (shard
// boundaries break groups automatically — the owner rides the top sort
// bits). group_id_out[i] = GLOBAL group index of sorted row i;
// leader_pos_out[g] = first sorted row of global group g;
// group_counts_out[s] = number of groups owned by shard s.
void guber_presort_sharded_grouped(
    const uint64_t* key_hash, int64_t n, uint64_t buckets,
    uint64_t n_shards, int32_t* order_out, int64_t* counts_out,
    int32_t* group_id_out, int32_t* leader_pos_out,
    int64_t* group_counts_out) {
  const uint64_t bmask = buckets - 1;
  int bucket_bits = 0;
  while ((1ULL << bucket_bits) < buckets) ++bucket_bits;
  int shard_bits = 1;
  while ((1ULL << shard_bits) < n_shards) ++shard_bits;

  for (uint64_t s = 0; s < n_shards; ++s) {
    counts_out[s] = 0;
    group_counts_out[s] = 0;
  }

  if ((n_shards << bucket_bits) <= SHARDED_COUNTING_SPACE_MAX) {
    static thread_local std::vector<uint32_t> sk, fp, ends;
    build_sharded_keys(key_hash, n, bmask, bucket_bits, n_shards,
                       counts_out, sk, fp);
    const uint64_t space = n_shards << bucket_bits;
    counting_argsort_fp(sk.data(), fp.data(), n, space, order_out, ends);
    emit_groups(ends, space, fp, order_out, group_id_out, leader_pos_out,
                group_counts_out, bucket_bits);
    return;
  }

  std::vector<uint64_t> keys(n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t kh = key_hash[i];
    uint64_t owner = splitmix64(kh ^ SHARD_SALT) % n_shards;
    ++counts_out[owner];
    uint64_t bkt = splitmix64(kh ^ BUCKET_SALT) & bmask;
    uint64_t fp = kh >> 32;
    if (fp == 0) fp = 1;
    keys[i] = (owner << (32 + bucket_bits)) | (bkt << 32) | fp;
  }
  std::vector<uint64_t> sorted(keys);
  radix_argsort(sorted, n, 32 + bucket_bits + shard_bits, order_out);

  int64_t g = 0;
  uint64_t prev = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t k = keys[order_out[i]];
    if (i == 0 || k != prev) {
      leader_pos_out[g] = static_cast<int32_t>(i);
      ++group_counts_out[k >> (32 + bucket_bits)];
      ++g;
      prev = k;
    }
    group_id_out[i] = static_cast<int32_t>(g - 1);
  }
}

}  // extern "C"
