"""ctypes bindings for libguberhash.so (see guberhash.cc)."""

from __future__ import annotations

import ctypes
import pathlib
import threading
from typing import List

import numpy as np

_SO = pathlib.Path(__file__).resolve().parent / "libguberhash.so"
if not _SO.exists():
    raise ImportError(f"native hash library not built: {_SO}")

_lib = ctypes.CDLL(str(_SO))
_lib.guber_hash_batch.argtypes = [
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64,
    ctypes.c_uint64,
    ctypes.POINTER(ctypes.c_uint64),
]
_lib.guber_crc32_batch.argtypes = [
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint32),
]
try:  # symbol absent in a stale prebuilt .so — the hash/crc fast paths
    # above must keep working regardless; presort() raises if missing
    _lib.guber_presort.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    _HAS_PRESORT = True
except AttributeError:
    _HAS_PRESORT = False

try:
    _i32p = ctypes.POINTER(ctypes.c_int32)
    _lib.guber_gather_pad_i64_clip.argtypes = [
        ctypes.POINTER(ctypes.c_int64), _i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _i32p,
    ]
    _lib.guber_gather_pad_i32.argtypes = [
        _i32p, _i32p, ctypes.c_int64, ctypes.c_int64, _i32p,
    ]
    _lib.guber_gather_pad_u64.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), _i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
    ]
    _lib.guber_gather_pad_u8.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), _i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
    ]
    _lib.guber_unpermute_i32.argtypes = [
        _i32p, _i32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        _i32p,
    ]
    _HAS_MARSHAL = True
except AttributeError:
    _HAS_MARSHAL = False


def _ptr(a, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def gather_pad_i64_clip(src, order, b: int, lo: int, hi: int) -> np.ndarray:
    """int32[b] = clip(src[order], lo, hi) padded with its last value."""
    src = np.ascontiguousarray(src, np.int64)
    out = np.empty(b, np.int32)
    _lib.guber_gather_pad_i64_clip(
        _ptr(src, ctypes.c_int64), _ptr(order, ctypes.c_int32),
        src.shape[0], b, lo, hi, _ptr(out, ctypes.c_int32),
    )
    return out


def gather_pad_i32(src, order, b: int) -> np.ndarray:
    src = np.ascontiguousarray(src, np.int32)
    out = np.empty(b, np.int32)
    _lib.guber_gather_pad_i32(
        _ptr(src, ctypes.c_int32), _ptr(order, ctypes.c_int32),
        src.shape[0], b, _ptr(out, ctypes.c_int32),
    )
    return out


def gather_pad_u64(src, order, b: int) -> np.ndarray:
    src = np.ascontiguousarray(src, np.uint64)
    out = np.empty(b, np.uint64)
    _lib.guber_gather_pad_u64(
        _ptr(src, ctypes.c_uint64), _ptr(order, ctypes.c_int32),
        src.shape[0], b, _ptr(out, ctypes.c_uint64),
    )
    return out


def gather_pad_u8(src, order, b: int) -> np.ndarray:
    src = np.ascontiguousarray(src, np.uint8)
    out = np.empty(b, np.uint8)
    _lib.guber_gather_pad_u8(
        _ptr(src, ctypes.c_uint8), _ptr(order, ctypes.c_int32),
        src.shape[0], b, _ptr(out, ctypes.c_uint8),
    )
    return out


def unpermute_i32(sorted_stack: np.ndarray, order: np.ndarray,
                  n: int) -> np.ndarray:
    """[k, b] row-major response stack -> out[:, order[:n]] scatter:
    out[a, order[i]] = sorted[a, i] for i < n (padding rows untouched)."""
    sorted_stack = np.ascontiguousarray(sorted_stack, np.int32)
    k, b = sorted_stack.shape
    out = np.empty((k, b), np.int32)
    _lib.guber_unpermute_i32(
        _ptr(sorted_stack, ctypes.c_int32), _ptr(order, ctypes.c_int32),
        n, b, k, _ptr(out, ctypes.c_int32),
    )
    return out


try:
    _lib.guber_presort_grouped.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _HAS_PRESORT_GROUPED = True
except AttributeError:
    _HAS_PRESORT_GROUPED = False


def presort_grouped(key_hash: np.ndarray, buckets: int):
    """(order int32[n], group_id int32[n], leader_pos int32[n], G) —
    the presort permutation plus the duplicate-key group structure of
    the sorted stream (only leader_pos[:G] is meaningful)."""
    if not _HAS_PRESORT_GROUPED:
        raise AttributeError(
            "libguberhash.so predates guber_presort_grouped; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    n = kh.shape[0]
    order = np.empty(n, np.int32)
    group_id = np.empty(n, np.int32)
    leader_pos = np.empty(n, np.int32)
    G = ctypes.c_int64(0)
    _lib.guber_presort_grouped(
        _ptr(kh, ctypes.c_uint64), n, ctypes.c_uint64(buckets),
        _ptr(order, ctypes.c_int32), _ptr(group_id, ctypes.c_int32),
        _ptr(leader_pos, ctypes.c_int32), ctypes.byref(G),
    )
    return order, group_id, leader_pos, G.value


try:
    _lib.guber_presort_sharded.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _HAS_PRESORT_SHARDED = True
except AttributeError:
    _HAS_PRESORT_SHARDED = False

# Fixed seed: slot hashes are instance-local but stable across restarts for
# debuggability.
_SEED = 0x67756265726E6174  # "gubernat"


def _pack(keys: List[str]):
    bufs = [k.encode("utf-8") for k in keys]
    offsets = np.zeros(len(bufs) + 1, np.int64)
    np.cumsum([len(b) for b in bufs], out=offsets[1:])
    return b"".join(bufs), offsets


def hash_batch_seed(keys: List[str], seed: int) -> np.ndarray:
    """uint64[len(keys)] XXH64 hashes with an explicit seed (test hook)."""
    buf, offsets = _pack(keys)
    out = np.empty(len(keys), np.uint64)
    _lib.guber_hash_batch(
        buf,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(keys),
        ctypes.c_uint64(seed),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return out


def hash_batch(keys: List[str]) -> np.ndarray:
    """uint64[len(keys)] XXH64 slot hashes."""
    return hash_batch_seed(keys, _SEED)


def crc32_batch(keys: List[str]) -> np.ndarray:
    """uint32[len(keys)] IEEE crc32 ring points (matches zlib.crc32)."""
    buf, offsets = _pack(keys)
    out = np.empty(len(keys), np.uint32)
    _lib.guber_crc32_batch(
        buf,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(keys),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


def presort(key_hash: np.ndarray, buckets: int) -> np.ndarray:
    """int32[n] stable argsort of key hashes by (bucket, fingerprint) —
    the order decide_presorted requires. Bit-identical to
    np.argsort(store.group_sort_key_np(kh, buckets), kind="stable") and
    ~15x faster (LSD radix in C)."""
    if not _HAS_PRESORT:
        raise AttributeError(
            "libguberhash.so predates guber_presort; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    out = np.empty(kh.shape[0], np.int32)
    _lib.guber_presort(
        kh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        kh.shape[0],
        ctypes.c_uint64(buckets),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out


try:
    _lib.guber_presort_sharded_grouped.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _HAS_PRESORT_SHARDED_GROUPED = True
except AttributeError:
    _HAS_PRESORT_SHARDED_GROUPED = False


def presort_sharded_grouped(key_hash: np.ndarray, buckets: int,
                            n_shards: int):
    """(order, counts, group_id, leader_pos, group_counts) — the sharded
    presort plus per-shard duplicate-key group structure. group_id[i] is
    the GLOBAL group index of sorted row i; leader_pos[:sum(group_counts)]
    holds each global group's first sorted row; group_counts[s] counts
    shard s's groups."""
    if not _HAS_PRESORT_SHARDED_GROUPED:
        raise AttributeError(
            "libguberhash.so predates guber_presort_sharded_grouped; "
            "rebuild with make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    n = kh.shape[0]
    order = np.empty(n, np.int32)
    counts = np.empty(n_shards, np.int64)
    group_id = np.empty(n, np.int32)
    leader_pos = np.empty(n, np.int32)
    group_counts = np.empty(n_shards, np.int64)
    _lib.guber_presort_sharded_grouped(
        _ptr(kh, ctypes.c_uint64), n, ctypes.c_uint64(buckets),
        ctypes.c_uint64(n_shards), _ptr(order, ctypes.c_int32),
        _ptr(counts, ctypes.c_int64), _ptr(group_id, ctypes.c_int32),
        _ptr(leader_pos, ctypes.c_int32),
        _ptr(group_counts, ctypes.c_int64),
    )
    return order, counts, group_id, leader_pos, group_counts


def presort_sharded(key_hash: np.ndarray, buckets: int, n_shards: int):
    """(order int32[n], counts int64[n_shards]) — stable argsort by
    (owner_shard, bucket, fingerprint) plus per-shard row counts. The
    contiguous per-shard runs of the permutation are the mesh engine's
    per-chip sub-batches (parallel/sharded.py pad_request_sharded)."""
    if not _HAS_PRESORT_SHARDED:
        raise AttributeError(
            "libguberhash.so predates guber_presort_sharded; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    order = np.empty(kh.shape[0], np.int32)
    counts = np.empty(n_shards, np.int64)
    _lib.guber_presort_sharded(
        kh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        kh.shape[0],
        ctypes.c_uint64(buckets),
        ctypes.c_uint64(n_shards),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return order, counts


try:
    _u64p = ctypes.POINTER(ctypes.c_uint64)
    _i64p = ctypes.POINTER(ctypes.c_int64)
    _u8p = ctypes.POINTER(ctypes.c_uint8)
    _lib.guber_prep_sharded.restype = ctypes.c_int64
    _lib.guber_prep_sharded.argtypes = [
        _u64p, _i64p, _i64p, _i64p, _i32p, _u8p,          # inputs
        ctypes.c_int64, ctypes.c_uint64, ctypes.c_int64,  # n, buckets, ns
        _i64p, ctypes.c_int64, ctypes.c_int64,            # rungs, n_rungs, g_override
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # clips
        _i32p, _i64p, _i64p,                              # order, counts, picked
        _u64p, _i32p, _i32p, _i32p, _i32p, _u8p, _u8p,    # fields
        _u64p, _i32p, _i32p, _u8p, _i32p,                 # groups
        _i64p,                                            # take_idx
    ]
    _lib.guber_prep_threads.restype = ctypes.c_int64
    _lib.guber_unflatten_resp.argtypes = [
        _i32p, _i32p, _i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, _i32p,
    ]
    _HAS_PREP = True
except AttributeError:
    _HAS_PREP = False


def prep_threads() -> int:
    """Effective prep thread-pool width (GUBER_PREP_THREADS env,
    default hardware_concurrency; resolved once per process)."""
    if not _HAS_PREP:
        return 1
    return int(_lib.guber_prep_threads())


_PREP_GENS = 2


def set_prep_generations(gens: int) -> None:
    """Size the prep-buffer ring (serve/batcher.py's fetch_depth sets
    gens = depth + 1 at construction, before traffic). Generation k is
    reused at the k+gens'th prep call on the same thread.

    NOTE the ring is NOT what guarantees in-flight correctness under the
    batcher's out-of-order fetch pipeline — no fixed depth could (a
    stalled fetch can be outrun by later submits without bound). The
    guarantees are: (a) decide handles COPY the order/take views they
    keep (sharded.decide_submit), and (b) jax commits host inputs during
    dispatch, before submit returns (verified by mutate-after-dispatch).
    The deeper ring is defense-in-depth for PJRT backends whose dispatch
    might stage host buffers lazily. Threads pick the new width up on
    their next prep call."""
    global _PREP_GENS
    _PREP_GENS = max(2, int(gens))


class _PrepBuffers:
    """Reusable output buffers for prep_sharded, rotated across calls.
    Fresh np.empty per call costs ~0.5-1ms of soft page faults at
    32k batches (every large allocation is a new zeroed mmap); reusing
    warm pages removes that entirely. The ring holds _PREP_GENS
    generations (default two: at most two batches in flight, submits
    serialized — serve/batcher.py) so a pipelined engine never sees
    generation k's arrays overwritten before its wait."""

    _SPECS = (
        ("order", np.int32), ("counts", np.int64), ("take", np.int64),
        ("kh", np.uint64), ("hits", np.int32), ("limit", np.int32),
        ("dur", np.int32), ("algo", np.int32), ("gnp", np.uint8),
        ("valid", np.uint8), ("gid", np.int32), ("gkh", np.uint64),
        ("glead", np.int32), ("gend", np.int32), ("gvalid", np.uint8),
    )

    def __init__(self):
        self._gens: list = []
        self._flip = 0

    def take(self, sizes: dict) -> dict:
        if len(self._gens) != _PREP_GENS:
            # ring width changed (set_prep_generations) or first use
            self._gens = [{} for _ in range(_PREP_GENS)]
            self._flip = 0
        gen = self._gens[self._flip]
        self._flip = (self._flip + 1) % len(self._gens)
        out = {}
        for name, dtype in self._SPECS:
            need = sizes[name]
            cur = gen.get(name)
            if cur is None or cur.shape[0] < need:
                cur = np.empty(need, dtype)
                gen[name] = cur
            out[name] = cur
        return out


class _PrepBuffersTL(threading.local):
    """Per-thread buffer sets: K concurrent prep workers (the batcher's
    prep pool) each flip-flop their own generations, so one worker's
    in-flight batch is never overwritten by another's call."""

    def __init__(self):
        self.bufs = _PrepBuffers()


_prep_buffers_tl = _PrepBuffersTL()


def prep_sharded(
    key_hash, hits, limit, duration, algo, gnp,
    buckets: int, n_shards: int, rungs, g_override: int,
    lo: int, hi: int, dlo: int, dhi: int,
):
    """One-call sharded batch prep (guber_prep_sharded): presort by
    (owner, bucket, fingerprint), duplicate-key group structure with
    engine.build_groups conventions, and all six clipped+padded device
    fields as [n_shards, B_sub] arrays. Returns
    (order, counts, take_idx, fields_dict, groups_dict, B_sub, G_sub).
    Raises ValueError when g_override can't hold a shard's group count
    (mirrors pad_request_sharded's numpy path).

    LIFETIME: returned arrays are views into a reusable buffer ring —
    valid until the _PREP_GENS'th next prep_sharded call on the same
    thread (default 2; see set_prep_generations). Callers keeping
    results past that — e.g. decide handles under a deep fetch
    pipeline — must copy."""
    if not _HAS_PREP:
        raise AttributeError(
            "libguberhash.so predates guber_prep_sharded; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    hits = np.ascontiguousarray(hits, np.int64)
    limit = np.ascontiguousarray(limit, np.int64)
    duration = np.ascontiguousarray(duration, np.int64)
    algo = np.ascontiguousarray(algo, np.int32)
    gnp = np.ascontiguousarray(np.asarray(gnp, bool).view(np.uint8))
    n = kh.shape[0]
    rungs = np.ascontiguousarray(rungs, np.int64)
    # B_sub <= smallest rung covering n (shard counts never exceed n)
    alloc_idx = int(np.searchsorted(rungs, min(n, int(rungs[-1]))))
    B_alloc = int(rungs[min(alloc_idx, rungs.shape[0] - 1)])
    if g_override > 0:
        B_alloc = max(B_alloc, int(g_override))

    nb = n_shards * B_alloc
    buf = _prep_buffers_tl.bufs.take(dict(
        order=n, counts=n_shards, take=n,
        kh=nb, hits=nb, limit=nb, dur=nb, algo=nb, gnp=nb, valid=nb,
        gid=nb, gkh=nb, glead=nb, gend=nb, gvalid=nb,
    ))
    order = buf["order"][:n]
    counts = buf["counts"][:n_shards]
    picked = np.empty(2, np.int64)
    take_idx = buf["take"][:n]
    kh_o, hi_o, li_o, du_o = buf["kh"], buf["hits"], buf["limit"], buf["dur"]
    al_o, gn_o, va_o, gi_o = buf["algo"], buf["gnp"], buf["valid"], buf["gid"]
    gk_o, gl_o, ge_o, gv_o = buf["gkh"], buf["glead"], buf["gend"], buf["gvalid"]

    rc = _lib.guber_prep_sharded(
        _ptr(kh, ctypes.c_uint64), _ptr(hits, ctypes.c_int64),
        _ptr(limit, ctypes.c_int64), _ptr(duration, ctypes.c_int64),
        _ptr(algo, ctypes.c_int32), _ptr(gnp, ctypes.c_uint8),
        n, ctypes.c_uint64(buckets), n_shards,
        _ptr(rungs, ctypes.c_int64), rungs.shape[0], g_override,
        lo, hi, dlo, dhi,
        _ptr(order, ctypes.c_int32), _ptr(counts, ctypes.c_int64),
        _ptr(picked, ctypes.c_int64),
        _ptr(kh_o, ctypes.c_uint64), _ptr(hi_o, ctypes.c_int32),
        _ptr(li_o, ctypes.c_int32), _ptr(du_o, ctypes.c_int32),
        _ptr(al_o, ctypes.c_int32), _ptr(gn_o, ctypes.c_uint8),
        _ptr(va_o, ctypes.c_uint8),
        _ptr(gk_o, ctypes.c_uint64), _ptr(gl_o, ctypes.c_int32),
        _ptr(ge_o, ctypes.c_int32), _ptr(gv_o, ctypes.c_uint8),
        _ptr(gi_o, ctypes.c_int32),
        _ptr(take_idx, ctypes.c_int64),
    )
    if rc == -2:
        raise ValueError(
            f"group_rung {g_override} < max shard group count"
        )
    if rc != 0:
        raise RuntimeError(f"guber_prep_sharded failed: rc={rc}")
    B, G = int(picked[0]), int(picked[1])

    def view2(a, w):
        return a[: n_shards * w].reshape(n_shards, w)

    fields = dict(
        key_hash=view2(kh_o, B), hits=view2(hi_o, B),
        limit=view2(li_o, B), duration=view2(du_o, B),
        algo=view2(al_o, B), gnp=view2(gn_o, B).view(bool),
        valid=view2(va_o, B).view(bool),
    )
    groups = dict(
        key_hash=view2(gk_o, G), leader_pos=view2(gl_o, G),
        end_pos=view2(ge_o, G), valid=view2(gv_o, G).view(bool),
        group_id=view2(gi_o, B),
    )
    return order, counts, take_idx, fields, groups, B, G


try:
    _lib.guber_merge_runs.restype = ctypes.c_int64
    _vpp = ctypes.POINTER(ctypes.c_void_p)
    _lib.guber_merge_runs.argtypes = [
        _vpp, _vpp, _vpp, _vpp, _vpp, _vpp, _vpp, _vpp,
        _i64p, _i64p, ctypes.c_int64, ctypes.c_int64,
        _i64p, ctypes.c_int64,
        _u64p, _i32p, _u64p, _i32p, _i32p, _i32p, _i32p, _u8p, _u8p,
        _i32p, _i32p, _u64p, _i32p, _u8p, _i64p, _i64p,
    ]
    _HAS_MERGE = True
except AttributeError:
    _HAS_MERGE = False

try:
    _lib.guber_prep_run.restype = ctypes.c_int64
    _lib.guber_prep_run.argtypes = [
        _u64p, _i64p, _i64p, _i64p, _i32p, _u8p,
        ctypes.c_int64, ctypes.c_uint64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        _i32p, _i64p, _u64p, _u64p, _i32p, _i32p, _i32p, _i32p, _u8p,
    ]
    _HAS_PREP_RUN = True
except AttributeError:
    _HAS_PREP_RUN = False


def prep_run(fields: dict, buckets: int, n_shards: int,
             lo: int, hi: int, dlo: int, dhi: int) -> dict:
    """Fused arrival-time per-group prep (guber_prep_run): sharded
    presort + device-dtype clip/gather of all six fields + the merged
    composite sort-key stream, in ONE GIL-free call — the producer
    side of merge_runs_native. Output layout matches the engines'
    numpy prep_run fallbacks bit-for-bit."""
    if not _HAS_PREP_RUN:
        raise AttributeError(
            "libguberhash.so predates guber_prep_run; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(fields["key_hash"], np.uint64)
    hits = np.ascontiguousarray(fields["hits"], np.int64)
    limit = np.ascontiguousarray(fields["limit"], np.int64)
    duration = np.ascontiguousarray(fields["duration"], np.int64)
    algo = np.ascontiguousarray(fields["algo"], np.int32)
    gnp = np.ascontiguousarray(np.asarray(fields["gnp"], bool).view(np.uint8))
    n = kh.shape[0]
    order = np.empty(n, np.int32)
    counts = np.empty(n_shards, np.int64)
    skey = np.empty(n, np.uint64)
    kh_o = np.empty(n, np.uint64)
    hits_o = np.empty(n, np.int32)
    lim_o = np.empty(n, np.int32)
    dur_o = np.empty(n, np.int32)
    algo_o = np.empty(n, np.int32)
    gnp_o = np.empty(n, np.uint8)
    rc = _lib.guber_prep_run(
        _ptr(kh, ctypes.c_uint64), _ptr(hits, ctypes.c_int64),
        _ptr(limit, ctypes.c_int64), _ptr(duration, ctypes.c_int64),
        _ptr(algo, ctypes.c_int32), _ptr(gnp, ctypes.c_uint8),
        n, ctypes.c_uint64(buckets), n_shards, lo, hi, dlo, dhi,
        _ptr(order, ctypes.c_int32), _ptr(counts, ctypes.c_int64),
        _ptr(skey, ctypes.c_uint64), _ptr(kh_o, ctypes.c_uint64),
        _ptr(hits_o, ctypes.c_int32), _ptr(lim_o, ctypes.c_int32),
        _ptr(dur_o, ctypes.c_int32), _ptr(algo_o, ctypes.c_int32),
        _ptr(gnp_o, ctypes.c_uint8),
    )
    if rc != 0:
        raise RuntimeError(f"guber_prep_run failed: rc={rc}")
    run = dict(
        n=n, skey=skey, order=order, counts=counts,
        fields=dict(
            key_hash=kh_o, hits=hits_o, limit=lim_o, duration=dur_o,
            algo=algo_o, gnp=gnp_o.view(bool),
        ),
    )
    run["_addrs"] = run_addrs(run)
    return run


def run_addrs(run: dict) -> tuple:
    """Raw data addresses of one prep run's arrays, in
    guber_merge_runs' table column order. prep_run stamps this into the
    run at arrival time so the flush-time merge pays zero per-run
    ctypes-interface construction on the submit thread."""
    f = run["fields"]
    return (
        run["skey"].ctypes.data,
        f["key_hash"].ctypes.data,
        f["hits"].ctypes.data,
        f["limit"].ctypes.data,
        f["duration"].ctypes.data,
        f["algo"].ctypes.data,
        f["gnp"].ctypes.data,
        run["order"].ctypes.data,
    )


def merge_runs_native(runs, B: int, g_rungs=None) -> dict:
    """Fused k-way merge of pre-sorted per-group runs (guber_merge_runs):
    one GIL-free pass produces the merged sort-key stream, the global
    caller-order permutation, all six device-dtype field arrays padded
    to B rows (tail repeats the last merged row, valid=False — the
    engine's padding convention; pass B == n for a flat merge), and the
    duplicate-key group stream. `runs` are engine prep_run dicts in
    caller order; ties across runs resolve in run order, so the merged
    permutation equals np.argsort(concat, kind='stable') — the
    merge-combine equivalence contract (tests/test_prep_pipeline.py).

    Returns dict(n, skey[n], order[B], key_hash/hits/limit/duration/
    algo[B], gnp/valid[B] (bool), group_id[n], leader_pos[n], G_real).
    With `g_rungs` (engine.group_rungs(B)), the group stream is padded
    in the same pass to the smallest fitting rung G — build_groups'
    conventions — and the dict gains G, group_key_hash/group_end/
    group_valid [:G], padded leader_pos [:G], and a B-sized group_id.
    """
    if not _HAS_MERGE:
        raise AttributeError(
            "libguberhash.so predates guber_merge_runs; rebuild with "
            "make -C gubernator_tpu/native"
        )
    k = len(runs)
    n = int(sum(r["n"] for r in runs))
    assert B >= n, (B, n)

    # pointer tables from the per-run address tuples prep stamped at
    # ARRIVAL (run_addrs below) — `.ctypes.data` per array here would
    # cost ~8k ctypes-interface constructions of pure submit-thread
    # Python, which is exactly the wall this path exists to remove.
    # The run dicts keep the arrays alive for the duration of the call.
    addrs = [r.get("_addrs") or run_addrs(r) for r in runs]
    tabs = [
        (ctypes.c_void_p * k)(*[a[col] for a in addrs])
        for col in range(8)
    ]
    ns = np.asarray([r["n"] for r in runs], np.int64)
    bases = np.zeros(k, np.int64)
    np.cumsum(ns[:-1], out=bases[1:])

    if g_rungs is not None:
        rungs = np.ascontiguousarray(g_rungs, np.int64)
        g_max = int(rungs[-1])
    else:
        rungs = np.empty(0, np.int64)
        g_max = 0
    skey = np.empty(n, np.uint64)
    order = np.empty(B, np.int32)
    kh = np.empty(B, np.uint64)
    hits = np.empty(B, np.int32)
    limit = np.empty(B, np.int32)
    dur = np.empty(B, np.int32)
    algo = np.empty(B, np.int32)
    gnp = np.empty(B, np.uint8)
    valid = np.empty(B, np.uint8)
    gid = np.empty(max(B if g_rungs is not None else n, 1), np.int32)
    lead = np.empty(max(n, g_max, 1), np.int32)
    gkh = np.empty(max(g_max, 1), np.uint64)
    gend = np.empty(max(g_max, 1), np.int32)
    gvalid = np.empty(max(g_max, 1), np.uint8)
    g_real = ctypes.c_int64(0)
    g_pick = ctypes.c_int64(0)
    rc = _lib.guber_merge_runs(
        *tabs,
        _ptr(ns, ctypes.c_int64), _ptr(bases, ctypes.c_int64), k, B,
        _ptr(rungs, ctypes.c_int64), rungs.shape[0],
        _ptr(skey, ctypes.c_uint64), _ptr(order, ctypes.c_int32),
        _ptr(kh, ctypes.c_uint64), _ptr(hits, ctypes.c_int32),
        _ptr(limit, ctypes.c_int32), _ptr(dur, ctypes.c_int32),
        _ptr(algo, ctypes.c_int32), _ptr(gnp, ctypes.c_uint8),
        _ptr(valid, ctypes.c_uint8), _ptr(gid, ctypes.c_int32),
        _ptr(lead, ctypes.c_int32), _ptr(gkh, ctypes.c_uint64),
        _ptr(gend, ctypes.c_int32), _ptr(gvalid, ctypes.c_uint8),
        ctypes.byref(g_real), ctypes.byref(g_pick),
    )
    if rc != 0:
        raise RuntimeError(f"guber_merge_runs failed: rc={rc}")
    out = dict(
        n=n, skey=skey, order=order, key_hash=kh, hits=hits,
        limit=limit, duration=dur, algo=algo, gnp=gnp.view(bool),
        valid=valid.view(bool), G_real=int(g_real.value),
    )
    if g_rungs is not None:
        G = int(g_pick.value)
        out.update(
            G=G, group_id=gid, leader_pos=lead[:G],
            group_key_hash=gkh[:G], group_end=gend[:G],
            group_valid=gvalid[:G].view(bool),
        )
    else:
        out.update(group_id=gid[:n], leader_pos=lead[:n])
    return out


def unflatten_resp(packed, order, counts, n: int, b_sub: int) -> np.ndarray:
    """[4, n] response columns from a mesh packed matrix
    ([n_shards, 4*b_sub + stats] int32): the native twin of
    `out[order] = flat[take_idx]` per column. `b_sub` comes from the
    caller's handle — inferring it from the stride would silently skew
    every column if the stats tail ever grew."""
    packed = np.ascontiguousarray(packed, np.int32)
    n_shards, stride = packed.shape
    assert stride >= 4 * b_sub, (stride, b_sub)
    counts = np.ascontiguousarray(counts, np.int64)
    out = np.empty((4, n), np.int32)
    _lib.guber_unflatten_resp(
        _ptr(packed, ctypes.c_int32), _ptr(order, ctypes.c_int32),
        _ptr(counts, ctypes.c_int64), n, n_shards, b_sub, stride,
        _ptr(out, ctypes.c_int32),
    )
    return out
