"""ctypes bindings for libguberhash.so (see guberhash.cc)."""

from __future__ import annotations

import ctypes
import pathlib
from typing import List

import numpy as np

_SO = pathlib.Path(__file__).resolve().parent / "libguberhash.so"
if not _SO.exists():
    raise ImportError(f"native hash library not built: {_SO}")

_lib = ctypes.CDLL(str(_SO))
_lib.guber_hash_batch.argtypes = [
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64,
    ctypes.c_uint64,
    ctypes.POINTER(ctypes.c_uint64),
]
_lib.guber_crc32_batch.argtypes = [
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint32),
]
try:  # symbol absent in a stale prebuilt .so — the hash/crc fast paths
    # above must keep working regardless; presort() raises if missing
    _lib.guber_presort.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    _HAS_PRESORT = True
except AttributeError:
    _HAS_PRESORT = False

try:
    _i32p = ctypes.POINTER(ctypes.c_int32)
    _lib.guber_gather_pad_i64_clip.argtypes = [
        ctypes.POINTER(ctypes.c_int64), _i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _i32p,
    ]
    _lib.guber_gather_pad_i32.argtypes = [
        _i32p, _i32p, ctypes.c_int64, ctypes.c_int64, _i32p,
    ]
    _lib.guber_gather_pad_u64.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), _i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
    ]
    _lib.guber_gather_pad_u8.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), _i32p, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
    ]
    _lib.guber_unpermute_i32.argtypes = [
        _i32p, _i32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        _i32p,
    ]
    _HAS_MARSHAL = True
except AttributeError:
    _HAS_MARSHAL = False


def _ptr(a, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def gather_pad_i64_clip(src, order, b: int, lo: int, hi: int) -> np.ndarray:
    """int32[b] = clip(src[order], lo, hi) padded with its last value."""
    src = np.ascontiguousarray(src, np.int64)
    out = np.empty(b, np.int32)
    _lib.guber_gather_pad_i64_clip(
        _ptr(src, ctypes.c_int64), _ptr(order, ctypes.c_int32),
        src.shape[0], b, lo, hi, _ptr(out, ctypes.c_int32),
    )
    return out


def gather_pad_i32(src, order, b: int) -> np.ndarray:
    src = np.ascontiguousarray(src, np.int32)
    out = np.empty(b, np.int32)
    _lib.guber_gather_pad_i32(
        _ptr(src, ctypes.c_int32), _ptr(order, ctypes.c_int32),
        src.shape[0], b, _ptr(out, ctypes.c_int32),
    )
    return out


def gather_pad_u64(src, order, b: int) -> np.ndarray:
    src = np.ascontiguousarray(src, np.uint64)
    out = np.empty(b, np.uint64)
    _lib.guber_gather_pad_u64(
        _ptr(src, ctypes.c_uint64), _ptr(order, ctypes.c_int32),
        src.shape[0], b, _ptr(out, ctypes.c_uint64),
    )
    return out


def gather_pad_u8(src, order, b: int) -> np.ndarray:
    src = np.ascontiguousarray(src, np.uint8)
    out = np.empty(b, np.uint8)
    _lib.guber_gather_pad_u8(
        _ptr(src, ctypes.c_uint8), _ptr(order, ctypes.c_int32),
        src.shape[0], b, _ptr(out, ctypes.c_uint8),
    )
    return out


def unpermute_i32(sorted_stack: np.ndarray, order: np.ndarray,
                  n: int) -> np.ndarray:
    """[k, b] row-major response stack -> out[:, order[:n]] scatter:
    out[a, order[i]] = sorted[a, i] for i < n (padding rows untouched)."""
    sorted_stack = np.ascontiguousarray(sorted_stack, np.int32)
    k, b = sorted_stack.shape
    out = np.empty((k, b), np.int32)
    _lib.guber_unpermute_i32(
        _ptr(sorted_stack, ctypes.c_int32), _ptr(order, ctypes.c_int32),
        n, b, k, _ptr(out, ctypes.c_int32),
    )
    return out


try:
    _lib.guber_presort_grouped.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _HAS_PRESORT_GROUPED = True
except AttributeError:
    _HAS_PRESORT_GROUPED = False


def presort_grouped(key_hash: np.ndarray, buckets: int):
    """(order int32[n], group_id int32[n], leader_pos int32[n], G) —
    the presort permutation plus the duplicate-key group structure of
    the sorted stream (only leader_pos[:G] is meaningful)."""
    if not _HAS_PRESORT_GROUPED:
        raise AttributeError(
            "libguberhash.so predates guber_presort_grouped; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    n = kh.shape[0]
    order = np.empty(n, np.int32)
    group_id = np.empty(n, np.int32)
    leader_pos = np.empty(n, np.int32)
    G = ctypes.c_int64(0)
    _lib.guber_presort_grouped(
        _ptr(kh, ctypes.c_uint64), n, ctypes.c_uint64(buckets),
        _ptr(order, ctypes.c_int32), _ptr(group_id, ctypes.c_int32),
        _ptr(leader_pos, ctypes.c_int32), ctypes.byref(G),
    )
    return order, group_id, leader_pos, G.value


try:
    _lib.guber_presort_sharded.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _HAS_PRESORT_SHARDED = True
except AttributeError:
    _HAS_PRESORT_SHARDED = False

# Fixed seed: slot hashes are instance-local but stable across restarts for
# debuggability.
_SEED = 0x67756265726E6174  # "gubernat"


def _pack(keys: List[str]):
    bufs = [k.encode("utf-8") for k in keys]
    offsets = np.zeros(len(bufs) + 1, np.int64)
    np.cumsum([len(b) for b in bufs], out=offsets[1:])
    return b"".join(bufs), offsets


def hash_batch_seed(keys: List[str], seed: int) -> np.ndarray:
    """uint64[len(keys)] XXH64 hashes with an explicit seed (test hook)."""
    buf, offsets = _pack(keys)
    out = np.empty(len(keys), np.uint64)
    _lib.guber_hash_batch(
        buf,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(keys),
        ctypes.c_uint64(seed),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return out


def hash_batch(keys: List[str]) -> np.ndarray:
    """uint64[len(keys)] XXH64 slot hashes."""
    return hash_batch_seed(keys, _SEED)


def crc32_batch(keys: List[str]) -> np.ndarray:
    """uint32[len(keys)] IEEE crc32 ring points (matches zlib.crc32)."""
    buf, offsets = _pack(keys)
    out = np.empty(len(keys), np.uint32)
    _lib.guber_crc32_batch(
        buf,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(keys),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


def presort(key_hash: np.ndarray, buckets: int) -> np.ndarray:
    """int32[n] stable argsort of key hashes by (bucket, fingerprint) —
    the order decide_presorted requires. Bit-identical to
    np.argsort(store.group_sort_key_np(kh, buckets), kind="stable") and
    ~15x faster (LSD radix in C)."""
    if not _HAS_PRESORT:
        raise AttributeError(
            "libguberhash.so predates guber_presort; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    out = np.empty(kh.shape[0], np.int32)
    _lib.guber_presort(
        kh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        kh.shape[0],
        ctypes.c_uint64(buckets),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out


try:
    _lib.guber_presort_sharded_grouped.argtypes = [
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _HAS_PRESORT_SHARDED_GROUPED = True
except AttributeError:
    _HAS_PRESORT_SHARDED_GROUPED = False


def presort_sharded_grouped(key_hash: np.ndarray, buckets: int,
                            n_shards: int):
    """(order, counts, group_id, leader_pos, group_counts) — the sharded
    presort plus per-shard duplicate-key group structure. group_id[i] is
    the GLOBAL group index of sorted row i; leader_pos[:sum(group_counts)]
    holds each global group's first sorted row; group_counts[s] counts
    shard s's groups."""
    if not _HAS_PRESORT_SHARDED_GROUPED:
        raise AttributeError(
            "libguberhash.so predates guber_presort_sharded_grouped; "
            "rebuild with make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    n = kh.shape[0]
    order = np.empty(n, np.int32)
    counts = np.empty(n_shards, np.int64)
    group_id = np.empty(n, np.int32)
    leader_pos = np.empty(n, np.int32)
    group_counts = np.empty(n_shards, np.int64)
    _lib.guber_presort_sharded_grouped(
        _ptr(kh, ctypes.c_uint64), n, ctypes.c_uint64(buckets),
        ctypes.c_uint64(n_shards), _ptr(order, ctypes.c_int32),
        _ptr(counts, ctypes.c_int64), _ptr(group_id, ctypes.c_int32),
        _ptr(leader_pos, ctypes.c_int32),
        _ptr(group_counts, ctypes.c_int64),
    )
    return order, counts, group_id, leader_pos, group_counts


def presort_sharded(key_hash: np.ndarray, buckets: int, n_shards: int):
    """(order int32[n], counts int64[n_shards]) — stable argsort by
    (owner_shard, bucket, fingerprint) plus per-shard row counts. The
    contiguous per-shard runs of the permutation are the mesh engine's
    per-chip sub-batches (parallel/sharded.py pad_request_sharded)."""
    if not _HAS_PRESORT_SHARDED:
        raise AttributeError(
            "libguberhash.so predates guber_presort_sharded; rebuild with "
            "make -C gubernator_tpu/native"
        )
    kh = np.ascontiguousarray(key_hash, np.uint64)
    order = np.empty(kh.shape[0], np.int32)
    counts = np.empty(n_shards, np.int64)
    _lib.guber_presort_sharded(
        kh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        kh.shape[0],
        ctypes.c_uint64(buckets),
        ctypes.c_uint64(n_shards),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return order, counts
