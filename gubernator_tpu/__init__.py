"""gubernator-tpu: a TPU-native distributed rate-limiting framework.

A brand-new implementation of the capabilities of Mailgun's Gubernator
(reference: github.com/mailgun/gubernator v0.5.0), re-designed TPU-first:

- Rate-limit bucket state lives as dense integer arrays in TPU HBM (a d-way
  set-associative fingerprint "slot store", a counting-sketch relative of the
  reference's LRU hash map, /root/reference/cache/lru.go).
- Token-bucket / leaky-bucket decisions (reference algorithms.go:24,88) are a
  single branch-free, vmapped, jitted XLA kernel evaluated over request
  batches; duplicate keys within a batch are made associative with a
  sort + segmented-prefix-sum pass.
- The consistent-hash peer ring (reference hash.go) maps onto mesh axes of a
  `jax.sharding.Mesh`; cross-shard combination and the GLOBAL gossip loop
  (reference global.go) become `jax.lax.psum` collectives over ICI.
- The serving edge keeps the reference's public contract: gRPC `V1` and
  `PeersV1` services, HTTP JSON gateway, Prometheus `/metrics`, `GUBER_*`
  env config, micro-batched peer forwarding.

Integer time/counter math is int64 end to end (matching the reference's
wire types), so x64 mode is enabled at import.
"""

import jax

# Rate-limit math is int64 on the wire (proto int64 hits/limit/duration and
# unix-millisecond timestamps); enable x64 so device state matches exactly.
jax.config.update("jax_enable_x64", True)

from gubernator_tpu.api.types import (  # noqa: E402
    Algorithm,
    Behavior,
    Status,
    RateLimitReq,
    RateLimitResp,
    HealthCheckResp,
    hash_key,
    MILLISECOND,
    SECOND,
    MINUTE,
    HOUR,
)

__version__ = "0.1.0"

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitReq",
    "RateLimitResp",
    "HealthCheckResp",
    "hash_key",
    "MILLISECOND",
    "SECOND",
    "MINUTE",
    "HOUR",
    "__version__",
]
