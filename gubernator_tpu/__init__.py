"""gubernator-tpu: a TPU-native distributed rate-limiting framework.

A brand-new implementation of the capabilities of Mailgun's Gubernator
(reference: github.com/mailgun/gubernator v0.5.0), re-designed TPU-first:

- Rate-limit bucket state lives as dense integer arrays in TPU HBM (a d-way
  set-associative fingerprint "slot store", a counting-sketch relative of the
  reference's LRU hash map, /root/reference/cache/lru.go).
- Token-bucket / leaky-bucket decisions (reference algorithms.go:24,88) are a
  single branch-free, vmapped, jitted XLA kernel evaluated over request
  batches; duplicate keys within a batch are made associative with a
  sort + segmented-prefix-sum pass.
- The consistent-hash peer ring (reference hash.go) maps onto mesh axes of a
  `jax.sharding.Mesh`; cross-shard combination and the GLOBAL gossip loop
  (reference global.go) become `jax.lax.psum` collectives over ICI.
- The serving edge keeps the reference's public contract: gRPC `V1` and
  `PeersV1` services, HTTP JSON gateway, Prometheus `/metrics`, `GUBER_*`
  env config, micro-batched peer forwarding.

Integer time/counter math is int64 end to end (matching the reference's
wire types); x64 mode is enabled by `gubernator_tpu.core` (the first
import of every jax-touching module). This package root is deliberately
JAX-free so the client seam (`gubernator_tpu.client`, the API types, the
generated stubs) imports on hosts without JAX installed — the reference
ships its Python client standalone (reference python/setup.py) and an
external consumer here gets the same: `import gubernator_tpu.client`
pulls in grpc + protobuf only (pinned by tests/test_client_nojax.py).
"""

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    Status,
    RateLimitReq,
    RateLimitResp,
    HealthCheckResp,
    hash_key,
    MILLISECOND,
    SECOND,
    MINUTE,
    HOUR,
)

__version__ = "0.1.0"

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitReq",
    "RateLimitResp",
    "HealthCheckResp",
    "hash_key",
    "MILLISECOND",
    "SECOND",
    "MINUTE",
    "HOUR",
    "__version__",
]
