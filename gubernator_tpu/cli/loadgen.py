"""CLI load generator.

`python -m gubernator_tpu.cli.loadgen <address>` replays a pool of random
token-bucket limits through a concurrent fan-out forever, dumping
OVER_LIMIT responses (the reference's cmd/gubernator-cli).
"""

import argparse
import asyncio
import sys
import time

from gubernator_tpu.api.types import Algorithm, Behavior, RateLimitReq, Status
from gubernator_tpu.client import AsyncV1Client, random_string


async def run(
    address: str, keys: int, concurrency: int, batch: int, duration: float
) -> None:
    client = AsyncV1Client(address)
    pool = [
        RateLimitReq(
            name=f"ID-{i:04d}",
            unique_key=random_string("id-"),
            hits=1,
            limit=(i % 100) + 1,
            duration=((i % 50) + 1) * 1000,
            algorithm=Algorithm.TOKEN_BUCKET,
            behavior=Behavior.BATCHING,
        )
        for i in range(keys)
    ]

    stats = {"sent": 0, "over": 0, "errors": 0}
    stop_at = time.monotonic() + duration if duration > 0 else None

    async def worker(wid: int):
        i = wid
        while stop_at is None or time.monotonic() < stop_at:
            reqs = [pool[(i + j) % len(pool)] for j in range(batch)]
            i += batch * concurrency
            try:
                resps = await client.get_rate_limits(reqs, timeout=5)
            except Exception as e:
                stats["errors"] += 1
                print(f"error: {e}", file=sys.stderr)
                await asyncio.sleep(0.1)
                continue
            stats["sent"] += len(resps)
            for r in resps:
                if r.status == Status.OVER_LIMIT:
                    stats["over"] += 1
                    print(f"over the limit: {r}")

    started = time.monotonic()
    try:
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
    finally:
        elapsed = time.monotonic() - started
        rate = stats["sent"] / elapsed if elapsed > 0 else 0.0
        print(
            f"sent={stats['sent']} over_limit={stats['over']} "
            f"errors={stats['errors']} rate={rate:.0f}/s",
            file=sys.stderr,
        )
        await client.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="gubernator-tpu load generator")
    parser.add_argument("address", nargs="?", default="127.0.0.1:9090")
    parser.add_argument("--keys", type=int, default=2000)
    parser.add_argument("--concurrency", type=int, default=10)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument(
        "--duration", type=float, default=0.0, help="seconds; 0 = forever"
    )
    args = parser.parse_args(argv)
    asyncio.run(
        run(args.address, args.keys, args.concurrency, args.batch, args.duration)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
