"""CLI load generator.

`python -m gubernator_tpu.cli.loadgen <address>` replays a pool of
random token-bucket limits through a concurrent fan-out forever,
dumping OVER_LIMIT responses (the reference's cmd/gubernator-cli).

r12: `--protocol {grpc,geb,http}` picks the door. r10's profiling
showed the loadgen ITSELF was the ceiling through the gRPC door — its
per-item protobuf encode capped offered load at ~110k dec/s no matter
what the serving side did (the masking problem). The `geb` protocol
speaks credit-windowed binary frames via gubernator_tpu.client_geb
(against a daemon's GUBER_GEB_PORT door or a bridge socket path), and
`http` POSTs binary GEB frames to the gateway's /v1/geb door — both
keep the generator off the critical path and exercise the new client
end to end.

r18: `--protocol shm` drives the bridge's shared-memory lane (requires
a co-located bridge socket path; refuses to fall back so the A/B pair
measures the lane, not a silent downgrade), and `--ring-route 1` turns
on the client's per-owner fast routing against a multi-node ring. The
`--json` summary carries `client` (the client's stats dict: negotiated
transport, downgrades and reason, frames_shm) so perf_gate can assert
the MECHANISM that carried the load, not just the rate.

`--share S` (0..1) switches the workload to the shed-r10 shape: hot
limit-1 keys frozen over limit mixed with never-over keys so a
fraction ~S of items answer OVER_LIMIT (`--share 0` = all cold). The
default workload (no --share) keeps the reference CLI's random pool.
`--json` prints one machine-readable summary line on stdout (implies
--quiet), which scripts/perf_gate.py consumes.
"""

import argparse
import asyncio
import json
import sys
import time

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    ChainLevel,
    RateLimitReq,
    Status,
)
from gubernator_tpu.client import AsyncV1Client, random_string

HOT_KEYS = 512
COLD_KEYS = 4096

#: r15 algorithm suite names (core/algorithms.py registry names, kept
#: as a local literal so the generator stays jax-free)
ALGOS = {
    "token": Algorithm.TOKEN_BUCKET,
    "leaky": Algorithm.LEAKY_BUCKET,
    "sliding": Algorithm.SLIDING_WINDOW,
    "gcra": Algorithm.GCRA,
}


def _chain_levels(depth: int, tenant: int):
    """Ancestor levels for --chain-depth: ONE shared head (the
    consolidation contract routes every chain by chain[0], so one
    hierarchy = one head), generous limits (the gate measures the
    chain lane's dispatch price, not refusals)."""
    if depth <= 0:
        return []
    return [
        ChainLevel("cg:global", 1 << 30, 0),
        ChainLevel(f"cg:region:{tenant % 4}", 1 << 28, 0),
        ChainLevel(f"cg:tenant:{tenant % 64}", 1 << 26, 0),
    ][:depth]


def _shed_pool(
    share: float,
    batch: int,
    keyspace: int = 0,
    algorithm: Algorithm = Algorithm.TOKEN_BUCKET,
    chain_depth: int = 0,
):
    """Pre-built batch rotation in the shed-r10 workload shape: the
    first `share` of each batch hits hot limit-1 keys (over limit
    after their first touch), the rest never-over keys. `keyspace=0`
    keeps the classic 8-batch/4096-cold-id rotation BIT-IDENTICAL to
    the r10-r12 workload (the committed PERF_GATE_BASELINE ratios were
    measured on it). `keyspace>0` (r13) widens the cold pool by
    pre-building enough batches to actually EMIT that many distinct
    cold ids (capped at 256 batches — ~2x a 65k-entry store's capacity
    at 1000-item batches, enough to hold the exact tier at pressure so
    the sketch tier's drop path carries real load)."""
    cut = int(share * batch)
    cold_per_batch = max(1, batch - cut)
    if keyspace > 0:
        n_pools = min(256, -(-keyspace // cold_per_batch))
        cold = keyspace
    else:
        n_pools = 8
        cold = COLD_KEYS
    pools = []
    for i in range(n_pools):
        reqs = []
        for j in range(batch):
            if j < cut:
                key, limit = f"shed_h{(i * 31 + j) % HOT_KEYS}", 1
            else:
                key = f"shed_c{(i * batch + j) % cold}"
                limit = 1_000_000_000
            reqs.append(
                RateLimitReq(
                    name="loadgen",
                    unique_key=key,
                    hits=1,
                    limit=limit,
                    duration=600_000,
                    algorithm=algorithm,
                    behavior=Behavior.BATCHING,
                    chain=_chain_levels(chain_depth, i * batch + j),
                )
            )
        pools.append(reqs)
    return pools


#: per-call bound so a wedged server surfaces as counted errors, never
#: workers hung past the duration (the pre-r12 grpc path used 5s; the
#: binary doors serve deep pipelines, so give them headroom)
CALL_TIMEOUT = 30.0


def _make_client(
    protocol: str,
    address: str,
    window: int,
    mode: str,
    ring_route: bool = False,
):
    if protocol == "grpc":
        return AsyncV1Client(address)
    if protocol in ("geb", "shm"):
        from gubernator_tpu.client_geb import AsyncGebClient

        # `geb` pins the socket transport so the r18 shm_r18 A/B pair
        # measures the lane, not whatever happened to negotiate;
        # `shm` refuses to run without the mapped ring
        return AsyncGebClient(
            address,
            window=window,
            mode=mode,
            timeout=CALL_TIMEOUT,
            shm="require" if protocol == "shm" else "off",
            ring_route=ring_route,
        )
    if protocol == "http":
        from gubernator_tpu.client_geb import AsyncHttpGebClient

        base = (
            address
            if address.startswith("http")
            else f"http://{address}"
        )
        return AsyncHttpGebClient(base, mode=mode, timeout=CALL_TIMEOUT)
    raise ValueError(f"unknown protocol {protocol!r}")


async def run(
    address: str,
    keys: int,
    concurrency: int,
    batch: int,
    duration: float,
    protocol: str = "grpc",
    share: float = -1.0,
    window: int = 0,
    mode: str = "auto",
    quiet: bool = False,
    json_out: bool = False,
    keyspace: int = 0,
    algorithm: str = "token",
    chain_depth: int = 0,
    ring_route: bool = False,
) -> dict:
    client = _make_client(protocol, address, window, mode, ring_route)
    algo = ALGOS[algorithm]
    if share >= 0.0:
        batches = _shed_pool(share, batch, keyspace, algo, chain_depth)
    else:
        pool = [
            RateLimitReq(
                name=f"ID-{i:04d}",
                unique_key=random_string("id-"),
                hits=1,
                limit=(i % 100) + 1,
                duration=((i % 50) + 1) * 1000,
                algorithm=algo,
                behavior=Behavior.BATCHING,
                chain=_chain_levels(chain_depth, i),
            )
            for i in range(keys)
        ]
        batches = None

    stats = {"sent": 0, "over": 0, "errors": 0}
    stop_at = time.monotonic() + duration if duration > 0 else None

    async def worker(wid: int):
        i = wid
        while stop_at is None or time.monotonic() < stop_at:
            if batches is not None:
                reqs = batches[i % len(batches)]
                i += 1
            else:
                reqs = [pool[(i + j) % len(pool)] for j in range(batch)]
                i += batch * concurrency
            try:
                if protocol == "grpc":
                    resps = await client.get_rate_limits(
                        reqs, timeout=CALL_TIMEOUT
                    )
                else:  # geb/http bound via their client-level timeout
                    resps = await client.get_rate_limits(reqs)
            except Exception as e:
                stats["errors"] += 1
                print(f"error: {e}", file=sys.stderr)
                await asyncio.sleep(0.1)
                continue
            stats["sent"] += len(resps)
            for r in resps:
                if r.status == Status.OVER_LIMIT:
                    stats["over"] += 1
                    if not quiet:
                        print(f"over the limit: {r}")

    started = time.monotonic()
    try:
        await asyncio.gather(*(worker(w) for w in range(concurrency)))
    finally:
        elapsed = time.monotonic() - started
        rate = stats["sent"] / elapsed if elapsed > 0 else 0.0
        summary = dict(
            protocol=protocol,
            sent=stats["sent"],
            over_limit=stats["over"],
            errors=stats["errors"],
            seconds=round(elapsed, 4),
            decisions_per_sec=round(rate, 1),
            over_limit_share=round(
                stats["over"] / stats["sent"], 4
            )
            if stats["sent"]
            else 0.0,
        )
        if hasattr(client, "stats"):
            # r18: record what actually negotiated (transport, fast vs
            # string framing, ring routing, downgrade reason) so A/B
            # runs can prove which lane carried the load
            summary["client"] = client.stats()
        print(
            f"sent={stats['sent']} over_limit={stats['over']} "
            f"errors={stats['errors']} rate={rate:.0f}/s",
            file=sys.stderr,
        )
        if json_out:
            print(json.dumps(summary))
        await client.close()
    return summary


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="gubernator-tpu load generator")
    parser.add_argument("address", nargs="?", default="127.0.0.1:9090")
    parser.add_argument(
        "--protocol",
        choices=("grpc", "geb", "http", "shm"),
        default="grpc",
        help="front door: gRPC protobuf, binary GEB frames over the "
        "socket (daemon GUBER_GEB_PORT or a bridge socket path; shm "
        "negotiation pinned OFF so A/B pairs stay honest), binary GEB "
        "over HTTP POST /v1/geb, or the r18 shared-memory lane "
        "(requires a co-located bridge unix socket; refuses to fall "
        "back)",
    )
    parser.add_argument("--keys", type=int, default=2000)
    parser.add_argument("--concurrency", type=int, default=10)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument(
        "--duration", type=float, default=0.0, help="seconds; 0 = forever"
    )
    parser.add_argument(
        "--share", type=float, default=-1.0,
        help="shed-r10 workload shape with this over-limit share "
        "(0..1); negative = the default random pool",
    )
    parser.add_argument(
        "--keyspace", type=int, default=0,
        help="widen the --share workload's cold-key pool to this many "
        "distinct ids (0 = the classic 4096); sized past the store's "
        "entry capacity this drives the r13 sketch tier's drop path",
    )
    parser.add_argument(
        "--window", type=int, default=0,
        help="geb protocol: cap the credit window (0 = the server's "
        "advertised window; 1 = round-trip, the pre-r7 shape)",
    )
    parser.add_argument(
        "--mode", choices=("auto", "fast", "string"), default="auto",
        help="geb/http framing: pre-hashed fast records vs string "
        "items (auto negotiates via the hello)",
    )
    parser.add_argument(
        "--ring-route", type=int, choices=(0, 1), default=0,
        help="geb/shm protocols: 1 = shard fast frames per owner "
        "across per-node connections on a multi-node ring (r18 "
        "client-side routing); 0 = the classic single connection",
    )
    parser.add_argument(
        "--algorithm", choices=sorted(ALGOS), default="token",
        help="rate-limit algorithm for every generated request "
        "(r15 suite: token, leaky, sliding, gcra)",
    )
    parser.add_argument(
        "--chain-depth", type=int, default=0,
        help="ancestor quota-chain levels per request (r15; 0 = "
        "plain). Chained items ride string GEBC frames / the proto "
        "chain field; fast framing is bypassed by contract",
    )
    parser.add_argument("--quiet", action="store_true",
                        help="don't print each OVER_LIMIT response")
    parser.add_argument("--json", action="store_true",
                        help="one JSON summary line on stdout "
                        "(implies --quiet)")
    args = parser.parse_args(argv)
    asyncio.run(
        run(
            args.address,
            args.keys,
            args.concurrency,
            args.batch,
            args.duration,
            protocol=args.protocol,
            share=args.share,
            window=args.window,
            mode=args.mode,
            quiet=args.quiet or args.json,
            json_out=args.json,
            keyspace=args.keyspace,
            algorithm=args.algorithm,
            chain_depth=args.chain_depth,
            ring_route=bool(args.ring_route),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
