"""Serving-path benchmark suite: the reference's benchmark configs over a
real in-process cluster.

Reproduces the four benchmarks of reference benchmark_test.go against
localhost gRPC — the apples-to-apples serving numbers (the device-kernel
throughput number lives in bench.py):

  no_batching      BenchmarkServer_GetPeerRateLimitNoBatching (:27-53) —
                   direct PeersV1/GetPeerRateLimits unary calls
  get_rate_limit   BenchmarkServer_GetRateLimit (:55-79) — single-item
                   V1/GetRateLimits
  ping             BenchmarkServer_Ping (:81-98) — V1/HealthCheck
  thundering_herd  BenchmarkServer_ThunderingHeard [sic] (:109-137) —
                   100 concurrent workers issuing GetRateLimits
  batched          no reference analogue: one 1000-item GetRateLimits per
                   call, the shape production batching actually sends
                   (reference README.md:111-117 observes ~1000-item peaks)

Usage: python -m gubernator_tpu.cli.bench_serving [--backend tpu|exact]
       [--seconds N] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Callable, List

import grpc

from gubernator_tpu.api.grpc_glue import PeersV1Stub, V1Stub
from gubernator_tpu.api.proto.gen import gubernator_pb2, peers_pb2
from gubernator_tpu.cluster import LocalCluster

ADDRESSES = [f"127.0.0.1:{p}" for p in range(9980, 9986)]
PYTHON_HTTP_ADDR = "127.0.0.1:19978"  # node 0's gateway under --edge


def _compile_cache_dir():
    """Repo-local XLA compile cache dir (gitignored)."""
    import pathlib

    d = pathlib.Path(__file__).resolve().parents[2] / ".jax_cache"
    d.mkdir(exist_ok=True)
    return d


def _front_door_call(url: str, body: bytes):
    """One HTTP POST closure per front door (python gateway / C++ edge)."""
    import urllib.request

    def call(i: int):
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        urllib.request.urlopen(req, timeout=10).read()

    return call


def _req(key: str) -> gubernator_pb2.RateLimitReq:
    return gubernator_pb2.RateLimitReq(
        name="get_rate_limit_benchmark",
        unique_key=key,
        hits=1,
        limit=1_000_000,
        duration=10_000,
        algorithm=gubernator_pb2.TOKEN_BUCKET,
    )


def _measure(
    name: str,
    call: Callable[[int], None],
    seconds: float,
    workers: int = 1,
) -> dict:
    """Run `call(i)` as fast as possible for `seconds` on N workers,
    recording per-call latency (p50/p99/p99.9 — BASELINE config 3's
    target is p99 < 1ms under GLOBAL). Latency is sampled every
    LAT_SAMPLE-th call into a compact double array so instrumentation
    can't perturb the ops/s headline or grow unbounded on long runs."""
    from array import array

    LAT_SAMPLE = 8
    stop = time.monotonic() + seconds
    counts = [0] * workers
    errors = [0] * workers
    lats = [array("d") for _ in range(workers)]

    def run(w: int):
        i = 0
        append = lats[w].append
        while time.monotonic() < stop:
            sampled = counts[w] % LAT_SAMPLE == 0
            t0 = time.monotonic() if sampled else 0.0
            try:
                call(w * 1_000_000 + i)
                if sampled:
                    append(time.monotonic() - t0)
                counts[w] += 1
            except (grpc.RpcError, OSError):
                # OSError covers urllib/socket failures on the edge path
                errors[w] += 1
            i += 1

    threads = [
        threading.Thread(target=run, args=(w,), daemon=True)
        for w in range(workers)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    n = sum(counts)
    res = {
        "name": name,
        "ops": n,
        "errors": sum(errors),
        "seconds": round(elapsed, 3),
        "ops_per_sec": round(n / elapsed, 1),
        "workers": workers,
    }
    all_lat = sorted(v for per_w in lats for v in per_w)
    if all_lat:
        def pct(p: float) -> float:
            idx = min(len(all_lat) - 1, int(p * (len(all_lat) - 1)))
            return round(all_lat[idx] * 1e3, 3)

        res["p50_ms"] = pct(0.50)
        res["p99_ms"] = pct(0.99)
        res["p999_ms"] = pct(0.999)
    print(
        f"{name:18s} {res['ops_per_sec']:12,.0f} ops/s   "
        f"({n} ops, {workers} workers, {elapsed:.1f}s)  "
        f"p50={res.get('p50_ms', '-')}ms p99={res.get('p99_ms', '-')}ms "
        f"p99.9={res.get('p999_ms', '-')}ms",
        file=sys.stderr,
    )
    return res


async def _attach_edge_bridge(server, sock_path):
    from gubernator_tpu.serve.edge_bridge import EdgeBridge

    bridge = EdgeBridge(server.instance, sock_path)
    await bridge.start()
    return bridge


def _jax_cache():
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", str(_compile_cache_dir().resolve())
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


async def _boot_stack(conf, metric, depth):
    """Boot the SHIPPED stack (make_backend -> warmup -> Instance);
    returns (instance, backend, warmup_seconds)."""
    import asyncio

    from gubernator_tpu.serve.instance import Instance
    from gubernator_tpu.serve.server import make_backend

    backend = make_backend(conf)
    print(f"{metric} depth {depth}: warmup (ladder compiles)...",
          file=sys.stderr)
    t0 = time.monotonic()
    await asyncio.to_thread(backend.warmup)
    warm_s = time.monotonic() - t0
    inst = Instance(conf, backend)
    inst.start()
    return inst, backend, warm_s


async def _prefill_sequential(inst, n_ids, group, limit, duration):
    """Saturate the exact tier: drive `n_ids` SEQUENTIAL ids (same
    params as the measured traffic) so the measured window runs at the
    steady state the scenario is about — tier pressure, not a cold
    store. The zipf head's small ids overlap these, so hot keys decide
    exactly while the tail fights for ways."""
    import asyncio

    import numpy as np

    from gubernator_tpu.cli import keystreams

    n_chunks = -(-n_ids // group)

    async def filler(w: int, W: int):
        ones = np.ones(group, np.int64)
        algo = np.zeros(group, np.int32)
        for c in range(w, n_chunks, W):
            ids = np.arange(
                c * group, (c + 1) * group, dtype=np.uint64
            )
            await inst.batcher.decide_arrays(
                dict(
                    key_hash=keystreams.hash_ids(ids), hits=ones,
                    limit=ones * limit, duration=ones * duration,
                    algo=algo,
                )
            )

    t0 = time.monotonic()
    await asyncio.gather(*[filler(w, 8) for w in range(8)])
    print(
        f"prefill: {n_ids:,} sequential ids in "
        f"{time.monotonic() - t0:.0f}s", file=sys.stderr,
    )


async def _measure_window(
    inst, backend, pool, depth, seconds, group, metric, limit=1000,
    duration=60_000, churn=False, key_space=1 << 40, algo_id=0,
) -> dict:
    """One timed window of pre-hashed key traffic through the
    batcher's array door — the zipf10m/zipf100m/key-churn scenarios'
    one measurement loop. `churn=True` advances the whole pool by a
    fresh phase every pass (keystreams.churn_pool) so no key is ever
    hot twice. `algo_id` drives the stream under a non-token algorithm
    (the r21 zipf100m sliding/GCRA arms)."""
    import asyncio

    import numpy as np

    from gubernator_tpu.cli import keystreams

    stop_at = time.monotonic() + seconds
    done_rows = 0
    base = backend.stats()

    async def worker(w: int):
        nonlocal done_rows
        i = w * 101
        ones = np.ones(group, np.int64)
        algo = np.full(group, algo_id, np.int32)
        passes = 0
        while time.monotonic() < stop_at:
            if churn:
                # every pass is a FRESH key set: the adversarial
                # tier-thrash stream (ROADMAP item 4). One GROUP-sized
                # pool per pass (worker-disjoint phase stride), not a
                # full staging pool — regenerating 2^18 hashed ids per
                # submitted group was measured event-loop cost, not
                # system-under-test cost
                passes += 1
                kh = keystreams.churn_pool(
                    key_space, group, passes * workers + w
                )
            else:
                off = (i * group) % (pool.shape[0] - group)
                i += 1
                kh = pool[off : off + group]
            fields = dict(
                key_hash=kh,
                hits=ones,
                limit=ones * limit,
                duration=ones * duration,
                algo=algo,
            )
            await inst.batcher.decide_arrays(fields)
            done_rows += group

    # enough concurrent groups outstanding to keep the submit
    # gate saturated (deep accumulation engages only then):
    # ~2 full deep batches of groups, floor 8
    workers = max(8, 2 * depth // group)
    t0 = time.monotonic()
    await asyncio.gather(*[worker(w) for w in range(workers)])
    elapsed = time.monotonic() - t0
    end = backend.stats()
    batches = end["batches"] - base["batches"]
    row = dict(
        metric=metric,
        depth=depth,
        decisions_per_sec=round(done_rows / elapsed, 1),
        mean_device_batch=(
            round(done_rows / batches, 1) if batches else 0.0
        ),
        device_batches=batches,
        seconds=round(elapsed, 3),
        workers=workers,
        group_rows=group,
        # exact-tier pressure: with the sketch tier on, dropped
        # creates ARE the sketch-served group count (fail-closed);
        # with it off they are silent over-admission
        dropped_creates=end["dropped"] - base["dropped"],
        evictions=end["evictions"] - base["evictions"],
    )
    if inst.promoter is not None:
        row["promoter"] = inst.promoter.stats()
    return row


async def _drive_pool(
    conf, pool, depth, seconds, group, metric, limit=1000,
    duration=60_000, churn=False, key_space=1 << 40, prefill_ids=0,
) -> dict:
    """_boot_stack + optional _prefill_sequential + one
    _measure_window + stop — the single-phase scenario driver."""
    # a caller group can never exceed the ladder top (the batcher
    # ships an oversized group alone and choose_bucket would refuse)
    group = min(group, depth)
    inst, backend, warm_s = await _boot_stack(conf, metric, depth)
    try:
        if prefill_ids:
            await _prefill_sequential(
                inst, prefill_ids, group, limit, duration
            )
        row = await _measure_window(
            inst, backend, pool, depth, seconds, group, metric,
            limit, duration, churn, key_space,
        )
        row["warmup_seconds"] = round(warm_s, 1)
        return row
    finally:
        await inst.stop()


def run_zipf10m(args) -> int:
    """BASELINE config 4 through the SHIPPED serving configuration.

    Each depth row boots the serving stack exactly as the daemon does —
    GUBER_* env knobs -> config_from_env (validation included) ->
    make_backend (store sized by GUBER_STORE_MIB/GUBER_STORE_TARGET_KEYS,
    ladder from GUBER_DEVICE_BATCH_LIMIT) -> warmup (the deep rungs
    compile here, before traffic) -> Instance + DeviceBatcher with
    GUBER_DEVICE_DEEP_BATCH accumulation — then drives zipfian traffic
    through the batcher's array door (`decide_arrays`, the same entry the
    edge bridge's pre-hashed GEB6 frames use) from concurrent callers
    whose groups the deep-batch collector coalesces to the rung. The
    emitted rows demonstrate the measured big-store law on the shipped
    path: at FIXED store footprint, throughput scales with batch depth
    because the writeback's full-table pass is paid once per batch
    (docs/round5.md; BENCH_ZIPF10M_PROFILE_r5.json).

    Scoping: on a TPU this is config 4 itself (1 GiB store, 10M keys);
    on a CPU-only host pass a scaled --store-mib/--keys and the artifact
    records scope="cpu" — the depth-scaling shape, not the absolute
    numbers, is the claim.
    """
    import asyncio
    import os

    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.serve.config import config_from_env

    _jax_cache()

    depths = [int(d) for d in args.depths.split(",") if d.strip()]
    # the one shared zipf key recipe (cli/keystreams.py) over args.keys;
    # pre-hashed like edge GEB6 frames, staged outside the timed region
    pool = keystreams.zipf_pool(args.keys, 1 << 22)
    rows = []

    async def run_depth(conf, depth) -> dict:
        return await _drive_pool(
            conf, pool, depth, args.seconds, args.group,
            "zipf10m_serving_mode",
        )

    for depth in depths:
        env = dict(os.environ)
        env.update(
            {
                "GUBER_BACKEND": args.backend,
                "GUBER_DEVICE_BATCH_LIMIT": str(depth),
                "GUBER_DEVICE_DEEP_BATCH": "1",
                "GUBER_STORE_MIB": str(args.store_mib),
                "GUBER_STORE_TARGET_KEYS": str(args.keys),
                "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
            }
        )
        env.pop("GUBER_STORE_SLOTS", None)
        # the historical exact-only scenario: the whole MiB budget goes
        # to the exact tier (the r13 sketch sibling is --scenario
        # zipf100m); an explicit GUBER_SKETCH in the environment wins
        env.setdefault("GUBER_SKETCH", "0")
        conf = config_from_env(env)  # the shipped knob surface, validated
        r = asyncio.run(run_depth(conf, depth))
        print(
            f"depth {depth:>7}: {r['decisions_per_sec']:>14,.0f} dec/s  "
            f"(mean device batch {r['mean_device_batch']:,.0f}, "
            f"{r['device_batches']} batches)",
            file=sys.stderr,
        )
        rows.append(r)

    import jax as _jax

    doc = dict(
        scenario="zipf10m_throughput_serving_mode",
        scope=_jax.devices()[0].platform,
        device=_jax.devices()[0].device_kind,
        backend=args.backend,
        store_mib=args.store_mib,
        key_space=args.keys,
        served_via=(
            "config_from_env -> make_backend -> Instance/DeviceBatcher"
            " (GUBER_DEVICE_DEEP_BATCH=1), array door"
        ),
        env_knobs={
            "GUBER_BACKEND": args.backend,
            "GUBER_DEVICE_DEEP_BATCH": "1",
            "GUBER_STORE_MIB": str(args.store_mib),
            "GUBER_STORE_TARGET_KEYS": str(args.keys),
            "GUBER_DEVICE_BATCH_LIMIT": "<row depth>",
            "GUBER_PREP_THREADS": os.environ.get(
                "GUBER_PREP_THREADS", "<default>"
            ),
            "GUBER_PREP_AT_ARRIVAL": os.environ.get(
                "GUBER_PREP_AT_ARRIVAL", "1"
            ),
        },
        notes=(
            "depth rows share one fixed store footprint; throughput "
            "scaling with depth is the big-store writeback-amortization "
            "law on the shipped serving path (docs/round5.md, "
            "BENCH_ZIPF10M_PROFILE_r5.json)."
        ),
        rows=rows,
    )
    if args.json:
        print(json.dumps(doc))
    return 0


def _run_shard_child(args) -> int:
    """One shard-ladder row in THIS process (spawned by run_shard with
    XLA_FLAGS/JAX_PLATFORMS pinned before jax ever initialized): boot
    the shipped stack from GUBER_* env (backend tpu = the flat
    degenerate policy, mesh = GUBER_SHARDS simulated devices) and
    measure one zipf window through the batcher's array door."""
    import asyncio

    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.serve.config import config_from_env

    _jax_cache()
    conf = config_from_env()
    n = int(args.shards.split(",")[0])
    pool = keystreams.zipf_pool(args.keys, 1 << 18)
    row = asyncio.run(
        _drive_pool(
            conf, pool, conf.device_batch_limit, args.seconds,
            args.group, f"shard_{args.shard_child}_{n}",
        )
    )
    row["shards"] = n
    row["policy"] = args.shard_child
    print(json.dumps(row))
    return 0


def run_shard(args) -> int:
    """Shard-scaling ladder on SIMULATED host devices (r14): the same
    partitioned engine under the flat policy (1 shard) and the mesh
    policy at each --shards rung, every rung in its own subprocess so
    XLA_FLAGS --xla_force_host_platform_device_count lands before jax
    initializes (the tests/conftest.py mechanism). On a CPU box the
    virtual devices SHARE the cores, so the ladder measures the
    partitioned dispatch overhead (host shard routing + shard_map
    program), not chip scaling — the scaling dividend this prices
    exists on real meshes where each shard owns a chip; the artifact
    records that scoping."""
    import os
    import subprocess

    if args.shard_child:
        return _run_shard_child(args)

    ladder = [int(x) for x in args.shards.split(",") if x.strip()]
    rows = []
    configs = [("flat", 1)] + [("mesh", n) for n in ladder]
    for policy, n in configs:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (
                    f"--xla_force_host_platform_device_count={max(n, 1)}"
                ),
                "GUBER_BACKEND": "tpu" if policy == "flat" else "mesh",
                "GUBER_DEVICE_BATCH_LIMIT": str(args.shard_depth),
                "GUBER_STORE_SLOTS": str(args.shard_slots),
                "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
            }
        )
        if policy == "mesh":
            env["GUBER_SHARDS"] = str(n)
        for k in ("GUBER_STORE_MIB", "GUBER_STORE_TARGET_KEYS",
                  "GUBER_SHARDS" if policy == "flat" else ""):
            env.pop(k, None) if k else None
        cmd = [
            sys.executable, "-m", "gubernator_tpu.cli.bench_serving",
            "--scenario", "shard", "--shard-child", policy,
            "--shards", str(n), "--seconds", str(args.seconds),
            "--group", str(args.group), "--keys", str(args.keys),
        ]
        print(
            f"shard ladder: {policy} x{n} "
            f"(simulated devices = {max(n, 1)})...",
            file=sys.stderr,
        )
        out = subprocess.run(
            cmd, capture_output=True, text=True, env=env, timeout=1800
        )
        if out.returncode != 0:
            print(out.stderr[-2000:], file=sys.stderr)
            return 1
        row = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"  {policy} x{n}: {row['decisions_per_sec']:>12,.0f} dec/s"
            f"  (mean device batch {row['mean_device_batch']:,.0f})",
            file=sys.stderr,
        )
        rows.append(row)

    flat_rate = rows[0]["decisions_per_sec"]
    for r in rows:
        r["vs_flat"] = round(r["decisions_per_sec"] / flat_rate, 4)
    doc = dict(
        scenario="shard_ladder_r14",
        scope="cpu-simulated-devices",
        host_cpus=os.cpu_count(),
        shards_ladder=ladder,
        served_via=(
            "config_from_env -> make_backend (GUBER_BACKEND=tpu|mesh, "
            "GUBER_SHARDS) -> Instance/DeviceBatcher array door; one "
            "subprocess per rung with XLA_FLAGS "
            "--xla_force_host_platform_device_count pinned pre-init"
        ),
        env_knobs={
            "GUBER_DEVICE_BATCH_LIMIT": str(args.shard_depth),
            "GUBER_STORE_SLOTS": str(args.shard_slots),
            "GUBER_SHARDS": "<row shards>",
        },
        key_space=args.keys,
        notes=(
            "Simulated host devices share this box's cores, so rows "
            "measure the PARTITIONED DISPATCH PRICE of the one r14 "
            "engine (host owner-routing + shard_map program vs the "
            "flat plain-jit degenerate policy) — not chip scaling. "
            "On a real mesh each shard owns a chip and per-chip "
            "decide work drops to ~B/n (tests/test_sharded.py "
            "test_batch_is_sharded_not_replicated pins the sub-batch "
            "economy); `make perf-gate` guards the flat-vs-mesh "
            "paired ratio (shard_r14) against decay."
        ),
        rows=rows,
    )
    if args.json:
        print(json.dumps(doc))
    return 0


def _filler_hashes(slots: int) -> "np.ndarray":
    """One uint64 key hash per store bucket (error-measurement rig):
    with every bucket's ways held by LIVE entries that are ALSO present
    in each batch (found-writers), a rank-0 miss can never evict — so
    every measured key provably decides on the sketch tier."""
    import numpy as np

    from gubernator_tpu.core.store import group_sort_key_np

    out = {}
    rng = np.random.default_rng(123)
    while len(out) < slots:
        cand = rng.integers(1, 2**63, 1024).astype(np.uint64)
        bkt = (group_sort_key_np(cand, slots) >> np.uint64(32)).astype(
            np.int64
        )
        for h, b in zip(cand.tolist(), bkt.tolist()):
            out.setdefault(int(b), h)
    return np.array([out[b] for b in range(slots)], np.uint64)


def measure_tail_error(
    batches: int = 96, sketch_mib: int = 8, seed: int = 7,
    derivation: str = "v2", algorithm: str = "token", rows: int = 0,
) -> dict:
    """Measured tail-key error of the sketch tier on a pinned zipf
    stream (the r13 acceptance phase, derivation- and algorithm-aware
    since r21; also driven by the property test in
    tests/test_sketch_tier.py).

    Rig: a tiny exact store whose buckets are pinned full of immortal
    filler entries included in every batch, so EVERY measured key's
    create drops and decides from the sketch — the clean measurement of
    sketch error, uncontaminated by exact-tier wins. Limits are huge so
    every hit admits and charges regardless of `algorithm` (the
    window-ring serves sliding/GCRA through the same per-window cells),
    making host-side tallies the exact ground truth for the counts the
    sketch was charged with. Reports max/mean overestimate against the
    documented classic-CM bound e*N/width (conservative update only
    tightens it) and the under-count count, which must be ZERO
    (one-sided error = fail-closed). `derivation` selects the counter
    geometry at the SAME byte budget: "v2" (2 rows of saturating int32,
    4x the width of r13 -> 4x tighter bound per byte) or "r13" (4 rows
    of int64, the committed r13 geometry)."""
    import math

    import numpy as np

    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.core.algorithms import ALGO_NAMES
    from gubernator_tpu.core.engine import TpuEngine
    from gubernator_tpu.core.sketches import derive_sketch_config
    from gubernator_tpu.core.store import StoreConfig

    cfg = StoreConfig(rows=1, slots=64)
    skc = derive_sketch_config(
        mib=sketch_mib, rows=rows, derivation=derivation
    )
    eng = TpuEngine(cfg, buckets=(4096,), sketch=skc)
    T0 = 1_700_000_000_000
    fill = _filler_hashes(cfg.slots)
    nf = fill.shape[0]
    B = 4096
    DUR, LIM = 600_000, 1 << 30
    onesf = np.ones(nf, np.int64)
    # create the immortal fillers (limit/duration arbitrary, just live)
    eng.decide_arrays(
        fill, onesf, onesf * 1000, onesf * 1_000_000_000,
        np.zeros(nf, np.int32), np.zeros(nf, bool), T0,
    )
    nm = B - nf
    hits = np.concatenate([np.zeros(nf, np.int64), np.ones(nm, np.int64)])
    limit = np.full(B, LIM, np.int64)
    dur = np.full(B, DUR, np.int64)
    algo = np.full(B, ALGO_NAMES[algorithm], np.int32)
    algo[:nf] = 0
    gnp = np.zeros(B, bool)
    rng = np.random.default_rng(seed)
    true = np.zeros(10_000, np.int64)
    for b in range(batches):
        ids = keystreams.zipf_ids(10_000, nm, rng)
        kh = np.concatenate([fill, keystreams.hash_ids(ids)])
        eng.decide_arrays(kh, hits, limit, dur, algo, gnp, T0 + b)
        np.add.at(true, ids, 1)
    touched = np.flatnonzero(true)
    est = eng.sketch_estimates(
        keystreams.hash_ids(touched), np.full(touched.shape[0], DUR),
        T0 + batches + 1,
    )
    diff = est - true[touched]
    n_charged = int(true.sum())
    bound = math.e * n_charged / skc.width
    return dict(
        metric="sketch_tail_error",
        algorithm=algorithm,
        derivation=derivation,
        distinct_keys=int(touched.shape[0]),
        charged_hits=n_charged,
        sketch_rows=skc.rows,
        sketch_width=skc.width,
        counter_bytes=skc.counter_bytes,
        under_counts=int((diff < 0).sum()),
        max_overestimate=int(diff.max()),
        mean_overestimate=round(float(diff.mean()), 4),
        documented_bound=round(bound, 2),
        bound_formula="e * charged_hits / width (classic CM; "
        "conservative update only tightens it)",
        within_bound=bool(diff.max() <= bound),
        batches=batches,
        seed=seed,
    )


def measure_tail_error_ab(
    batches: int = 96, sketch_mib: int = 8, seed: int = 7
) -> dict:
    """The r21 derivation A/B at ONE byte budget: the committed r13
    geometry vs the v2 additive-error geometry on the identical pinned
    stream. The acceptance claim is strict: v2's measured max
    overestimate must sit BELOW r13's theoretical bound (and v2's own
    bound is 4x tighter), with zero under-counts on both sides."""
    r13 = measure_tail_error(
        batches=batches, sketch_mib=sketch_mib, seed=seed,
        derivation="r13",
    )
    v2 = measure_tail_error(
        batches=batches, sketch_mib=sketch_mib, seed=seed,
        derivation="v2",
    )
    return dict(
        metric="sketch_tail_error_derivation_ab",
        sketch_mib=sketch_mib,
        r13=r13,
        v2=v2,
        v2_bound_over_r13_bound=round(
            v2["documented_bound"] / r13["documented_bound"], 4
        ),
        v2_max_below_r13_bound=bool(
            v2["max_overestimate"] < r13["documented_bound"]
        ),
        zero_under_counts=bool(
            v2["under_counts"] == 0 and r13["under_counts"] == 0
        ),
    )


def run_zipf100m(args) -> int:
    """The r13 sketch-tier flagship: ~100M-key cardinality at the SAME
    fixed device budget the exact-only zipf10m scenario uses. Since r21
    the tail-error phase runs the r13-vs-v2 derivation A/B plus sliding
    and GCRA arms (window-ring serving), and two algorithm arm rows
    drive the 100M-key stream under sliding/GCRA on the sketch stack.

    Three phases, one artifact (BENCH_SKETCH_r21.json; r13 shape was
    BENCH_SKETCH_r13.json):

    1. `zipf10m_exact_baseline` — the r6 flagship shape: the whole
       GUBER_STORE_MIB budget as one exact tier, 10M-key zipf. This is
       the in-run baseline the acceptance compares against (same box,
       same minutes — box-speed cancels in the ratio).
    2. `zipf100m_sketch_tier` — GUBER_SKETCH=1 at the SAME total
       budget: the sketch's footprint is carved out of the budget
       (exact tier shrinks to fit), and the zipf stream spans
       args.keys (default 100M) ids — 10x the exact tier's entry
       count, impossible for the exact-only geometry. Dropped creates
       (= sketch-served decisions) and promoter stats are recorded.
    3. `sketch_tail_error` — the measured one-sided error bound on a
       pinned stream (measure_tail_error): zero under-counts, max
       overestimate within e*N/width.
    """
    import asyncio
    import os

    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.serve.config import config_from_env

    _jax_cache()

    depth = int(args.depths.split(",")[0])

    def conf_for(sketch: bool, keys: int):
        env = dict(os.environ)
        env.update(
            {
                "GUBER_BACKEND": "tpu",
                "GUBER_DEVICE_BATCH_LIMIT": str(depth),
                "GUBER_DEVICE_DEEP_BATCH": "1",
                "GUBER_STORE_MIB": str(args.store_mib),
                "GUBER_STORE_TARGET_KEYS": str(keys),
                "GUBER_SKETCH": "1" if sketch else "0",
                "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
            }
        )
        env.pop("GUBER_STORE_SLOTS", None)
        return config_from_env(env)

    import statistics

    conf_a = conf_for(False, 10_000_000)
    conf_b = conf_for(True, args.keys)
    pool10 = keystreams.zipf_pool(10_000_000, 1 << 22)
    pool100 = keystreams.zipf_pool(args.keys, 1 << 22)
    DUR = 600_000
    group = min(args.group, depth)
    rounds = max(2, getattr(args, "rounds", 3))

    async def run_paired():
        """Both stacks resident, INTERLEAVED alternating-order windows
        (the r9 methodology): this box's ambient throttling drifts 2x
        on minute scales, so adjacent-phase comparisons are noise —
        per-round paired ratios are the only robust statistic here."""
        a_inst, a_be, a_warm = await _boot_stack(
            conf_a, "zipf10m_exact_baseline", depth
        )
        b_inst, b_be, b_warm = await _boot_stack(
            conf_b, "zipf100m_sketch_tier", depth
        )
        try:
            # phase B runs at the steady state the scenario is about:
            # the exact tier saturated (1.25x its entry capacity of
            # sequential ids; the zipf HEAD overlaps them, so hot keys
            # decide exactly while the tail fights for ways)
            from gubernator_tpu.core.store import store_capacity

            await _prefill_sequential(
                b_inst,
                int(store_capacity(conf_b.store_config()) * 1.25),
                group, 1000, DUR,
            )
            a_rows, b_rows, pairs = [], [], []
            for rnd in range(rounds):
                order = (
                    [("a", a_inst, a_be, pool10,
                      "zipf10m_exact_baseline"),
                     ("b", b_inst, b_be, pool100,
                      "zipf100m_sketch_tier")]
                )
                if rnd % 2:
                    order.reverse()
                rates = {}
                for which, inst, be, pool, metric in order:
                    r = await _measure_window(
                        inst, be, pool, depth, args.seconds, group,
                        metric, 1000, DUR,
                    )
                    rates[which] = r
                    (a_rows if which == "a" else b_rows).append(r)
                ratio = (
                    rates["b"]["decisions_per_sec"]
                    / rates["a"]["decisions_per_sec"]
                )
                pairs.append(round(ratio, 4))
                print(
                    f"round {rnd}: exact "
                    f"{rates['a']['decisions_per_sec']:>11,.0f} "
                    f"sketch "
                    f"{rates['b']['decisions_per_sec']:>11,.0f} dec/s"
                    f"  ratio {ratio:.3f}  (dropped->sketch "
                    f"{rates['b']['dropped_creates']}, evictions "
                    f"{rates['b']['evictions']})",
                    file=sys.stderr,
                )

            def agg(rws, metric, warm):
                med = statistics.median(
                    r["decisions_per_sec"] for r in rws
                )
                return dict(
                    metric=metric,
                    depth=depth,
                    decisions_per_sec=med,
                    rounds=[r["decisions_per_sec"] for r in rws],
                    warmup_seconds=round(warm, 1),
                    workers=rws[0]["workers"],
                    group_rows=group,
                    dropped_creates=sum(
                        r["dropped_creates"] for r in rws
                    ),
                    evictions=sum(r["evictions"] for r in rws),
                    **(
                        {"promoter": rws[-1]["promoter"]}
                        if "promoter" in rws[-1]
                        else {}
                    ),
                )

            # r21 algorithm arms: the SAME 100M-key stream under
            # sliding and GCRA on the resident sketch stack — the
            # window-ring must keep serving the saturation tier's
            # dropped creates (dropped_creates > 0) when operators
            # pick the fairness algorithms, not just token
            from gubernator_tpu.core.algorithms import ALGO_NAMES

            arm_rows = []
            for arm in ("sliding", "gcra"):
                r = await _measure_window(
                    b_inst, b_be, pool100, depth, args.seconds, group,
                    f"zipf100m_sketch_{arm}", 1000, DUR,
                    algo_id=ALGO_NAMES[arm],
                )
                r["algorithm"] = arm
                arm_rows.append(r)
                print(
                    f"arm {arm}: "
                    f"{r['decisions_per_sec']:>11,.0f} dec/s "
                    f"(dropped->sketch {r['dropped_creates']})",
                    file=sys.stderr,
                )

            return (
                agg(a_rows, "zipf10m_exact_baseline", a_warm),
                agg(b_rows, "zipf100m_sketch_tier", b_warm),
                pairs,
                arm_rows,
            )
        finally:
            await b_inst.stop()
            await a_inst.stop()

    row_a, row_b, pairs, arm_rows = asyncio.run(run_paired())
    rows = [row_a, row_b] + arm_rows
    paired_ratio = statistics.median(pairs)
    for r in rows:
        print(
            f"{r['metric']:24s} {r['decisions_per_sec']:>14,.0f} dec/s "
            f"(median; dropped->sketch {r['dropped_creates']}, "
            f"evictions {r['evictions']})",
            file=sys.stderr,
        )
    print(
        "measuring tail error (pinned stream, r13-vs-v2 A/B)...",
        file=sys.stderr,
    )
    err_ab = measure_tail_error_ab()
    err = err_ab["v2"]
    print(
        f"tail error v2: max over {err['max_overestimate']} "
        f"(v2 bound {err['documented_bound']}, r13 bound "
        f"{err_ab['r13']['documented_bound']}), under-counts "
        f"{err['under_counts']}",
        file=sys.stderr,
    )
    err_arms = {}
    for arm in ("sliding", "gcra"):
        e = measure_tail_error(algorithm=arm)
        err_arms[arm] = e
        print(
            f"tail error {arm}: max over {e['max_overestimate']} "
            f"(bound {e['documented_bound']}), under-counts "
            f"{e['under_counts']}",
            file=sys.stderr,
        )

    import jax as _jax

    base_v = rows[0]["decisions_per_sec"]
    sk_v = rows[1]["decisions_per_sec"]
    doc = dict(
        scenario="zipf100m_sketch_tier",
        scope=_jax.devices()[0].platform,
        device=_jax.devices()[0].device_kind,
        store_mib=args.store_mib,
        key_space=args.keys,
        depth=depth,
        served_via=(
            "config_from_env -> make_backend (sketch carve-out) -> "
            "Instance/DeviceBatcher (deep batch), array door; BOTH "
            "stacks resident, interleaved alternating-order windows "
            "(r9 methodology) — the paired per-round ratio is the "
            "drift-robust headline"
        ),
        paired_ratios=pairs,
        env_knobs={
            "GUBER_STORE_MIB": str(args.store_mib),
            "GUBER_SKETCH": "1 (phase 2) / 0 (phase 1)",
            "GUBER_SKETCH_MIB": os.environ.get(
                "GUBER_SKETCH_MIB", "0 (auto: store_mib/4, cap 256)"
            ),
            "GUBER_DEVICE_BATCH_LIMIT": str(depth),
            "GUBER_DEVICE_DEEP_BATCH": "1",
        },
        rows=rows,
        tail_error=err,
        tail_error_derivation_ab=err_ab,
        tail_error_arms=err_arms,
        sketch_over_exact_baseline=round(paired_ratio, 4),
        acceptance=dict(
            target="zipf100m at the fixed total budget sustains >= the "
            "zipf10m exact-only baseline, tail error within bound, "
            "zero under-counts; r21: v2 max overestimate strictly "
            "below the r13 bound at the same budget, sliding+GCRA "
            "arms sketch-served at 100M-key cardinality",
            throughput_met=bool(paired_ratio >= 1.0),
            error_met=bool(
                err["within_bound"] and err["under_counts"] == 0
            ),
            derivation_met=bool(
                err_ab["v2_max_below_r13_bound"]
                and err_ab["zero_under_counts"]
            ),
            arms_met=bool(
                all(
                    e["within_bound"] and e["under_counts"] == 0
                    for e in err_arms.values()
                )
                and all(r["dropped_creates"] > 0 for r in arm_rows)
            ),
        ),
        acceptance_note=(
            None
            if paired_ratio >= 1.0
            else (
                "CPU-container scoping: the >= target leans on the "
                "TPU footprint-law dividend — the sketch phase's "
                "exact tier is HALF the baseline's footprint, worth "
                "~1.7x per batch on v5e "
                "(BENCH_ZIPF10M_PROFILE_r5.json) against the sketch's "
                "~10-14% kernel cost — but on this throttled 1-core "
                "container the writeback's footprint-proportional "
                "term is flat (512 vs 1024 MiB exact measured within "
                "5% here), and the 100M-key stream's near-unique "
                "batches carry ~3x the unique-key groups of the 10M "
                "baseline (store I/O scales with groups). The "
                "CARDINALITY claim stands as measured: 10x the key "
                "space at the same fixed budget with bounded "
                "fail-closed tail error, zero under-counts, and "
                "saturation-tier traffic actually served — vs silent "
                "over-admission at this pressure exact-only."
            )
        ),
        notes=(
            "the sketch phase's exact tier is the budget minus the "
            "sketch carve-out (config.store_config), so both phases "
            "fit the SAME total device budget (power-of-two floors "
            "mean the two-tier phase provisions 512 MiB exact + "
            "256 MiB sketch of the 1024); its exact tier is PREFILLED "
            "to 1.25x capacity before the rounds so the windows "
            "measure tier-pressure steady state. dropped_creates in "
            "the sketch phase are sketch-served fail-closed "
            "decisions; in the baseline they are silent "
            "over-admission."
        ),
    )
    if args.json:
        print(json.dumps(doc))
    return 0


def run_churn(args) -> int:
    """Adversarial key-churn scenario (ROADMAP item 4): every pass is
    an entirely fresh key set (cli/keystreams.py churn_pool), defeating
    the shed cache, the exact tier's residency, and the promoter's
    top-K by construction — the worst case for tier thrash. The row
    pins that the stack survives it at full load: bounded promoter
    memory, no error, dropped creates absorbed by the sketch tier."""
    import asyncio
    import os

    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.serve.config import config_from_env

    _jax_cache()

    depth = int(args.depths.split(",")[0])
    env = dict(os.environ)
    env.update(
        {
            "GUBER_BACKEND": "tpu",
            "GUBER_DEVICE_BATCH_LIMIT": str(depth),
            "GUBER_DEVICE_DEEP_BATCH": "1",
            "GUBER_STORE_MIB": str(args.store_mib),
            "GUBER_STORE_TARGET_KEYS": str(args.keys),
            "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
        }
    )
    env.pop("GUBER_STORE_SLOTS", None)
    conf = config_from_env(env)
    # the churn path generates its key stream per pass inside the
    # measurement loop; this pool only satisfies the non-churn
    # signature and is never indexed
    group = min(args.group, depth)
    pool = keystreams.churn_pool(args.keys, 2 * group, 0)
    r = asyncio.run(
        _drive_pool(
            conf, pool, depth, args.seconds, args.group, "key_churn",
            churn=True, key_space=args.keys,
        )
    )
    print(
        f"key-churn: {r['decisions_per_sec']:>14,.0f} dec/s "
        f"(dropped->sketch {r['dropped_creates']}, promoter "
        f"{r.get('promoter')})",
        file=sys.stderr,
    )
    if args.json:
        import jax as _jax

        print(
            json.dumps(
                dict(
                    scenario="key_churn",
                    scope=_jax.devices()[0].platform,
                    store_mib=args.store_mib,
                    key_space=args.keys,
                    depth=depth,
                    rows=[r],
                )
            )
        )
    return 0


def run_shed(args) -> int:
    """Over-limit-heavy serving scenario (r10): the shed cache's home
    turf, through the SHIPPED boot path.

    Boots env knobs -> config_from_env (GUBER_SHED_CACHE honored and
    recorded) -> make_backend -> Instance, then drives token-bucket
    traffic whose OVER-LIMIT SHARE is controlled per round: a hot pool
    of limit-1 keys (over limit from their second hit, frozen for the
    whole run) mixed with never-over keys at the round's target ratio.
    Each round reports measured over-limit share, decisions/s, and the
    shed cache's hit rate — the skew ladder `make profile-shed` A/Bs
    ON vs OFF over the edge door (BENCH_SHED_r10.json).
    """
    import asyncio
    import os

    from gubernator_tpu.api.types import RateLimitReq, Status
    from gubernator_tpu.serve.config import config_from_env
    from gubernator_tpu.serve.instance import Instance
    from gubernator_tpu.serve.server import make_backend

    if args.backend != "exact":
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            str(_compile_cache_dir().resolve()),
        )
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0
        )

    env = dict(os.environ)
    env.setdefault("GUBER_BACKEND", args.backend)
    # a syntactically-valid self address: the in-process instance never
    # dials itself, but the ring refuses port 0 at connect()
    env.setdefault("GUBER_GRPC_ADDRESS", "127.0.0.1:19099")
    conf = config_from_env(env)
    backend = make_backend(conf)
    shares = [
        float(s) for s in args.shed_shares.split(",") if s.strip()
    ]
    rows = []

    async def run_rounds():
        from gubernator_tpu.api.types import PeerInfo

        warmup = getattr(backend, "warmup", None)
        if warmup is not None:
            print("warmup (ladder compiles)...", file=sys.stderr)
            await asyncio.to_thread(warmup)
        inst = Instance(conf, backend)
        inst.start()
        await inst.set_peers(
            [PeerInfo(address=conf.resolved_advertise(), is_owner=True)]
        )
        try:
            HOT, COLD, GROUP = 512, 4096, 256

            def batch_for(share: float, w: int, i: int):
                cut = int(share * GROUP)
                reqs = []
                for j in range(GROUP):
                    if j < cut:
                        k, limit = f"h{(i * 31 + j) % HOT}", 1
                    else:
                        k, limit = (
                            f"c{(w * 7919 + i * GROUP + j) % COLD}",
                            1_000_000_000,
                        )
                    reqs.append(
                        RateLimitReq(
                            name="shed", unique_key=k, hits=1,
                            limit=limit, duration=600_000,
                        )
                    )
                return reqs

            for share in shares:
                # warm pass freezes the hot pool over limit
                for i in range(4):
                    await inst.get_rate_limits(batch_for(1.0, 0, i))
                if inst.shed is not None:
                    inst.shed.reset_counters()
                stop_at = time.monotonic() + args.seconds
                done = over = 0

                async def worker(w: int):
                    nonlocal done, over
                    i = 0
                    while time.monotonic() < stop_at:
                        resps = await inst.get_rate_limits(
                            batch_for(share, w, i)
                        )
                        done += len(resps)
                        over += sum(
                            1 for r in resps
                            if r.status == Status.OVER_LIMIT
                        )
                        i += 1

                t0 = time.monotonic()
                await asyncio.gather(*[worker(w) for w in range(8)])
                elapsed = time.monotonic() - t0
                shed_stats = (
                    inst.shed.stats() if inst.shed is not None else None
                )
                r = dict(
                    metric="shed_overlimit_serving",
                    target_over_limit_share=share,
                    over_limit_share=round(over / done, 4) if done else 0,
                    decisions_per_sec=round(done / elapsed, 1),
                    seconds=round(elapsed, 3),
                    shed=shed_stats,
                )
                print(
                    f"share {share:.2f}: "
                    f"{r['decisions_per_sec']:>12,.0f} dec/s  "
                    f"(over-limit {r['over_limit_share']:.2f}, shed "
                    f"hit-rate "
                    f"{shed_stats['hit_rate'] if shed_stats else '-'}"
                    f")",
                    file=sys.stderr,
                )
                rows.append(r)
        finally:
            await inst.stop()

    asyncio.run(run_rounds())
    doc = dict(
        scenario="shed_overlimit",
        backend=conf.backend,
        served_via=(
            "config_from_env -> make_backend -> Instance "
            "(instance-tier shed screen); the bridge-tier A/B lives "
            "in scripts/profile_shed.py"
        ),
        env_knobs={
            "GUBER_BACKEND": conf.backend,
            "GUBER_SHED_CACHE": env.get("GUBER_SHED_CACHE", "1"),
            "GUBER_SHED_CACHE_KEYS": env.get(
                "GUBER_SHED_CACHE_KEYS", "<default>"
            ),
            "GUBER_PREP_AT_ARRIVAL": env.get(
                "GUBER_PREP_AT_ARRIVAL", "1"
            ),
        },
        rows=rows,
    )
    if args.json:
        print(json.dumps(doc))
    return 0


def _algo_env(args):
    """GUBER_* env for the r15 algorithm scenarios: the shipped boot
    path on the device backend, moderate store, shed cache OFF so the
    token arm of an A/B pays the same host path as the non-sheddable
    algorithms."""
    import os

    depth = int(args.depths.split(",")[0])
    env = dict(os.environ)
    env.update(
        {
            "GUBER_BACKEND": "tpu",
            "GUBER_DEVICE_BATCH_LIMIT": str(depth),
            "GUBER_DEVICE_DEEP_BATCH": "1",
            "GUBER_STORE_SLOTS": str(1 << 14),
            "GUBER_SHED_CACHE": "0",
            "GUBER_GRPC_ADDRESS": "127.0.0.1:0",
        }
    )
    env.pop("GUBER_STORE_MIB", None)
    env.pop("GUBER_STORE_TARGET_KEYS", None)
    return env, depth


def run_flash_crowd(args) -> int:
    """Flash-crowd scenario (r15): `--algorithm` under a suddenly-hot
    key set that rotates every phase (cli/keystreams.flash_crowd_pool)
    over the zipf background. The algorithm-suite shape: a fixed
    window admits ~2x limit around each boundary of a crowd this
    bursty; the sliding blend and GCRA's emission spacing do not.
    Reports dec/s plus the over-limit share the algorithm enforced."""
    import asyncio

    import numpy as np

    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.core.algorithms import ALGO_NAMES
    from gubernator_tpu.serve.config import config_from_env

    _jax_cache()
    env, depth = _algo_env(args)
    conf = config_from_env(env)
    algo_id = ALGO_NAMES[args.algorithm]
    group = min(args.group, depth)
    limit, duration = 200, 1000

    async def run():
        inst, backend, warm_s = await _boot_stack(
            conf, f"flash_crowd_{args.algorithm}", depth
        )
        try:
            stop_at = time.monotonic() + args.seconds
            done = 0
            over = 0
            t0 = time.monotonic()

            async def worker(w: int):
                nonlocal done, over
                ones = np.ones(group, np.int64)
                algo = np.full(group, algo_id, np.int32)
                passes = 0
                while time.monotonic() < stop_at:
                    # the crowd rotates every ~500ms: a fresh flash
                    phase = int((time.monotonic() - t0) * 2)
                    passes += 1
                    kh = keystreams.flash_crowd_pool(
                        1 << 20, group, phase,
                        rng=np.random.default_rng(
                            phase * 1000 + passes * 17 + w
                        ),
                    )
                    status, _l, _r, _t = (
                        await inst.batcher.decide_arrays(
                            dict(
                                key_hash=kh, hits=ones,
                                limit=ones * limit,
                                duration=ones * duration,
                                algo=algo,
                            )
                        )
                    )
                    done += group
                    over += int(np.sum(np.asarray(status) != 0))

            workers = max(8, 2 * depth // group)
            await asyncio.gather(*[worker(w) for w in range(workers)])
            elapsed = time.monotonic() - t0
            return dict(
                metric=f"flash_crowd_{args.algorithm}",
                algorithm=args.algorithm,
                depth=depth,
                decisions_per_sec=round(done / elapsed, 1),
                over_limit_share=round(over / max(done, 1), 4),
                limit=limit,
                duration_ms=duration,
                seconds=round(elapsed, 3),
                workers=workers,
                group_rows=group,
                warmup_seconds=round(warm_s, 1),
            )
        finally:
            await inst.stop()

    row = asyncio.run(run())
    print(
        f"flash-crowd[{args.algorithm}]: "
        f"{row['decisions_per_sec']:>12,.0f} dec/s  "
        f"over-limit {row['over_limit_share']:.1%}",
        file=sys.stderr,
    )
    if args.json:
        import jax as _jax

        print(json.dumps(dict(
            scenario="flash_crowd",
            scope=_jax.devices()[0].platform,
            rows=[row],
        )))
    return 0


def run_mixed_tenant_zipf(args) -> int:
    """Mixed-tenant quota-chain scenario (r15): every request names a
    global -> (region ->) tenant chain (depth = --chain-depth) over a
    zipf tenant draw (keystreams.tenant_zipf_ids) — the multi-tenant
    front door quota chains exist for. Drives the batcher's dedicated
    chain lane (object path, one coalesced chain-coupled kernel pass
    per flush); reports chains/s, device rows/s (the expansion
    factor), refusal share, and which level refused."""
    import asyncio
    import collections

    import numpy as np

    from gubernator_tpu.api.types import ChainLevel, RateLimitReq
    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.serve.config import config_from_env

    _jax_cache()
    env, depth = _algo_env(args)
    conf = config_from_env(env)
    d = max(1, min(int(args.chain_depth), 3))
    tenants = 64
    chain_group = 256
    # ancestors, shallow to deep; truncated to depth KEEPING the head
    # (the consolidation contract routes every chain by chain[0])
    # tenant limit sized so the zipf head tenant (~18% of traffic at
    # a=1.2) exhausts its quota inside a few bench seconds — the
    # most-restrictive-wins refusals are the scenario's point
    lv_limits = {"global": 1 << 30, "region": 1 << 24, "tenant": 1200}

    async def run():
        inst, backend, warm_s = await _boot_stack(
            conf, f"tenant_chain_d{d}", depth
        )
        try:
            stop_at = time.monotonic() + args.seconds
            done = 0
            refused = 0
            level_hist = collections.Counter()
            t0 = time.monotonic()

            async def worker(w: int):
                nonlocal done, refused
                rng = np.random.default_rng(100 + w)
                passes = 0
                while time.monotonic() < stop_at:
                    passes += 1
                    ts = keystreams.tenant_zipf_ids(
                        tenants, chain_group, rng
                    )
                    reqs = []
                    for j, t in enumerate(ts):
                        chain = [
                            ChainLevel("global", lv_limits["global"], 0),
                            ChainLevel(
                                f"region:{int(t) % 4}",
                                lv_limits["region"], 0,
                            ),
                            ChainLevel(
                                f"tenant:{int(t)}",
                                lv_limits["tenant"], 0,
                            ),
                        ][-d:]
                        # keep ONE head per hierarchy: depth-truncated
                        # chains still start at the deepest kept level
                        reqs.append(RateLimitReq(
                            name="mtz",
                            unique_key=(
                                f"k:{int(t)}:"
                                f"{int(rng.integers(1 << 14))}"
                            ),
                            hits=1,
                            limit=1 << 20,
                            duration=60_000,
                            chain=chain,
                        ))
                    resps = await inst.batcher.decide_chain(reqs)
                    done += len(resps)
                    for r in resps:
                        if int(r.status) != 0:
                            refused += 1
                            level_hist[
                                r.metadata.get("chain_level", "leaf")
                            ] += 1

            await asyncio.gather(*[worker(w) for w in range(8)])
            elapsed = time.monotonic() - t0
            return dict(
                metric=f"tenant_chain_depth{d}",
                chain_depth=d,
                tenants=tenants,
                chains_per_sec=round(done / elapsed, 1),
                device_rows_per_sec=round(done * (d + 1) / elapsed, 1),
                refusal_share=round(refused / max(done, 1), 4),
                refusing_level=dict(level_hist),
                seconds=round(elapsed, 3),
                warmup_seconds=round(warm_s, 1),
            )
        finally:
            await inst.stop()

    row = asyncio.run(run())
    print(
        f"mixed-tenant-zipf[d{d}]: {row['chains_per_sec']:>10,.0f} "
        f"chains/s ({row['device_rows_per_sec']:,.0f} rows/s, "
        f"refused {row['refusal_share']:.1%})",
        file=sys.stderr,
    )
    if args.json:
        import jax as _jax

        print(json.dumps(dict(
            scenario="mixed_tenant_zipf",
            scope=_jax.devices()[0].platform,
            rows=[row],
        )))
    return 0


def run_gcra_vs_token(args) -> int:
    """GCRA-vs-token fairness A/B (r15): one hot key under demand far
    above its limit, token bucket then GCRA on fresh stacks. The
    token window admits its whole budget at the window start and
    refuses the rest (bursty admission: long refusal runs, high
    inter-admission-gap variance); GCRA's emission interval spaces
    the SAME average admission rate evenly. Reported per arm:
    admitted/s, max refusal run, and the coefficient of variation of
    inter-admission gaps — the fairness number (lower = smoother)."""
    import asyncio

    import numpy as np

    from gubernator_tpu.cli import keystreams
    from gubernator_tpu.core.algorithms import ALGO_NAMES
    from gubernator_tpu.serve.config import config_from_env

    _jax_cache()
    env, depth = _algo_env(args)
    limit, duration = 50, 2000

    async def one_arm(algo_name: str) -> dict:
        conf = config_from_env(env)
        inst, backend, warm_s = await _boot_stack(
            conf, f"gcra_vs_token_{algo_name}", depth
        )
        try:
            algo_id = ALGO_NAMES[algo_name]
            kh = keystreams.hash_ids(np.array([7], np.uint64))
            one = np.ones(1, np.int64)
            algo = np.full(1, algo_id, np.int32)
            stop_at = time.monotonic() + args.seconds
            admits = []
            statuses = []
            while time.monotonic() < stop_at:
                status, _l, _r, _t = (
                    await inst.batcher.decide_arrays(
                        dict(
                            key_hash=kh, hits=one,
                            limit=one * limit,
                            duration=one * duration, algo=algo,
                        )
                    )
                )
                ok = int(np.asarray(status)[0]) == 0
                statuses.append(ok)
                if ok:
                    admits.append(time.monotonic())
            gaps = np.diff(np.asarray(admits))
            run_len = max_run = 0
            for ok in statuses:
                run_len = 0 if ok else run_len + 1
                max_run = max(max_run, run_len)
            cv = (
                float(np.std(gaps) / np.mean(gaps))
                if gaps.size > 1 and np.mean(gaps) > 0
                else 0.0
            )
            return dict(
                algorithm=algo_name,
                requests=len(statuses),
                admitted=len(admits),
                admitted_per_sec=round(
                    len(admits) / args.seconds, 1
                ),
                max_refusal_run=max_run,
                admission_gap_cv=round(cv, 3),
                limit=limit,
                duration_ms=duration,
                warmup_seconds=round(warm_s, 1),
            )
        finally:
            await inst.stop()

    rows = []
    for name in ("token", "gcra"):
        r = asyncio.run(one_arm(name))
        rows.append(r)
        print(
            f"gcra-vs-token[{name}]: {r['admitted']} admitted "
            f"of {r['requests']}  gap-CV {r['admission_gap_cv']} "
            f"max-refusal-run {r['max_refusal_run']}",
            file=sys.stderr,
        )
    if args.json:
        import jax as _jax

        print(json.dumps(dict(
            scenario="gcra_vs_token",
            scope=_jax.devices()[0].platform,
            note=(
                "same demand, same average admission rate; GCRA's "
                "emission interval spreads admissions evenly where "
                "the token window grants its whole budget at the "
                "window start — compare admission_gap_cv and "
                "max_refusal_run, not admitted_per_sec"
            ),
            rows=rows,
        )))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="serving benchmarks")
    parser.add_argument("--backend", default="exact")
    parser.add_argument("--seconds", type=float, default=3.0)
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument("--json", action="store_true")
    parser.add_argument(
        "--scenario",
        default="cluster",
        choices=[
            "cluster", "zipf10m", "zipf100m", "key-churn", "shed",
            "shard", "flash-crowd", "mixed-tenant-zipf",
            "gcra-vs-token",
        ],
        help="cluster = the reference benchmark suite over localhost "
        "gRPC; zipf10m = BASELINE config 4 through the shipped serving "
        "config (deep-batch ladder, GUBER_STORE_MIB-sized store); "
        "zipf100m = the r13 two-tier flagship: 100M-key zipf at the "
        "SAME fixed budget (sketch carve-out) vs the exact-only 10M "
        "baseline, plus the measured tail-error phase with the r21 "
        "derivation A/B and sliding/gcra window-ring arms "
        "(BENCH_SKETCH_r21.json); key-churn = adversarial fresh-keys-"
        "every-pass stream (tier thrash worst case, ROADMAP item 4); "
        "shed = over-limit-heavy skew ladder through the shipped boot "
        "path (the r10 shed cache's workload; GUBER_SHED_CACHE "
        "honored and recorded, over-limit share reported per round); "
        "flash-crowd = suddenly-hot rotating key set under "
        "--algorithm (r15 suite); mixed-tenant-zipf = quota chains "
        "over a zipf tenant draw at --chain-depth; gcra-vs-token = "
        "single-hot-key admission-fairness A/B",
    )
    parser.add_argument(
        "--algorithm",
        default="sliding",
        choices=["token", "leaky", "sliding", "gcra"],
        help="flash-crowd: the rate-limit algorithm under test "
        "(core/algorithms.py registry names)",
    )
    parser.add_argument(
        "--chain-depth",
        type=int,
        default=3,
        help="mixed-tenant-zipf: ancestor levels per request (1-3; "
        "3 = global -> region -> tenant above the leaf)",
    )
    parser.add_argument(
        "--rounds", type=int, default=3,
        help="zipf100m: interleaved paired baseline/sketch rounds",
    )
    parser.add_argument(
        "--shards", default="1,2,4,8",
        help="shard scenario: comma list of mesh shard counts, each "
        "run on that many SIMULATED host devices in a fresh "
        "subprocess (a flat 1-shard row is always included as the "
        "degenerate-policy baseline)",
    )
    parser.add_argument(
        "--shard-depth", type=int, default=8192,
        help="shard scenario: GUBER_DEVICE_BATCH_LIMIT per rung",
    )
    parser.add_argument(
        "--shard-slots", type=int, default=1 << 12,
        help="shard scenario: GUBER_STORE_SLOTS per rung (per-shard "
        "table geometry is identical across the ladder)",
    )
    parser.add_argument(
        "--shard-child", default="",
        help=argparse.SUPPRESS,  # internal: one ladder row in-process
    )
    parser.add_argument(
        "--shed-shares",
        default="0.0,0.3,0.6,0.9",
        help="shed scenario: comma list of target over-limit traffic "
        "shares, one measurement round each",
    )
    parser.add_argument(
        "--depths",
        default="4096,16384,32768,131072",
        help="zipf10m: comma list of GUBER_DEVICE_BATCH_LIMIT rungs",
    )
    parser.add_argument(
        "--keys", type=int, default=10_000_000,
        help="zipf10m: live-key budget (GUBER_STORE_TARGET_KEYS)",
    )
    parser.add_argument(
        "--store-mib", type=int, default=1024,
        help="zipf10m: fixed store footprint (GUBER_STORE_MIB)",
    )
    parser.add_argument(
        "--group", type=int, default=4096,
        help="zipf10m: rows per caller group (edge-frame shape)",
    )
    parser.add_argument(
        "--edge",
        action="store_true",
        help="also bench through the native C++ edge (requires "
        "make -C gubernator_tpu/native/edge)",
    )
    parser.add_argument(
        "--edge-only",
        action="store_true",
        help="with --edge: run ONLY the edge-door scenarios (skip the "
        "reference suite) — the A/B loop for protocol comparisons",
    )
    parser.add_argument(
        "--edge-bin",
        default="",
        help="path to an alternative guber-edge binary (e.g. a pre-r7 "
        "build for a windowed-vs-roundtrip protocol A/B); default: the "
        "in-tree build",
    )
    parser.add_argument(
        "--edge-workers",
        type=int,
        default=2,
        help="edge backend connections (guber-edge --workers). The "
        "windowed protocol (r7) keeps N frames in flight per "
        "connection, so the binary's default 2 suffices; pre-r7 "
        "builds needed 8 to hide the one-frame-per-roundtrip wait",
    )
    parser.add_argument(
        "--edge-clients",
        type=int,
        default=16,
        help="concurrent client threads for edge_grpc_batched_"
        "concurrent (in-flight frame demand; past the backend "
        "connection count only a windowed edge can keep them all "
        "moving)",
    )
    parser.add_argument(
        "--fetch-depth",
        type=int,
        default=None,
        help="in-flight device batches per node (GUBER_FETCH_DEPTH); "
        "raise toward 16 when the device sits behind a high-latency "
        "tunnel",
    )
    parser.add_argument(
        "--prep-at-arrival",
        choices=["0", "1"],
        default=None,
        help="override GUBER_PREP_AT_ARRIVAL for every node this "
        "harness boots (r9 host-prep pipeline A/B; default: env / on)",
    )
    args = parser.parse_args(argv)
    if args.fetch_depth is not None:
        import os

        os.environ["GUBER_FETCH_DEPTH"] = str(args.fetch_depth)
    if args.prep_at_arrival is not None:
        import os

        os.environ["GUBER_PREP_AT_ARRIVAL"] = args.prep_at_arrival
    if args.scenario == "flash-crowd":
        return run_flash_crowd(args)
    if args.scenario == "mixed-tenant-zipf":
        return run_mixed_tenant_zipf(args)
    if args.scenario == "gcra-vs-token":
        return run_gcra_vs_token(args)
    if args.scenario == "shed":
        if args.backend == "exact":
            print(
                "shed is a device scenario by default: using "
                "--backend tpu (pass GUBER_BACKEND=exact to force)",
                file=sys.stderr,
            )
            args.backend = "tpu"
        return run_shed(args)
    if args.scenario == "zipf10m":
        if args.backend == "exact":
            # config 4 is a device scenario (the exact backend decides
            # inline and cannot deep-batch; config.validate refuses the
            # combination) — remap the cluster-suite default, loudly
            print(
                "zipf10m is a device scenario: using --backend tpu "
                "(exact cannot deep-batch)",
                file=sys.stderr,
            )
            args.backend = "tpu"
        return run_zipf10m(args)
    if args.scenario == "zipf100m":
        # two-tier defaults: one deep rung, 100M-key space when the
        # user left the zipf10m defaults in place
        if args.depths == parser.get_default("depths"):
            args.depths = "32768"
        if args.keys == parser.get_default("keys"):
            args.keys = 100_000_000
        return run_zipf100m(args)
    if args.scenario == "key-churn":
        if args.depths == parser.get_default("depths"):
            args.depths = "32768"
        return run_churn(args)
    if args.scenario == "shard":
        if args.keys == parser.get_default("keys"):
            # dispatch-price ladder: the key set must fit every rung's
            # exact tier so tier behavior can't confound the topology
            # comparison (per-shard tables multiply capacity with n)
            args.keys = 50_000
        return run_shard(args)

    backend_factory = None
    # device backends boot with the daemon's shipped co-batch depth
    # (GUBER_DEVICE_BATCH_LIMIT, default 8192 here): the windowed edge
    # keeps many frames in flight per connection (r7), and the device
    # batcher folds those concurrent ~1000-item groups into one deep
    # launch — the ladder rungs compile at warmup exactly as the daemon
    # compiles them (make_backend), so this is the served path, not a
    # bench-only trick.
    import os as _os

    device_limit = int(
        _os.environ.get("GUBER_DEVICE_BATCH_LIMIT", "8192")
    )
    if args.backend == "exact":
        from gubernator_tpu.serve.backends import ExactBackend

        backend_factory = lambda: ExactBackend(100_000)  # noqa: E731
    elif args.backend == "mesh":
        from gubernator_tpu.core.engine import buckets_for_limit
        from gubernator_tpu.core.store import StoreConfig
        from gubernator_tpu.serve.backends import MeshBackend

        backend_factory = lambda: MeshBackend(  # noqa: E731
            StoreConfig(rows=16, slots=1 << 12),
            buckets=buckets_for_limit(device_limit),
        )
    elif args.backend == "tpu":
        from gubernator_tpu.core.engine import buckets_for_limit
        from gubernator_tpu.core.store import StoreConfig
        from gubernator_tpu.serve.backends import TpuBackend

        # same store shape as the mesh run so the two device artifacts
        # are apples-to-apples
        backend_factory = lambda: TpuBackend(  # noqa: E731
            StoreConfig(rows=16, slots=1 << 12),
            buckets=buckets_for_limit(device_limit),
        )
    else:
        # an unknown name silently benching the wrong backend would
        # publish numbers under a false label
        parser.error(f"unknown --backend {args.backend!r}")

    device_backend = args.backend in ("mesh", "tpu")
    if device_backend:
        # N nodes build N identical engines; the persistent cache makes
        # nodes 1..N-1 deserialize instead of recompile (measured: 212s
        # cold -> 112s warm per engine on v5e-via-tunnel, the residue
        # being warmup execution round-trips, not compilation)
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            str(_compile_cache_dir().resolve()),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    # node 0 also serves the Python HTTP/JSON gateway so the edge's
    # front-door multiplier is a measured comparison, not a claim
    # (gated on --edge: gRPC-only runs must not fail on a busy port)
    http_addresses = [""] * args.nodes
    if args.edge:
        http_addresses[0] = PYTHON_HTTP_ADDR
    cluster = LocalCluster(
        ADDRESSES[: args.nodes],
        backend_factory=backend_factory,
        http_addresses=http_addresses,
        device_batch_limit=device_limit if device_backend else None,
    )
    print("starting cluster...", file=sys.stderr)
    # device backends pay per-node warmup at boot (~2 min/node with a warm
    # compile cache over the tunnel); the default 90s would kill the run
    cluster.start(timeout=120 + (300 * args.nodes if device_backend else 0))
    try:
        target = cluster.peer_at(0)
        chan = grpc.insecure_channel(target)
        v1 = V1Stub(chan)
        peers = PeersV1Stub(chan)

        results = []

        def no_batching(i: int):
            peers.GetPeerRateLimits(
                peers_pb2.GetPeerRateLimitsReq(requests=[_req(f"k{i % 1000}")])
            )

        def get_rate_limit(i: int):
            v1.GetRateLimits(
                gubernator_pb2.GetRateLimitsReq(
                    requests=[_req(f"k{i % 1000}")]
                )
            )

        def ping(i: int):
            v1.HealthCheck(gubernator_pb2.HealthCheckReq())

        # per-worker channels for the herd so one channel isn't the choke
        herd_stubs: List[V1Stub] = [
            V1Stub(grpc.insecure_channel(cluster.get_peer()))
            for _ in range(100)
        ]

        def herd(i: int):
            herd_stubs[i % 100].GetRateLimits(
                gubernator_pb2.GetRateLimitsReq(
                    requests=[_req(f"k{i % 1000}")]
                )
            )

        batch = gubernator_pb2.GetRateLimitsReq(
            requests=[_req(f"k{i}") for i in range(1000)]
        )

        def batched(i: int):
            v1.GetRateLimits(batch)

        # GLOBAL behavior against node 0 (mixed owners: replica answers
        # locally, hits gossip async) — BASELINE config 3's latency
        # scenario; its target is p99 < 1ms
        def global_req(i: int):
            r = _req(f"g{i % 1000}")
            r.behavior = gubernator_pb2.GLOBAL
            return r

        def global_call(i: int):
            v1.GetRateLimits(
                gubernator_pb2.GetRateLimitsReq(requests=[global_req(i)])
            )

        # optional: front node 0 with the native edge (HTTP/JSON in C++,
        # batched frames into the same instance) and measure through it
        edge_proc = None
        if args.edge:
            import json as _json
            import pathlib
            import subprocess
            import urllib.request

            edge_bin = (
                pathlib.Path(args.edge_bin)
                if args.edge_bin
                else pathlib.Path(__file__).resolve().parents[1]
                / "native" / "edge" / "guber-edge"
            )
            if not edge_bin.exists():
                print(
                    "edge binary missing; build it with "
                    "make -C gubernator_tpu/native/edge",
                    file=sys.stderr,
                )
                return 1
            sock = "/tmp/guber-bench-edge.sock"
            try:
                import os

                os.unlink(sock)
            except FileNotFoundError:
                pass
            edge_bridge = cluster.run(
                _attach_edge_bridge(cluster.servers[0], sock)
            )
            edge_port = 19979
            edge_grpc_port = 19981
            edge_proc = subprocess.Popen(
                [str(edge_bin), "--listen", str(edge_port),
                 "--grpc-listen", str(edge_grpc_port),
                 "--backend", sock, "--workers",
                 str(args.edge_workers)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            # poll for readiness instead of hoping a fixed sleep suffices
            import socket as _socket

            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    _socket.create_connection(
                        ("127.0.0.1", edge_port), timeout=1
                    ).close()
                    break
                except OSError:
                    time.sleep(0.05)
            edge_body = _json.dumps(
                {
                    "requests": [
                        {"name": "edge", "uniqueKey": "K", "hits": 1,
                         "limit": 1000000, "duration": 10000}
                    ]
                }
            ).encode()

            through_edge = _front_door_call(
                f"http://127.0.0.1:{edge_port}/v1/GetRateLimits", edge_body
            )

            # same workload against node 0's Python HTTP gateway: the
            # apples-to-apples denominator for the edge multiplier
            # (skipped under --edge-only: the A/B loop compares edge
            # binaries, not doors)
            if not args.edge_only:
                results.append(
                    _measure(
                        "python_http_front_door",
                        _front_door_call(
                            f"http://{PYTHON_HTTP_ADDR}/v1/GetRateLimits",
                            edge_body,
                        ),
                        args.seconds, workers=16,
                    )
                )
                results.append(
                    _measure("edge_front_door", through_edge,
                             args.seconds, workers=16)
                )

            # BASELINE config 3's honest low-concurrency restatement:
            # ONE client, GLOBAL behavior, through the compiled edge —
            # the reference's "most responses < 1ms" is a per-response
            # production latency, not a saturated-tail number
            if not args.edge_only:
                global_edge_body = _json.dumps(
                    {
                        "requests": [
                            {"name": "edge", "uniqueKey": "G", "hits": 1,
                             "limit": 1000000, "duration": 10000,
                             "behavior": "GLOBAL"}
                        ]
                    }
                ).encode()
                results.append(
                    _measure(
                        "global_1way_edge",
                        _front_door_call(
                            f"http://127.0.0.1:{edge_port}"
                            "/v1/GetRateLimits",
                            global_edge_body,
                        ),
                        args.seconds, workers=1,
                    )
                )

            # gRPC front doors under the SAME 16-way single-item load:
            # the compiled edge terminates h2/HPACK/proto itself
            # (native/edge/h2_grpc.inc) vs the Python grpc.aio listener
            # whose 16-way tail collapse r3 measured. Per-worker
            # channels, like the herd.
            one_req = gubernator_pb2.GetRateLimitsReq(
                requests=[_req("K")]
            )

            def _grpc_door(target):
                stubs = [
                    V1Stub(grpc.insecure_channel(target))
                    for _ in range(16)
                ]

                def call(i: int):
                    stubs[(i // 1_000_000) % 16].GetRateLimits(one_req)

                return call

            if not args.edge_only:
                results.append(
                    _measure(
                        "python_grpc_front_door",
                        _grpc_door(cluster.peer_at(0)),
                        args.seconds, workers=16,
                    )
                )
                results.append(
                    _measure(
                        "edge_grpc_front_door",
                        _grpc_door(f"127.0.0.1:{edge_grpc_port}"),
                        args.seconds, workers=16,
                    )
                )

            # and the batched saturation shape through the edge's gRPC
            # door — on device backends this rides the pre-hashed GEB6
            # array path end-to-end
            batch_1000 = gubernator_pb2.GetRateLimitsReq(
                requests=[_req(f"k{i}") for i in range(1000)]
            )
            n_ec = args.edge_clients
            eg_stubs = [
                V1Stub(
                    grpc.insecure_channel(f"127.0.0.1:{edge_grpc_port}")
                )
                for _ in range(n_ec)
            ]

            def edge_grpc_batched(i: int):
                eg_stubs[(i // 1_000_000) % n_ec].GetRateLimits(
                    batch_1000
                )

            eb = _measure(
                "edge_grpc_batched_concurrent", edge_grpc_batched,
                args.seconds, workers=n_ec,
            )
            eb["decisions_per_sec"] = round(eb["ops_per_sec"] * 1000, 1)
            print(
                f"{'':18s} -> {eb['decisions_per_sec']:12,.0f} decisions/s",
                file=sys.stderr,
            )
            results.append(eb)

        if not args.edge_only:
            results.append(
                _measure("no_batching", no_batching, args.seconds)
            )
            results.append(
                _measure("get_rate_limit", get_rate_limit, args.seconds)
            )
            results.append(_measure("ping", ping, args.seconds))
            results.append(_measure("global", global_call, args.seconds))
            results.append(
                _measure(
                    "thundering_herd", herd, args.seconds, workers=100
                )
            )
            b = _measure("batched", batched, args.seconds)
            b["decisions_per_sec"] = round(b["ops_per_sec"] * 1000, 1)
            print(
                f"{'':18s} -> {b['decisions_per_sec']:12,.0f} "
                "decisions/s",
                file=sys.stderr,
            )
            results.append(b)

            # 16 concurrent clients each sending 1000-item batches: the
            # saturation shape. One outstanding call per client means
            # the single-client "batched" row measures round-trip
            # latency, not capacity; with the batcher's fetch_depth
            # pipeline the service overlaps many device batches, which
            # only concurrency exposes.
            conc_stubs: List[V1Stub] = [
                V1Stub(grpc.insecure_channel(cluster.peer_at(0)))
                for _ in range(16)
            ]

            def batched_concurrent(i: int):
                # call index is w*1_000_000 + seq: key the stub by
                # worker so each client thread owns one channel
                # end-to-end
                conc_stubs[(i // 1_000_000) % 16].GetRateLimits(batch)

            bc = _measure(
                "batched_concurrent", batched_concurrent, args.seconds,
                workers=16,
            )
            bc["decisions_per_sec"] = round(bc["ops_per_sec"] * 1000, 1)
            print(
                f"{'':18s} -> {bc['decisions_per_sec']:12,.0f} "
                "decisions/s",
                file=sys.stderr,
            )
            results.append(bc)

        if args.json:
            doc = {
                "backend": args.backend,
                "nodes": args.nodes,
                "seconds_per_scenario": args.seconds,
                "results": results,
            }
            if device_backend:
                import jax

                doc["device"] = jax.devices()[0].device_kind
                doc["n_devices"] = len(jax.devices())
            print(json.dumps(doc))
        return 0
    finally:
        try:
            if "edge_proc" in locals() and edge_proc is not None:
                edge_proc.kill()
                edge_proc.wait(timeout=5)
            if "edge_bridge" in locals() and edge_bridge is not None:
                cluster.run(edge_bridge.stop())
            import os as _os

            _os.unlink("/tmp/guber-bench-edge.sock")
        except Exception:
            pass
        cluster.stop()


if __name__ == "__main__":
    sys.exit(main())
