"""Standalone local test cluster.

`python -m gubernator_tpu.cli.cluster_main` boots a 6-node cluster on
127.0.0.1:9090-9095 and prints "Ready" (the reference's
cmd/gubernator-cluster, used by client e2e test fixtures).
"""

import sys
import time

from gubernator_tpu.cluster import LocalCluster


def main(argv=None) -> int:
    addresses = [f"127.0.0.1:{p}" for p in range(9090, 9096)]
    cluster = LocalCluster(addresses, global_sync_wait=0.05)
    cluster.start()
    print("Ready", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
