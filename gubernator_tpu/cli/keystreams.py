"""Shared key-stream recipes for every bench and scenario (r13).

The zipf key recipe used to live twice — cli/bench_serving.py's zipf10m
scenario and scripts/bench_scenarios.py's r5 sweep each had their own
copy of the same three constants — so one drifted edit would silently
decouple the serving bench from the kernel bench it claims to mirror.
This module is now the single source of truth (both import it, and the
constants are test-pinned), plus the streams the r13 sketch-tier work
needs:

- `zipf` — the canonical heavy-tail workload (a=1.2 over `key_space`
  ids, splitmix-style hashed), bit-identical to the historical recipe
  for any (key_space, size, seed).
- `key-churn` — the adversarial stream from ROADMAP item 4: every
  phase presents an ENTIRELY FRESH key set (sequential ids offset by
  phase), so every recency/frequency structure in the stack — the shed
  cache, the exact tier's slots, the promoter's top-K — is defeated by
  construction. This is the worst case for tier thrash: nothing is ever
  hot twice, every create fights for a way, and the sketch tier absorbs
  the overflow.

Keys are emitted as uint64 slot hashes (the pre-hashed array-door
shape, same as edge GEB6/GEB7 frames); numpy only, jax-free.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: the historical zipf recipe constants — changing any of these breaks
#: comparability with every committed BENCH_* artifact
ZIPF_A = 1.2
MIX_MUL = 0x9E3779B97F4A7C15
MIX_XOR = 0xDEADBEEFCAFEF00D

STREAMS = ("zipf", "key-churn", "flash-crowd")


def hash_ids(ids: np.ndarray) -> np.ndarray:
    """uint64 key hashes from integer key ids — the staging-side twin
    of hashing a key string once (the benches pre-hash like the edge's
    GEB6 frames, outside any timed region)."""
    return (
        np.asarray(ids).astype(np.uint64) * np.uint64(MIX_MUL)
    ) ^ np.uint64(MIX_XOR)


def zipf_ids(
    key_space: int, size, rng: Optional[np.random.Generator] = None
) -> np.ndarray:
    """Zipf(a=1.2) key ids folded into `key_space`; `size` may be an
    int or a shape tuple. Seed 42 is the benches' pinned default."""
    rng = rng or np.random.default_rng(42)
    return rng.zipf(ZIPF_A, size=size) % key_space


def zipf_pool(
    key_space: int, size, rng: Optional[np.random.Generator] = None
) -> np.ndarray:
    """Pre-hashed zipf key pool — the one zipf recipe every scenario
    shares (bench_serving zipf10m/zipf100m, the r5 sweep, the error-
    bound property tests)."""
    return hash_ids(zipf_ids(key_space, size, rng))


def churn_pool(key_space: int, size: int, phase: int = 0) -> np.ndarray:
    """Adversarial key-churn pool: `size` sequential ids starting at
    phase*size (mod key_space). Consecutive phases are disjoint key
    sets until the space wraps — by then the earliest keys' windows are
    long gone, so reuse never becomes locality."""
    ks = np.uint64(max(int(key_space), 1))
    ids = (
        np.arange(size, dtype=np.uint64)
        + np.uint64(int(phase)) * np.uint64(size)
    ) % ks
    return hash_ids(ids)


def flash_crowd_pool(
    key_space: int,
    size: int,
    phase: int = 0,
    crowd: int = 64,
    share: float = 0.8,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Flash-crowd stream (r15): `share` of the traffic hammers a
    `crowd`-sized set of SUDDENLY-hot keys — fresh every phase, so no
    earlier window, shed entry, or promoter rank exists for them when
    the crowd arrives — over the canonical zipf background. The shape
    the sliding-window blend is for: a fixed window admits 2x limit
    around each boundary under this stream, the blend does not."""
    rng = rng or np.random.default_rng(1000 + phase)
    out = zipf_ids(key_space, size, rng)
    is_crowd = rng.random(size) < share
    # crowd ids live in a reserved stripe far above the zipf head and
    # advance by `crowd` each phase: disjoint from the background and
    # from every earlier phase's crowd until the space wraps
    stripe = (1 << 40) + phase * crowd
    out = np.where(
        is_crowd, stripe + rng.integers(0, crowd, size), out
    )
    return hash_ids(out)


def tenant_zipf_ids(
    tenants: int, size: int, rng: Optional[np.random.Generator] = None
) -> np.ndarray:
    """Tenant draw for the mixed-tenant-zipf chain scenario (r15):
    zipf over `tenants` — a few tenants dominate the front door, the
    long tail trickles, the multi-tenant shape quota chains exist
    for. Returns int64 tenant ids (not hashed: they become chain
    LEVEL keys, strings, serving-side)."""
    rng = rng or np.random.default_rng(43)
    return (rng.zipf(ZIPF_A, size=size) % tenants).astype(np.int64)


def stream_pool(
    name: str,
    key_space: int,
    size: int,
    rng: Optional[np.random.Generator] = None,
    phase: int = 0,
) -> np.ndarray:
    """Named-stream front door for CLI scenarios."""
    if name == "zipf":
        return zipf_pool(key_space, size, rng)
    if name == "key-churn":
        return churn_pool(key_space, size, phase)
    if name == "flash-crowd":
        return flash_crowd_pool(key_space, size, phase)
    raise ValueError(
        f"unknown key stream {name!r} (choose from {STREAMS})"
    )
