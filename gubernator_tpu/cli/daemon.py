"""Server daemon entry point.

`python -m gubernator_tpu.cli.daemon [--config FILE]` — configuration from
GUBER_* env vars with an optional KEY=value config file injected first
(the reference daemon's surface, cmd/gubernator/main.go + config.go).
"""

import argparse
import asyncio
import sys

from gubernator_tpu.serve.config import config_from_env, load_config_file
from gubernator_tpu.serve.logging_setup import setup_logging
from gubernator_tpu.serve.server import run_daemon


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="gubernator-tpu daemon")
    parser.add_argument(
        "--config",
        default="",
        help="environment config file of KEY=value lines",
    )
    args = parser.parse_args(argv)

    env = None
    if args.config:
        env = load_config_file(args.config)
    conf = config_from_env(env)

    setup_logging(
        level="debug" if conf.debug else conf.log_level,
        json_format=conf.log_json,
    )
    asyncio.run(run_daemon(conf))
    return 0


if __name__ == "__main__":
    sys.exit(main())
