"""Server daemon entry point.

`python -m gubernator_tpu.cli.daemon [--config FILE]` — configuration from
GUBER_* env vars with an optional KEY=value config file injected first
(the reference daemon's surface, cmd/gubernator/main.go + config.go).
"""

import argparse
import asyncio
import sys

from gubernator_tpu.serve.config import config_from_env, load_config_file
from gubernator_tpu.serve.logging_setup import setup_logging
from gubernator_tpu.serve.server import run_daemon


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="gubernator-tpu daemon")
    parser.add_argument(
        "--config",
        default="",
        help="environment config file of KEY=value lines",
    )
    args = parser.parse_args(argv)

    env = None
    if args.config:
        env = load_config_file(args.config)
    conf = config_from_env(env)

    setup_logging(
        level="debug" if conf.debug else conf.log_level,
        json_format=conf.log_json,
    )

    if conf.dist_coordinator:
        # multi-host mesh: join the jax.distributed program first; then
        # process 0 serves while every other process runs the lockstep
        # follower loop until the leader closes the step pipe
        from gubernator_tpu.parallel.multihost import (
            MultiHostMeshEngine,
            initialize_distributed,
        )

        # fail fast on the misconfigurations that otherwise deadlock the
        # whole mesh inside a collective or an accept() loop
        if conf.dist_process_id == 0:
            if conf.backend != "multihost":
                raise SystemExit(
                    "GUBER_DIST_COORDINATOR is set but GUBER_BACKEND="
                    f"{conf.backend!r}; the leader must use "
                    "GUBER_BACKEND=multihost"
                )
            if len(conf.dist_followers) != conf.dist_num_processes - 1:
                raise SystemExit(
                    f"GUBER_DIST_FOLLOWERS lists "
                    f"{len(conf.dist_followers)} addresses but "
                    f"GUBER_DIST_NUM_PROCESSES={conf.dist_num_processes} "
                    "implies "
                    f"{conf.dist_num_processes - 1} followers"
                )
        elif not conf.dist_step_listen:
            raise SystemExit(
                "follower processes (GUBER_DIST_PROCESS_ID > 0) require "
                "GUBER_DIST_STEP_LISTEN"
            )

        if conf.jax_platform:
            import jax

            jax.config.update("jax_platforms", conf.jax_platform)
        initialize_distributed(
            conf.dist_coordinator,
            conf.dist_num_processes,
            conf.dist_process_id,
        )
        if conf.dist_process_id != 0:
            from gubernator_tpu.core.engine import buckets_for_limit

            # the bucket ladder AND store geometry must match the
            # leader's exactly: warmup replays every bucket through the
            # step pipe and a follower missing one would die in
            # choose_bucket mid-lockstep; store_config() (not raw
            # rows/slots) so GUBER_STORE_MIB/TARGET_KEYS auto-sizing
            # derives the same shape on every process. Same for the
            # sketch geometry (r20): the hello handshake verifies both.
            eng = MultiHostMeshEngine(
                conf.store_config(),
                buckets=buckets_for_limit(conf.device_batch_limit),
                sketch=conf.sketch_config(),
            )
            eng.follower_loop(conf.dist_step_listen)
            return 0

    asyncio.run(run_daemon(conf))
    return 0


if __name__ == "__main__":
    sys.exit(main())
