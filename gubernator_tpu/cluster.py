"""In-process test cluster: N real servers, real localhost gRPC, one process.

The multi-node test pattern of the reference (reference cluster/cluster.go):
instances wired with static full-mesh peers (each marking itself owner of
its own address), fast GLOBAL sync so gossip convergence is testable in
tens of milliseconds (cluster.go:84), and accessors by index or at random.
All servers share one asyncio loop running on a dedicated thread, so tests
drive them with plain blocking gRPC clients from the main thread — real
sockets, no external dependencies, discovery bypassed.
"""

from __future__ import annotations

import asyncio
import random
import threading
from typing import Callable, List, Optional, Sequence

from gubernator_tpu.api.types import PeerInfo
from gubernator_tpu.serve.config import BehaviorConfig, ServerConfig
from gubernator_tpu.serve.server import Server


class LocalCluster:
    def __init__(
        self,
        addresses: Sequence[str],
        backend_factory: Optional[Callable[[], object]] = None,
        global_sync_wait: float = 0.05,  # fast gossip for tests
        device_batch_wait: float = 0.0,
        http_addresses: Optional[Sequence[str]] = None,
        device_batch_limit: Optional[int] = None,
        geb_ports: Optional[Sequence[int]] = None,
        trace_sample: float = 0.0,
    ):
        """`http_addresses` (parallel to `addresses`) additionally serves
        each node's HTTP JSON gateway — the harness default is gRPC-only
        like the reference's (cluster.go).

        `device_batch_limit` mirrors the daemon's
        GUBER_DEVICE_BATCH_LIMIT: the device batcher co-batches caller
        groups up to this many items per launch (the deep rungs the
        windowed edge protocol feeds, r7). None keeps the per-RPC
        default — existing harness users see identical behavior. The
        backend_factory must compile matching rungs
        (core.engine.buckets_for_limit) or oversized batches recompile
        at serve time."""
        self.addresses = list(addresses)
        self.http_addresses = (
            list(http_addresses) if http_addresses else [""] * len(addresses)
        )
        # `geb_ports` (parallel, r12): additionally serve each node's
        # GEB client-protocol door (GUBER_GEB_PORT); 0 = off per node
        self.geb_ports = (
            list(geb_ports) if geb_ports else [0] * len(addresses)
        )
        if len(self.http_addresses) != len(self.addresses) or len(
            self.geb_ports
        ) != len(self.addresses):
            # zip would silently truncate and leave nodes never started
            raise ValueError(
                f"http_addresses ({len(self.http_addresses)}) / "
                f"geb_ports ({len(self.geb_ports)}) must match "
                f"addresses ({len(self.addresses)})"
            )
        # `trace_sample` (r16): head-sampling probability for every
        # node's tracer (GUBER_TRACE_SAMPLE) — the cluster tests that
        # assert cross-node trace propagation turn it to 1.0
        self._trace_sample = trace_sample
        self.servers: List[Server] = []
        self._backend_factory = backend_factory
        self._global_sync_wait = global_sync_wait
        self._device_batch_wait = device_batch_wait
        self._device_batch_limit = device_batch_limit
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self, timeout: float = 90.0) -> None:
        started = threading.Event()
        failure: list = []

        def runner():
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(self._start_all())
            except Exception as e:
                failure.append(e)
                # tear down any partially-started servers and mark the
                # loop dead so a later stop() cannot schedule onto it
                # and hang (reference cluster_test.go covers exactly the
                # bad-address startup-failure path)
                try:
                    loop.run_until_complete(self._stop_all())
                except Exception:
                    pass
                loop.close()
                self._loop = None
                self.servers = []
                started.set()
                return
            started.set()
            loop.run_forever()

        self._thread = threading.Thread(
            target=runner, name="guber-cluster", daemon=True
        )
        self._thread.start()
        if not started.wait(timeout):
            raise TimeoutError("cluster failed to start in time")
        if failure:
            raise failure[0]

    async def _start_all(self) -> None:
        # Per-node GEB door map (r18): on one host the symmetric-port
        # convention (grpc port ⇒ geb port) is wrong — every node has a
        # distinct geb port — so hand each node the full grpc→door map
        # and the hello advertises routable doors to ring-routing
        # clients (GUBER_GEB_PEER_DOORS).
        doors = ",".join(
            f"{a}=127.0.0.1:{p}"
            for a, p in zip(self.addresses, self.geb_ports)
            if p
        )
        for addr, http_addr, geb_port in zip(
            self.addresses, self.http_addresses, self.geb_ports
        ):
            conf = ServerConfig(
                grpc_address=addr,
                http_address=http_addr,
                advertise_address=addr,
                behaviors=BehaviorConfig(
                    global_sync_wait=self._global_sync_wait
                ),
                device_batch_wait=self._device_batch_wait,
                backend="exact",
                geb_port=geb_port,
                geb_peer_doors=doors,
                trace_sample=self._trace_sample,
            )
            if self._device_batch_limit is not None:
                conf.device_batch_limit = self._device_batch_limit
            backend = (
                self._backend_factory()
                if self._backend_factory is not None
                else None
            )
            server = Server(conf, backend=backend)
            # static full-mesh peers; self marked owner (cluster.go:36-46)
            server.conf.peers = list(self.addresses)
            await server.start()
            self.servers.append(server)

    async def _stop_all(self) -> None:
        for s in self.servers:
            await s.stop()

    def stop(self) -> None:
        loop = self._loop
        if (
            loop is None
            or loop.is_closed()
            or self._thread is None
            or not self._thread.is_alive()
        ):
            # never started, or start failed (runner already cleaned up)
            self._loop = None
            self.servers = []
            return
        fut = asyncio.run_coroutine_threadsafe(self._stop_all(), loop)
        fut.result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        self._thread.join(timeout=10)
        self._loop = None
        self.servers = []

    # -- accessors (cluster.go:56-68) ---------------------------------------

    def get_peer(self) -> str:
        """A random node's address."""
        return random.choice(self.addresses)

    def peer_at(self, i: int) -> str:
        return self.addresses[i]

    def instance_at(self, i: int):
        return self.servers[i].instance

    def run(self, coro, timeout: float = 30.0):
        """Run a coroutine on the cluster loop from test code."""
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout=timeout)
