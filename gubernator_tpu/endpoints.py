"""Shared endpoint parsing for every client/bridge surface (r12).

The GEB frame protocol and the bridge/daemon config all carry endpoints
as 'host:port' split on the LAST colon, or a unix-socket path. An IPv6
literal ('[::1]:9100', bare '::1') silently misparses under that rule —
the bracketed host handed to the resolver, or the whole address
mistaken for a unix path. r7 refused IPv6 loudly at the BRIDGE config
sites (edge.cc endpoint_is_ipv6ish, serve/edge_bridge.py
reject_ipv6_endpoint); this module is the one shared helper so the
client tier (client.py, client_geb.py) and the serving tier agree on
the rule instead of each growing its own misparse. Hostnames and IPv4
only, by design, fleet-wide.

JAX-free and dependency-free: importable from the packaged clients.
"""

from __future__ import annotations

from typing import Tuple, Union


def endpoint_is_ipv6ish(spec: str) -> bool:
    """True when `spec` looks like an IPv6 literal — the shapes the
    last-colon split would silently misparse (r7's rule, mirrored from
    edge.cc endpoint_is_ipv6ish)."""
    return "[" in spec or "]" in spec or spec.count(":") > 1


def reject_ipv6_endpoint(spec: str, what: str) -> str:
    """Refuse an IPv6-ish endpoint loudly at parse time instead of
    misparsing it silently (ADVICE r5 #2). Returns `spec` for
    chaining."""
    if endpoint_is_ipv6ish(spec):
        raise ValueError(
            f"{what} {spec!r} looks like an IPv6 literal; endpoints "
            f"must be 'host:port' with an IPv4 address or hostname "
            f"(the wire protocol splits on the last ':')"
        )
    return spec


def parse_endpoint(
    spec: str, what: str = "endpoint"
) -> Tuple[str, Union[str, Tuple[str, int]]]:
    """Parse one endpoint spec into ('unix', path) or
    ('tcp', (host, port)).

    Accepted shapes:
      'host:port'            TCP (IPv4 address or hostname only)
      '/path/to.sock'        unix socket (absolute path)
      'unix:/path/to.sock'   unix socket, explicit scheme

    Anything IPv6-ish is refused loudly (see endpoint_is_ipv6ish); a
    TCP spec with a missing/empty/non-numeric port raises ValueError
    naming `what`, never a downstream resolver error.
    """
    if not spec:
        raise ValueError(f"{what} cannot be empty")
    if spec.startswith("unix:"):
        path = spec[len("unix:"):]
        if not path:
            raise ValueError(f"{what} {spec!r} has an empty unix path")
        return ("unix", path)
    if spec.startswith("/"):
        return ("unix", spec)
    reject_ipv6_endpoint(spec, what)
    host, sep, port = spec.rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"{what} {spec!r} must be 'host:port' or a unix socket path"
        )
    try:
        port_n = int(port)
    except ValueError:
        raise ValueError(
            f"{what} {spec!r} has a non-numeric port {port!r}"
        ) from None
    if not (0 < port_n < 65536):
        raise ValueError(
            f"{what} {spec!r} port must be in 1..65535"
        )
    return ("tcp", (host, port_n))
