"""Benchmark: rate-limit decisions/sec on one chip.

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Everything else goes to stderr.

Config mirrors BASELINE.md's flagship single-chip target (config 2: mixed
token+leaky traffic over 100k keys against the slot store in HBM). The
measured program is the production decide kernel (core/kernels.py) stepped
S times inside one lax.fori_loop — the store threads through the loop carry
exactly as it does batch-over-batch in serving, with zero host involvement,
so the number is pure device decision throughput. vs_baseline compares
against the reference's published single-node client-facing rate of
~2,000 req/s (reference README.md:94-99; BASELINE.md).

MEASUREMENT NOTES (r3):
- The accumulator reduces EVERY response field (status + a checksum of
  remaining/reset_time/limit). Hygiene, not a correction: a status-only
  reduction would let XLA dead-code-eliminate the other fields' math if
  it ever grew expensive; today the measured difference is ~0.3%
  (back-to-back A/B), far inside run variance.
- Numbers through the remote-TPU tunnel drift ±15% across hours with
  ambient load (same binary measured 34.1-40.7M in one r3 session).
  Conclusions about code changes need BACK-TO-BACK A/Bs in one window:
  the r3 group-rung change measured +6.8% that way (G=8192 32.2M vs
  G=7680 34.3M in a slow window; 38-40.7M in fast windows).
"""

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    import gubernator_tpu.core  # noqa: F401  (enables x64)
    from gubernator_tpu.core.kernels import BatchRequest, decide_presorted
    from gubernator_tpu.core.store import (
        StoreConfig,
        group_sort_key_np,
        new_store,
    )

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    import os

    B = int(os.environ.get("GUBER_DEVICE_BATCH_LIMIT", "32768"))
    # requests per batch (reference hard cap is 1000/RPC; the
    # device batch coalesces many RPCs, serve/batcher.py). Larger batches
    # amortize the gather/scatter fixed costs: measured 37.5M @ 32k with
    # the b/4 group rung (~0.87ms/batch — inside the serving latency
    # envelope). 32k keeps the flagship number consistent with the p99
    # < 1ms serving story; the override rides the SAME env knob the
    # serving tier uses (GUBER_DEVICE_BATCH_LIMIT), so throughput-mode
    # configs (e.g. 131072 on a big store) bench at their serving depth.
    R = 8  # distinct pre-staged batches cycled through. The per-step
    # i%R dynamic-slice of the staged [R, B] arrays costs ~145us/batch
    # (measured r3: R=1 runs 716us/batch vs R=8's 861) — kept
    # DELIBERATELY: each step must consume a fresh input buffer the way
    # serving consumes each batch's host transfer, and with R=1 XLA can
    # hoist loop-invariant key-derived work (bucket/fingerprint of an
    # unchanging key array), overstating steady-state throughput.
    S = 1024  # decide steps fused into one device program (large S
    # amortizes the ~100ms per-call latency of a tunnel-attached device
    # to ~100us/call; on directly-attached hardware it changes nothing)
    KEYS = 100_000
    # 16 ways x 32k buckets: 524k entries capacity, ~20% load at 100k
    # keys (the guidance ceiling is ~50%). ways=16 makes each bucket row
    # exactly 128 lanes (the native TPU vector width) — the fast path for
    # the whole-row gather and delta-add scatter; the 16 MiB store also
    # sweeps faster than wider geometries
    ROWS, SLOTS = 16, 1 << 15

    rng = np.random.default_rng(42)
    store = new_store(StoreConfig(rows=ROWS, slots=SLOTS))

    # mixed token+leaky traffic, zipf-ish key popularity over 100k keys.
    # Batches are presorted by (bucket, fingerprint) on the host — in
    # serving that is one numpy argsort per batch, pipelined with device
    # compute (engine.pad_request_sorted) — so the measured program is
    # the production decide_presorted kernel.
    zipf = rng.zipf(1.2, size=(R, B)) % KEYS
    key_hash = (
        (zipf.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15))
        ^ np.uint64(0xDEADBEEFCAFEF00D)
    )
    limit = rng.integers(10, 10_000, (R, B))
    # presort + group structure with the SHIPPED fast path (native radix,
    # core/engine.py) — the same code serving runs per batch; numpy
    # argsort kept as the cross-check + fallback
    from gubernator_tpu.core.engine import (
        _np_presort,
        _presort,
        _presort_grouped,
        choose_bucket,
        group_rungs,
    )

    t_sort = time.monotonic()
    grouped = [_presort_grouped(key_hash[r], SLOTS) for r in range(R)]
    dt_native = (time.monotonic() - t_sort) / R * 1e6
    order = np.stack([g[0] for g in grouped])
    t_sort = time.monotonic()
    order_np = np.argsort(
        group_sort_key_np(key_hash, SLOTS), axis=1, kind="stable"
    )
    dt_np = (time.monotonic() - t_sort) / R * 1e6
    assert (order == order_np).all() or _presort is _np_presort
    key_hash = np.take_along_axis(key_hash, order, axis=1)
    zipf = np.take_along_axis(zipf, order, axis=1)
    limit = np.take_along_axis(limit, order, axis=1)
    log(
        f"host presort+groups: native {dt_native:.0f} us/batch (numpy "
        f"argsort alone {dt_np:.0f}) — pipelined with device compute in "
        "serving"
    )

    # group structure (store I/O runs at unique-key granularity): one
    # shared G rung across the staged batches, assembled per batch by the
    # same helper serving uses (engine.build_groups)
    from gubernator_tpu.core.engine import build_groups

    G_max = max(g[3] for g in grouped)
    G = choose_bucket(group_rungs(B), G_max)
    log(f"unique-key groups: max {G_max}/{B} per batch -> G rung {G}")
    per_batch = [
        build_groups(key_hash[r], gid, lp, g_real, B, B, G)
        for r, (_o, gid, lp, g_real) in enumerate(grouped)
    ]
    groups = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack(xs)), *per_batch
    )

    reqs = BatchRequest(
        key_hash=jnp.asarray(key_hash),
        hits=jnp.ones((R, B), jnp.int32),
        limit=jnp.asarray(limit, jnp.int32),
        duration=jnp.full((R, B), 60_000, jnp.int32),
        algo=jnp.asarray(zipf % 2, jnp.int32),  # per-key stable algorithm
        gnp=jnp.zeros((R, B), bool),
        valid=jnp.ones((R, B), bool),
    )
    t0 = jnp.int32(1000)  # engine-ms (epoch-relative; see core.store)

    def steps(store, reqs, groups):
        def body(i, carry):
            store, over, chk = carry
            r = jax.tree.map(lambda x: x[i % R], reqs)
            g = jax.tree.map(lambda x: x[i % R], groups)
            now = t0 + i  # clock advances 1ms per batch
            store, resp, _ = decide_presorted(store, r, now, g)
            over = over + jnp.sum(resp.status, dtype=jnp.int32)
            # consume EVERY response field: a status-only reduction lets
            # XLA dead-code-eliminate the remaining/reset/limit math and
            # overstate serving throughput (wrap-safe int32 checksum)
            chk = chk + jnp.sum(
                resp.remaining ^ resp.reset_time ^ resp.limit,
                dtype=jnp.int32,
            )
            return store, over, chk

        return lax.fori_loop(
            0, S, body,
            (store, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)),
        )

    stepped = jax.jit(steps, donate_argnums=(0,))

    log("compiling...")
    t = time.monotonic()
    store, acc, chk = stepped(store, reqs, groups)
    int(acc), int(chk)  # fetch the loop-dependent scalars: a HARD barrier (through
    # the remote-device tunnel, block_until_ready can return before the
    # fused loop finishes — measured; the 4-byte fetch cannot)
    log(f"compile+first run: {time.monotonic() - t:.1f}s")

    times = []
    for rep in range(5):
        t = time.monotonic()
        store, acc, chk = stepped(store, reqs, groups)
        over, _ = int(acc), int(chk)  # barrier (see above)
        dt = time.monotonic() - t
        times.append(dt)
        log(
            f"rep {rep}: {dt*1000:.1f} ms for {S} batches of {B} "
            f"-> {S*B/dt/1e6:.2f} M decisions/s "
            f"(over_limit={over})"
        )

    best = min(times)
    value = S * B / best
    per_batch_us = best / S * 1e6
    log(f"best: {value/1e6:.2f} M decisions/s, {per_batch_us:.0f} us/batch")

    baseline = 2000.0  # reference production node: >2,000 req/s
    print(
        json.dumps(
            {
                "metric": "rate_limit_decisions_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "decisions/s",
                "vs_baseline": round(value / baseline, 1),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
