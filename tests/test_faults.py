"""Fault-injection harness tests (serve/faults.py) — the fast,
deterministic tier-1 slice of the chaos story: the GUBER_FAULT_SPEC
grammar, rule matching (probability / host / budget), and the real
injection points in PeerClient and DeviceBatcher. The full
kill-a-node soak lives in test_chaos_soak.py (marked slow).
"""

import asyncio

import pytest

from gubernator_tpu.api.types import Behavior, RateLimitReq, RateLimitResp
from gubernator_tpu.serve.batcher import DeviceBatcher
from gubernator_tpu.serve.config import BehaviorConfig
from gubernator_tpu.serve.faults import (
    FAULTS,
    FaultError,
    FaultInjector,
    parse_duration_s,
    parse_fault_spec,
)
from gubernator_tpu.serve.peers import PeerClient


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


# -- grammar ---------------------------------------------------------------


def test_parse_issue_example_spec():
    rules = parse_fault_spec(
        "peer_rpc:delay=200ms:p=0.1,peer_rpc:error:p=0.05,"
        "device_submit:hang"
    )
    assert [(r.point, r.action) for r in rules] == [
        ("peer_rpc", "delay"),
        ("peer_rpc", "error"),
        ("device_submit", "hang"),
    ]
    assert rules[0].delay_s == pytest.approx(0.2)
    assert rules[0].p == pytest.approx(0.1)
    assert rules[1].p == pytest.approx(0.05)


def test_parse_durations():
    assert parse_duration_s("200ms") == pytest.approx(0.2)
    assert parse_duration_s("1.5s") == pytest.approx(1.5)
    assert parse_duration_s("50") == pytest.approx(0.05)  # bare = ms
    with pytest.raises(ValueError):
        parse_duration_s("fast")


@pytest.mark.parametrize(
    "bad",
    [
        "nonsense",  # no action
        "warp_core:error",  # unknown point
        "peer_rpc:explode",  # unknown action
        "peer_rpc:delay",  # delay without duration
        "peer_rpc:error:p=1.5",  # probability out of range
        "peer_rpc:error:zeal=9",  # unknown param
        "peer_rpc:hang=5s",  # hang takes no value
    ],
)
def test_parse_rejects_typos_loudly(bad):
    # a silently-dropped rule would let a chaos run pass for the wrong
    # reason — every typo must be a hard error
    with pytest.raises(ValueError):
        parse_fault_spec(bad)


def test_empty_spec_disables():
    inj = FaultInjector()
    inj.configure("")
    assert not inj.enabled


# -- rule matching ---------------------------------------------------------


def test_host_filter_and_budget():
    inj = FaultInjector()
    inj.configure("peer_rpc:error:host=10.0.0.3:n=2")

    async def run():
        # other peers unaffected
        await inj.inject("peer_rpc", peer="10.0.0.4:81")
        for _ in range(2):  # budget: exactly two injections
            with pytest.raises(FaultError):
                await inj.inject("peer_rpc", peer="10.0.0.3:81")
        await inj.inject("peer_rpc", peer="10.0.0.3:81")  # budget spent

    asyncio.run(run())


def test_probability_deterministic_with_seed():
    def count(seed):
        inj = FaultInjector()
        inj.configure("peer_rpc:error:p=0.3", seed=seed)
        hits = 0

        async def run():
            nonlocal hits
            for _ in range(200):
                try:
                    await inj.inject("peer_rpc")
                except FaultError:
                    hits += 1

        asyncio.run(run())
        return hits

    a, b = count(42), count(42)
    assert a == b  # reproducible
    assert 30 <= a <= 90  # ~0.3 of 200


def test_delay_rule_sleeps():
    inj = FaultInjector()
    inj.configure("edge_frame:delay=30ms")

    async def run():
        import time

        t0 = time.monotonic()
        await inj.inject("edge_frame")
        assert time.monotonic() - t0 >= 0.025

    asyncio.run(run())


# -- real injection points -------------------------------------------------


class _OkStub:
    def __init__(self):
        self.calls = 0

    async def GetPeerRateLimits(self, pb_req, timeout=None):
        from gubernator_tpu.api import convert
        from gubernator_tpu.api.proto.gen import peers_pb2

        self.calls += 1
        return peers_pb2.GetPeerRateLimitsResp(
            rate_limits=[
                convert.resp_to_pb(RateLimitResp(limit=5, remaining=4))
                for _ in pb_req.requests
            ]
        )


def test_peer_rpc_injection_exercises_retry_then_gives_up():
    """One budgeted injected error is absorbed by a retry; an unbounded
    error rule exhausts the budget and surfaces the failure."""

    async def run():
        FAULTS.configure("peer_rpc:error:n=1")
        stub = _OkStub()
        c = PeerClient(
            BehaviorConfig(peer_retries=2, peer_backoff=0.001,
                           peer_backoff_max=0.002),
            "127.0.0.1:1",
        )
        c.stub = stub
        r = RateLimitReq(name="f", unique_key="k", hits=1, limit=5,
                         duration=1000, behavior=Behavior.NO_BATCHING)
        resps = await c.get_peer_rate_limits([r])
        assert resps[0].remaining == 4
        assert stub.calls == 1  # injected fault fired BEFORE the stub

        FAULTS.configure("peer_rpc:error")  # every attempt now fails
        with pytest.raises(FaultError):
            await c.get_peer_rate_limits([r])

    asyncio.run(run())


class _HostBackend:
    # deliberately NOT inline_decide: decides must ride the queued
    # flusher path, where the device_submit injection point lives

    def decide(self, reqs, gnp):
        return [RateLimitResp(limit=r.limit, remaining=r.limit - r.hits)
                for r in reqs]

    def update_globals(self, updates):
        pass


def test_device_submit_injection_fails_batch_not_flusher():
    """An injected device_submit error must fail THAT batch's callers
    and leave the flusher alive for the next batch — the same contract
    as a real submit failure."""

    async def run():
        b = DeviceBatcher(_HostBackend(), batch_wait=0.0)
        b.start()
        try:
            FAULTS.configure("device_submit:error:n=1")
            r = RateLimitReq(name="f", unique_key="k", hits=1, limit=5,
                             duration=1000)
            res = await asyncio.gather(
                b.decide([r], [False]), b.decide([r], [False]),
                return_exceptions=True,
            )
            assert any(isinstance(x, FaultError) for x in res)
            # flusher survived: a later decide succeeds
            FAULTS.clear()
            out = await b.decide([r], [False])
            assert out[0].remaining == 4
        finally:
            await b.stop()

    asyncio.run(run())


def test_injection_counts_metric():
    from gubernator_tpu.serve import metrics

    inj = FaultInjector()
    inj.configure("peer_serve:delay=1ms")

    async def run():
        before = metrics.FAULTS_INJECTED.labels(
            point="peer_serve", action="delay"
        )._value.get()
        await inj.inject("peer_serve")
        assert metrics.FAULTS_INJECTED.labels(
            point="peer_serve", action="delay"
        )._value.get() == before + 1

    asyncio.run(run())
