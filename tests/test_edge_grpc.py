"""gRPC termination in the native edge, e2e with the REAL grpc client.

The reference serves its primary protocol, gRPC, from compiled Go
(reference cmd/gubernator/main.go:59-80); here the C++ edge terminates
HTTP/2 + HPACK + gRPC framing itself (native/edge/h2_grpc.inc) and rides
the same backend frames as the JSON door. These tests drive it with
grpc-python — a full-fat client whose HPACK encoder uses Huffman,
incremental indexing, and CONTINUATION-free small headers — so the h2
implementation is validated against a real peer, not a synthetic one.

Skipped when the edge binary is not built.
"""

import json
import urllib.request

import grpc
import pytest

from gubernator_tpu.api.grpc_glue import PeersV1Stub, V1Stub
from gubernator_tpu.api.proto.gen import gubernator_pb2, peers_pb2

from tests._util import edge_binary

EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

DAEMON_HTTP = 19384
EDGE_HTTP = 19385
EDGE_GRPC = 19386
GRPC = 19394
SOCK = "/tmp/guber-edge-grpc-pytest.sock"


@pytest.fixture(scope="module")
def edge_stack():
    from tests._util import spawn_daemon_edge

    daemon, edge = spawn_daemon_edge(
        dict(
            GUBER_BACKEND="exact",
            GUBER_GRPC_ADDRESS=f"127.0.0.1:{GRPC}",
            GUBER_HTTP_ADDRESS=f"127.0.0.1:{DAEMON_HTTP}",
            GUBER_EDGE_SOCKET=SOCK,
        ),
        SOCK,
        edge_http=EDGE_HTTP,
        edge_grpc=EDGE_GRPC,
    )
    yield
    edge.kill()
    daemon.terminate()
    daemon.wait(timeout=10)


def _req(key: str, limit=5, hits=1, **kw) -> gubernator_pb2.RateLimitReq:
    return gubernator_pb2.RateLimitReq(
        name="ge", unique_key=key, hits=hits, limit=limit,
        duration=60_000, **kw,
    )


def test_grpc_edge_token_bucket_and_shared_state(edge_stack):
    chan = grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}")
    v1 = V1Stub(chan)
    # drain a 3-limit bucket through the gRPC door
    for expect in (2, 1, 0):
        r = v1.GetRateLimits(
            gubernator_pb2.GetRateLimitsReq(requests=[_req("tb", limit=3)])
        )
        assert r.responses[0].status == gubernator_pb2.UNDER_LIMIT
        assert r.responses[0].remaining == expect
    r = v1.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(requests=[_req("tb", limit=3)])
    )
    assert r.responses[0].status == gubernator_pb2.OVER_LIMIT

    # same bucket via the daemon's own JSON listener: shared state
    body = json.dumps(
        {"requests": [{"name": "ge", "uniqueKey": "tb", "hits": 0,
                       "limit": 3, "duration": 60000}]}
    ).encode()
    out = json.loads(
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{DAEMON_HTTP}/v1/GetRateLimits",
                data=body,
                headers={"Content-Type": "application/json"},
            ),
            timeout=10,
        ).read()
    )
    assert out["responses"][0]["remaining"] == "0"


def test_grpc_edge_health_and_batches(edge_stack):
    chan = grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}")
    v1 = V1Stub(chan)
    h = v1.HealthCheck(gubernator_pb2.HealthCheckReq())
    assert h.status == "healthy"

    # a full 1000-item batch round-trips with order preserved
    r = v1.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(
            requests=[_req(f"bk{i}", limit=1000 + i) for i in range(1000)]
        )
    )
    assert len(r.responses) == 1000
    assert [x.limit for x in r.responses][:3] == [1000, 1001, 1002]
    assert r.responses[999].limit == 1999

    # empty request -> empty response, not an error
    r = v1.GetRateLimits(gubernator_pb2.GetRateLimitsReq())
    assert len(r.responses) == 0


def test_grpc_edge_validation_errors_per_item(edge_stack):
    chan = grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}")
    v1 = V1Stub(chan)
    r = v1.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(
            requests=[
                gubernator_pb2.RateLimitReq(  # missing unique_key
                    name="ge", hits=1, limit=5, duration=60_000
                ),
                _req("ok-key"),
            ]
        )
    )
    assert "unique_key" in r.responses[0].error
    assert r.responses[1].status == gubernator_pb2.UNDER_LIMIT


def test_grpc_edge_unimplemented_methods(edge_stack):
    chan = grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}")
    peers = PeersV1Stub(chan)
    with pytest.raises(grpc.RpcError) as ei:
        peers.GetPeerRateLimits(
            peers_pb2.GetPeerRateLimitsReq(requests=[_req("p1")])
        )
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_grpc_edge_concurrent_streams_one_channel(edge_stack):
    """grpc multiplexes concurrent calls over one connection: the h2
    layer must interleave streams, not serialize or corrupt them."""
    chan = grpc.insecure_channel(f"127.0.0.1:{EDGE_GRPC}")
    v1 = V1Stub(chan)
    futs = [
        v1.GetRateLimits.future(
            gubernator_pb2.GetRateLimitsReq(
                requests=[_req(f"cc{i}", limit=100 + i)]
            )
        )
        for i in range(32)
    ]
    for i, f in enumerate(futs):
        r = f.result(timeout=30)
        assert r.responses[0].limit == 100 + i
        assert r.responses[0].remaining == 100 + i - 1
