"""Algorithm suite v2 (r15): sliding-window + GCRA correctness pins.

Three layers, mirroring the r10/r13 rigs:

- directed host-oracle semantics (the blend's decay, GCRA's emission
  arithmetic, creation corners, the mismatch rule);
- engine-vs-oracle coverage rides tests/test_fuzz_differential.py
  (the per-key algorithm draw spans all four ids since r15);
- the acceptance pin: fuzzed BYTE-IDENTITY between the device serving
  pipeline (instance -> batcher -> arrival prep -> merged submit ->
  kernel) and the host-oracle instance (ExactBackend) under the fake
  clock, with clock jumps crossing subwindow, window, and multi-window
  boundaries — on BOTH the flat single-chip backend and the simulated
  8-device mesh policy (conftest pins 8 CPU devices).

Duplicate-key discipline follows test_fuzz_differential.py: one hits
draw per (key, batch) and at most one peek, so the kernel's cumulative
rule and the oracle's sequential loop provably coincide.
"""

import asyncio

import numpy as np
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    PeerInfo,
    RateLimitReq,
    Status,
)
from gubernator_tpu.core.algorithms import (
    ALGO_GCRA,
    ALGO_SLIDING,
    ALGORITHMS,
    gcra_budget,
    gcra_params,
    sliding_rotate,
    sliding_used,
)
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.oracle import gcra, get_rate_limit, sliding_window
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve.backends import (
    ExactBackend,
    MeshBackend,
    TpuBackend,
)
from gubernator_tpu.serve.config import ServerConfig
from gubernator_tpu.serve.instance import Instance

T0 = 1_700_000_000_000
ADDR = "127.0.0.1:7975"


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self) -> int:
        return self.t


# -- shared integer conventions (core/algorithms.py) ------------------------


def test_gcra_params_guards():
    # emission interval floors at 1ms even when limit >> duration
    assert gcra_params(1000, 10) == (1, 1000)
    # limit 0: T = duration (div guard), tau = 0 -> budget always 0
    T, tau = gcra_params(0, 5000)
    assert (T, tau) == (5000, 0)
    assert gcra_budget(0, T0, 0, 5000) == 0
    # tau saturates at int32 max instead of overflowing the envelope
    _, tau = gcra_params(1 << 40, 1)
    assert tau == (1 << 31) - 1


def test_sliding_rotate_and_blend():
    d = 1000
    ws = 10_000
    expire = ws + 2 * d
    # same window: untouched
    assert sliding_rotate(expire, d, ws + 500, 3, 7) == (ws, 3, 7)
    # one window later: cur shifts into prev
    assert sliding_rotate(expire, d, ws + d + 1, 3, 7) == (ws + d, 0, 3)
    # two+ windows later: both clear
    assert sliding_rotate(expire, d, ws + 2 * d, 3, 7) == (
        ws + 2 * d, 0, 0,
    )
    # blend weight decays linearly (floor): at 25% into the window 75%
    # of prev still counts
    assert sliding_used(ws, d, ws + 250, 2, 8) == 2 + 6
    assert sliding_used(ws, d, ws + 999, 2, 8) == 2  # 8*1//1000 == 0


# -- directed oracle semantics ----------------------------------------------


def test_oracle_sliding_window_blend_over_boundary():
    cache = LRUCache()
    r = RateLimitReq(name="s", unique_key="k", hits=1, limit=10,
                     duration=1000, algorithm=Algorithm.SLIDING_WINDOW)
    # consume 6 in the creation window
    now = T0
    for i in range(6):
        rl = sliding_window(cache, r, now + i)
        assert rl.status == Status.UNDER_LIMIT
    # 40% into the NEXT window: used = floor(6 * 0.6) = 3 -> budget 7
    peek = RateLimitReq(name="s", unique_key="k", hits=0, limit=10,
                        duration=1000,
                        algorithm=Algorithm.SLIDING_WINDOW)
    rl = sliding_window(cache, peek, T0 + 1400)
    assert rl.remaining == 10 - (6 * 600) // 1000
    # two windows later the old counts are gone entirely
    rl = sliding_window(cache, peek, T0 + 3100)
    assert rl.remaining == 10


def test_oracle_sliding_refused_hits_do_not_debit():
    cache = LRUCache()
    r = RateLimitReq(name="s", unique_key="nr", hits=4, limit=5,
                     duration=1000, algorithm=Algorithm.SLIDING_WINDOW)
    assert sliding_window(cache, r, T0).status == Status.UNDER_LIMIT
    # 4 consumed, budget 1: a 4-hit request is refused and consumes
    # nothing (remaining stays 1)
    rl = sliding_window(cache, r, T0 + 10)
    assert rl.status == Status.OVER_LIMIT
    peek = RateLimitReq(name="s", unique_key="nr", hits=0, limit=5,
                        duration=1000,
                        algorithm=Algorithm.SLIDING_WINDOW)
    assert sliding_window(cache, peek, T0 + 20).remaining == 1


def test_oracle_gcra_emission_and_burst():
    cache = LRUCache()
    # limit 10 per 1000ms -> T=100ms, tau=1000ms: a full burst of 10
    # admits at once, then one token re-emerges every 100ms
    r = RateLimitReq(name="g", unique_key="k", hits=10, limit=10,
                     duration=1000, algorithm=Algorithm.GCRA)
    rl = gcra(cache, r, T0)
    assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 0)
    assert rl.reset_time == T0 + 10 * 100  # the fresh TAT
    one = RateLimitReq(name="g", unique_key="k", hits=1, limit=10,
                       duration=1000, algorithm=Algorithm.GCRA)
    assert gcra(cache, one, T0 + 50).status == Status.OVER_LIMIT
    # 100ms after the burst exactly one token has re-emerged
    rl = gcra(cache, one, T0 + 100)
    assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 0)
    # a refused request reports the earliest instant it could succeed
    r3 = RateLimitReq(name="g", unique_key="k", hits=3, limit=10,
                      duration=1000, algorithm=Algorithm.GCRA)
    rl = gcra(cache, r3, T0 + 150)
    assert rl.status == Status.OVER_LIMIT
    T, tau = gcra_params(10, 1000)
    # stored TAT after the admits above: T0+1000 (burst) + 100 (one)
    assert rl.reset_time == (T0 + 1100) + 3 * T - tau


def test_oracle_gcra_drained_equals_fresh():
    cache = LRUCache()
    one = RateLimitReq(name="g", unique_key="d", hits=1, limit=5,
                       duration=500, algorithm=Algorithm.GCRA)
    a = gcra(cache, one, T0)
    # after > duration idle the bucket has fully drained: the entry
    # lazily expired (cache expiry IS the TAT) and a fresh decision is
    # indistinguishable from a first-contact one
    b = gcra(cache, one, T0 + 10_000)
    assert (a.status, a.limit, a.remaining) == (
        b.status, b.limit, b.remaining,
    )
    assert b.remaining == 4


def test_sliding_long_duration_caps_inside_envelope():
    """10-day sliding windows (a legal duration, above the 2^29-1
    sliding cap): the effective period caps IDENTICALLY on device and
    host (algorithms.sliding_dur vs the kernel clip), so the
    ws + 2*duration expire anchor stays inside int32 even for windows
    created late in the engine epoch — pre-fix the clamped anchor
    silently corrupted the rotation and broke kernel/oracle identity
    (review finding)."""
    from gubernator_tpu.core.algorithms import SLIDING_MAX_DURATION_MS
    from gubernator_tpu.core.engine import TpuEngine

    engine = TpuEngine(StoreConfig(rows=16, slots=1 << 8), buckets=(16,))
    cache = LRUCache()
    DAY = 86_400_000
    pin = RateLimitReq(name="sl", unique_key="pin", hits=1, limit=1,
                       duration=1000)
    engine.get_rate_limits([pin], now=T0)
    get_rate_limit(cache, pin, now=T0)
    r = RateLimitReq(
        name="sl", unique_key="long", hits=1, limit=10,
        duration=10 * DAY,  # effective period caps at ~6.2 days
        algorithm=Algorithm.SLIDING_WINDOW,
    )
    offsets = (
        6 * DAY,  # creation: ws + 2*10d would be ~2.2e9 uncapped
        6 * DAY + 1,
        6 * DAY + SLIDING_MAX_DURATION_MS // 2,
        6 * DAY + SLIDING_MAX_DURATION_MS + 5,  # capped-window rotate
        12 * DAY,  # near the top of the epoch envelope
    )
    for dt in offsets:
        g = engine.get_rate_limits([r], now=T0 + dt)[0]
        w = get_rate_limit(cache, r, now=T0 + dt)
        assert (
            g.status, g.limit, g.remaining, g.reset_time
        ) == (w.status, w.limit, w.remaining, w.reset_time), (dt, g, w)


def test_oracle_mismatch_rule():
    """Token/leaky keep the reference's recreate-as-token behavior;
    sliding/GCRA recreate as THEMSELVES (core/algorithms.py)."""
    cache = LRUCache()
    tok = RateLimitReq(name="m", unique_key="k", hits=1, limit=10,
                       duration=60_000)
    for _ in range(3):
        get_rate_limit(cache, tok, T0)
    # a sliding request over live token state recreates a sliding
    # window with full budget
    sld = RateLimitReq(name="m", unique_key="k", hits=1, limit=10,
                       duration=60_000,
                       algorithm=Algorithm.SLIDING_WINDOW)
    rl = get_rate_limit(cache, sld, T0 + 10)
    assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 9)
    # and a GCRA request over the sliding state recreates GCRA
    g = RateLimitReq(name="m", unique_key="k", hits=1, limit=10,
                     duration=60_000, algorithm=Algorithm.GCRA)
    rl = get_rate_limit(cache, g, T0 + 20)
    assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 9)


# -- serving-pipeline identity fuzz (the acceptance pin) --------------------


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)


async def _mk_instance(backend) -> Instance:
    conf = ServerConfig(grpc_address=ADDR, advertise_address=ADDR)
    inst = Instance(conf, backend)
    inst.start()
    await inst.set_peers([PeerInfo(address=ADDR, is_owner=True)])
    return inst


def _algo_stream(rng, keys, steps, algos):
    """Batches with duplicate keys, peeks, oversized hits, mid-window
    param changes; hits/params drawn once per (key, batch) and the
    algorithm pinned per KEY (test_fuzz_differential discipline).
    Clock jumps cross subwindow (1..150ms), reset (500..2500ms) and
    multi-window (60s) boundaries."""
    for step in range(steps):
        n = int(rng.integers(1, 9))
        picked = rng.choice(len(keys), size=n)
        per_key = {}
        batch = []
        for k in picked:
            if k not in per_key:
                per_key[k] = (
                    int(rng.choice([0, 1, 1, 1, 2, 5, 40])),
                    int(rng.choice([1, 3, 8, 30])),
                    int(rng.choice([400, 1000, 60_000])),
                )
            elif per_key[k][0] == 0:
                continue
            hits, limit, duration = per_key[k]
            batch.append(
                RateLimitReq(
                    name="algofuzz",
                    unique_key=keys[k],
                    hits=hits,
                    limit=limit,
                    duration=duration,
                    algorithm=Algorithm(algos[k % len(algos)]),
                )
            )
        dt = int(rng.choice([0, 1, 7, 50, 150, 500, 2500, 60_000]))
        yield step, batch, dt


def _assert_same(a, b, ctx):
    assert (
        a.status, a.limit, a.remaining, a.reset_time, a.error
    ) == (
        b.status, b.limit, b.remaining, b.reset_time, b.error
    ), (ctx, a, b)


def _run_pipeline_identity(monkeypatch, device_backend, seed, steps):
    """Byte-identity between the device serving pipeline and the
    host-oracle (ExactBackend) instance under one fake clock."""
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    async def run():
        dev = await _mk_instance(device_backend)
        host = await _mk_instance(ExactBackend(10_000))
        try:
            rng = np.random.default_rng(seed)
            keys = [f"a{i}" for i in range(16)]
            # sliding and GCRA keys interleaved with token/leaky ones:
            # the cross-algorithm store-coexistence pin rides the same
            # fuzz (every batch mixes all four algorithms in one
            # kernel pass over one store)
            algos = (0, 2, 3, 2, 1, 3)
            for step, batch, dt in _algo_stream(
                rng, keys, steps, algos
            ):
                clock.t += dt
                a = await dev.get_rate_limits(batch)
                b = await host.get_rate_limits(batch)
                for x, y, r in zip(a, b, batch):
                    _assert_same(x, y, (seed, step, r))
        finally:
            await dev.stop()
            await host.stop()

    asyncio.run(run())


@pytest.mark.parametrize("seed", [3, 11])
def test_pipeline_identity_flat(monkeypatch, seed):
    """Flat single-chip policy: instance -> batcher -> arrival prep ->
    merged submit -> kernel, vs the host oracle."""
    _run_pipeline_identity(
        monkeypatch,
        TpuBackend(StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)),
        seed,
        steps=140,
    )


def test_pipeline_identity_mesh(monkeypatch):
    """Simulated 8-device mesh policy (conftest pins 8 CPU devices):
    the same byte-identity through the sharded engine."""
    import jax

    assert len(jax.devices()) == 8, "conftest should provide 8 devices"
    _run_pipeline_identity(
        monkeypatch,
        MeshBackend(StoreConfig(rows=16, slots=256), buckets=(64,)),
        7,
        steps=80,
    )


def test_registry_covers_wire_enum():
    """Every api.types.Algorithm value has a registry row and the two
    id spaces agree (the CLI/bench name map rides the registry)."""
    for a in Algorithm:
        assert int(a) in ALGORITHMS
        assert ALGORITHMS[int(a)].algo == int(a)
    assert ALGORITHMS[ALGO_SLIDING].name == "sliding"
    assert ALGORITHMS[ALGO_GCRA].name == "gcra"
