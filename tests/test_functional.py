"""Functional tests against a real in-process cluster.

The port of the reference's integration suite (reference
functional_test.go): a 6-node cluster on localhost gRPC sockets in one
process (discovery bypassed, static full-mesh peers), driven through real
clients — including the GLOBAL stale-then-synced convergence contract.
Nodes here run the TPU backend (slot store + decide kernel) so the whole
flagship path gRPC -> batcher -> device kernel is exercised end to end.
"""

import time

import grpc
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    RateLimitReq,
    Status,
    MILLISECOND,
    SECOND,
)
from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import LocalCluster
from gubernator_tpu.core.hashing import ring_hash
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve import metrics
from gubernator_tpu.serve.backends import TpuBackend

ADDRESSES = [f"127.0.0.1:{p}" for p in range(19990, 19996)]


def _tpu_backend():
    return TpuBackend(
        StoreConfig(rows=4, slots=1 << 12), buckets=(64, 256, 1024)
    )


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(ADDRESSES, backend_factory=_tpu_backend)
    c.start()
    yield c
    c.stop()


def _hist_count(h) -> float:
    """Observation count of a prometheus Histogram."""
    for metric in h.collect():
        for s in metric.samples:
            if s.name.endswith("_count"):
                return s.value
    return 0.0


def owner_index(key: str, addresses=None) -> int:
    """Which cluster node owns this ring key (hash.go successor rule)."""
    addresses = ADDRESSES if addresses is None else addresses
    points = sorted((ring_hash(a), a) for a in addresses)
    h = ring_hash(key)
    for point, addr in points:
        if point >= h:
            return addresses.index(addr)
    return addresses.index(points[0][1])


def test_health_check(cluster):
    with V1Client(cluster.get_peer()) as client:
        h = client.health_check(timeout=5)
    assert h.status == "healthy"
    assert h.peer_count == 6


def test_over_the_limit(cluster):
    # reference functional_test.go:51-95
    with V1Client(cluster.get_peer()) as client:
        expects = [
            (1, Status.UNDER_LIMIT),
            (0, Status.UNDER_LIMIT),
            (0, Status.OVER_LIMIT),
        ]
        for remaining, status in expects:
            resp = client.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_over_limit",
                        unique_key="account:1234",
                        algorithm=Algorithm.TOKEN_BUCKET,
                        duration=SECOND,
                        limit=2,
                        hits=1,
                    )
                ],
                timeout=10,
            )
            rl = resp[0]
            assert rl.error == ""
            assert rl.status == status
            assert rl.remaining == remaining
            assert rl.limit == 2
            assert rl.reset_time != 0


def test_token_bucket_window_reset(cluster):
    # reference functional_test.go:97-146 (25ms window for CI stability)
    with V1Client(cluster.get_peer()) as client:
        def hit():
            return client.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_token_bucket",
                        unique_key="account:1234",
                        algorithm=Algorithm.TOKEN_BUCKET,
                        duration=25 * MILLISECOND,
                        limit=2,
                        hits=1,
                    )
                ],
                timeout=10,
            )[0]

        rl = hit()
        assert (rl.remaining, rl.status) == (1, Status.UNDER_LIMIT)
        rl = hit()
        assert (rl.remaining, rl.status) == (0, Status.UNDER_LIMIT)
        time.sleep(0.03)
        rl = hit()
        assert (rl.remaining, rl.status) == (1, Status.UNDER_LIMIT)
        assert rl.reset_time != 0


def test_leaky_bucket_drain(cluster):
    # reference functional_test.go:148-206. Token period 400ms: the
    # assertions tolerate ±~350ms of scheduling delay between hits —
    # at the reference's 10ms (or r2's 40ms) period this test flaked
    # under full-suite load on a one-core box, where a preempted client
    # thread lets an extra token leak between two hits.
    with V1Client(cluster.get_peer()) as client:
        def hit(hits):
            return client.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_leaky_bucket",
                        unique_key="account:1234",
                        algorithm=Algorithm.LEAKY_BUCKET,
                        duration=2000 * MILLISECOND,  # rate = 400ms/token
                        limit=5,
                        hits=hits,
                    )
                ],
                timeout=10,
            )[0]

        rl = hit(5)
        assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 0)
        rl = hit(1)
        assert (rl.status, rl.remaining) == (Status.OVER_LIMIT, 0)
        time.sleep(0.45)  # one token leaks back
        rl = hit(1)
        assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 0)
        time.sleep(0.85)  # two more
        rl = hit(1)
        assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 1)


def test_missing_fields(cluster):
    # reference functional_test.go:208-269
    cases = [
        (
            RateLimitReq(
                name="test_missing_fields",
                unique_key="account:1234",
                hits=1,
                limit=10,
                duration=0,
            ),
            "",
            Status.UNDER_LIMIT,
        ),
        (
            RateLimitReq(
                name="test_missing_fields",
                unique_key="account:12345",
                hits=1,
                duration=10_000,
                limit=0,
            ),
            "",
            Status.OVER_LIMIT,
        ),
        (
            RateLimitReq(
                unique_key="account:1234", hits=1, duration=10_000, limit=5
            ),
            "field 'namespace' cannot be empty",
            Status.UNDER_LIMIT,
        ),
        (
            RateLimitReq(
                name="test_missing_fields", hits=1, duration=10_000, limit=5
            ),
            "field 'unique_key' cannot be empty",
            Status.UNDER_LIMIT,
        ),
    ]
    with V1Client(cluster.get_peer()) as client:
        for i, (req, want_err, want_status) in enumerate(cases):
            rl = client.get_rate_limits([req], timeout=10)[0]
            assert rl.error == want_err, i
            assert rl.status == want_status, i


def test_batch_too_large(cluster):
    with V1Client(cluster.get_peer()) as client:
        reqs = [
            RateLimitReq(
                name="too_big", unique_key=f"k{i}", hits=1, limit=10,
                duration=1000,
            )
            for i in range(1001)
        ]
        with pytest.raises(grpc.RpcError) as exc:
            client.get_rate_limits(reqs, timeout=10)
        assert exc.value.code() == grpc.StatusCode.OUT_OF_RANGE


def test_forwarding_sets_owner_metadata(cluster):
    # pick a key NOT owned by node 0, send it to node 0, expect the
    # response to name the owner (gubernator.go:151)
    key = next(
        f"account:{i}"
        for i in range(1000)
        if owner_index("test_forward_" + f"account:{i}") != 0
    )
    own = owner_index("test_forward_" + key)
    with V1Client(cluster.peer_at(0)) as client:
        rl = client.get_rate_limits(
            [
                RateLimitReq(
                    name="test_forward",
                    unique_key=key,
                    hits=1,
                    limit=10,
                    duration=SECOND,
                    behavior=Behavior.BATCHING,
                )
            ],
            timeout=10,
        )[0]
    assert rl.error == ""
    assert rl.remaining == 9
    assert rl.metadata.get("owner") == ADDRESSES[own]


def test_no_batching_forwarding(cluster):
    key = next(
        f"account:{i}"
        for i in range(1000)
        if owner_index("test_nobatch_" + f"account:{i}") != 0
    )
    with V1Client(cluster.peer_at(0)) as client:
        rl = client.get_rate_limits(
            [
                RateLimitReq(
                    name="test_nobatch",
                    unique_key=key,
                    hits=1,
                    limit=10,
                    duration=SECOND,
                    behavior=Behavior.NO_BATCHING,
                )
            ],
            timeout=10,
        )[0]
    assert rl.error == ""
    assert rl.remaining == 9


def test_global_rate_limits(cluster):
    # reference functional_test.go:271-331: connect to a non-owner, first
    # two hits see the same stale remaining from the local replica (created
    # on first miss), after the gossip interval the third hit sees the
    # owner's accurate count.
    key = next(
        f"account:{i}"
        for i in range(1000)
        if owner_index("test_global_" + f"account:{i}") != 0
    )

    async_before = _hist_count(metrics.GLOBAL_ASYNC_DURATIONS)
    bcast_before = _hist_count(metrics.GLOBAL_BROADCAST_DURATIONS)

    with V1Client(cluster.peer_at(0)) as client:
        def send_hit(status, remaining):
            rl = client.get_rate_limits(
                [
                    RateLimitReq(
                        name="test_global",
                        unique_key=key,
                        algorithm=Algorithm.TOKEN_BUCKET,
                        behavior=Behavior.GLOBAL,
                        duration=3 * SECOND,
                        hits=1,
                        limit=5,
                    )
                ],
                timeout=10,
            )[0]
            assert rl.error == ""
            assert rl.status == status
            assert rl.remaining == remaining
            assert rl.limit == 5

        # first hit misses the replica: processed locally (remaining 4) and
        # the hit is queued to the owner
        send_hit(Status.UNDER_LIMIT, 4)
        # second hit reads the same locally-created entry, still 4 because
        # replica reads don't charge
        send_hit(Status.UNDER_LIMIT, 4)
        # after gossip: owner has absorbed 2 async hits (3 remaining), its
        # broadcast overwrote our local replica
        time.sleep(0.5)
        send_hit(Status.UNDER_LIMIT, 3)

    # the gossip loops actually ran (adaptation of the reference's
    # per-instance histogram asserts; metrics are process-global here).
    # The replica update lands mid-broadcast while observe() fires at the
    # end of the peer loop, so poll briefly.
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        if (
            _hist_count(metrics.GLOBAL_ASYNC_DURATIONS) > async_before
            and _hist_count(metrics.GLOBAL_BROADCAST_DURATIONS) > bcast_before
        ):
            break
        time.sleep(0.05)
    assert _hist_count(metrics.GLOBAL_ASYNC_DURATIONS) > async_before
    assert _hist_count(metrics.GLOBAL_BROADCAST_DURATIONS) > bcast_before


def test_traffic_stats_observability(cluster):
    """Every served request feeds the HLL + heavy-hitter sketches
    (core/sketches.py; surfaced at /v1/debug/stats)."""
    client = V1Client(cluster.peer_at(0))
    for i in range(5):
        client.get_rate_limits(
            [
                RateLimitReq(
                    name="test_traffic",
                    unique_key="hot" if i % 2 == 0 else f"cold{i}",
                    hits=1,
                    limit=100,
                    duration=10 * SECOND,
                )
            ]
        )
    snap = cluster.instance_at(0).traffic.snapshot()
    assert snap["observed_total"] >= 5
    keys = {h["key"] for h in snap["hot_keys"]}
    assert "test_traffic_hot" in keys
    assert snap["distinct_keys_estimate"] >= 2


def test_health_unhealthy_on_bad_peer(cluster):
    """A peer that cannot even be dialed (malformed address) makes the
    node report unhealthy with the failed peer named, and recovers once
    the peer list is fixed (reference gubernator.go:260-291)."""
    from gubernator_tpu.api.types import PeerInfo

    server = cluster.servers[0]
    inst = server.instance
    good = [
        PeerInfo(address=a, is_owner=(a == ADDRESSES[0]))
        for a in ADDRESSES
    ]
    bad = good + [PeerInfo(address="not-an-address:-1")]

    try:
        cluster.run(inst.set_peers(bad))
        h = inst.health_check()
        assert h.status == "unhealthy"
        assert "not-an-address:-1" in h.message
        # healthy peers still serve
        assert h.peer_count == len(ADDRESSES)
    finally:
        # restore for any test running after this one (module-scoped
        # cluster fixture)
        cluster.run(inst.set_peers(good))
    assert inst.health_check().status == "healthy"


def test_device_and_cache_metrics_observed(cluster):
    """The serving flusher must populate device_batch_size,
    device_launch_milliseconds and cache_access_count{hit|miss} — the
    reference exports cache hit/miss counts on every access
    (cache/lru.go:164-176), and r1 shipped these declared-but-dead
    (VERDICT weak #4/#5)."""
    batch_before = _hist_count(metrics.DEVICE_BATCH_SIZE)
    launch_before = _hist_count(metrics.DEVICE_LAUNCH_MS)

    def counter(label):
        for m in metrics.CACHE_ACCESS_COUNT.collect():
            for s in m.samples:
                if s.name.endswith("_total") and s.labels.get("type") == label:
                    return s.value
        return 0.0

    miss_before = counter("miss")
    hit_before = counter("hit")

    with V1Client(cluster.peer_at(0)) as client:
        req = RateLimitReq(
            name="test_metrics", unique_key="m1", hits=1, limit=10,
            duration=SECOND,
        )
        client.get_rate_limits([req])  # miss (creation)
        client.get_rate_limits([req])  # hit

    assert _hist_count(metrics.DEVICE_BATCH_SIZE) > batch_before
    assert _hist_count(metrics.DEVICE_LAUNCH_MS) > launch_before
    assert counter("miss") > miss_before
    assert counter("hit") > hit_before
    # the generic interceptor must have metered the RPCs by full method
    # name (reference prometheus.go:104-127 meters every method)
    found = {
        s.labels["method"]
        for m in metrics.GRPC_REQUEST_COUNTS.collect()
        for s in m.samples
        if s.name.endswith("_total") and s.value > 0
    }
    assert "/pb.gubernator.V1/GetRateLimits" in found, found


def test_dead_owner_forward_fails_per_item():
    """A forwarded request whose owner peer has DIED (accepted into the
    ring at set_peers time, gone at RPC time) must come back as a
    per-item error response; co-batched keys owned by live nodes decide
    normally. The reference fans a batch send error back to every
    waiting request the same way (peers.go:183-195)."""
    from _util import free_ports

    addresses = [f"127.0.0.1:{p}" for p in free_ports(3)]
    c = LocalCluster(addresses)  # exact backend: fast start/stop
    c.start()
    try:
        def owned_by(node_i):
            for n in range(10_000):
                k = f"deadfwd_{n}"
                hk = RateLimitReq(name="dead", unique_key=k).hash_key()
                if owner_index(hk, addresses) == node_i:
                    return k
            raise AssertionError("no key found")

        dead_key = owned_by(2)
        live_key = owned_by(0)
        # kill node 2's server; nodes 0/1 still list it as a peer
        c.run(c.servers[2].stop())

        with V1Client(c.peer_at(0)) as client:
            resps = client.get_rate_limits(
                [
                    RateLimitReq(name="dead", unique_key=dead_key,
                                 hits=1, limit=5, duration=SECOND),
                    RateLimitReq(name="dead", unique_key=live_key,
                                 hits=1, limit=5, duration=SECOND),
                ],
                timeout=15,
            )
        assert "while fetching rate limit" in resps[0].error, resps[0]
        assert resps[1].error == ""
        assert resps[1].status == Status.UNDER_LIMIT
        assert resps[1].remaining == 4
    finally:
        c.stop()
