"""Functional cluster scenarios THROUGH the native edge (r2 verdict #8).

The C++ edge was fuzz-tested standalone but had never fronted a node in
the multi-node functional suite. Here a 3-node in-process cluster runs
with node 0 fronted by guber-edge (HTTP/JSON -> unix-socket frames ->
node 0's instance), and the reference's forwarding and GLOBAL behaviors
are exercised end to end through the edge: a non-owned key forwarded to
its owner over real gRPC, state shared with direct access to the owner
node, and a GLOBAL key's stale-then-synced replica sequence
(reference functional_test.go:271-311).

Skipped when the edge binary is not built.
"""

import json
import pathlib
import subprocess
import time
import urllib.request

import pytest

from gubernator_tpu.api.proto.gen import gubernator_pb2
from gubernator_tpu.api.grpc_glue import V1Stub
from gubernator_tpu.cluster import LocalCluster
from gubernator_tpu.serve.backends import ExactBackend

from tests._util import edge_binary

EDGE_BIN = edge_binary()
SOCK = "/tmp/guber-functional-edge.sock"
EDGE_PORT = 19283
ADDRS = [f"127.0.0.1:{p}" for p in range(9820, 9823)]

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)


def _post_edge(body: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{EDGE_PORT}/v1/GetRateLimits",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


async def _attach_bridge(server):
    from gubernator_tpu.serve.edge_bridge import EdgeBridge

    bridge = EdgeBridge(server.instance, SOCK)
    await bridge.start()
    return bridge


@pytest.fixture(scope="module")
def edge_cluster():
    try:
        pathlib.Path(SOCK).unlink()
    except FileNotFoundError:
        pass
    cluster = LocalCluster(
        ADDRS, backend_factory=lambda: ExactBackend(10_000)
    )
    cluster.start()
    bridge = cluster.run(_attach_bridge(cluster.servers[0]))
    edge = subprocess.Popen(
        [str(EDGE_BIN), "--listen", str(EDGE_PORT), "--backend", SOCK],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.monotonic() + 10
    import socket as _socket

    ready = False
    while time.monotonic() < deadline:
        if edge.poll() is not None:
            out = edge.stdout.read()
            cluster.run(bridge.stop())
            cluster.stop()
            pytest.fail(f"guber-edge died at startup:\n{out}")
        try:
            _socket.create_connection(
                ("127.0.0.1", EDGE_PORT), timeout=1
            ).close()
            ready = True
            break
        except OSError:
            time.sleep(0.05)
    if not ready:
        edge.kill()
        cluster.run(bridge.stop())
        cluster.stop()
        pytest.fail("guber-edge never started listening")
    try:
        yield cluster
    finally:
        edge.kill()
        edge.wait(timeout=5)
        cluster.run(bridge.stop())
        cluster.stop()


def _key_owned_by_other_node(cluster, name: str) -> str:
    """A unique_key NOT owned by node 0 (so the edge's node forwards)."""
    inst = cluster.servers[0].instance
    for i in range(1000):
        key = f"fk-{i}"
        peer = inst.get_peer(f"{name}_{key}")
        if not peer.is_owner:
            return key
    raise AssertionError("no forwarded key found in 1000 tries")


def test_forwarded_key_through_edge(edge_cluster):
    """Edge -> node 0 -> owner peer over real gRPC: transitions
    1 -> 0 -> OVER arrive through the edge, and the owner's own gRPC
    surface sees the same consumed state (one shared window)."""
    key = _key_owned_by_other_node(edge_cluster, "edgefwd")
    body = {
        "requests": [
            {"name": "edgefwd", "uniqueKey": key, "hits": 1,
             "limit": 2, "duration": 60_000}
        ]
    }
    out1 = _post_edge(body)["responses"][0]
    assert out1.get("status", "UNDER_LIMIT") == "UNDER_LIMIT"
    assert int(out1["remaining"]) == 1
    # forwarded responses carry the owner metadata like the gRPC path
    assert out1.get("metadata", {}).get("owner") in ADDRS[1:], out1
    out2 = _post_edge(body)["responses"][0]
    assert int(out2["remaining"]) == 0
    out3 = _post_edge(body)["responses"][0]
    assert out3.get("status") == "OVER_LIMIT"

    # the owner node's direct gRPC surface shares the same window
    import grpc

    owner_addr = out1["metadata"]["owner"]
    stub = V1Stub(grpc.insecure_channel(owner_addr))
    r = gubernator_pb2.RateLimitReq(
        name="edgefwd", unique_key=key, hits=0, limit=2, duration=60_000
    )
    peek = stub.GetRateLimits(
        gubernator_pb2.GetRateLimitsReq(requests=[r])
    ).responses[0]
    assert peek.remaining == 0
    assert peek.status == gubernator_pb2.OVER_LIMIT


def test_global_through_edge(edge_cluster):
    """GLOBAL key through the edge: replica answers locally, async hits
    gossip to the owner, broadcast comes back — the reference's
    stale-then-synced contract through the native front door."""
    key = _key_owned_by_other_node(edge_cluster, "edgeglob")
    body = {
        "requests": [
            {"name": "edgeglob", "uniqueKey": key, "hits": 1,
             "limit": 5, "duration": 60_000, "behavior": "GLOBAL"}
        ]
    }
    seq = []
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        seq.append(int(_post_edge(body)["responses"][0]["remaining"]))
        if seq[-1] <= 2:  # gossip applied at least two earlier hits
            break
        time.sleep(0.4)
    # first answer is the locally-processed miss (4); convergence pulls
    # the replica's remaining down as the owner's broadcasts land
    assert seq[0] == 4, seq
    assert seq[-1] <= 2, f"gossip never converged through the edge: {seq}"
