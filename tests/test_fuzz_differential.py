"""Randomized differential fuzz: TpuEngine vs the exact oracle.

Long random interleavings over a small key space — mixed algorithms,
peeks, oversized hits, GLOBAL replica installs, irregular clock advances,
and multi-request batches with duplicate keys — must agree with the
pure-Python oracle decision for decision. This is the deep-coverage
companion to the targeted behavioral tests in test_kernels.py; any
divergence prints a replayable (seed, step) pair.

Duplicate keys within one batch follow the kernel's documented
cumulative-attempt rule, which equals sequential-greedy when duplicate
hits are equal (kernels.py module docstring) — the fuzzer therefore
draws ONE hits value per (key, batch) so oracle-sequential and kernel
semantics coincide exactly. Each key's ALGORITHM is also pinned for the
whole run: when every duplicate mismatches the stored entry's type, the
reference recreates the window once per request while the kernel
recreates once per batch (documented divergence, kernels.py) — a
sequential-oracle loop cannot model the latter. Algorithm switching
itself is covered by test_kernels.py::test_algorithm_switch paths.
"""

import numpy as np
import pytest

from gubernator_tpu.api.types import (
    Algorithm,
    RateLimitReq,
    RateLimitResp,
    Status,
)
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.engine import TpuEngine
from gubernator_tpu.core.oracle import get_rate_limit
from gubernator_tpu.core.store import StoreConfig

T0 = 1_700_000_000_000


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 7])
def test_fuzz_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    # store big enough that eviction never fires (eviction is covered by
    # test_eviction_recreates_window; here state loss would desync the
    # oracle by design)
    engine = TpuEngine(
        StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64)
    )
    cache = LRUCache()
    keys = [f"k:{i}" for i in range(24)]
    now = T0

    for step in range(300):
        now += int(rng.choice([0, 1, 3, 7, 50, 400, 5000]))
        n = int(rng.integers(1, 12))
        picked = rng.choice(len(keys), size=n)
        # one hits/limit draw per key per batch; algorithm pinned per
        # key for the whole run (see module docstring). Peeks (hits=0)
        # appear at most once per batch: the reference's sequential
        # duplicate peeks each re-apply sub-tick leak (documented
        # divergence in kernels.py) which a one-snapshot batch cannot
        # model.
        per_key = {}
        batch = []
        for k in picked:
            if k not in per_key:
                per_key[k] = (
                    int(rng.choice([0, 1, 1, 2, 5, 40])),
                    int(rng.choice([1, 3, 8, 30])),
                    int(rng.choice([100, 1000, 60_000])),
                    # all four algorithms of the r15 suite, pinned per
                    # key for the run (see module docstring)
                    Algorithm(int(k) % 4),
                )
            elif per_key[k][0] == 0:
                continue
            hits, limit, duration, algo = per_key[k]
            batch.append(
                RateLimitReq(
                    name="fuzz",
                    unique_key=keys[k],
                    hits=hits,
                    limit=limit,
                    duration=duration,
                    algorithm=algo,
                )
            )

        got = engine.get_rate_limits(batch, now=now)
        want = [get_rate_limit(cache, r, now=now) for r in batch]
        for i, (g, w) in enumerate(zip(got, want)):
            ctx = f"seed={seed} step={step} i={i} req={batch[i]}"
            assert g.status == w.status, ctx
            assert g.limit == w.limit, ctx
            assert g.remaining == w.remaining, ctx
            assert g.reset_time == w.reset_time, ctx


def test_epoch_rebase_preserves_state():
    """Advancing the clock past the int32 engine envelope (~12.4 days)
    triggers a store rebase; a still-live window created mid-epoch must
    keep its remaining count and expiry across the rebase. (Durations
    clamp at MAX_DURATION_MS ~ 12.4 days, so the window is created a few
    days in — its expiry then straddles the rebase boundary.)"""
    engine = TpuEngine(StoreConfig(rows=16, slots=1 << 8), buckets=(16,))
    day = 86_400_000
    # pin the epoch at T0 with an unrelated request
    engine.get_rate_limits(
        [RateLimitReq(name="rb", unique_key="pin", hits=1, limit=1,
                      duration=1000)],
        now=T0,
    )
    r = RateLimitReq(
        name="rb", unique_key="x", hits=1, limit=10, duration=10 * day
    )
    first = engine.get_rate_limits([r], now=T0 + 5 * day)[0]
    assert first.remaining == 9
    assert first.reset_time == T0 + 15 * day

    # +13 days from epoch: beyond REBASE_AT (2^30 ms) -> rebase; the
    # window (expires at +15d) must survive with its count intact
    second = engine.get_rate_limits([r], now=T0 + 13 * day)[0]
    assert second.status == Status.UNDER_LIMIT
    assert second.remaining == 8, "state lost or duplicated across rebase"
    assert second.reset_time == T0 + 15 * day

    # past the window: fresh
    third = engine.get_rate_limits([r], now=T0 + 16 * day)[0]
    assert third.remaining == 9


def test_pipelined_submit_across_rebase_keeps_epoch():
    """A decide_submit in flight while a LATER submit rebases the clock
    must still convert its reset times against the epoch it was computed
    under (regression: decide_wait used the live epoch, shifting an
    in-flight batch's reset_time by the rebase delta of up to ~12 days)."""
    import numpy as np

    engine = TpuEngine(StoreConfig(rows=16, slots=1 << 8), buckets=(16,))
    day = 86_400_000

    def arrays(key, now):
        kh = np.asarray([hash(key) % (2**63) + 1], np.uint64)
        one = np.ones(1, np.int64)
        return engine.decide_submit(
            kh, one, one * 10, one * 10 * day, np.zeros(1, np.int32),
            np.zeros(1, bool), now,
        )

    h1 = arrays("a", T0)  # epoch pinned at T0; window resets T0+10d
    h2 = arrays("b", T0 + 13 * day)  # forces a rebase before h1's wait
    _, _, _, reset1 = engine.decide_wait(h1)
    assert int(reset1[0]) == T0 + 10 * day, reset1
    _, _, _, reset2 = engine.decide_wait(h2)
    assert int(reset2[0]) == T0 + 23 * day, reset2


def test_epoch_far_future_jump_resets():
    """A forward jump no rebase can represent (> int32 range in one step)
    resets the store — the documented state-loss contract — instead of
    corrupting stored times."""
    engine = TpuEngine(StoreConfig(rows=16, slots=1 << 8), buckets=(16,))
    r = RateLimitReq(
        name="jump", unique_key="y", hits=1, limit=5, duration=1000
    )
    assert engine.get_rate_limits([r], now=T0)[0].remaining == 4
    # ~25 days forward in one step: no window survives, store resets
    far = T0 + 2_200_000_000
    resp = engine.get_rate_limits([r], now=far)[0]
    assert resp.remaining == 4
    assert resp.reset_time == far + 1000


@pytest.mark.parametrize("seed", [5, 6, 8])
def test_fuzz_global_paths_vs_exact_backend(seed):
    """GLOBAL-path fuzz: interleave owned decides, non-owner replica reads
    (gnp), and owner-broadcast installs (update_globals), comparing the
    TPU backend against the exact host backend, which implements the
    reference's replica semantics directly (serve/backends.py)."""
    from gubernator_tpu.serve.backends import ExactBackend, TpuBackend

    rng = np.random.default_rng(seed)
    tpu = TpuBackend(StoreConfig(rows=16, slots=1 << 10), buckets=(16, 64))
    exact = ExactBackend()
    keys = [f"g:{i}" for i in range(16)]
    now = T0

    for step in range(150):
        now += int(rng.choice([1, 5, 60, 700]))
        roll = rng.random()
        if roll < 0.25:
            # owner broadcast: install replica statuses for some keys
            picked = rng.choice(len(keys), size=3, replace=False)
            updates = []
            for k in picked:
                updates.append(
                    (
                        f"fuzzg_{keys[k]}",
                        RateLimitResp(
                            status=Status(int(rng.integers(0, 2))),
                            limit=int(rng.choice([5, 9])),
                            remaining=int(rng.integers(0, 5)),
                            reset_time=now + int(rng.choice([500, 5000])),
                        ),
                    )
                )
            tpu.update_globals(updates, now=now)
            exact.update_globals(updates, now=now)
            continue
        # mixed owned + replica-read traffic (unique keys per batch: the
        # exact backend serves replicas per-request while the kernel
        # shares one group snapshot)
        picked = rng.choice(len(keys), size=4, replace=False)
        batch = []
        gnp = []
        for k in picked:
            batch.append(
                RateLimitReq(
                    name="fuzzg",
                    unique_key=keys[k],
                    hits=int(rng.choice([0, 1, 2])),
                    limit=int(rng.choice([5, 9])),
                    duration=int(rng.choice([1000, 60_000])),
                    algorithm=Algorithm.TOKEN_BUCKET,
                )
            )
            gnp.append(bool(rng.random() < 0.5))
        got = tpu.decide(batch, gnp, now=now)
        want = exact.decide(batch, gnp, now=now)
        for i, (g, w) in enumerate(zip(got, want)):
            ctx = (
                f"seed={seed} step={step} i={i} gnp={gnp[i]} "
                f"req={batch[i]}"
            )
            assert g.status == w.status, ctx
            assert g.limit == w.limit, ctx
            assert g.remaining == w.remaining, ctx
            assert g.reset_time == w.reset_time, ctx
