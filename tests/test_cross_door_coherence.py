"""One bucket, four doors, two decide paths — every hit accounted for.

The r4 serving stack answers the same store through four front doors:
the daemon's own gRPC and HTTP listeners (request-object path through
the instance) and the edge's gRPC and HTTP terminators (pre-hashed GEB4
array path when eligible). A hash-parity or routing bug between any two
of them silently splits one logical bucket into several. This test
hammers ONE key through all four doors concurrently and asserts exact
conservation: remaining == limit - total_successful_hits, with OVER
refusals consuming nothing (the reference's token semantics,
algorithms.go:57-62) — across paths, protocols, and the co-batching of
all of it into shared device batches.

Runs the tpu backend on CPU like the other daemon e2e suites.
"""

import json
import pathlib
import threading
import urllib.request

import grpc
import pytest

from gubernator_tpu.api.grpc_glue import V1Stub
from gubernator_tpu.api.proto.gen import gubernator_pb2
from tests._util import spawn_daemon_edge

from tests._util import edge_binary

ROOT = pathlib.Path(__file__).resolve().parent.parent
EDGE_BIN = edge_binary()

pytestmark = pytest.mark.skipif(
    not EDGE_BIN.exists(),
    reason="edge binary not built (make -C gubernator_tpu/native/edge)",
)

DAEMON_GRPC = 19694
DAEMON_HTTP = 19695
EDGE_HTTP = 19696
EDGE_GRPC = 19697
SOCK = "/tmp/guber-coherence-pytest.sock"

LIMIT = 100_000
N_PER_DOOR = 120  # 4 doors x 120 hits, far under limit: all must admit

# one persistent stub per gRPC door (channels reused across calls)
_STUBS = {}


@pytest.fixture(scope="module")
def stack():
    daemon, edge = spawn_daemon_edge(
        dict(
            GUBER_BACKEND="tpu",
            GUBER_JAX_PLATFORM="cpu",
            GUBER_STORE_SLOTS=str(1 << 10),
            GUBER_GRPC_ADDRESS=f"127.0.0.1:{DAEMON_GRPC}",
            GUBER_HTTP_ADDRESS=f"127.0.0.1:{DAEMON_HTTP}",
            GUBER_EDGE_SOCKET=SOCK,
            GUBER_FETCH_DEPTH="4",
            JAX_COMPILATION_CACHE_DIR=str(ROOT / ".jax_cache_cpu"),
        ),
        SOCK,
        edge_http=EDGE_HTTP,
        edge_grpc=EDGE_GRPC,
    )
    yield
    edge.kill()
    daemon.terminate()
    daemon.wait(timeout=10)
    _STUBS.clear()


def _call_door(kind, port, key, hits, limit=LIMIT):
    """(status, remaining) for one request through the given door."""
    if kind == "grpc":
        stub = _STUBS.get(port)
        if stub is None:
            stub = _STUBS.setdefault(
                port, V1Stub(grpc.insecure_channel(f"127.0.0.1:{port}"))
            )
        r = stub.GetRateLimits(
            gubernator_pb2.GetRateLimitsReq(
                requests=[
                    gubernator_pb2.RateLimitReq(
                        name="coh", unique_key=key, hits=hits,
                        limit=limit, duration=600_000,
                    )
                ]
            ),
            timeout=30,
        ).responses[0]
        return int(r.status), int(r.remaining)
    # bounded 503 retry (r15 deflake; see tests/_util.post_json)
    from _util import post_json

    out = post_json(
        f"http://127.0.0.1:{port}/v1/GetRateLimits",
        {"requests": [{"name": "coh", "uniqueKey": key, "hits": hits,
                       "limit": limit, "duration": 600000}]},
    )["responses"][0]
    return (1 if out["status"] == "OVER_LIMIT" else 0,
            int(out["remaining"]))


ALL_DOORS = [
    ("grpc", DAEMON_GRPC),
    ("http", DAEMON_HTTP),
    ("grpc", EDGE_GRPC),
    ("http", EDGE_HTTP),
]


def test_one_bucket_four_doors_exact_conservation(stack):
    key = "conserved"
    errors = []
    over_counts = [0] * len(ALL_DOORS)

    def run(i, kind, port):
        try:
            for _ in range(N_PER_DOOR):
                status, _rem = _call_door(kind, port, key, 1)
                if status:
                    over_counts[i] += 1
        except Exception as e:  # pragma: no cover - diagnostic
            errors.append((kind, port, repr(e)))

    threads = [
        threading.Thread(target=run, args=(i, k, p))
        for i, (k, p) in enumerate(ALL_DOORS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # far under limit: nothing may have been refused
    assert sum(over_counts) == 0, over_counts

    # a zero-hit peek through each door must agree on the exact count
    expected = LIMIT - len(ALL_DOORS) * N_PER_DOOR
    for kind, port in ALL_DOORS:
        status, remaining = _call_door(kind, port, key, 0)
        assert remaining == expected, (
            kind, port, remaining, expected,
            "hits leaked or split across doors/paths",
        )
        assert status == 0


def test_over_limit_consumes_nothing_across_doors(stack):
    """Exhaust a tiny bucket through the edge, then hammer it OVER from
    every door: remaining must stay exactly 0 (refusals don't consume,
    reference algorithms.go:57-62 — a cross-path regression would show
    as drift)."""
    key = "exhausted"
    status, remaining = _call_door("grpc", EDGE_GRPC, key, 5, limit=5)
    assert status == 0 and remaining == 0

    for kind, port in ALL_DOORS:
        for _ in range(3):
            status, remaining = _call_door(kind, port, key, 1, limit=5)
            assert status == 1 and remaining == 0, (kind, port, remaining)
