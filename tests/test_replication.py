"""Bucket replication (r11, serve/replication.py): successor placement,
non-mutating snapshot reads, the standby table (LWW + bounds), takeover
seeding, reconcile handback, the GLOBAL backlog bound, the supervisor's
backoff reset, and the ON==OFF differential identity guarantee across
the exact and device pipelines.
"""

import asyncio

import grpc
import numpy as np
import pytest

from gubernator_tpu.api.grpc_glue import add_peers_servicer
from gubernator_tpu.api.types import (
    Algorithm,
    Behavior,
    PeerInfo,
    RateLimitReq,
    Status,
    millisecond_now,
)
from gubernator_tpu.core.cache import LRUCache
from gubernator_tpu.core.store import StoreConfig
from gubernator_tpu.serve import metrics
from gubernator_tpu.serve.backends import ExactBackend, TpuBackend
from gubernator_tpu.serve.config import BehaviorConfig, ServerConfig
from gubernator_tpu.serve.instance import Instance
from gubernator_tpu.serve.peers import ConsistentHashPicker, PeerClient
from gubernator_tpu.serve.replication import ReplicationManager, Snapshot

ADDR = "127.0.0.1:1"

T0 = 1_700_000_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = t

    def __call__(self) -> int:
        return self.t


def _req(key, hits=1, limit=5, duration=60_000, algo=Algorithm.TOKEN_BUCKET,
         behavior=Behavior.BATCHING):
    return RateLimitReq(
        name="repl", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo, behavior=behavior,
    )


def _snap(key, remaining=0, reset_time=None, limit=5, duration=60_000,
          status=Status.OVER_LIMIT, snapshot_ms=None, now=None):
    now = millisecond_now() if now is None else now
    return Snapshot(
        key=key, algorithm=int(Algorithm.TOKEN_BUCKET), limit=limit,
        duration=duration, remaining=remaining,
        reset_time=now + 60_000 if reset_time is None else reset_time,
        status=int(status),
        snapshot_ms=now if snapshot_ms is None else snapshot_ms,
    )


def _counter(metric, **labels) -> float:
    m = metric.labels(**labels) if labels else metric
    return m._value.get()


# -- ring successor --------------------------------------------------------


def _picker(hosts):
    p = ConsistentHashPicker()
    for h in hosts:
        p.add(PeerClient(BehaviorConfig(), h))
    return p


def test_get_successor_is_ring_owner_without_current_owner():
    hosts = [f"10.0.0.{i}:81" for i in range(1, 6)]
    p = _picker(hosts)
    for i in range(200):
        key = f"repl_s{i}"
        owner = p.get(key)
        succ = p.get_successor(key)
        assert succ is not None and succ.host != owner.host
        # the defining property: the successor is exactly where the
        # ring routes this key once the owner is gone
        without = _picker([h for h in hosts if h != owner.host])
        assert succ.host == without.get(key).host


def test_get_successor_single_host_is_none():
    p = _picker(["10.0.0.1:81"])
    assert p.get_successor("any_key") is None


# -- non-mutating snapshot reads -------------------------------------------


def test_lru_peek_is_non_mutating():
    c = LRUCache(2)
    c.add("a", 1, T0 + 1000)
    c.add("b", 2, T0 + 1000)
    s0 = c.stats()
    assert c.peek("a", T0) == (1, True)
    assert c.peek("missing", T0) == (None, False)
    assert c.peek("b", T0 + 2000) == (None, False)  # expired: not deleted
    s1 = c.stats()
    assert (s1.hit, s1.miss, s1.size) == (s0.hit, s0.miss, s0.size)
    # recency untouched: "a" (peeked last) must still be the eviction
    # victim, because peek didn't move it to the front
    c.add("c", 3, T0 + 1000)
    assert c.peek("a", T0) == (None, False)
    assert c.peek("b", T0)[1]


def test_exact_snapshot_read_rows_and_gates():
    be = ExactBackend(100)
    now = millisecond_now()
    tok = _req("t1", hits=2, limit=10)
    over = _req("t2", hits=9, limit=5)  # created over limit: sticky
    leaky = _req("l1", hits=1, algo=Algorithm.LEAKY_BUCKET)
    be.decide([tok, over, leaky], [False] * 3, now=now)
    s0 = be.stats()
    rows = be.snapshot_read(
        [tok.hash_key(), over.hash_key(), leaky.hash_key(), "repl_miss"],
        now + 5,
    )
    limit, duration, remaining, reset, is_over = rows[0]
    assert (limit, remaining, reset, is_over) == (10, 8, now + 60_000, False)
    assert duration == 0  # not persisted by the exact token window
    assert rows[1][2] == 5 and rows[1][4] is True  # sticky over
    assert rows[2] is None  # leaky out of scope
    assert rows[3] is None
    # non-mutating: hit/miss accounting untouched by the reads above
    assert be.stats() == s0


def test_engine_snapshot_read_matches_decide_and_mutates_nothing():
    from gubernator_tpu.core.hashing import slot_hash_batch

    def mk():
        return TpuBackend(StoreConfig(rows=4, slots=1 << 10), buckets=(64,))

    a, b = mk(), mk()
    now = millisecond_now()
    keys = [f"repl_d{i}" for i in range(4)]
    kh = slot_hash_batch(keys)
    hits = np.array([2, 5, 9, 1], np.int64)
    limit = np.array([10, 5, 5, 10], np.int64)
    dur = np.full(4, 60_000, np.int64)
    algo = np.array([0, 0, 0, 1], np.int32)
    gnp = np.zeros(4, bool)
    for be in (a, b):
        be.engine.decide_arrays(kh, hits, limit, dur, algo, gnp, now)
    rows = a.snapshot_read(keys, now + 10)
    assert rows[0] == (10, 60_000, 8, now + 60_000, False)
    assert rows[1] == (5, 60_000, 0, now + 60_000, True)  # exhausted
    assert rows[2][4] is True  # created-over sticky flag
    assert rows[3] is None  # leaky
    # non-mutation: the snapshotted engine keeps deciding identically
    # to its never-snapshotted twin
    ones = np.ones(4, np.int64)
    ra = a.engine.decide_arrays(kh, ones, limit, dur, algo, gnp, now + 20)
    rb = b.engine.decide_arrays(kh, ones, limit, dur, algo, gnp, now + 20)
    for x, y in zip(ra, rb):
        assert np.array_equal(x, y)


# -- manager tables ---------------------------------------------------------


class _DummyInstance:
    pass


def _mgr(**conf_kw) -> ReplicationManager:
    conf = ServerConfig(
        grpc_address=ADDR, advertise_address=ADDR, replication=True,
        **conf_kw,
    )
    return ReplicationManager(conf, _DummyInstance())


def test_queue_dirty_gates_and_backlog_bound():
    async def run():
        m = _mgr(replication_backlog=2)
        m.queue_dirty(_req("a", hits=0))  # peek: nothing to replicate
        m.queue_dirty(_req("b", algo=Algorithm.LEAKY_BUCKET))
        assert not m._dirty
        before = _counter(metrics.REPLICATION_DROPPED, what="dirty_backlog")
        m.queue_dirty(_req("a"))
        m.queue_dirty(_req("b"))
        m.queue_dirty(_req("c"))  # past the cap: dropped + counted
        assert sorted(m._dirty) == [
            _req("a").hash_key(), _req("b").hash_key()
        ]
        m.queue_dirty(_req("a", limit=9))  # existing key: still updates
        assert m._dirty[_req("a").hash_key()][1] == 9
        after = _counter(metrics.REPLICATION_DROPPED, what="dirty_backlog")
        assert after == before + 1

    asyncio.run(run())


def test_queue_dirty_fields_bridge_tier():
    """The edge fold's array-level dirty marking: same gates as
    queue_dirty (hits > 0, token only), bounded, last-row-wins per
    key."""
    m = _mgr(replication_backlog=2)
    keys = ["a", "b", "a", "c", "d", "e"]
    fields = dict(
        hits=np.array([1, 0, 2, 1, 1, 1], np.int64),
        limit=np.array([5, 5, 7, 5, 5, 5], np.int64),
        duration=np.full(6, 60_000, np.int64),
        algo=np.array([0, 0, 0, 1, 0, 0], np.int32),
    )
    before = _counter(metrics.REPLICATION_DROPPED, what="dirty_backlog")
    m.queue_dirty_fields(keys, fields)
    # b is a peek and c is leaky (ineligible); a repeats (last row
    # wins: limit 7); e arrives past the 2-key cap: dropped + counted
    assert sorted(m._dirty) == ["a", "d"]
    assert m._dirty["a"][1] == 7
    assert _counter(
        metrics.REPLICATION_DROPPED, what="dirty_backlog"
    ) == before + 1


def test_standby_eviction_tracks_freshness_not_first_insert():
    """At capacity the evictee must be the STALEST snapshot: a hot key
    re-replicated every window must survive the arrival of a new key
    even though it was inserted first."""

    async def run():
        m = _mgr(replication_standby_keys=2)

        class _Inst:
            def get_peer(self, key):
                raise RuntimeError("not owned")

        m.instance = _Inst()
        now = millisecond_now()
        await m.install("o:1", [_snap("hot", reset_time=now + 1000,
                                      snapshot_ms=now, now=now)])
        await m.install("o:1", [_snap("cold", reset_time=now + 1000,
                                      snapshot_ms=now, now=now)])
        # the hot key refreshes (newer window)
        await m.install("o:1", [_snap("hot", reset_time=now + 5000,
                                      snapshot_ms=now + 1, now=now)])
        # a new key arrives at capacity: "cold" (stalest) must go
        await m.install("o:1", [_snap("new", reset_time=now + 1000,
                                      snapshot_ms=now, now=now)])
        assert sorted(m._standby) == ["hot", "new"]

    asyncio.run(run())


def test_standby_lww_bound_and_pop():
    async def run():
        m = _mgr(replication_standby_keys=2)
        now = millisecond_now()

        # non-owned keys go standby (get_peer raising = not owned)
        class _Inst:
            def get_peer(self, key):
                raise RuntimeError("no ring")

        m.instance = _Inst()
        newer = _snap("k1", remaining=1, reset_time=now + 9000, now=now)
        older = _snap("k1", remaining=3, reset_time=now + 4000, now=now)
        await m.install("o:1", [newer])
        await m.install("o:1", [older])  # LWW: older loses
        assert m._standby["k1"].remaining == 1
        await m.install("o:1", [newer])  # duplicate: idempotent no-op
        assert m.standby_len == 1
        await m.install("o:1", [_snap("k2", now=now), _snap("k3", now=now)])
        assert m.standby_len == 2  # bounded: oldest evicted
        # expired snapshots are refused outright
        await m.install("o:1", [_snap("k4", reset_time=now - 1, now=now)])
        assert "k4" not in m._standby
        # pop is one-shot and expiry-gated
        assert m.standby_pop("k3") is not None
        assert m.standby_pop("k3") is None
        m._standby["k5"] = _snap("k5", reset_time=millisecond_now() - 1)
        assert m.standby_pop("k5") is None

    asyncio.run(run())


# -- instance integration ---------------------------------------------------


def _conf(**kw) -> ServerConfig:
    conf = ServerConfig(
        grpc_address=ADDR,
        advertise_address=ADDR,
        backend="exact",
        replication=True,
        replication_sync_wait=60.0,  # flushes driven manually
        behaviors=BehaviorConfig(
            peer_timeout=0.2, peer_retries=0, peer_backoff=0.001,
            peer_backoff_max=0.002, breaker_failures=3,
            breaker_cooldown=0.2,
        ),
    )
    for k, v in kw.items():
        setattr(conf, k, v)
    return conf


async def _instance(conf=None, backend=None) -> Instance:
    conf = conf or _conf()
    inst = Instance(conf, backend if backend is not None else ExactBackend(1000))
    inst.start()
    await inst.set_peers([PeerInfo(address=conf.advertise_address,
                                   is_owner=True)])
    return inst


def test_replication_refused_without_snapshot_surface():
    class _NoSnap:
        inline_decide = True

        def decide(self, reqs, gnp, now=None):  # pragma: no cover
            return []

    with pytest.raises(ValueError, match="snapshot_read"):
        Instance(_conf(), _NoSnap())


def test_reconcile_install_continues_window_on_owner():
    """A snapshot received for a key THIS node owns (the handback from
    its interim successor) installs straight into the store: the next
    decide continues the replicated window, not a fresh one."""

    async def run():
        inst = await _instance()
        try:
            key = _req("own1").hash_key()
            now = millisecond_now()
            await inst.repl.install(
                "succ:1",
                [_snap(key, remaining=1, reset_time=now + 30_000,
                       status=Status.UNDER_LIMIT, now=now)],
            )
            assert inst.repl.standby_len == 0  # not parked: installed
            r = (await inst.get_rate_limits([_req("own1", hits=1)]))[0]
            # continuation proof: remaining 1 -> 0 under the replicated
            # reset_time; a fresh window would be remaining=4 with a
            # new reset
            assert r.remaining == 0 and r.reset_time == now + 30_000
        finally:
            await inst.stop()

    asyncio.run(run())


async def _two_peer_instance(conf):
    """This node + a dead peer; returns (inst, dead_addr, dead_keys)."""
    from tests._util import free_ports

    dead = f"127.0.0.1:{free_ports(1)[0]}"
    inst = Instance(conf, ExactBackend(1000))
    inst.start()
    await inst.set_peers([
        PeerInfo(address=conf.advertise_address, is_owner=True),
        PeerInfo(address=dead, is_owner=False),
    ])
    keys = [f"dk{i}" for i in range(256)
            if inst.get_peer(_req(f"dk{i}").hash_key()).host == dead]
    assert keys, "no key landed on the dead peer"
    return inst, dead, keys


def test_takeover_seeds_standby_and_stamps_metadata():
    async def run():
        inst, dead, keys = await _two_peer_instance(_conf())
        try:
            key = _req(keys[0]).hash_key()
            now = millisecond_now()
            before = _counter(metrics.REPLICATED_TAKEOVERS)
            await inst.repl.install(
                dead, [_snap(key, remaining=0, reset_time=now + 30_000)]
            )
            assert inst.repl.standby_len == 1
            r = (await inst.get_rate_limits([_req(keys[0], hits=1)]))[0]
            # the dead owner's frozen refusal survived: no quota amnesia
            assert r.error == ""
            assert r.status == Status.OVER_LIMIT
            assert r.remaining == 0 and r.reset_time == now + 30_000
            assert r.metadata["replicated"] == "true"
            assert r.metadata["owner"] == ADDR  # the successor answered
            assert _counter(metrics.REPLICATED_TAKEOVERS) == before + 1
            # seeded key is tracked for the handback on owner return
            assert key in inst.repl._taken
            assert inst.repl.standby_len == 0
            # an UN-replicated dead-owner key still gets a successor
            # answer (fresh window), also stamped
            r2 = (await inst.get_rate_limits([_req(keys[1], hits=1)]))[0]
            assert r2.error == "" and r2.metadata["replicated"] == "true"
            assert r2.status == Status.UNDER_LIMIT
        finally:
            await inst.stop()

    asyncio.run(run())


def test_update_peer_globals_purges_standby():
    async def run():
        inst, dead, keys = await _two_peer_instance(_conf())
        try:
            key = _req(keys[0]).hash_key()
            await inst.repl.install(dead, [_snap(key)])
            assert inst.repl.standby_len == 1
            # the owner broadcasting status for the key supersedes the
            # replicated snapshot (reconcile contract)
            from gubernator_tpu.api.types import RateLimitResp

            await inst.update_peer_globals(
                [(key, RateLimitResp(limit=5, remaining=5,
                                     reset_time=millisecond_now() + 1000))]
            )
            assert inst.repl.standby_len == 0
        finally:
            await inst.stop()

    asyncio.run(run())


# -- full amnesia cycle over real gRPC --------------------------------------


def test_amnesia_cycle_kill_takeover_restart_reconcile():
    """The tentpole end-to-end, in-process: drive a key over-limit on
    its owner, kill the owner, assert the successor answers OVER_LIMIT
    from the replicated snapshot, restart the owner with a FRESH store
    (quota amnesia), hand back, and assert the key is still over-limit
    on the reborn owner."""
    from tests._util import free_ports
    from gubernator_tpu.serve.server import PeersV1Servicer

    async def serve(inst, addr):
        server = grpc.aio.server()
        add_peers_servicer(server, PeersV1Servicer(inst))
        assert server.add_insecure_port(addr) != 0
        await server.start()
        return server

    async def run():
        pa, pb = free_ports(2)
        addr_a, addr_b = f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"

        def conf_for(me):
            c = _conf()
            c.grpc_address = me
            c.advertise_address = me
            return c

        peers = None

        async def boot(me):
            inst = Instance(conf_for(me), ExactBackend(1000))
            inst.start()
            await inst.set_peers(peers)
            return inst, await serve(inst, me)

        peers = [PeerInfo(address=addr_a, is_owner=True),
                 PeerInfo(address=addr_b, is_owner=False)]
        a, srv_a = await boot(addr_a)
        peers = [PeerInfo(address=addr_a, is_owner=False),
                 PeerInfo(address=addr_b, is_owner=True)]
        b, srv_b = await boot(addr_b)

        srv_b2 = b2 = None
        try:
            # a key B owns, driven over-limit THROUGH A (forwarded)
            bkey = next(
                f"bk{i}" for i in range(256)
                if a.get_peer(_req(f"bk{i}").hash_key()).host == addr_b
            )
            r = (await a.get_rate_limits([_req(bkey, hits=9, limit=5)]))[0]
            assert r.error == "" and r.status == Status.OVER_LIMIT
            assert r.metadata["owner"] == addr_b

            # owner flushes its dirty window to the successor (A)
            await b.repl.flush_once()
            assert a.repl.standby_len == 1

            # SIGKILL analogue: B's listener vanishes mid-flight
            await srv_b.stop(None)
            await b.stop()

            r = (await a.get_rate_limits([_req(bkey, hits=1, limit=5)]))[0]
            assert r.error == ""
            assert r.status == Status.OVER_LIMIT, (
                "quota amnesia: the successor forgot the dead owner's "
                "over-limit window"
            )
            assert r.metadata["replicated"] == "true"

            # owner restarts with a FRESH store on the same address
            peers2 = [PeerInfo(address=addr_a, is_owner=False),
                      PeerInfo(address=addr_b, is_owner=True)]
            b2 = Instance(conf_for(addr_b), ExactBackend(1000))
            b2.start()
            await b2.set_peers(peers2)
            srv_b2 = await serve(b2, addr_b)

            # reconcile: A hands the interim window back (retried every
            # flush tick; the breaker may need its cooldown first)
            deadline = asyncio.get_running_loop().time() + 5.0
            while a.repl._taken:
                await a.repl.flush_once()
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("handback never landed")
                await asyncio.sleep(0.05)

            # the reborn owner answers from the handed-back window:
            # STILL over-limit, no amnesia across the restart
            r = (await b2.get_rate_limits([_req(bkey, hits=1, limit=5)]))[0]
            assert r.error == "" and r.status == Status.OVER_LIMIT
            # and through A (forwarded to the returned owner)
            r = (await a.get_rate_limits([_req(bkey, hits=1, limit=5)]))[0]
            assert r.error == "" and r.status == Status.OVER_LIMIT
            assert r.metadata["owner"] == addr_b
            assert "replicated" not in r.metadata
        finally:
            await srv_a.stop(None)
            if srv_b2 is not None:
                await srv_b2.stop(None)
            await a.stop()
            if b2 is not None:
                await b2.stop()

    asyncio.run(run())


# -- differential identity: replication ON == OFF without failures ----------


def _pin_clock(monkeypatch, clock):
    import gubernator_tpu.api.types as types_mod
    import gubernator_tpu.core.engine as engine_mod
    import gubernator_tpu.core.oracle as oracle_mod
    import gubernator_tpu.serve.replication as repl_mod

    monkeypatch.setattr(types_mod, "millisecond_now", clock)
    monkeypatch.setattr(engine_mod, "millisecond_now", clock)
    monkeypatch.setattr(oracle_mod, "millisecond_now", clock)
    monkeypatch.setattr(repl_mod, "millisecond_now", clock)


def _assert_same(a, b, ctx):
    assert (
        a.status, a.limit, a.remaining, a.reset_time, a.error, a.metadata
    ) == (
        b.status, b.limit, b.remaining, b.reset_time, b.error, b.metadata
    ), (ctx, a, b)


def _fuzz_stream(rng, keys, steps):
    for step in range(steps):
        n = int(rng.integers(1, 7))
        batch = []
        for _ in range(n):
            k = int(rng.integers(len(keys)))
            batch.append(RateLimitReq(
                name="replfuzz",
                unique_key=keys[k],
                hits=int(rng.choice([0, 1, 1, 1, 2, 9])),
                limit=int(rng.choice([1, 1, 2, 3, 50])),
                duration=int(rng.choice([400, 2000, 60_000])),
                algorithm=Algorithm(k % 2),
            ))
        yield step, batch, int(rng.choice([0, 0, 1, 7, 150, 500, 2500]))


async def _fuzz_pair(mk_backend, clock, steps, seed):
    """ON and OFF twins: identical ring (self + a dead successor so the
    flush loop really snapshots and sends), only the knob differs.
    Only self-owned keys are driven — the no-failure contract."""
    from tests._util import free_ports

    # a 2-point crc32 ring can split very lopsidedly; re-roll the dead
    # successor's port until this node owns a workable share of the
    # fuzz key space (no flaky splits)
    def owned(dead_addr, count=200):
        picker = ConsistentHashPicker()
        me = PeerClient(BehaviorConfig(), ADDR, is_owner=True)
        picker.add(me)
        picker.add(PeerClient(BehaviorConfig(), dead_addr))
        return [
            f"f{i}" for i in range(count)
            if picker.get(
                RateLimitReq(name="replfuzz", unique_key=f"f{i}").hash_key()
            ) is me
        ]

    for port in free_ports(16):
        dead = f"127.0.0.1:{port}"
        keys = owned(dead)[:12]
        if len(keys) >= 8:
            break
    assert len(keys) >= 8, "no workable ring split in 16 rolls"

    async def mk(repl):
        conf = _conf(replication=repl)
        inst = Instance(conf, mk_backend())
        inst.start()
        await inst.set_peers([
            PeerInfo(address=ADDR, is_owner=True),
            PeerInfo(address=dead, is_owner=False),
        ])
        return inst

    on = await mk(True)
    off = await mk(False)
    if on.shed is not None:
        on.shed.now_fn = clock
        off.shed.now_fn = clock
    for k in keys:
        req = RateLimitReq(name="replfuzz", unique_key=k)
        assert on.get_peer(req.hash_key()).is_owner
    try:
        rng = np.random.default_rng(seed)
        snapshotted = 0
        for step, batch, dt in _fuzz_stream(rng, keys, steps):
            clock.t += dt
            a = await on.get_rate_limits(batch)
            b = await off.get_rate_limits(batch)
            for x, y, r in zip(a, b, batch):
                _assert_same(x, y, (step, r))
            if step % 25 == 24:
                snapshotted += len(on.repl._dirty)
                await on.repl.flush_once()
        assert snapshotted > 0, "fuzz never flushed a dirty window"
    finally:
        await on.stop()
        await off.stop()


@pytest.mark.parametrize("seed", [3, 11])
def test_differential_identity_fuzz_exact(monkeypatch, seed):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)
    asyncio.run(_fuzz_pair(lambda: ExactBackend(10_000), clock, 250, seed))


def test_differential_identity_fuzz_device(monkeypatch):
    clock = FakeClock()
    _pin_clock(monkeypatch, clock)

    def be():
        return TpuBackend(StoreConfig(rows=16, slots=1 << 10),
                          buckets=(16, 64))

    asyncio.run(_fuzz_pair(be, clock, 100, 5))


# -- satellites: GLOBAL backlog bound + supervisor backoff reset ------------


def test_global_manager_backlog_bound():
    from gubernator_tpu.serve.global_mgr import GlobalManager

    async def run():
        mgr = GlobalManager(BehaviorConfig(global_backlog=2), None)
        before_h = _counter(metrics.GLOBAL_BACKLOG_DROPPED, queue="hits")
        before_u = _counter(metrics.GLOBAL_BACKLOG_DROPPED, queue="updates")
        g = Behavior.GLOBAL
        mgr.queue_hit(_req("a", hits=1, behavior=g))
        mgr.queue_hit(_req("b", hits=2, behavior=g))
        mgr.queue_hit(_req("c", hits=3, behavior=g))  # new key: dropped
        assert len(mgr._hits) == 2
        # existing keys keep aggregating at the cap
        mgr.queue_hit(_req("a", hits=5, behavior=g))
        assert mgr._hits[_req("a").hash_key()].hits == 6
        mgr.queue_update(_req("a", behavior=g))
        mgr.queue_update(_req("b", behavior=g))
        mgr.queue_update(_req("c", behavior=g))  # dropped
        mgr.queue_update(_req("b", behavior=g))  # existing: refreshed
        assert len(mgr._updates) == 2
        assert _counter(
            metrics.GLOBAL_BACKLOG_DROPPED, queue="hits"
        ) == before_h + 1
        assert _counter(
            metrics.GLOBAL_BACKLOG_DROPPED, queue="updates"
        ) == before_u + 1

    asyncio.run(run())


def test_supervise_resets_backoff_after_long_healthy_run(monkeypatch):
    """A loop that dies after a run longer than SUPERVISE_RESET_S must
    restart at the BASE backoff, not the escalated one (previously
    untested: a one-off crash after days of health was penalized like a
    crash loop)."""
    from gubernator_tpu.serve import global_mgr

    class _TimeShim:
        def __init__(self):
            self.t = 0.0

        def monotonic(self):
            return self.t

    class _AsyncioShim:
        CancelledError = asyncio.CancelledError

        def __init__(self):
            self.sleeps = []

        async def sleep(self, d):
            self.sleeps.append(d)

    tshim, ashim = _TimeShim(), _AsyncioShim()
    monkeypatch.setattr(global_mgr, "time", tshim)
    monkeypatch.setattr(global_mgr, "asyncio", ashim)

    calls = [0]

    async def loop_factory():
        calls[0] += 1
        if calls[0] <= 2:
            raise RuntimeError(f"fast crash {calls[0]}")
        if calls[0] == 3:
            # a long healthy run, then a one-off death
            tshim.t += global_mgr.SUPERVISE_RESET_S + 1.0
            raise RuntimeError("one-off after health")
        raise asyncio.CancelledError

    async def run():
        with pytest.raises(asyncio.CancelledError):
            await global_mgr.supervise("test_loop", loop_factory)

    asyncio.run(run())
    base = global_mgr.SUPERVISE_BACKOFF_S
    assert ashim.sleeps[0] == base  # first crash: base
    assert ashim.sleeps[1] == 2 * base  # crash loop: escalates
    assert ashim.sleeps[2] == base, (
        "backoff must reset to base after a healthy run longer than "
        "SUPERVISE_RESET_S"
    )
